"""Replicated control plane: leased leadership + journal shipping.

Active/standby replication built on the PR 10 crash-safety primitives
(write-ahead journal, epoch fencing, restart reconciliation):

- :mod:`.lease` — the ``<journal>.epoch`` sidecar extended from a plain
  fencing token into a *leased leadership claim* (holder id + epoch +
  lease expiry on the injected clock, renewed atomically).  Exactly one
  incarnation may append and mutate; ``StaleEpochError`` remains the
  zombie kill-path.
- :mod:`.shipper` — leader-side :class:`JournalShipper` streams journal
  appends as length-prefixed records resumable by byte offset;
  follower-side :class:`JournalTailer` tails them into a byte-identical
  replica plus an incrementally reconciled replay state.
- :mod:`.standby` — :class:`ReplicationController` (leader: acquire +
  renew the lease) and :class:`WarmStandby` (follower: tail, pre-warm
  kernels, and on lease expiry advance the epoch and take over from the
  already-tailed state — strictly faster than a cold ``recover()``).

See docs/operations.md ("Replication and failover") for the operational
walk-through.
"""

from .lease import LeaderLease, LeaseHeldError, LeaseState, read_lease
from .shipper import JournalShipper, JournalTailer, ShipBatch
from .standby import ReplicationController, WarmStandby

__all__ = [
    "JournalShipper",
    "JournalTailer",
    "LeaderLease",
    "LeaseHeldError",
    "LeaseState",
    "ReplicationController",
    "ShipBatch",
    "WarmStandby",
    "read_lease",
]
