"""Journal shipping: leader-side shipper, follower-side tailer.

The leader streams journal appends to the standby as *length-prefixed
records over the existing JSONL format*, resumable by byte offset::

    [4-byte big-endian length][record line bytes, no newline] ...

The shipper reads straight from the durable journal file (the write-
ahead discipline means the file *is* the authoritative stream) and only
ever ships complete lines — a torn tail stays on the leader until its
newline lands.  The tailer appends each record to a byte-identical
replica file and simultaneously feeds it through a
:class:`~cruise_control_tpu.executor.journal.ReplayAccumulator`, so the
follower's reconciled state is always current and takeover never pays a
full-journal replay.

Compaction resets: :meth:`ExecutionJournal.compact` atomically rewrites
the source file, invalidating follower offsets.  The shipper detects
this (compaction counter bump, or an offset past the new end of file)
and flags ``reset`` — the tailer truncates its replica and re-syncs
from offset 0 (cheap by construction: a compacted journal is one
checkpoint record plus the tail written since).

Transport is left to the caller: :class:`ShipBatch` is a plain value
object, so the pair works in-process (tests, simulator, same-host warm
standby) or across any byte channel that delivers batches in order.
"""

from __future__ import annotations

import json
import logging
import os
import struct
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional

from ..executor.journal import (ExecutionJournal, JournalReplay,
                                ReplayAccumulator)

logger = logging.getLogger("cruise-control.replication")

#: 4-byte big-endian unsigned record-length prefix
FRAME_HEADER = struct.Struct(">I")


def frame_records(lines: List[bytes]) -> bytes:
    """Length-prefix each record line (newline stripped by the caller)."""
    return b"".join(FRAME_HEADER.pack(len(line)) + line for line in lines)


def iter_frames(buf: bytes) -> Iterator[bytes]:
    """Decode length-prefixed records; a torn trailing frame is an error
    (the shipper only emits whole frames — truncation means transport
    corruption, not a torn journal tail)."""
    pos = 0
    while pos < len(buf):
        if pos + FRAME_HEADER.size > len(buf):
            raise ValueError("torn frame header in shipped batch")
        (length,) = FRAME_HEADER.unpack_from(buf, pos)
        pos += FRAME_HEADER.size
        if pos + length > len(buf):
            raise ValueError("torn frame payload in shipped batch")
        yield buf[pos:pos + length]
        pos += length


@dataclass(frozen=True)
class ShipBatch:
    """One shipper→tailer transfer."""

    #: length-prefixed record lines
    frames: bytes
    #: source byte offset the frames start at
    base_offset: int
    #: source byte offset to resume from next time
    next_offset: int
    #: source was rewritten (compaction / fresh leader); tailer must
    #: truncate its replica and apply from offset 0
    reset: bool
    #: leader's total journal entry count at ship time (lag accounting)
    leader_entries: int
    #: leader's compaction counter at ship time
    compactions: int


class JournalShipper:
    """Leader side: serve journal bytes from a given offset."""

    def __init__(self, journal: ExecutionJournal):
        self._journal = journal

    @property
    def journal(self) -> ExecutionJournal:
        return self._journal

    def ship_since(self, offset: int, known_compactions: int = 0,
                   max_bytes: int = 1 << 20) -> ShipBatch:
        """Read complete record lines from ``offset``, framed.

        ``known_compactions`` is the tailer's view of the leader's
        compaction counter; a mismatch (or an offset past end-of-file)
        means the source was rewritten underneath the stream and the
        batch restarts from 0 with ``reset`` set.
        """
        path = self._journal.path
        compactions = self._journal.compactions
        size = self._journal.size_bytes()
        reset = compactions != known_compactions or offset > size
        base = 0 if reset else int(offset)
        chunk = b""
        if size > base:
            try:
                with open(path, "rb") as f:
                    f.seek(base)
                    chunk = f.read(max_bytes)
                    # liveness: a single record longer than max_bytes must
                    # still make progress — grow the read until its newline
                    # lands (or EOF proves the tail torn)
                    while b"\n" not in chunk and len(chunk) < size - base:
                        chunk += f.read(max_bytes)
            except OSError:
                chunk = b""
        # ship whole lines only: everything past the last newline is a
        # potentially torn in-flight append
        cut = chunk.rfind(b"\n")
        chunk = chunk[:cut + 1] if cut >= 0 else b""
        lines = chunk.split(b"\n")[:-1] if chunk else []
        return ShipBatch(
            frames=frame_records(lines),
            base_offset=base,
            next_offset=base + len(chunk),
            reset=reset,
            leader_entries=self._journal.entries,
            compactions=compactions,
        )


class JournalTailer:
    """Follower side: apply shipped batches into a warm replica.

    Maintains (1) a byte-identical replica file of the leader journal's
    shipped prefix and (2) an incrementally reconciled
    :class:`ReplayAccumulator` — the takeover path reads the accumulated
    state directly instead of replaying the replica from disk.
    """

    def __init__(self, replica_path: str,
                 fsync: bool = False,
                 on_record: Optional[Callable[[dict], None]] = None):
        self._path = replica_path
        self._fsync = fsync
        self._on_record = on_record
        directory = os.path.dirname(os.path.abspath(replica_path))
        os.makedirs(directory, exist_ok=True)
        self._fh = None
        self.offset = 0
        self.entries = 0
        self.compactions = 0
        self.resets = 0
        self.leader_entries = 0
        self._acc = ReplayAccumulator()

    @property
    def path(self) -> str:
        return self._path

    @property
    def lag_records(self) -> int:
        """Leader entries not yet tailed, per the last shipped batch."""
        return max(self.leader_entries - self.entries, 0)

    def _reset_replica(self) -> None:
        self.close()
        with open(self._path, "wb"):
            pass
        self.offset = 0
        self.entries = 0
        self.resets += 1
        self._acc = ReplayAccumulator()

    def apply(self, batch: ShipBatch) -> int:
        """Append a shipped batch to the replica; returns records applied.

        Corrupt frames are skipped with a warning (mirrors
        ``iter_jsonl``'s tolerance) but still written to the replica so
        the byte stream stays identical to the source.
        """
        if batch.reset and (self.offset != 0 or self.entries != 0
                            or self.compactions != batch.compactions):
            self._reset_replica()
        applied = 0
        if batch.frames:
            if self._fh is None:
                self._fh = open(self._path, "ab")
            for line in iter_frames(batch.frames):
                self._fh.write(line + b"\n")
                try:
                    rec = json.loads(line.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    logger.warning("Skipping unparsable shipped record")
                    continue
                self._acc.feed(rec)
                self.entries += 1
                applied += 1
                if self._on_record is not None:
                    self._on_record(rec)
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())
        self.offset = batch.next_offset
        self.compactions = batch.compactions
        self.leader_entries = batch.leader_entries
        return applied

    def pull(self, shipper: JournalShipper, max_bytes: int = 1 << 20) -> int:
        """One tail step: request the next batch and apply it."""
        batch = shipper.ship_since(self.offset,
                                   known_compactions=self.compactions,
                                   max_bytes=max_bytes)
        return self.apply(batch)

    def replay_state(self, epoch: int = 0) -> JournalReplay:
        """The incrementally accumulated replay — what a cold
        ``journal.replay()`` of the replica would return, without
        touching disk."""
        return self._acc.result(epoch=epoch)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:  # pragma: no cover
                pass
            self._fh = None
