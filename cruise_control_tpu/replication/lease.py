"""Leased leadership over the journal epoch sidecar.

The ``<journal>.epoch`` sidecar has been a fencing token since PR 10:
whoever atomically bumps ``{"epoch": N}`` fences every older holder
(:class:`~cruise_control_tpu.executor.journal.StaleEpochError` on their
next append).  This module extends the same file into a *leased
leadership claim*::

    {"epoch": N, "holder": "cc-host-a", "leaseExpiryMs": 1234567}

- ``epoch`` stays the fencing token — the journal only ever reads this
  key, so legacy sidecars and leased sidecars are interchangeable.
- ``holder`` + ``leaseExpiryMs`` make leadership *time-bounded*: the
  leader re-stamps the expiry (same epoch, same holder) every
  ``replication.lease.renew.ms``; a standby may only claim once the
  expiry passes on its clock.
- Acquisition advances the epoch, so taking over and fencing the
  ex-leader are one atomic sidecar replace — there is no window in
  which both incarnations may append.

All timing flows through the injected ``now_ms`` seam (graftlint G011:
no raw wall-clock in replication paths), so leases behave identically
under the virtual-time simulator.  The sidecar lives on storage shared
by both incarnations (the same property the journal itself needs for
takeover); atomic replace makes each write all-or-nothing.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Callable, Optional

from ..common.atomicio import atomic_replace
from ..executor.journal import StaleEpochError


class LeaseHeldError(RuntimeError):
    """Raised when acquisition is attempted against an unexpired lease
    held by someone else — the claimant must keep waiting."""


@dataclass(frozen=True)
class LeaseState:
    """Decoded sidecar contents (legacy sidecars decode with no holder,
    i.e. an expired lease at their recorded epoch)."""

    epoch: int = 0
    holder: Optional[str] = None
    expiry_ms: int = 0

    def expired(self, now_ms: int) -> bool:
        return self.holder is None or int(now_ms) >= self.expiry_ms


def read_lease(epoch_path: str) -> LeaseState:
    """Parse the sidecar; unreadable/absent files decode as an expired,
    epoch-0 claim (mirrors the journal's tolerant epoch read)."""
    try:
        with open(epoch_path, "r", encoding="utf-8") as f:
            data = json.loads(f.read())
        holder = data.get("holder")
        return LeaseState(
            epoch=int(data["epoch"]),
            holder=str(holder) if holder is not None else None,
            expiry_ms=int(data.get("leaseExpiryMs", 0)),
        )
    except (OSError, ValueError, KeyError, TypeError):
        return LeaseState()


class LeaderLease:
    """One incarnation's handle on the leased leadership claim.

    ``now_ms`` is required, not defaulted: lease timing must route
    through the injected clock seam so virtual-time simulation and
    deterministic replay stay exact.
    """

    def __init__(self, epoch_path: str, holder: str,
                 now_ms: Callable[[], int],
                 lease_ms: int = 30_000, renew_ms: int = 10_000,
                 fsync: bool = True):
        self._epoch_path = epoch_path
        self._holder = str(holder)
        self._now_ms = now_ms
        self._lease_ms = int(lease_ms)
        self._renew_ms = int(renew_ms)
        self._fsync = fsync
        self._epoch: Optional[int] = None
        self._expiry_ms: int = 0
        self._last_renew_ms: Optional[int] = None
        directory = os.path.dirname(os.path.abspath(epoch_path))
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------ state

    @property
    def path(self) -> str:
        return self._epoch_path

    @property
    def holder_id(self) -> str:
        return self._holder

    @property
    def lease_ms(self) -> int:
        return self._lease_ms

    @property
    def renew_ms(self) -> int:
        return self._renew_ms

    @property
    def epoch(self) -> Optional[int]:
        """Epoch this handle claimed; ``None`` until :meth:`acquire`."""
        return self._epoch

    def read(self) -> LeaseState:
        return read_lease(self._epoch_path)

    def held(self) -> bool:
        """Does the sidecar currently name *this* holder at the epoch we
        claimed (regardless of expiry — an expired-but-unsuperseded
        leader is still the only legal appender)?"""
        st = self.read()
        return st.holder == self._holder and st.epoch == self._epoch

    # ---------------------------------------------------------- actions

    def _write(self, epoch: int, expiry_ms: int) -> None:
        payload = json.dumps(
            {"epoch": int(epoch), "holder": self._holder,
             "leaseExpiryMs": int(expiry_ms)},
            sort_keys=True, separators=(",", ":"))
        atomic_replace(self._epoch_path, payload.encode("utf-8"),
                       fsync=self._fsync)

    def acquire(self) -> int:
        """Claim leadership: advance the epoch and stamp holder+expiry.

        One atomic sidecar replace both grants the lease and fences
        every prior epoch holder.  Raises :class:`LeaseHeldError` while
        another holder's lease is unexpired — the claim must wait out
        the lease, never race it.
        """
        st = self.read()
        now = int(self._now_ms())
        if st.holder not in (None, self._holder) and not st.expired(now):
            raise LeaseHeldError(
                f"lease held by {st.holder!r} (epoch {st.epoch}) until "
                f"{st.expiry_ms} ms; now {now} ms")
        self._epoch = st.epoch + 1
        self._expiry_ms = now + self._lease_ms
        self._last_renew_ms = now
        self._write(self._epoch, self._expiry_ms)
        return self._epoch

    def renew(self) -> LeaseState:
        """Re-stamp the expiry at the held epoch (atomic replace).

        Raises :class:`~cruise_control_tpu.executor.journal.
        StaleEpochError` if the sidecar no longer names this holder at
        this epoch — the lease was taken over; the caller is a zombie
        and must stop serving."""
        st = self.read()
        now = int(self._now_ms())
        if st.epoch != self._epoch or st.holder != self._holder:
            raise StaleEpochError(
                f"lease superseded: sidecar holds {st.holder!r} at epoch "
                f"{st.epoch}, this incarnation claimed epoch {self._epoch}")
        self._expiry_ms = now + self._lease_ms
        self._last_renew_ms = now
        self._write(self._epoch, self._expiry_ms)
        return LeaseState(self._epoch, self._holder, self._expiry_ms)

    def renew_due(self) -> bool:
        """True once ``renew_ms`` has elapsed since the last stamp."""
        if self._last_renew_ms is None:
            return True
        return int(self._now_ms()) - self._last_renew_ms >= self._renew_ms

    def maybe_renew(self) -> Optional[LeaseState]:
        """Renew iff due; the leader's per-tick entry point."""
        if not self.renew_due():
            return None
        return self.renew()

    def state_snapshot(self) -> dict:
        st = self.read()
        return {
            "holder": st.holder,
            "epoch": st.epoch,
            "leaseExpiryMs": st.expiry_ms,
            "leaseMs": self._lease_ms,
            "renewMs": self._renew_ms,
            "expired": st.expired(int(self._now_ms())),
            "heldByMe": st.holder == self._holder and st.epoch == self._epoch,
        }
