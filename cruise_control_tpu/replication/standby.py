"""Leader/follower roles over the lease + shipper primitives.

:class:`ReplicationController` is the leader half: acquire the lease,
adopt the claimed epoch into the execution journal, and keep re-stamping
the expiry.  :class:`WarmStandby` is the follower half: tail the
leader's journal into a warm replica (pre-warming compiled kernels on
first contact), watch the lease, and on expiry *take over* — advance the
epoch via lease acquisition (one atomic sidecar replace that also fences
the ex-leader), hand the already-tailed replica to the executor, and
complete reconciliation from the accumulated state.  The takeover skips
the full-journal replay a cold ``Executor.recover()`` pays, which is
exactly the warm-vs-cold margin ``BENCH_SIZE=recovery`` measures.

Both roles surface a ``state_snapshot()`` consumed by ``/state`` as
``ReplicationState`` (role, lease expiry, follower lag).  The follower's
tail loop registers with the PR 10
:class:`~cruise_control_tpu.common.watchdog.Watchdog` (named heartbeat,
``active_fn``-gated) so a stalled tailer is restarted with backoff and
surfaced as degraded instead of silently falling behind.

All timing is injected (``now_ms`` / ``sleep_s`` seams — graftlint G011
holds for this package), so the whole failover dance runs under the
virtual-time simulator.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from ..executor.journal import ExecutionJournal
from .lease import LeaderLease
from .shipper import JournalShipper, JournalTailer

logger = logging.getLogger("cruise-control.replication")

#: watchdog heartbeat name for the follower tail loop
TAILER_HEARTBEAT = "replication-tailer"


class ReplicationController:
    """Leader-side replication: hold and renew the leadership lease.

    ``attach()`` is the promotion-to-leader handshake: acquire the lease
    (advancing the epoch, fencing all priors) and have the journal adopt
    that epoch so every subsequent append carries it.
    """

    def __init__(self, lease: LeaderLease,
                 journal: Optional[ExecutionJournal] = None,
                 shipper: Optional[JournalShipper] = None):
        self._lease = lease
        self._journal = journal
        self._shipper = shipper or (JournalShipper(journal)
                                    if journal is not None else None)
        self.role = "leader"

    @property
    def lease(self) -> LeaderLease:
        return self._lease

    @property
    def shipper(self) -> Optional[JournalShipper]:
        return self._shipper

    def attach(self) -> int:
        """Acquire the lease and adopt its epoch into the journal."""
        epoch = self._lease.acquire()
        if self._journal is not None:
            self._journal.adopt_epoch()
        return epoch

    def tick(self):
        """Per-tick (or per-loop) leader duty: renew the lease when due.

        Propagates ``StaleEpochError`` if the lease was taken over —
        the caller is a zombie and must stop serving."""
        return self._lease.maybe_renew()

    def state_snapshot(self) -> dict:
        out = {"role": self.role, **self._lease.state_snapshot(),
               "followerLagRecords": None}
        if self._journal is not None:
            out["journalEntries"] = self._journal.entries
            out["journalCompactions"] = self._journal.compactions
        return out


class WarmStandby:
    """Follower-side replication: tail, stay warm, take over on expiry.

    ``executor`` is the standby's (journal-less) executor; ``promote()``
    builds an :class:`ExecutionJournal` over the tailed replica —
    fencing against the *leader's* sidecar via ``epoch_path`` — attaches
    it, and runs ``recover(advance=False, replay=<tailed state>)``.
    ``warm_fn`` (called once, on first tailed records) is the hook into
    the existing ``warm_kernels`` path so the anneal/heal programs are
    compiled before they are ever needed.
    """

    def __init__(self, shipper: JournalShipper, tailer: JournalTailer,
                 lease: LeaderLease, now_ms: Callable[[], int],
                 executor=None, warm_fn: Optional[Callable[[], None]] = None,
                 sleep_s: Optional[Callable[[float], None]] = None,
                 poll_interval_ms: int = 1_000, fsync: bool = False):
        self._shipper = shipper
        self._tailer = tailer
        self._lease = lease
        self._now_ms = now_ms
        self._executor = executor
        self._warm_fn = warm_fn
        self._sleep_s = sleep_s
        self._poll_interval_ms = int(poll_interval_ms)
        self._fsync = fsync
        self.role = "follower"
        self.warmed = False
        self.takeovers = 0
        self.journal: Optional[ExecutionJournal] = None
        self.last_takeover: Optional[dict] = None
        self._watchdog = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: test hook — makes the tail thread exit without clearing the
        #: running flag, simulating a stalled loop for the watchdog
        self._stall_for_test = False

    # ------------------------------------------------------------- tail

    @property
    def tailer(self) -> JournalTailer:
        return self._tailer

    @property
    def lease(self) -> LeaderLease:
        return self._lease

    @property
    def lag_records(self) -> int:
        return self._tailer.lag_records

    def poll(self) -> int:
        """One tail step: pull + apply the next batch, beat the
        watchdog, fire the one-shot kernel pre-warm on first contact."""
        applied = self._tailer.pull(self._shipper)
        if self._watchdog is not None:
            self._watchdog.beat(TAILER_HEARTBEAT)
        if (applied and not self.warmed and self._warm_fn is not None
                and self.role == "follower"):
            self.warmed = True
            try:
                self._warm_fn()
            except Exception:
                logger.exception("standby kernel pre-warm failed; takeover "
                                 "will compile on demand")
        return applied

    # --------------------------------------------------------- takeover

    def lease_expired(self) -> bool:
        return self._lease.read().expired(int(self._now_ms()))

    def promote(self, executor=None) -> dict:
        """Take over leadership from the already-tailed state.

        Sequence (docs/operations.md "Replication and failover"):

        1. ``lease.acquire()`` — advances the epoch and stamps this
           holder in one atomic sidecar replace; the fenced ex-leader's
           next append raises ``StaleEpochError``.
        2. Build an :class:`ExecutionJournal` over the replica file,
           fenced against the *shared* sidecar, seeded with the tailer's
           entry count (no re-parse).
        3. ``recover(advance=False, replay=<accumulated state>)`` —
           adopt the claimed epoch and reconcile/resume the open
           execution without replaying the journal from disk.
        """
        ex = executor or self._executor
        if ex is None:
            raise RuntimeError("WarmStandby.promote() needs an executor")
        from cruise_control_tpu.obs.tracing import NOOP_TRACER
        tracer = getattr(ex, "_tracer", None) or NOOP_TRACER
        with tracer.span("standby-takeover",
                         lagRecords=self._tailer.lag_records) as _sp:
            epoch = self._lease.acquire()
            self.journal = ExecutionJournal(
                self._tailer.path, fsync=self._fsync, now_ms=self._now_ms,
                epoch_path=self._lease.path,
                entries_hint=self._tailer.entries)
            ex.attach_journal(self.journal)
            summary = ex.recover(advance=False,
                                 replay=self._tailer.replay_state(epoch=epoch))
            _sp.set("epoch", epoch)
        self.role = "leader"
        self.takeovers += 1
        self.last_takeover = summary
        return summary

    def maybe_takeover(self, executor=None) -> Optional[dict]:
        """Promote iff the leader's lease has expired; the follower's
        per-tick entry point."""
        if self.role != "follower" or not self.lease_expired():
            return None
        return self.promote(executor=executor)

    # -------------------------------------------------- tail loop (S2)

    @property
    def running(self) -> bool:
        return self._thread is not None and not self._stop.is_set()

    def register_watchdog(self, watchdog) -> None:
        """Register the tail loop with the thread watchdog: heartbeat on
        every poll, ``active_fn``-gated (an intentionally stopped
        standby is idle, not stalled), restarted with the watchdog's
        bounded backoff when the loop wedges."""
        self._watchdog = watchdog
        watchdog.register(TAILER_HEARTBEAT,
                          restart_fn=self._restart_thread,
                          active_fn=lambda: self.running)

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._stall_for_test:
                return  # thread dies with running still claimed
            try:
                self.poll()
            except Exception:
                logger.exception("standby tail step failed; retrying")
            if self._sleep_s is not None:
                self._sleep_s(self._poll_interval_ms / 1000.0)

    def start(self) -> None:
        """Spawn the tail loop thread (wall-clock deployments; the
        simulator drives :meth:`poll` from its tick loop instead)."""
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=TAILER_HEARTBEAT, daemon=True)
        self._thread.start()

    def _restart_thread(self) -> None:
        self._stall_for_test = False
        self._thread = None
        self.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        self._thread = None
        self._tailer.close()

    # ------------------------------------------------------------ state

    def state_snapshot(self) -> dict:
        st = self._lease.read()
        return {
            "role": self.role,
            "holder": st.holder,
            "epoch": st.epoch,
            "leaseExpiryMs": st.expiry_ms,
            "leaseMs": self._lease.lease_ms,
            "renewMs": self._lease.renew_ms,
            "expired": st.expired(int(self._now_ms())),
            "heldByMe": (st.holder == self._lease.holder_id
                         and st.epoch == self._lease.epoch),
            "followerLagRecords": self.lag_records,
            "tailedRecords": self._tailer.entries,
            "tailerResets": self._tailer.resets,
            "takeovers": self.takeovers,
            "warmedKernels": self.warmed,
        }
