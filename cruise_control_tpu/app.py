"""Service facade: the TPU Cruise Control application object.

Rebuild of ``KafkaCruiseControl.java:64-731`` + the proposal-cache side of
``GoalOptimizer.java`` (precompute/caching keyed by model generation,
``GoalOptimizer.java:126-325``): wires LoadMonitor, the optimizer, the
Executor, and the AnomalyDetector service; exposes the operations the REST
runnables call (``servlet/handler/async/runnable/*.java``): rebalance,
proposals, add/remove/demote brokers, fix offline replicas, pause/resume
sampling, stop execution. Implements
:class:`~cruise_control_tpu.detector.anomalies.SelfHealingContext` so
anomaly fixes run through the exact same paths.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import logging
logger = logging.getLogger(__name__)

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import optimizer as OPT
from cruise_control_tpu.analyzer.annealer import AnnealConfig
from cruise_control_tpu.common.config import CruiseControlConfig
from cruise_control_tpu.common.metrics import REGISTRY
from cruise_control_tpu.detector.anomalies import AnomalyType, SelfHealingNotifier
from cruise_control_tpu.detector.detectors import (
    METRIC_ANOMALY_FINDER_REGISTRY,
    AnomalyDetectorService,
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    MetricAnomalyDetector,
    SlowBrokerFinder,
)
from cruise_control_tpu.executor.executor import (
    ClusterAdapter,
    Executor,
    ExecutorConfig,
    FakeClusterAdapter,
)
from cruise_control_tpu.models.cluster import Assignment, ClusterTopology
from cruise_control_tpu.monitor.aggregator import ModelCompletenessRequirements
from cruise_control_tpu.monitor.load_monitor import (
    LoadMonitor,
    MetadataSource,
    NotEnoughValidWindowsError,
)
from cruise_control_tpu.monitor.sampler import MetricSampler
from cruise_control_tpu.obs.tracing import Tracer
from cruise_control_tpu.parallel.mesh import mesh_from_config, mesh_state


@dataclasses.dataclass
class CachedProposals:
    result: OPT.OptimizerResult
    generation: "object"
    computed_at_ms: int


class CruiseControlApp:
    """The running service: all subsystems + operation surface."""

    def __init__(self, config: CruiseControlConfig,
                 metadata_source: MetadataSource,
                 sampler: Optional[MetricSampler] = None,
                 cluster_adapter: Optional[ClusterAdapter] = None,
                 capacity_resolver=None, sample_store=None,
                 mesh=None, now_fn=None, sleep_fn=None):
        from cruise_control_tpu.common.config import resolve_pluggable
        self.config = config
        # virtual-time seam: every timestamp that drives *decisions* (cache
        # freshness, detector thresholds, executor deadlines) flows through
        # now_fn/sleep_fn so the scenario simulator can run hours of cluster
        # time in seconds of wall time. Wall-clock *measurements* (tick
        # latency, self-heal latency) intentionally stay on time.monotonic.
        self._now_s = now_fn or time.time
        self._sleep_fn = sleep_fn or time.sleep
        _now_s = self._now_s
        self._now_ms_fn = lambda: int(_now_s() * 1000)
        # thread watchdog: every background loop checks a heartbeat in; the
        # watchdog's own monitor thread (or the simulator tick loop) polls
        # for stalls and restarts restartable threads with bounded backoff
        from cruise_control_tpu.common.watchdog import Watchdog
        self.watchdog = Watchdog(
            now_ms=self._now_ms_fn,
            stall_ms=config.get("watchdog.stall.ms"),
            max_restarts=config.get("watchdog.max.restarts"),
            backoff_ms=config.get("watchdog.backoff.ms"))
        self._watchdog_thread: Optional[threading.Thread] = None
        self._watchdog_shutdown = threading.Event()
        # graftscope span tracer (obs.tracing.*): spans over the virtual-
        # time seam (deterministic timelines under the simulator), wall
        # durations into the per-stage registry timers. Disabled it hands
        # out the shared no-op span — bit-identical behavior.
        self.tracer = Tracer(
            now_fn=self._now_s,
            capacity=config.get("obs.tracing.buffer.spans"),
            enabled=bool(config.get("obs.tracing.enable")),
            registry=REGISTRY)
        # compile/retrace observatory (obs.observatory.enable): installed
        # once per process (module singleton) — per-function compile
        # accounting for /observatory and the metrics registry
        if config.get("obs.observatory.enable"):
            from cruise_control_tpu.obs.observatory import OBSERVATORY
            OBSERVATORY.install()
        # tick flight recorder (obs.flightrec.*): bounded ring of decision
        # records on the injected clock — what the loop decided and why
        # (engine/heal/decode path, goal verdicts, top attributed moves,
        # detector decisions). Canonical JSONL via GET /flightrecorder;
        # tools/replay_tick.py replays any record bit-identically.
        from cruise_control_tpu.obs.flightrec import FlightRecorder
        self.flightrec = FlightRecorder(
            now_fn=self._now_s,
            capacity=config.get("obs.flightrec.ticks"),
            enabled=bool(config.get("obs.flightrec.enable")),
            top_moves=config.get("obs.flightrec.top.moves"))
        # graftwatch cost observatory (obs.costmodel.*): compiled-program
        # cost/memory ledger + live-buffer census + headroom forecaster.
        # Process-wide singleton (the OBSERVATORY precedent); the compile
        # listener feeds per-function compile wall into the ledger.
        from cruise_control_tpu.obs.costmodel import COSTS
        self.costmodel = COSTS
        if config.get("obs.costmodel.enable"):
            COSTS.configure(
                enabled=True,
                deep=bool(config.get("obs.costmodel.deep")),
                sample_interval_ms=config.get(
                    "obs.costmodel.sample.interval.ms"),
                hbm_limit_bytes=config.get("obs.costmodel.hbm.limit.bytes"),
                registry=REGISTRY, now_ms_fn=self._now_ms_fn)
            from cruise_control_tpu.obs.observatory import OBSERVATORY
            OBSERVATORY.add_compile_listener(COSTS.on_compile)
        #: graftwatch health watch (healthwatch.*) — constructed after the
        #: anomaly detector so alerts fire through its notifier seam
        self.healthwatch = None
        self.constraint = config.balancing_constraint()
        self.default_goals = tuple(config.get("default.goals"))
        if mesh is None:
            # optimizer.mesh.enable/.devices — config-driven scale-out; an
            # explicit mesh arg (tests, driver dry-run) always wins
            mesh = mesh_from_config(config)
        self.mesh = mesh
        # goal.balancedness.* weights — per-app config threaded into every
        # optimize call (KafkaCruiseControlUtils.java:530 semantics; NOT a
        # module global, so two apps in one process score independently)
        self._balancedness_weights = (
            config.get("goal.balancedness.priority.weight"),
            config.get("goal.balancedness.strictness.weight"))
        if sampler is None:
            # metric.sampler.class (MetricSampler SPI): factories take the
            # service config; dotted paths resolve to a factory/class
            from cruise_control_tpu.monitor.sampler import SAMPLER_REGISTRY
            factory = resolve_pluggable(
                config.get("metric.sampler.class"), SAMPLER_REGISTRY)
            sampler = factory(config)
        if capacity_resolver is None:
            # broker.capacity.config.resolver.class. A config-less boot
            # (tests, demos) with the DEFAULT file resolver and no capacity
            # file falls through to the monitor's static default; an
            # EXPLICITLY configured resolver/file that cannot be read must
            # fail the boot (the reference does) — silently optimizing
            # against wrong capacities is the worst outcome.
            import os as _os
            from cruise_control_tpu.monitor.capacity import (
                CAPACITY_RESOLVER_REGISTRY)
            name = config.get("broker.capacity.config.resolver.class")
            factory = resolve_pluggable(name, CAPACITY_RESOLVER_REGISTRY)
            is_file_resolver = name in ("FileCapacityResolver",
                                        "BrokerCapacityConfigFileResolver")
            explicit = ("broker.capacity.config.resolver.class"
                        in config.originals
                        or "capacity.config.file" in config.originals)
            file_ok = _os.path.exists(config.get("capacity.config.file"))
            if factory is not None:
                if is_file_resolver and not file_ok and explicit:
                    raise ValueError(
                        "capacity.config.file "
                        f"{config.get('capacity.config.file')!r} does not "
                        "exist but a capacity resolver was explicitly "
                        "configured")
                if not is_file_resolver or file_ok:
                    capacity_resolver = factory(config)
        import re
        _pat = config.get("topics.excluded.from.partition.movement")
        self._excluded_topics_rx = re.compile(_pat) if _pat else None
        from cruise_control_tpu.models.cluster import set_static_cpu_weights
        set_static_cpu_weights(
            config.get("leader.network.inbound.weight.for.cpu.util"),
            config.get("leader.network.outbound.weight.for.cpu.util"),
            config.get("follower.network.inbound.weight.for.cpu.util"))
        self.load_monitor = LoadMonitor(
            metadata_source, sampler,
            capacity_resolver=capacity_resolver,
            sample_store=sample_store,
            num_windows=config.get("num.partition.metrics.windows"),
            window_ms=config.get("partition.metrics.window.ms"),
            min_samples_per_window=config.get(
                "min.samples.per.partition.metrics.window"),
            max_allowed_extrapolations=config.get(
                "max.allowed.extrapolations.per.partition"),
            sampling_interval_ms=config.get("metric.sampling.interval.ms"),
            use_lr_model=config.get("use.linear.regression.model"),
            lr_model_buckets=(
                config.get("linear.regression.model.cpu.util.bucket.size"),
                config.get(
                    "linear.regression.model.min.num.cpu.util.buckets"),
                config.get(
                    "linear.regression.model.required.samples.per.bucket")),
            num_metric_fetchers=config.get("num.metric.fetchers"),
            broker_num_windows=config.get("num.broker.metrics.windows"),
            broker_window_ms=config.get("broker.metrics.window.ms"),
            min_samples_per_broker_window=config.get(
                "min.samples.per.broker.metrics.window"),
            max_allowed_extrapolations_per_broker=config.get(
                "max.allowed.extrapolations.per.broker"),
            partition_completeness_cache_size=config.get(
                "partition.metric.sample.aggregator.completeness.cache.size"),
            broker_completeness_cache_size=config.get(
                "broker.metric.sample.aggregator.completeness.cache.size"),
            now_fn=self._now_ms_fn if now_fn is not None else None,
            heartbeat=lambda: self.watchdog.beat("load-monitor-sampler"),
            store_heartbeat=lambda: self.watchdog.beat("sample-store-flush"),
            tracer=self.tracer)
        self._metadata_source = metadata_source
        adapter = cluster_adapter or FakeClusterAdapter({})
        # write-ahead execution journal (executor.journal.path; empty =
        # disabled): every task transition is durable before its cluster
        # effect, and startup() reconciles whatever the journal left open
        from cruise_control_tpu.executor.journal import ExecutionJournal
        _journal_path = config.get("executor.journal.path")
        self.journal = (ExecutionJournal(
            _journal_path, fsync=config.get("executor.journal.fsync"),
            now_ms=self._now_ms_fn,
            epoch_path=config.get("executor.journal.epoch.path") or None,
            compact_records=config.get("executor.journal.compact.records"))
            if _journal_path else None)
        #: replication role (ReplicationController / WarmStandby),
        #: attached by the deployment or the scenario runner; surfaced
        #: in /state as ReplicationState
        self.replication = None
        check_ms = config.get("execution.progress.check.interval.ms")
        # default.replica.movement.strategies: the strategy chain used when
        # a request names none
        from cruise_control_tpu.executor.tasks import STRATEGIES
        _chain = None
        for _name in config.get("default.replica.movement.strategies"):
            _cls = STRATEGIES.get(_name)
            if _cls is not None:
                _chain = _cls() if _chain is None else _chain.chain(_cls())
        from cruise_control_tpu.executor.executor import (
            EXECUTOR_NOTIFIER_REGISTRY, ExecutorNotifier)
        self.executor = Executor(
            adapter,
            strategy=_chain,
            clock=self._now_s,
            sleep=self._sleep_fn,
            journal=self.journal,
            heartbeat=lambda: self.watchdog.beat("executor-progress"),
            tracer=self.tracer,
            notifier=resolve_pluggable(
                config.get("executor.notifier.class"),
                EXECUTOR_NOTIFIER_REGISTRY, base=ExecutorNotifier)(),
            config=ExecutorConfig(
                max_num_cluster_movements=config.get(
                    "max.num.cluster.movements"),
                num_concurrent_partition_movements_per_broker=config.get(
                    "num.concurrent.partition.movements.per.broker"),
                num_concurrent_intra_broker_partition_movements=config.get(
                    "num.concurrent.intra.broker.partition.movements"),
                num_concurrent_leader_movements=config.get(
                    "num.concurrent.leader.movements"),
                execution_progress_check_interval_ms=check_ms,
                default_replication_throttle=config.get(
                    "default.replication.throttle"),
                leader_movement_timeout_ms=config.get(
                    "leader.movement.timeout.ms"),
                task_execution_alerting_threshold_ms=config.get(
                    "task.execution.alerting.threshold.ms"),
                removal_history_retention_ms=config.get(
                    "removal.history.retention.time.ms"),
                demotion_history_retention_ms=config.get(
                    "demotion.history.retention.time.ms"),
                inter_broker_movement_rate_alerting_threshold=config.get(
                    "inter.broker.replica.movement.rate.alerting.threshold"),
                intra_broker_movement_rate_alerting_threshold=config.get(
                    "intra.broker.replica.movement.rate.alerting.threshold"),
                adapter_retries=config.get("executor.adapter.retries"),
                adapter_retry_backoff_ms=config.get(
                    "executor.adapter.retry.backoff.ms"),
                adapter_retry_backoff_max_ms=config.get(
                    "executor.adapter.retry.backoff.max.ms"),
                task_stuck_deadline_ms=config.get(
                    "executor.task.stuck.deadline.ms")))
        from cruise_control_tpu.detector.anomalies import (
            AnomalyNotifier, NOTIFIER_REGISTRY)
        notifier_cls = resolve_pluggable(
            config.get("anomaly.notifier.class"), NOTIFIER_REGISTRY,
            base=AnomalyNotifier)
        _notifier_kw = dict(
            broker_failure_alert_threshold_ms=config.get(
                "broker.failure.alert.threshold.ms"),
            self_healing_threshold_ms=config.get(
                "broker.failure.self.healing.threshold.ms"),
            enabled={t: bool(config.get("self.healing.enabled"))
                     for t in AnomalyType})
        try:
            notifier = notifier_cls(now_fn=self._now_ms_fn, **_notifier_kw)
        except TypeError:
            # a pluggable notifier predating the virtual-time seam
            notifier = notifier_cls(**_notifier_kw)
        # the full finder suite the reference schedules
        # (AnomalyDetector.java:167-180): broker failure, goal violation,
        # disk failure (adapter logdir state), metric anomaly and slow-broker
        # (windowed broker metric history from the monitor).
        from cruise_control_tpu.detector.anomalies import (
            BrokerFailures, DiskFailures, GoalViolations, MetricAnomaly,
            resolve_anomaly_class)
        # provisioner: batched rightsizing grid shared by the goal-violation
        # detector (an unfixable violation becomes an under-provisioned
        # anomaly carrying the recommendation) and the RIGHTSIZE / WHAT_IF
        # endpoints
        from cruise_control_tpu.provisioner import Provisioner
        self.provisioner = Provisioner(
            constraint=self.constraint,
            goal_names=tuple(config.get("anomaly.detection.goals")),
            headroom_margin=config.get("provision.headroom.margin"),
            max_added_brokers=config.get("provision.max.added.brokers"),
            max_removed_brokers=config.get("provision.max.removed.brokers"),
            balancedness_weights=self._balancedness_weights,
            tracer=self.tracer)
        #: most recent rightsizing verdict (surfaced in /state; guarded by
        #: _cache_lock)
        self._last_provision_recommendation: Optional[dict] = None
        self.anomaly_detector = AnomalyDetectorService(
            notifier, context=self,
            has_ongoing_execution=lambda: self.executor.has_ongoing_execution,
            detectors={
                "broker_failure": BrokerFailureDetector(
                    metadata_source,
                    # failed.brokers.zk.path is the reference-compat alias
                    # for the record location (we persist to a file)
                    persist_path=(config.get("failed.brokers.zk.path")
                                  or config.get("failed.brokers.file.path")
                                  or None),
                    report_backoff_ms=config.get(
                        "broker.failure.detection.backoff.ms"),
                    now_fn=self._now_ms_fn,
                    anomaly_class=resolve_anomaly_class(
                        config.get("broker.failures.class"), BrokerFailures),
                ).detect,
                "goal_violation": GoalViolationDetector(
                    self.load_monitor,
                    goal_names=tuple(config.get("anomaly.detection.goals")),
                    allow_capacity_estimation=config.get(
                        "anomaly.detection.allow.capacity.estimation"),
                    anomaly_class=resolve_anomaly_class(
                        config.get("goal.violations.class"), GoalViolations),
                    provisioner=self.provisioner,
                    on_recommendation=self._record_provision_recommendation,
                    now_fn=self._now_ms_fn,
                ).detect,
                "disk_failure": DiskFailureDetector(
                    adapter.describe_logdirs,
                    now_fn=self._now_ms_fn,
                    anomaly_class=resolve_anomaly_class(
                        config.get("disk.failures.class"), DiskFailures),
                ).detect,
                "metric_anomaly": MetricAnomalyDetector(
                    self.load_monitor.broker_metric_history,
                    metrics=("cpu",),
                    finder=resolve_pluggable(
                        config.get("metric.anomaly.finder.class"),
                        METRIC_ANOMALY_FINDER_REGISTRY),
                    anomaly_class=resolve_anomaly_class(
                        config.get("metric.anomaly.class"), MetricAnomaly),
                    upper_percentile=config.get(
                        "metric.anomaly.percentile.upper.threshold"),
                    lower_percentile=config.get(
                        "metric.anomaly.percentile.lower.threshold"),
                    now_fn=self._now_ms_fn).detect,
                "slow_broker": SlowBrokerFinder(
                    self.load_monitor.broker_metric_history,
                    score_threshold=config.get("slow.broker.demotion.score"),
                    removal_threshold=config.get(
                        "slow.broker.decommission.score"),
                    now_fn=self._now_ms_fn).detect,
            },
            interval_ms=config.get("anomaly.detection.interval.ms"),
            intervals_ms={
                "goal_violation": config.get(
                    "goal.violation.detection.interval.ms"),
                "metric_anomaly": config.get(
                    "metric.anomaly.detection.interval.ms"),
                "disk_failure": config.get(
                    "disk.failure.detection.interval.ms"),
            },
            recheck_delay_ms=config.get("anomaly.detection.recheck.delay.ms"),
            num_cached_states=config.get("num.cached.recent.anomaly.states"),
            now_fn=self._now_ms_fn,
            heartbeat=lambda: self.watchdog.beat("anomaly-detector"),
            decision_sink=lambda payload: self.flightrec.record(
                "detector", payload))
        if config.get("healthwatch.enable"):
            # graftwatch health watch: per-tick health vectors in a device
            # ring, vmapped burn-rate alerting on the injected clock.
            # Alert decisions audit to the flight recorder through the
            # same decision_sink seam the detector uses, and fire through
            # the detector's notifier.
            from cruise_control_tpu.obs import healthwatch as HW
            self.healthwatch = HW.HealthWatch(
                HW.rules_from_config(config),
                ring_ticks=config.get("healthwatch.ring.ticks"),
                tick_slo_ms=float(config.get("healthwatch.tick.slo.ms")),
                now_ms_fn=self._now_ms_fn,
                registry=REGISTRY,
                decision_sink=lambda payload: self.flightrec.record(
                    "alert", payload),
                notifier=notifier)
        # heartbeat registry: stall detection is gated on each thread's
        # active predicate, so an idle executor or a not-yet-started (or
        # deliberately paused) loop never reads as stalled
        self.watchdog.register(
            "load-monitor-sampler",
            restart_fn=self.load_monitor.restart_sampler,
            active_fn=lambda: self.load_monitor.sampler_supervised)
        self.watchdog.register(
            "sample-store-flush",
            active_fn=lambda: self.load_monitor.sampler_supervised)
        self.watchdog.register(
            "anomaly-detector",
            restart_fn=self.anomaly_detector.restart,
            active_fn=lambda: self.anomaly_detector.supervised)
        self.watchdog.register(
            "executor-progress",
            active_fn=lambda: self.executor.has_ongoing_execution)
        self._proposal_cache: Optional[CachedProposals] = None
        self._cache_lock = threading.Lock()
        #: one-shot: escape kernels warmed after the first default-goal
        #: computation (see _compute_and_cache)
        self._escape_kernels_warmed = False
        #: previous accepted assignment for anneal warm starts:
        #: {"broker_of", "leader_of" (host np arrays), "digest"} — consumed
        #: by the NEXT default-goal computation iff the monitor's structural
        #: digest is unchanged (guarded by _cache_lock)
        self._warm_proposal: Optional[dict] = None
        self._precompute_thread: Optional[threading.Thread] = None
        self._precompute_shutdown = threading.Event()
        #: serializes the default-goal cacheable computation
        self._compute_gate = threading.Lock()
        self._default_requirements = ModelCompletenessRequirements(
            min_required_num_windows=1,
            min_monitored_partitions_percentage=config.get(
                "min.valid.partition.ratio"))
        #: (cache key, goals) for _ready_goals — readiness is stable within
        #: one (aggregator generation, window)
        self._ready_goals_cache: Optional[tuple] = None
        #: degraded-mode record of the most recent optimize() that fell back
        #: to a lower engine (surfaced in /state AnalyzerState)
        self._last_fallback: Optional[dict] = None
        #: last fallback record graftwatch saw (edge detection for the
        #: health vector's per-tick fallback flag)
        self._health_prev_fallback: Optional[dict] = None
        #: consecutive precompute_tick failures (warning rate is capped)
        self._precompute_failures = 0
        #: incremental tick path (analyzer/rescore.py): the goal-verdict
        #: baseline the cached proposal was computed against, plus counters
        #: for /state (all guarded by _cache_lock; the baseline itself is
        #: only read/mutated under _compute_gate)
        self._rescore_state = None
        self.proposal_cache_hits = 0
        self.proposal_cache_misses = 0
        self.incremental_refreshes = 0
        self.anneal_skips = 0
        self.last_tick_ms: Optional[float] = None
        #: self-heal timing counters for /state (guarded by _cache_lock):
        #: wall-clock of the most recent healing-context optimize and which
        #: route it took — "masked" (destination propose-mask in the
        #: annealer's sampler) or "full" (healing without a mask)
        self.last_self_heal_ms: Optional[float] = None
        self.self_heal_path: Optional[str] = None
        #: most recent scenario-simulator scorecard (surfaced in /state as
        #: SimulatorState; guarded by _cache_lock)
        self._last_simulation: Optional[dict] = None

    # ----------------------------------------------------------------- boot

    def startup(self):
        """KafkaCruiseControl.startUp (KafkaCruiseControl.java:156-165)."""
        # opt-in TSan-style lock tracing (GRAFT_TSAN=1): instrument every
        # lock-owning component before any background thread starts; the
        # report dumps at shutdown. Zero effect when the variable is unset.
        from cruise_control_tpu.common import sanitizer as _sanitizer
        if _sanitizer.tsan_enabled():
            self._lock_sanitizer = _sanitizer.install_tracing(
                self, self.executor, self.load_monitor,
                self.anomaly_detector, self.load_monitor.partition_aggregator,
                self.load_monitor.broker_aggregator)
        # restart reconciliation BEFORE any background thread can start an
        # execution: replay the journal, fence out zombies, and resolve
        # whatever the previous incarnation left in flight
        if self.journal is not None:
            recovery = self.executor.recover()
            if recovery.get("openExecution"):
                logger.warning("restart reconciliation: %s", recovery)
        self.load_monitor.startup(
            load_stored_samples=not self.config.get("skip.loading.samples"))
        self.anomaly_detector.start()
        # proposal precompute loop (GoalOptimizer.run, GoalOptimizer.java:
        # 126-176): keep the default-goal proposal cache warm so PROPOSALS /
        # REBALANCE requests hit a ready result. Disabled with
        # num.proposal.precompute.threads=0.
        n_pre = self.config.get("num.proposal.precompute.threads")
        if n_pre > 1:
            logger.info("num.proposal.precompute.threads=%d: the device "
                        "computation is serialized by the compute gate, so "
                        "one precompute thread runs", n_pre)
        if n_pre > 0:
            self._precompute_shutdown.clear()
            self._precompute_thread = threading.Thread(
                target=self._precompute_loop, daemon=True,
                name="proposal-precompute")
            self._precompute_thread.start()
            self.watchdog.register(
                "proposal-precompute",
                restart_fn=self._restart_precompute,
                active_fn=lambda: (
                    self._precompute_thread is not None
                    and not self._precompute_shutdown.is_set()))
        # watchdog monitor thread (watchdog.interval.ms = 0 disables it;
        # the scenario simulator drives poll() from its tick loop instead)
        wd_interval_ms = self.config.get("watchdog.interval.ms")
        if wd_interval_ms > 0:
            self._watchdog_shutdown.clear()
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, args=(wd_interval_ms / 1000.0,),
                daemon=True, name="watchdog")
            self._watchdog_thread.start()

    def _watchdog_loop(self, interval_s: float):
        while not self._watchdog_shutdown.wait(interval_s):
            self.watchdog.poll()

    def _restart_precompute(self):
        """Watchdog restart hook for the proposal-precompute thread."""
        if (self._precompute_shutdown.is_set()
                or self._precompute_thread is None
                or self._precompute_thread.is_alive()):
            return
        self._precompute_thread = threading.Thread(
            target=self._precompute_loop, daemon=True,
            name="proposal-precompute")
        self._precompute_thread.start()

    def shutdown(self):
        self._watchdog_shutdown.set()
        if self._watchdog_thread is not None:
            self._watchdog_thread.join(timeout=5)
        self._precompute_shutdown.set()
        if self._precompute_thread is not None:
            self._precompute_thread.join(timeout=5)
        self.anomaly_detector.shutdown()
        self.load_monitor.shutdown()
        if self.journal is not None:
            self.journal.close()
        san = getattr(self, "_lock_sanitizer", None)
        if san is not None:
            logger.info("GRAFT_TSAN report: %s", san.dump())

    def _cached_result_if_fresh(self) -> Optional[OPT.OptimizerResult]:
        """THE freshness rule (shared by the request path, the precompute
        loop, and state reporting): same model generation and younger than
        proposal.expiration.ms."""
        with self._cache_lock:
            c = self._proposal_cache
            if c is None:
                return None
            gen = self.load_monitor.model_generation()
            age = self._now_s() * 1000 - c.computed_at_ms
            if (not c.generation.is_stale(gen)
                    and age < self.config.get("proposal.expiration.ms")):
                return c.result
            return None

    def _cache_is_fresh(self) -> bool:
        return self._cached_result_if_fresh() is not None

    def precompute_tick(self) -> bool:
        """One precompute check: recompute the default-goal proposals when
        the cache is missing/stale/expired. Returns True if it computed.

        Tries the incremental path first: a tick whose load deltas flip no
        goal verdict re-arms the cached proposal without annealing."""
        self.watchdog.beat("proposal-precompute")
        started_ms = self._now_ms_fn()
        if self._cache_is_fresh():
            self._observe_health("fresh", started_ms)
            return False
        if not self._compute_gate.acquire(blocking=False):
            # a request thread is already computing
            self._observe_health("busy", started_ms)
            return False
        t0 = time.monotonic()
        outcome, computed = "failed", False
        # the precompute span is also the tick's AMBIENT parent: spans
        # opened on background threads meanwhile (escape-kernel warm,
        # executor progress) join this tick's tree
        with self.tracer.span("precompute-tick") as _sp:
            self.tracer.set_ambient(_sp)
            try:
                if self._cache_is_fresh():
                    outcome = "fresh"
                elif self._try_incremental_refresh():
                    self._precompute_failures = 0
                    with self._cache_lock:
                        self.last_tick_ms = (time.monotonic() - t0) * 1000.0
                    outcome, computed = "incremental", True
                else:
                    self._compute_and_cache()
                    self._precompute_failures = 0
                    with self._cache_lock:
                        self.last_tick_ms = (time.monotonic() - t0) * 1000.0
                    outcome, computed = "computed", True
            except NotEnoughValidWindowsError:
                outcome = "not-ready"  # monitor not ready: expected at startup
            except Exception:
                # a permanently-broken precompute loop must stay visible
                # without flooding the log: warn on the first few consecutive
                # failures, then only every 10th, and count every one in the
                # registry
                self._precompute_failures += 1
                REGISTRY.counter("proposal.precompute.failures")
                n = self._precompute_failures
                if n <= 3 or n % 10 == 0:
                    logger.warning(
                        "proposal precompute failed (%d consecutive)",
                        n, exc_info=True)
                outcome = "failed"
            finally:
                _sp.set("outcome", outcome)
                self.tracer.clear_ambient()
                self._compute_gate.release()
        # graftwatch sees EVERY tick outcome (including the early returns
        # above): the burn-rate windows are per-tick fractions, so a
        # skipped observation would silently dilute them
        self._observe_health(outcome, started_ms)
        return computed

    def _observe_health(self, outcome: str, started_ms: float) -> None:
        """Fold one precompute outcome into graftwatch: the bounded-
        cadence device-memory sample plus one health vector into the
        burn-rate ring. Pure observation on the injected clock — no-op
        unless obs.costmodel.enable / healthwatch.enable are set."""
        if self.costmodel.enabled:
            self.costmodel.maybe_sample(self._now_ms_fn())
        hw = self.healthwatch
        if hw is None:
            return
        wall_ms = max(self._now_ms_fn() - started_ms, 0.0)
        with self._cache_lock:
            hits, misses = self.proposal_cache_hits, self.proposal_cache_misses
            heal_ms = self.last_self_heal_ms or 0.0
            fallback = self._last_fallback
            cache = self._proposal_cache
        # fallback is a per-tick edge, not a level: flag only the tick on
        # which a NEW fallback record appeared
        fallback_tick = 0.0
        if fallback is not self._health_prev_fallback:
            self._health_prev_fallback = fallback
            if fallback is not None:
                fallback_tick = 1.0
        engine = ""
        hard = soft = 0.0
        if cache is not None:
            from cruise_control_tpu.analyzer import goals as G
            engine = cache.result.engine
            for name in cache.result.violated_goals_after:
                if G.is_hard(name):
                    hard += 1.0
                else:
                    soft += 1.0
        lag = 0.0
        rep = self.replication_state()
        records = rep.get("followerLagRecords")
        if records:
            try:
                vals = (records.values()
                        if hasattr(records, "values") else records)
                lag = float(max(float(v) for v in vals))
            except (TypeError, ValueError):
                lag = 0.0
        total = hits + misses
        hw.observe({
            "ok": (1.0 if outcome in ("fresh", "computed",
                                      "incremental", "busy") else 0.0),
            "latencyMs": wall_ms,
            "notReady": 1.0 if outcome == "not-ready" else 0.0,
            "failed": 1.0 if outcome == "failed" else 0.0,
            "fallback": fallback_tick,
            "engineAnneal": 1.0 if engine == "anneal" else 0.0,
            "healWallMs": heal_ms,
            "cacheHitRatio": (hits / total) if total else 1.0,
            "watchdogRestarts": float(self.watchdog.total_restarts),
            "replicationLag": lag,
            "hardViolations": hard,
            "softViolations": soft,
        })

    def _precompute_loop(self):
        # re-check at a fraction of the expiration so a generation change is
        # picked up promptly; the computation itself rate-limits the loop
        interval_s = max(
            1.0, min(self.config.get("proposal.expiration.ms") / 4000.0, 30.0))
        self.precompute_tick()      # warm immediately, don't wait one interval
        while not self._precompute_shutdown.wait(interval_s):
            self.precompute_tick()

    def _try_incremental_refresh(self) -> bool:
        """Incremental tick (callers hold ``_compute_gate``): when the model
        build spliced only a small fraction of partitions and the rescore of
        the new loads flips no goal verdict, the cached proposal is still
        the answer the anneal would re-derive — re-stamp it at the current
        generation and skip the anneal entirely. Any doubt (digest drift,
        capacity drift, dirty mass over threshold, a verdict flip, the
        rescore erroring) falls through to the full computation."""
        threshold = self.config.get("proposal.cache.dirty.mass.threshold")
        if threshold <= 0:
            return False
        with self._cache_lock:
            c = self._proposal_cache
            rs = self._rescore_state
        if c is None or rs is None or rs.digest is None:
            return False
        # expiration still applies: an expired cache must be recomputed
        age = self._now_s() * 1000 - c.computed_at_ms
        if age >= self.config.get("proposal.expiration.ms"):
            return False
        # generation BEFORE the model build, same staleness discipline as
        # _compute_and_cache
        gen_now = self.load_monitor.model_generation()
        try:
            topo, assign = self._model()
        except NotEnoughValidWindowsError:
            return False
        info = self.load_monitor.last_build_info()
        if (not info or info.get("kind") not in ("splice", "refresh")
                or info.get("digest") != rs.digest
                or info.get("dirtyPartitionIndex") is None):
            return False         # structural change (or cold build): anneal
        monitored = info.get("monitoredPartitions") or 0
        dirty = info.get("dirtyPartitions") or 0
        if monitored <= 0 or dirty / monitored > threshold:
            return False
        try:
            from cruise_control_tpu.analyzer import rescore as RS
            with self.tracer.span("dirty-diff", dirtyPartitions=int(dirty)):
                out = RS.rescore_deltas(rs, topo, info["dirtyPartitionIndex"])
        except Exception:
            logger.warning("incremental rescore failed; falling back to "
                           "full computation", exc_info=True)
            return False
        if out is None or out.any_flip:
            return False
        with self._cache_lock:
            self._proposal_cache = CachedProposals(
                c.result, gen_now, int(self._now_s() * 1000))
            rs.dt = out.dt       # next tick splices against these arrays
            self.incremental_refreshes += 1
            self.anneal_skips += 1
        REGISTRY.counter("proposal.incremental.refresh")
        if self.flightrec.enabled:
            from cruise_control_tpu.obs.flightrec import assignment_digest
            payload = {
                "outcome": "incremental",
                "inputsDigest": rs.digest,
                "buildTickId": info.get("tickId"),
                "buildKind": info.get("kind"),
                "dirtyPartitions": int(dirty),
                "monitoredPartitions": int(monitored),
                "engine": "cached",
                "decodePath": c.result.decode_path,
                "healPath": c.result.heal_path,
                "fallbackReason": None,
                "violatedGoalsBefore": c.result.violated_goals_before,
                "violatedGoalsAfter": c.result.violated_goals_after,
                "numReplicaMovements": c.result.num_replica_movements,
                "numLeadershipMovements": c.result.num_leadership_movements,
            }
            if c.result.final_assignment is not None:
                payload["proposalDigest"] = assignment_digest(
                    np.asarray(c.result.final_assignment.broker_of),
                    np.asarray(c.result.final_assignment.leader_of))
            self.flightrec.record("tick", payload)
        logger.debug("incremental refresh: %d dirty partitions, no verdict "
                     "flip — anneal skipped", out.dirty_partitions)
        return True

    # ------------------------------------------------------------- optimize

    def _anneal_config(self) -> AnnealConfig:
        return AnnealConfig(
            num_chains=self.config.get("anneal.num.chains"),
            steps=self.config.get("anneal.steps"),
            tries_move=self.config.get("anneal.tries.move"),
            tries_lead=self.config.get("anneal.tries.lead"),
            tries_swap=self.config.get("anneal.tries.swap"))

    def _bucketing(self) -> Optional[bool]:
        """optimizer.bucketing config -> optimize()'s tri-state flag
        (None = the engages_bucketing auto policy)."""
        mode = str(self.config.get("optimizer.bucketing") or "auto").lower()
        return None if mode == "auto" else mode in ("on", "true", "1")

    def _warm_start_for(self, topo: ClusterTopology):
        """WarmStart for the default-goal computation, or None.

        Engages only when (a) anneal.warm.fraction > 0, (b) a previous
        accepted assignment was recorded, (c) the monitor's STRUCTURAL
        digest is unchanged since then (the legality gate: same partitions,
        replica sets, racks — only loads moved), and (d) the shapes still
        match the freshly-built model (belt-and-braces; the optimizer
        re-checks). Splice/refresh builds also carry the dirty partition
        index, so warm chains keep the dirty partitions' CURRENT rows and
        only the untouched remainder starts from the carried optimum."""
        frac = float(self.config.get("anneal.warm.fraction") or 0.0)
        if frac <= 0:
            return None
        with self._cache_lock:
            prev = self._warm_proposal
        info = self.load_monitor.last_build_info()
        if (prev is None or not info or not info.get("digest")
                or info["digest"] != prev["digest"]
                or prev["broker_of"].shape[0] != topo.num_replicas
                or prev["leader_of"].shape[0] != topo.num_partitions):
            return None
        dirty = (info.get("dirtyPartitionIndex")
                 if info.get("kind") in ("splice", "refresh") else None)
        from cruise_control_tpu.analyzer.annealer import WarmStart
        return WarmStart(broker_of=prev["broker_of"],
                         leader_of=prev["leader_of"],
                         dirty_partitions=dirty, fraction=frac)

    def _optimize(self, topo: ClusterTopology, assign: Assignment,
                  goal_names: Optional[Sequence[str]] = None,
                  options: Optional[G.DeviceOptions] = None,
                  warm_start=None) -> OPT.OptimizerResult:
        res = OPT.optimize(
            topo, assign,
            goal_names=tuple(goal_names or self.default_goals),
            constraint=self.constraint,
            options=options,
            engine=self.config.get("optimizer.engine"),
            anneal_config=self._anneal_config(),
            balancedness_weights=self._balancedness_weights,
            mesh=self.mesh,
            bucketing=self._bucketing(),
            warm_start=warm_start,
            anneal_telemetry=bool(
                self.config.get("anneal.telemetry.enable")),
            tracer=self.tracer,
            provenance=bool(self.config.get("obs.provenance.enable")))
        if res.fallback_reason:
            # degraded mode: remember the most recent fallback for /state
            # (read by the REST thread, so it shares the cache lock)
            with self._cache_lock:
                self._last_fallback = {
                    "engine": res.engine,
                    "reason": res.fallback_reason,
                    "atMs": int(self._now_s() * 1000)}
        if res.heal_path is not None:
            # self-heal timing: every healing entry point (add/remove
            # brokers, fix_offline_replicas, destination-constrained
            # rebalance) funnels through here — record the wall and route
            # for /state (read by the REST thread: cache lock)
            with self._cache_lock:
                self.last_self_heal_ms = res.wall_time_s * 1000.0
                self.self_heal_path = res.heal_path
        self._flight_record_tick(res)
        return res

    def _flight_record_tick(self, res: OPT.OptimizerResult,
                            outcome: str = "computed") -> None:
        """One flight-recorder record per proposal computation: what the
        tick decided and why. Every value is a deterministic function of the
        inputs (no wall-clock durations) — the byte-identical-log contract
        of obs/flightrec.py."""
        if not self.flightrec.enabled:
            return
        from cruise_control_tpu.obs.flightrec import assignment_digest
        info = self.load_monitor.last_build_info() or {}
        payload = {
            "outcome": outcome,
            # structural digest when the build is warm-cacheable
            # (splice/refresh at scale); small models never carry one —
            # the tick id still pins which aggregation the model came from
            "inputsDigest": info.get("digest"),
            "buildTickId": info.get("tickId"),
            "buildKind": info.get("kind"),
            "dirtyPartitions": info.get("dirtyPartitions"),
            "monitoredPartitions": info.get("monitoredPartitions"),
            "engine": res.engine,
            "decodePath": res.decode_path,
            "healPath": res.heal_path,
            "fallbackReason": res.fallback_reason,
            "violatedGoalsBefore": res.violated_goals_before,
            "violatedGoalsAfter": res.violated_goals_after,
            "numReplicaMovements": res.num_replica_movements,
            "numLeadershipMovements": res.num_leadership_movements,
        }
        if res.final_assignment is not None:
            payload["proposalDigest"] = assignment_digest(
                np.asarray(res.final_assignment.broker_of),
                np.asarray(res.final_assignment.leader_of))
        if res.move_attribution is not None:
            payload["numAttributedMoves"] = res.move_attribution["numMoves"]
            payload["topMoves"] = (
                res.move_attribution["moves"][:self.flightrec.top_moves])
        self.flightrec.record("tick", payload)

    def _model(self, requirements=None, data_from: Optional[str] = None,
               now_ms: Optional[int] = None,
               min_valid_partition_ratio: Optional[float] = None
               ) -> Tuple[ClusterTopology, Assignment]:
        """``data_from`` (ParameterUtils.DataFrom,
        GoalBasedOptimizationParameters.java:37-46): VALID_WINDOWS demands
        fully-monitored windows (partition ratio 1.0, ≥1 window);
        VALID_PARTITIONS uses every valid partition over all available
        windows (ratio 0.0)."""
        if requirements is None:
            if data_from and data_from.upper() == "VALID_WINDOWS":
                requirements = ModelCompletenessRequirements(
                    min_required_num_windows=1,
                    min_monitored_partitions_percentage=1.0,
                    include_all_topics=True)
            elif data_from and data_from.upper() == "VALID_PARTITIONS":
                requirements = ModelCompletenessRequirements(
                    min_required_num_windows=1,
                    min_monitored_partitions_percentage=0.0,
                    include_all_topics=True)
            else:
                requirements = self._default_requirements
        if min_valid_partition_ratio is not None:
            # ParameterUtils.MIN_VALID_PARTITION_RATIO_PARAM: per-request
            # override of min.valid.partition.ratio on the model gate
            import dataclasses as _dc
            requirements = _dc.replace(
                requirements,
                min_monitored_partitions_percentage=min_valid_partition_ratio)
        return self.load_monitor.cluster_model(now_ms=now_ms,
                                               requirements=requirements)

    def _ready_goals(self) -> Tuple[str, ...]:
        """GoalOptimizer ready goals (KafkaCruiseControl.java:714-717): a
        default goal is ready iff the monitored load meets THAT goal's own
        ModelCompletenessRequirements (Goal.java:126-148) — snapshot goals
        become ready after one window at any coverage, distribution goals
        only once half the window history is valid at the configured
        monitored-partition ratio."""
        agg = self.load_monitor.partition_aggregator
        # readiness only changes when samples/windows change: cache by
        # (aggregator generation, current window) so a polled STATE endpoint
        # does not re-aggregate the full [E, W, M] history per request
        key = (agg.generation, agg.samples_ingested,
               self.load_monitor._now() // agg.window_ms)
        cached = self._ready_goals_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        num_windows = agg.num_windows
        min_ratio = self.config.get("min.valid.partition.ratio")
        reqs = {g: G.completeness_requirements(g, num_windows, min_ratio)
                for g in self.default_goals}
        # only ~3 distinct requirement tuples exist across the goal set;
        # evaluate each ONCE (each check is a full window aggregation)
        met = {r: self.load_monitor.meet_completeness_requirements(r)
               for r in set(reqs.values())}
        ready = tuple(g for g in self.default_goals if met[reqs[g]])
        self._ready_goals_cache = (key, ready)
        return ready

    def _sanity_check_goals(self, goal_names: Optional[Sequence[str]],
                            skip_hard_goal_check: bool) -> None:
        """RunnableUtils.sanityCheckGoals: a request naming a custom goal
        list must include EVERY configured hard goal (not just those also in
        default.goals — KafkaCruiseControlUtils.java:179-190) unless
        skip_hard_goal_check=true. A lone PreferredLeaderElectionGoal list is
        exempt, matching the reference's special case."""
        if not goal_names or skip_hard_goal_check:
            return
        if list(goal_names) == ["PreferredLeaderElectionGoal"]:
            return
        hard = list(self.config.get("hard.goals"))
        missing = [g for g in hard if g not in goal_names]
        if missing:
            raise ValueError(
                f"Missing hard goals {missing} in the provided goal list "
                f"{list(goal_names)}. Add skip_hard_goal_check=true to "
                "skip the check or include the hard goals.")

    def _check_capacity_estimation(self, allow: bool) -> None:
        """allow_capacity_estimation=false refuses to optimize on brokers
        whose capacity fell back to the default (-1) entry. The service-wide
        ``sampling.allow.cpu.capacity.estimation`` switch (SamplingUtils'
        estimation gate) disallows estimated capacities regardless of the
        per-request parameter."""
        est = self.load_monitor.capacity_estimated_brokers
        if not self.config.get("sampling.allow.cpu.capacity.estimation"):
            allow = False
        if not allow and est:
            raise ValueError(
                f"Broker capacities were estimated for {sorted(est)} and "
                "capacity estimation is not allowed.")

    def _build_options(self, topo: ClusterTopology,
                       excluded_topics: Sequence[str] = (),
                       **kw) -> G.DeviceOptions:
        """build_options + the standing topics.excluded.from.partition.movement
        regex (every optimization, every entry point); the pattern is fixed
        at config time, so it is compiled once in __init__."""
        if self._excluded_topics_rx is not None:
            rx = self._excluded_topics_rx
            standing = [t for t in topo.topic_names if rx.fullmatch(t)]
            excluded_topics = tuple(excluded_topics) + tuple(
                t for t in standing if t not in set(excluded_topics))
        return G.build_options(topo, excluded_topics=excluded_topics, **kw)

    def _exclusions(self, exclude_recently_removed: bool,
                    exclude_recently_demoted: bool) -> Dict[str, Sequence[int]]:
        """Excluded-broker sets from the executor's recent history
        (exclude_recently_removed/demoted_brokers parameters). Keys appear
        only when the set is non-empty so standing flags from client tooling
        don't needlessly bypass the proposal cache."""
        out: Dict[str, Sequence[int]] = {}
        if exclude_recently_removed and self.executor.recently_removed_brokers:
            out["excluded_brokers_for_replica_move"] = sorted(
                self.executor.recently_removed_brokers)
        if exclude_recently_demoted and self.executor.recently_demoted_brokers:
            out["excluded_brokers_for_leadership"] = sorted(
                self.executor.recently_demoted_brokers)
        return out

    def proposals(self, goal_names: Optional[Sequence[str]] = None,
                  ignore_proposal_cache: bool = False,
                  data_from: Optional[str] = None,
                  min_valid_partition_ratio: Optional[float] = None,
                  use_ready_default_goals: bool = False,
                  exclude_recently_removed_brokers: bool = False,
                  exclude_recently_demoted_brokers: bool = False,
                  skip_hard_goal_check: bool = False,
                  allow_capacity_estimation: bool = True,
                  **option_kw) -> OPT.OptimizerResult:
        """ProposalsRunnable.getProposals: cached unless stale/bypassed."""
        if goal_names is None and use_ready_default_goals:
            goal_names = self._ready_goals()
        self._sanity_check_goals(goal_names, skip_hard_goal_check)
        option_kw.update(self._exclusions(exclude_recently_removed_brokers,
                                          exclude_recently_demoted_brokers))
        use_cache = (not ignore_proposal_cache and not goal_names
                     and not option_kw and not data_from
                     and min_valid_partition_ratio is None)
        if use_cache:
            cached = self._cached_result_if_fresh()
            if cached is not None:
                # the cached result was computed on the same model build
                # the estimation gate refers to — enforce it on hits too
                self._check_capacity_estimation(allow_capacity_estimation)
                with self._cache_lock:
                    self.proposal_cache_hits += 1
                return cached
            # one default-goal computation at a time: concurrent requests
            # (and the precompute tick) wait, then re-check the cache the
            # winner just filled (GoalOptimizer._cacheLock semantics)
            with self._compute_gate:
                cached = self._cached_result_if_fresh()
                if cached is None and self._try_incremental_refresh():
                    cached = self._cached_result_if_fresh()
                if cached is not None:
                    self._check_capacity_estimation(allow_capacity_estimation)
                    with self._cache_lock:
                        self.proposal_cache_hits += 1
                    return cached
                with self._cache_lock:
                    self.proposal_cache_misses += 1
                return self._compute_and_cache(allow_capacity_estimation)
        topo, assign = self._model(data_from=data_from,
                                   min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        options = (self._build_options(topo, **option_kw)
                   if option_kw or self.config.get(
                       "topics.excluded.from.partition.movement")
                   else None)
        return self._optimize(topo, assign, goal_names, options)

    def _compute_and_cache(self, allow_capacity_estimation: bool = True
                           ) -> OPT.OptimizerResult:
        """The default-goal cacheable computation (callers hold
        ``_compute_gate``)."""
        # capture the generation BEFORE building the model: a metadata/sample
        # change during the (long) optimization must leave the cache stale,
        # not be masked by a post-compute generation read
        gen0 = self.load_monitor.model_generation()
        topo, assign = self._model()
        self._check_capacity_estimation(allow_capacity_estimation)
        options = (self._build_options(topo)
                   if self.config.get(
                       "topics.excluded.from.partition.movement")
                   else None)
        result = self._optimize(topo, assign, None, options,
                                warm_start=self._warm_start_for(topo))
        if result.final_assignment is not None:
            # record the accepted assignment for the NEXT tick's warm start
            # (host copies: the next computation may run after these device
            # buffers are donated). Keyed to the STRUCTURAL digest — stable
            # across splice/refresh, changed by any topology change — so a
            # stale carry can never seed chains on a different cluster.
            info0 = self.load_monitor.last_build_info()
            if info0 and info0.get("digest"):
                with self._cache_lock:
                    self._warm_proposal = {
                        "broker_of": np.asarray(
                            result.final_assignment.broker_of, np.int32),
                        "leader_of": np.asarray(
                            result.final_assignment.leader_of, np.int32),
                        "digest": info0["digest"]}
        # goal-verdict baseline for the incremental tick path: scored on the
        # same model the proposal was computed from; only digest-carrying
        # (warm-cacheable) builds can ever splice, so skip the rest
        rs = None
        try:
            info = self.load_monitor.last_build_info()
            if info and info.get("digest") and self.config.get(
                    "proposal.cache.dirty.mass.threshold") > 0:
                from cruise_control_tpu.analyzer import rescore as RS
                rs = RS.build_baseline(topo, assign,
                                       tuple(self.default_goals),
                                       self.constraint,
                                       digest=info["digest"])
        except Exception:
            logger.warning("rescore baseline build failed; incremental "
                           "refresh disabled until next computation",
                           exc_info=True)
        with self._cache_lock:
            self._proposal_cache = CachedProposals(
                result, gen0, int(self._now_s() * 1000))
            self._rescore_state = rs
        import jax
        if (not self._escape_kernels_warmed
                and not OPT._routes_to_tiny_cpu(topo, self.mesh, options)
                and (jax.default_backend() != "cpu"
                     or topo.num_replicas * topo.num_brokers
                     > OPT.TINY_CPU_LIMIT)):
            # after the FIRST default-goal computation on a real-size
            # model: load the rarely-engaged escape kernels (topic-band
            # swap, fused lead descent) at this model's shapes so the
            # first request that needs one runs steady-state instead of
            # paying a multi-second compile/cache-load mid-request
            # (optimizer.warm_kernels). On a BACKGROUND thread: callers
            # hold _compute_gate here, and the cache is already filled —
            # a synchronous warm would stall every queued default-goal
            # request behind a multi-second load for an already-cached
            # answer. Models that optimize() routes to the host CPU
            # backend skip (shared _routes_to_tiny_cpu predicate): their
            # compiles are local/cheap and lazily-paid anyway, and the
            # warm must target the same backend the run uses. On a
            # CPU-only host the predicate is False for every model, so
            # the size guard additionally keeps toy models (tests) from
            # spawning background XLA CPU compiles.
            self._escape_kernels_warmed = True

            # polish-shape anneal warm only when this model will actually
            # run the ANNEAL engine (greedy-routed models never dispatch
            # polish — warming its program would spend device time and
            # cache space on a program that can never be used)
            eng = self.config.get("optimizer.engine")
            routes_anneal = OPT.routes_to_anneal(topo, eng)

            def _warm():
                try:
                    with self.tracer.span("escape-kernel-warm"):
                        OPT.warm_kernels(topo, assign,
                                         goal_names=tuple(self.default_goals),
                                         constraint=self.constraint,
                                         options=options,
                                         anneal_config=(self._anneal_config()
                                                        if routes_anneal
                                                        else None),
                                         mesh=self.mesh,
                                         bucketing=self._bucketing())
                except Exception:
                    logger.warning("escape-kernel warm failed",
                                   exc_info=True)
                finally:
                    # warming compiles on purpose: only after it completes
                    # do further traces count as steady-state retraces
                    self._mark_observatory_steady()

            threading.Thread(target=_warm, daemon=True,
                             name="escape-kernel-warm").start()
        else:
            self._mark_observatory_steady()
        return result

    def _mark_observatory_steady(self):
        """First successful default-goal computation (plus any escape-kernel
        warm it spawned) ⇒ the service is steady: jit traces from here on
        are retraces the observatory flags and /metrics counts."""
        if self.config.get("obs.observatory.enable"):
            from cruise_control_tpu.obs.observatory import OBSERVATORY
            OBSERVATORY.mark_steady()

    # ----------------------------------------------- operations (runnables)

    def rebalance(self, goal_names: Optional[Sequence[str]] = None,
                  dryrun: bool = True, self_healing: bool = False,
                  excluded_topics: Sequence[str] = (),
                  destination_broker_ids: Sequence[int] = (),
                  concurrency: Optional[int] = None,
                  data_from: Optional[str] = None,
                  min_valid_partition_ratio: Optional[float] = None,
                  use_ready_default_goals: bool = False,
                  exclude_recently_removed_brokers: bool = False,
                  exclude_recently_demoted_brokers: bool = False,
                  verbose: bool = False,
                  skip_hard_goal_check: bool = False,
                  allow_capacity_estimation: bool = True,
                  executor_kw: Optional[dict] = None,
                  **_ignored) -> dict:
        """RebalanceRunnable.rebalance (RebalanceRunnable.java:130-144)."""
        if self_healing:
            dryrun = False
            exclude_recently_removed_brokers = (
                exclude_recently_removed_brokers or self.config.get(
                    "self.healing.exclude.recently.removed.brokers"))
            exclude_recently_demoted_brokers = (
                exclude_recently_demoted_brokers or self.config.get(
                    "self.healing.exclude.recently.demoted.brokers"))
        goals = goal_names or (
            tuple(self.config.get("self.healing.goals")) or None
            if self_healing else None)
        if goals is None and use_ready_default_goals:
            goals = self._ready_goals()
        self._sanity_check_goals(goals, skip_hard_goal_check or self_healing)
        topo, assign = self._model(data_from=data_from,
                                   min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        options = self._build_options(
            topo, excluded_topics=excluded_topics,
            requested_destination_broker_ids=destination_broker_ids,
            **self._exclusions(exclude_recently_removed_brokers,
                               exclude_recently_demoted_brokers))
        result = self._optimize(topo, assign, goals, options)
        summary = result.to_json(verbose=verbose)
        if not dryrun:
            exec_summary = self.executor.execute_proposals(
                result.proposals, concurrency=concurrency,
                **(executor_kw or {}))
            summary["execution"] = exec_summary
        return summary

    def _record_provision_recommendation(self, rec) -> None:
        """Latest rightsizing verdict, surfaced in /state (called by the
        goal-violation detector and the RIGHTSIZE runnable)."""
        with self._cache_lock:
            self._last_provision_recommendation = rec.to_dict()

    def record_simulation_scorecard(self, scorecard: dict) -> None:
        """Latest scenario-simulator scorecard, surfaced in /state as
        SimulatorState (called by simulator.run_scenario)."""
        with self._cache_lock:
            self._last_simulation = dict(scorecard)

    def attach_replication(self, controller) -> None:
        """Attach this app's replication role (a ``ReplicationController``
        for the leader, a ``WarmStandby`` for the follower); its
        ``state_snapshot()`` backs ``/state``'s ReplicationState."""
        self.replication = controller

    def replication_state(self) -> dict:
        """ReplicationState for /state: role, lease expiry, follower lag.

        Unreplicated deployments report role "standalone" (with the
        journal epoch when journaling is on) so the field set is stable
        across topologies."""
        if self.replication is not None:
            return self.replication.state_snapshot()
        return {
            "role": "standalone",
            "holder": None,
            "epoch": self.journal.epoch if self.journal is not None else 0,
            "leaseExpiryMs": None,
            "followerLagRecords": None,
        }

    def what_if(self, add_broker_counts: Sequence[int] = (),
                add_broker_rack: Optional[str] = None,
                remove_broker_ids: Sequence[int] = (),
                fail_racks: Sequence[str] = (),
                scale_capacity: Sequence[str] = (),
                add_partitions: Sequence[str] = (),
                deep: bool = False,
                headroom_margin: Optional[float] = None,
                allow_capacity_estimation: bool = True,
                data_from: Optional[str] = None,
                min_valid_partition_ratio: Optional[float] = None,
                **_ignored) -> dict:
        """WHAT_IF: score counterfactual scenarios against the hard goals
        in one compiled batch (always includes the as-is baseline).

        ``scale_capacity`` entries are ``resource:factor`` (e.g.
        ``disk:0.5``); ``add_partitions`` entries are ``topic:count``."""
        from cruise_control_tpu import provisioner as PROV
        scenarios = [PROV.Scenario("baseline", ())]
        for n in add_broker_counts:
            scenarios.append(PROV.Scenario(
                f"add-{int(n)}",
                (PROV.add_brokers(int(n), rack=add_broker_rack),)))
        if remove_broker_ids:
            ids = tuple(int(b) for b in remove_broker_ids)
            scenarios.append(PROV.Scenario(
                "remove-" + ",".join(str(b) for b in ids),
                (PROV.remove_brokers(ids),)))
        for rack in fail_racks:
            scenarios.append(PROV.Scenario(
                f"fail-rack-{rack}", (PROV.fail_rack(rack),)))
        for spec in scale_capacity:
            res_name, _, factor = str(spec).partition(":")
            scenarios.append(PROV.Scenario(
                f"scale-{res_name}-{factor}",
                (PROV.scale_capacity(res_name, float(factor)),)))
        for spec in add_partitions:
            topic, _, count = str(spec).partition(":")
            scenarios.append(PROV.Scenario(
                f"add-partitions-{topic}-{count}",
                (PROV.add_partitions(topic, int(count)),)))
        topo, assign = self._model(
            data_from=data_from,
            min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        return self.provisioner.what_if(
            topo, assign, scenarios, deep=deep,
            headroom=headroom_margin).to_dict()

    def rightsize(self, headroom_margin: Optional[float] = None,
                  max_added_brokers: Optional[int] = None,
                  max_removed_brokers: Optional[int] = None,
                  deep: bool = False,
                  verbose: bool = False,
                  allow_capacity_estimation: bool = True,
                  data_from: Optional[str] = None,
                  min_valid_partition_ratio: Optional[float] = None,
                  **_ignored) -> dict:
        """RIGHTSIZE: classify the cluster UNDER/OVER/RIGHT_SIZED and
        record the verdict (RightsizeRunnable surface)."""
        topo, assign = self._model(
            data_from=data_from,
            min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        rec, grid = self.provisioner.recommend(
            topo, assign, headroom_margin=headroom_margin,
            max_added_brokers=max_added_brokers,
            max_removed_brokers=max_removed_brokers, deep=deep)
        self._record_provision_recommendation(rec)
        out = rec.to_dict()
        if verbose:
            out["whatIf"] = grid.to_dict()
        return out

    def add_brokers(self, broker_ids: Sequence[int], dryrun: bool = True,
                    data_from: Optional[str] = None,
                    min_valid_partition_ratio: Optional[float] = None,
                    verbose: bool = False,
                    allow_capacity_estimation: bool = True,
                    use_ready_default_goals: bool = False,
                    exclude_recently_removed_brokers: bool = False,
                    exclude_recently_demoted_brokers: bool = False,
                    throttle_added_broker: Optional[int] = None,
                    executor_kw: Optional[dict] = None,
                    **kw) -> dict:
        """AddBrokersRunnable: move load onto the new brokers."""
        topo, assign = self._model(data_from=data_from,
                                   min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        ids = set(int(b) for b in broker_ids)
        new_mask = np.array([int(b) in ids for b in topo.broker_ids])
        topo = dataclasses.replace(topo, broker_new=new_mask)
        options = self._build_options(
            topo, requested_destination_broker_ids=broker_ids,
            **self._exclusions(exclude_recently_removed_brokers,
                               exclude_recently_demoted_brokers))
        goals = self._ready_goals() if use_ready_default_goals else None
        result = self._optimize(topo, assign, goals, options)
        summary = result.to_json(verbose=verbose)
        if not dryrun:
            ek = dict(executor_kw or {})
            if throttle_added_broker is not None:
                ek["replication_throttle"] = throttle_added_broker
            summary["execution"] = self.executor.execute_proposals(
                result.proposals, **ek)
        return summary

    def remove_brokers(self, broker_ids: Sequence[int], dryrun: bool = True,
                       self_healing: bool = False,
                       data_from: Optional[str] = None,
                       min_valid_partition_ratio: Optional[float] = None,
                       verbose: bool = False,
                       allow_capacity_estimation: bool = True,
                       use_ready_default_goals: bool = False,
                       exclude_recently_removed_brokers: bool = False,
                       exclude_recently_demoted_brokers: bool = False,
                       throttle_removed_broker: Optional[int] = None,
                       executor_kw: Optional[dict] = None,
                       **kw) -> dict:
        """RemoveBrokersRunnable: drain the given brokers."""
        if self_healing:
            dryrun = False
            exclude_recently_removed_brokers = (
                exclude_recently_removed_brokers or self.config.get(
                    "self.healing.exclude.recently.removed.brokers"))
            exclude_recently_demoted_brokers = (
                exclude_recently_demoted_brokers or self.config.get(
                    "self.healing.exclude.recently.demoted.brokers"))
        topo, assign = self._model(data_from=data_from,
                                   min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        ids = set(int(b) for b in broker_ids)
        # removed brokers: not a legal destination; their replicas must leave
        idx = {int(b): i for i, b in enumerate(topo.broker_ids)}
        offline = topo.replica_offline.copy()
        dead_rows = [idx[b] for b in ids if b in idx]
        alive = topo.broker_alive.copy()
        for r_i in dead_rows:
            alive[r_i] = False
            offline |= (np.asarray(assign.broker_of) == r_i)
        topo = dataclasses.replace(topo, broker_alive=alive,
                                   replica_offline=offline)
        excl = self._exclusions(exclude_recently_removed_brokers,
                                exclude_recently_demoted_brokers)
        no_replicas = set(int(b) for b in broker_ids) | set(
            excl.get("excluded_brokers_for_replica_move", ()))
        no_leadership = set(int(b) for b in broker_ids) | set(
            excl.get("excluded_brokers_for_leadership", ()))
        options = self._build_options(
            topo, excluded_brokers_for_replica_move=sorted(no_replicas),
            excluded_brokers_for_leadership=sorted(no_leadership))
        goals = self._ready_goals() if use_ready_default_goals else None
        result = self._optimize(topo, assign, goals, options)
        summary = result.to_json(verbose=verbose)
        if not dryrun:
            ek = dict(executor_kw or {})
            if throttle_removed_broker is not None:
                ek["replication_throttle"] = throttle_removed_broker
            summary["execution"] = self.executor.execute_proposals(
                result.proposals, removed_brokers=ids, **ek)
        return summary

    def demote_brokers(self, broker_ids: Sequence[int], dryrun: bool = True,
                       self_healing: bool = False,
                       data_from: Optional[str] = None,
                       min_valid_partition_ratio: Optional[float] = None,
                       verbose: bool = False,
                       skip_urp_demotion: bool = False,
                       exclude_follower_demotion: bool = False,
                       allow_capacity_estimation: bool = True,
                       exclude_recently_demoted_brokers: bool = False,
                       broker_id_and_logdirs: Optional[
                           Dict[int, Sequence[str]]] = None,
                       executor_kw: Optional[dict] = None,
                       **kw) -> dict:
        """DemoteBrokerRunnable: move leadership off the given brokers
        and/or the given disks.

        ``skip_urp_demotion`` (DemoteBrokerParameters): leave partitions that
        are currently under-replicated (offline replicas) untouched.
        ``exclude_follower_demotion``: only leadership transfers, never
        follower reordering — this build's demotion is leadership-only, so
        the flag is accepted and already satisfied by construction.
        ``broker_id_and_logdirs``: demote DISKS — partitions whose leader
        replica resides on a named (broker, logdir) move leadership to the
        first eligible other replica (DemoteBrokerRunnable.java:150-158,
        disk DEMOTED state + PreferredLeaderElectionGoal)."""
        if self_healing:
            dryrun = False
        if broker_id_and_logdirs and (
                set(int(b) for b in broker_ids)
                & set(int(b) for b in broker_id_and_logdirs)):
            raise ValueError("Attempt to demote the broker and its disk in "
                             "the same request is not allowed.")
        if broker_id_and_logdirs:
            # disk demotion (optionally combined with broker demotion): the
            # deterministic PreferredLeaderElection walk covers both — any
            # partition led from a demoted disk OR broker elects its first
            # eligible replica (DemoteBrokerRunnable.java:150-158)
            return self._demote_disks(
                broker_id_and_logdirs,
                demoted_broker_ids=set(int(b) for b in broker_ids),
                dryrun=dryrun, verbose=verbose,
                data_from=data_from,
                min_valid_partition_ratio=min_valid_partition_ratio,
                skip_urp_demotion=skip_urp_demotion,
                allow_capacity_estimation=allow_capacity_estimation,
                exclude_recently_demoted_brokers=(
                    exclude_recently_demoted_brokers),
                executor_kw=executor_kw)
        topo, assign = self._model(data_from=data_from,
                                   min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        ids = set(int(b) for b in broker_ids)
        idx = {int(b): i for i, b in enumerate(topo.broker_ids)}
        demoted = topo.broker_demoted.copy()
        for b in ids:
            if b in idx:
                demoted[idx[b]] = True
        topo = dataclasses.replace(topo, broker_demoted=demoted)
        # demotion only moves LEADERSHIP (DemoteBrokerRunnable semantics):
        # immigrant-only mode pins every replica in place (only offline
        # replicas may still relocate, preserving self-healing)
        no_leadership = set(int(b) for b in broker_ids)
        if exclude_recently_demoted_brokers:
            no_leadership |= self.executor.recently_demoted_brokers
        options = self._build_options(
            topo, excluded_brokers_for_leadership=sorted(no_leadership),
            only_move_immigrant_replicas=True)
        result = self._optimize(
            topo, assign, ("LeaderReplicaDistributionGoal",
                           "LeaderBytesInDistributionGoal",
                           "PreferredLeaderElectionGoal"), options)
        if skip_urp_demotion:
            # partitions with an offline replica (URP) keep their leadership
            urp = {f"{p.topic}-{p.partition}"
                   for p in self._metadata_source.get_metadata().partitions
                   if p.offline_replicas}
            kept = [pr for pr in result.proposals
                    if pr.topic_partition not in urp]
            result = dataclasses.replace(
                result, proposals=kept,
                num_replica_movements=sum(len(pr.replicas_to_add)
                                          for pr in kept),
                num_leadership_movements=sum(1 for pr in kept
                                             if pr.has_leader_action))
        summary = result.to_json(verbose=verbose)
        if not dryrun:
            summary["execution"] = self.executor.execute_proposals(
                result.proposals, demoted_brokers=ids,
                **(executor_kw or {}))
        return summary

    def _demote_disks(self, broker_id_and_logdirs: Dict[int, Sequence[str]],
                      dryrun: bool, verbose: bool,
                      data_from: Optional[str],
                      skip_urp_demotion: bool,
                      exclude_recently_demoted_brokers: bool,
                      executor_kw: Optional[dict],
                      demoted_broker_ids: Optional[set] = None,
                      allow_capacity_estimation: bool = True,
                      min_valid_partition_ratio: Optional[float] = None
                      ) -> dict:
        """Disk demotion: deterministic leadership election off the demoted
        disks (the leadership-only core of PreferredLeaderElectionGoal with
        the named disks in DEMOTED state). ``demoted_broker_ids`` extends
        the walk to whole brokers for combined broker+disk requests."""
        from cruise_control_tpu.analyzer.proposals import ExecutionProposal
        from cruise_control_tpu.common import resources as res
        topo, assign = self._model(data_from=data_from,
                                   min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        if not topo.has_disks:
            raise ValueError("cluster model has no JBOD disk information")
        demoted_broker_ids = demoted_broker_ids or set()
        name_to_disk = {}
        for d in range(topo.num_disks):
            b_row = int(topo.broker_of_disk[d])
            name_to_disk[(int(topo.broker_ids[b_row]),
                          topo.disk_names[d])] = d
        demoted_disks = set()
        for b, logdirs in broker_id_and_logdirs.items():
            for ld in logdirs:
                d = name_to_disk.get((int(b), ld))
                if d is None:
                    raise ValueError(f"Broker {b} does not have logdir {ld}.")
                demoted_disks.add(d)
        no_leadership_brokers = (self.executor.recently_demoted_brokers
                                 if exclude_recently_demoted_brokers
                                 else set())
        urp = ({f"{p.topic}-{p.partition}"
                for p in self._metadata_source.get_metadata().partitions
                if p.offline_replicas} if skip_urp_demotion else set())

        bo = np.asarray(assign.broker_of)
        lo = np.asarray(assign.leader_of)
        dof = topo.disk_of_replica
        proposals = []
        skipped: List[str] = []
        for pi in range(topo.num_partitions):
            leader_r = int(lo[pi])
            leader_ext = int(topo.broker_ids[bo[leader_r]])
            if (int(dof[leader_r]) not in demoted_disks
                    and leader_ext not in demoted_broker_ids):
                continue
            topic = topo.topic_names[topo.topic_of_partition[pi]]
            part = int(topo.partition_index[pi])
            if f"{topic}-{part}" in urp:
                continue
            slots = topo.replicas_of_partition[pi]
            slots = slots[slots >= 0]
            # first eligible replica in preferred order: alive broker, disk
            # not demoted, broker not leadership-excluded
            new_leader_r = None
            for r in slots:
                r = int(r)
                if r == leader_r:
                    continue
                b_row = int(bo[r])
                b_ext = int(topo.broker_ids[b_row])
                if (topo.broker_alive[b_row]
                        and int(dof[r]) not in demoted_disks
                        and b_ext not in demoted_broker_ids
                        and b_ext not in no_leadership_brokers):
                    new_leader_r = r
                    break
            if new_leader_r is None:
                skipped.append(f"{topic}-{part}")
                continue            # no eligible replica: leadership stays
            ext = [int(topo.broker_ids[bo[int(r)]]) for r in slots]
            old_leader = int(topo.broker_ids[bo[leader_r]])
            new_first = int(topo.broker_ids[bo[new_leader_r]])
            new_order = ([new_first]
                         + [b for b in ext if b != new_first])
            proposals.append(ExecutionProposal(
                topic=topic, partition=part, old_leader=old_leader,
                old_replicas=tuple([old_leader]
                                   + [b for b in ext if b != old_leader]),
                new_replicas=tuple(new_order),
                data_size=float(topo.replica_base_load[leader_r, res.DISK])))
        summary = {
            "proposals": [p.to_json() for p in proposals],
            "numReplicaMovements": 0,
            "numLeadershipMovements": len(proposals),
            "demotedDisks": [f"{b}-{ld}"
                             for b, lds in broker_id_and_logdirs.items()
                             for ld in lds],
            "demotedBrokers": sorted(demoted_broker_ids),
        }
        if verbose:
            summary["partitionsWithoutEligibleLeader"] = skipped
        if not dryrun:
            summary["execution"] = self.executor.execute_proposals(
                proposals, demoted_brokers=demoted_broker_ids,
                **(executor_kw or {}))
        return summary

    def fix_offline_replicas(self, dryrun: bool = True,
                             self_healing: bool = False,
                             data_from: Optional[str] = None,
                             min_valid_partition_ratio: Optional[float] = None,
                             verbose: bool = False,
                             allow_capacity_estimation: bool = True,
                             use_ready_default_goals: bool = False,
                             exclude_recently_removed_brokers: bool = False,
                             exclude_recently_demoted_brokers: bool = False,
                             executor_kw: Optional[dict] = None,
                             **kw) -> dict:
        """FixOfflineReplicasRunnable: self-heal dead-disk/broker replicas."""
        if self_healing:
            dryrun = False
            exclude_recently_removed_brokers = (
                exclude_recently_removed_brokers or self.config.get(
                    "self.healing.exclude.recently.removed.brokers"))
            exclude_recently_demoted_brokers = (
                exclude_recently_demoted_brokers or self.config.get(
                    "self.healing.exclude.recently.demoted.brokers"))
        topo, assign = self._model(data_from=data_from,
                                   min_valid_partition_ratio=min_valid_partition_ratio)
        self._check_capacity_estimation(allow_capacity_estimation)
        excl = self._exclusions(exclude_recently_removed_brokers,
                                exclude_recently_demoted_brokers)
        options = self._build_options(topo, **excl) if excl else None
        goals = self._ready_goals() if use_ready_default_goals else None
        result = self._optimize(topo, assign, goals, options)
        summary = result.to_json(verbose=verbose)
        if not dryrun:
            summary["execution"] = self.executor.execute_proposals(
                result.proposals, **(executor_kw or {}))
        return summary

    def rebalance_disk(self, dryrun: bool = True, **kw) -> dict:
        """Intra-broker (JBOD) rebalance: IntraBrokerDiskCapacityGoal +
        IntraBrokerDiskUsageDistributionGoal via logdir moves."""
        from cruise_control_tpu.analyzer import intra_broker as IB
        topo, assign = self._model()
        if not topo.has_disks:
            raise ValueError("cluster model has no JBOD disk information")
        before = IB.disk_penalties(topo, assign)
        moves, new_dof = IB.rebalance_disks(
            topo, assign, goals=tuple(self.config.get("intra.broker.goals")))
        after = IB.disk_penalties(topo, assign, disk_of_replica=new_dof)
        summary = {
            "logdirMoves": [m.to_json() for m in moves],
            "numIntraBrokerReplicaMovements": len(moves),
            "intraBrokerDataToMoveMB": sum(m.data_size for m in moves),
            "goalSummary": [
                {"goal": g, "violationsBefore": before[g][0],
                 "violationsAfter": after[g][0]} for g in before],
        }
        if not dryrun and moves:
            summary["execution"] = self.executor.execute_logdir_moves(moves)
        return summary

    def rebalance_kafka_assigner(self, dryrun: bool = True,
                                 removed_brokers: Sequence[int] = (),
                                 **kw) -> dict:
        """Kafka-assigner mode (analyzer/kafkaassigner): deterministic even
        rack-aware placement + disk-usage balancing.

        ``removed_brokers``: REMOVE_BROKER with kafka_assigner=true — the
        decommissioned brokers are treated as dead for the placement (the
        reference marks them dead before running the assigner goals,
        RemoveBrokerRunnable kafka-assigner mode), so every replica leaves
        them. ADD_BROKER needs no special casing: the even placement spreads
        onto the new brokers by construction."""
        from cruise_control_tpu.analyzer import intra_broker as IB
        from cruise_control_tpu.analyzer import proposals as PR
        topo, assign = self._model()
        if removed_brokers:
            idx = {int(b): i for i, b in enumerate(
                topo.broker_ids if topo.broker_ids is not None
                else range(topo.num_brokers))}
            alive = topo.broker_alive.copy()
            for b in removed_brokers:
                if int(b) in idx:
                    alive[idx[int(b)]] = False
            topo = dataclasses.replace(topo, broker_alive=alive)
        new = IB.kafka_assigner_even_rack_aware(topo, assign)
        new = IB.kafka_assigner_disk_usage_distribution(topo, new)
        props = PR.diff(topo, assign, new)
        summary = {"proposals": [p.to_json() for p in props],
                   "numReplicaMovements": sum(len(p.replicas_to_add)
                                              for p in props),
                   "mode": "kafka_assigner"}
        if not dryrun:
            summary["execution"] = self.executor.execute_proposals(
                props, removed_brokers=removed_brokers)
        return summary

    def update_topic_replication_factor(self, topic_pattern: str,
                                        replication_factor: int,
                                        dryrun: bool = True,
                                        skip_rack_awareness_check: bool = False,
                                        **kw) -> dict:
        """UpdateTopicConfigurationRunnable: change matching topics' RF
        (ClusterModel.createOrDeleteReplicas, ClusterModel.java:906).

        Increase: add replicas on rack-diverse, least-loaded brokers that do
        not already host the partition. Decrease: drop follower replicas
        from the tail (never the leader). ``skip_rack_awareness_check``
        (ParameterUtils SKIP_RACK_AWARENESS_CHECK_PARAM): without it, an RF
        higher than the number of alive racks is rejected — it could not be
        placed rack-aware."""
        import re

        from cruise_control_tpu.analyzer.proposals import ExecutionProposal
        from cruise_control_tpu.common import resources as res
        pat = re.compile(topic_pattern)
        topo, assign = self._model()
        if not skip_rack_awareness_check and replication_factor > 1:
            # only an RF INCREASE places new replicas; a decrease drops tail
            # followers and needs no rack headroom
            tmask = np.array([bool(pat.fullmatch(t))
                              for t in topo.topic_names])
            matched = tmask[topo.topic_of_partition]
            increases = bool(
                (np.asarray(topo.rf_of_partition)[matched]
                 < replication_factor).any())
            alive_racks = len({int(r) for r, a in zip(topo.rack_of_broker,
                                                      topo.broker_alive) if a})
            if increases and replication_factor > alive_racks:
                raise ValueError(
                    f"replication factor {replication_factor} exceeds the "
                    f"number of alive racks ({alive_racks}); rack-aware "
                    "placement is impossible. Set "
                    "skip_rack_awareness_check=true to proceed anyway.")
        bo = np.asarray(assign.broker_of)
        lo = np.asarray(assign.leader_of)
        ids = np.asarray(topo.broker_ids)
        alive_rows = np.flatnonzero(topo.broker_alive)
        counts = np.bincount(bo, minlength=topo.num_brokers).astype(float)
        proposals: List[ExecutionProposal] = []
        for p in range(topo.num_partitions):
            t = topo.topic_names[topo.topic_of_partition[p]]
            if not pat.fullmatch(t):
                continue
            slots = topo.replicas_of_partition[p]
            slots = slots[slots >= 0]
            cur = [int(x) for x in bo[slots]]
            leader_row = int(bo[lo[p]])
            old_list = [leader_row] + [b for b in cur if b != leader_row]
            new_list = list(old_list)
            if replication_factor > len(cur):
                have_racks = {int(topo.rack_of_broker[b]) for b in new_list}
                for _ in range(replication_factor - len(cur)):
                    cands = [b for b in alive_rows if b not in new_list]
                    if not cands:
                        break
                    fresh = [b for b in cands
                             if int(topo.rack_of_broker[b]) not in have_racks]
                    pool = fresh or cands
                    pick = min(pool, key=lambda b: counts[b])
                    new_list.append(int(pick))
                    counts[pick] += 1
                    have_racks.add(int(topo.rack_of_broker[pick]))
            elif replication_factor < len(cur):
                if replication_factor < 1:
                    raise ValueError("replication_factor must be >= 1")
                new_list = new_list[:replication_factor]
            if new_list != old_list:
                disk = float(topo.replica_base_load[lo[p], res.DISK])
                proposals.append(ExecutionProposal(
                    topic=t,
                    partition=int(topo.partition_index[p]),
                    old_leader=int(ids[leader_row]),
                    old_replicas=tuple(int(ids[b]) for b in old_list),
                    new_replicas=tuple(int(ids[b]) for b in new_list),
                    data_size=disk))
        summary = {"proposals": [p.to_json() for p in proposals],
                   "numPartitionsChanged": len(proposals),
                   "replicationFactor": replication_factor}
        if not dryrun and proposals:
            summary["execution"] = self.executor.execute_proposals(proposals)
        return summary

    # ------------------------------------------------------------- controls

    def pause_sampling(self, reason: str = "Paused by user"):
        self.load_monitor.pause(reason)
        return {"paused": True, "reason": reason}

    def resume_sampling(self, reason: str = "Resumed by user"):
        self.load_monitor.resume(reason)
        return {"resumed": True, "reason": reason}

    def stop_execution(self, forced: bool = False):
        self.executor.stop_execution(forced)
        return {"stopRequested": True, "forced": forced}

    @property
    def is_reconciling(self) -> bool:
        """True while startup restart-reconciliation is resolving journaled
        tasks; the REST layer answers mutating requests 503 meanwhile."""
        return self.executor.recovering

    def set_self_healing(self, anomaly_type: Optional[str], enabled: bool) -> dict:
        types = ([AnomalyType[anomaly_type]] if anomaly_type
                 else list(AnomalyType))
        for t in types:
            self.anomaly_detector.notifier.set_self_healing_for(t, enabled)
        return {"selfHealingEnabled": {
            t.value: v for t, v in
            self.anomaly_detector.notifier.self_healing_enabled().items()}}

    # ----------------------------------------------------------------- state

    def observability_state(self) -> dict:
        """Graftscope view: the tracer's summary + the compile/retrace
        observatory snapshot (ObservabilityState in /state and the body of
        GET /observatory)."""
        from cruise_control_tpu.obs.observatory import OBSERVATORY
        out = {"tracing": self.tracer.summary(),
               "observatory": OBSERVATORY.snapshot(),
               "flightRecorder": self.flightrec.summary()}
        if self.costmodel.enabled:
            out["costModel"] = self.costmodel.snapshot()
        if self.healthwatch is not None:
            out["healthWatch"] = self.healthwatch.snapshot()
        return out

    def _model_geometry(self) -> Optional[dict]:
        """Bucketed geometry of the model the service is serving (None
        while the monitor can't build one): what the headroom forecaster
        prices."""
        from cruise_control_tpu.obs import costmodel as CMOD
        try:
            topo, _assign = self._model()
        except NotEnoughValidWindowsError:
            return None
        return CMOD.geometry_from_counts(
            topo.num_brokers, topo.num_hosts, topo.num_partitions,
            topo.num_replicas, topo.max_rf,
            chains=int(self.config.get("anneal.num.chains")))

    def headroom_state(self) -> dict:
        """GET /headroom: current device memory + the bucket-ladder
        forecast — will the next ×1.25 bucket step fit the remaining
        device memory? (obs/costmodel.py)"""
        if not self.costmodel.enabled:
            return {"enabled": False,
                    "reason": "obs.costmodel.enable is off"}
        forecast = self.costmodel.headroom_forecast(self._model_geometry())
        return {"enabled": True, "forecast": forecast,
                "census": self.costmodel.live_buffer_census()}

    def alerts_state(self, history: int = 64) -> dict:
        """GET /alerts: active burn-rate alerts, rule registry, counts
        and recent decision history (obs/healthwatch.py)."""
        if self.healthwatch is None:
            return {"enabled": False, "reason": "healthwatch.enable is off"}
        return self.healthwatch.snapshot(history=history)

    def explain(self, partition: Optional[str] = None) -> dict:
        """Per-move goal attribution of the cached default-goal proposal
        (GET /explain). ``partition``: optional "topic-index" filter."""
        with self._cache_lock:
            c = self._proposal_cache
        enabled = bool(self.config.get("obs.provenance.enable"))
        out = {"provenanceEnabled": enabled,
               "isProposalReady": c is not None}
        if c is None:
            return out
        ma = c.result.move_attribution
        if ma is None:
            # a cached computation from before the flag flipped (or the
            # flag is off): say why there is nothing to explain
            out["moveAttribution"] = None
            return out
        if partition:
            ma = {**ma, "moves": [m for m in ma["moves"]
                                  if m["topicPartition"] == partition]}
        out["moveAttribution"] = ma
        out["engine"] = c.result.engine
        out["computedAtMs"] = c.computed_at_ms
        return out

    def flightrecorder_jsonl(self) -> str:
        """Canonical JSONL export of the flight-recorder ring
        (GET /flightrecorder)."""
        return self.flightrec.export_jsonl()

    def state(self, super_verbose: bool = False) -> dict:
        """CruiseControlState for the STATE endpoint. ``super_verbose``
        (CruiseControlState.writeSuperVerbose): adds the extrapolated
        metric-sample flaws and the linear-regression model state."""
        with self._cache_lock:
            proposal_ready = self._proposal_cache is not None
            anneal_telemetry = (self._proposal_cache.result.anneal_telemetry
                                if self._proposal_cache is not None else None)
            last_fallback = self._last_fallback
            last_provision = self._last_provision_recommendation
            cache_hits = self.proposal_cache_hits
            cache_misses = self.proposal_cache_misses
            incr_refreshes = self.incremental_refreshes
            anneal_skips = self.anneal_skips
            last_tick_ms = self.last_tick_ms
            last_self_heal_ms = self.last_self_heal_ms
            self_heal_path = self.self_heal_path
            last_simulation = self._last_simulation
        out = {
            "MonitorState": self.load_monitor.state_snapshot(),
            "ExecutorState": self.executor.state_snapshot(),
            "AnalyzerState": {
                "isProposalReady": proposal_ready,
                "readyGoals": list(self._ready_goals()),
                "lastOptimizationFallback": last_fallback,
                "precomputeFailures": self._precompute_failures,
                "lastProvisionRecommendation": last_provision,
                "proposalCacheHits": cache_hits,
                "proposalCacheMisses": cache_misses,
                "incrementalRefreshes": incr_refreshes,
                "annealSkips": anneal_skips,
                "lastTickMs": last_tick_ms,
                "lastSelfHealMs": last_self_heal_ms,
                "selfHealPath": self_heal_path,
                "annealTelemetry": anneal_telemetry,
                **mesh_state(self.mesh),
            },
            "AnomalyDetectorState": self.anomaly_detector.state_snapshot(),
            "WatchdogState": self.watchdog.snapshot(),
            "ReplicationState": self.replication_state(),
            "ObservabilityState": self.observability_state(),
        }
        if last_simulation is not None:
            out["SimulatorState"] = last_simulation
        if super_verbose:
            out["MonitorState"]["extrapolatedMetricSamples"] = (
                self.load_monitor.sample_extrapolations())
            out["MonitorState"]["linearRegressionModelState"] = (
                self.load_monitor.cpu_model.to_json())
        return out

    def kafka_cluster_state(self, populate_disk_info: bool = False) -> dict:
        md = self._metadata_source.get_metadata()
        by_broker: Dict[int, Dict[str, int]] = {
            b.broker_id: {"replicaCount": 0, "leaderCount": 0,
                          "alive": b.alive} for b in md.brokers}
        if populate_disk_info:
            logdirs = self.executor.adapter.describe_logdirs()
            for bid, dirs in logdirs.items():
                if bid in by_broker:
                    by_broker[bid]["OnlineLogDirs"] = sorted(
                        d for d, ok in dirs.items() if ok)
                    by_broker[bid]["OfflineLogDirs"] = sorted(
                        d for d, ok in dirs.items() if not ok)
        urp, offline = [], []
        for p in md.partitions:
            for r in p.replicas:
                if r in by_broker:
                    by_broker[r]["replicaCount"] += 1
            if p.leader in by_broker:
                by_broker[p.leader]["leaderCount"] += 1
            if p.isr and set(p.isr) != set(p.replicas):
                urp.append(f"{p.topic}-{p.partition}")
            if p.offline_replicas:
                offline.append(f"{p.topic}-{p.partition}")
        return {"KafkaBrokerState": by_broker,
                "KafkaPartitionState": {
                    "urp": urp, "offline": offline,
                    "totalPartitions": len(md.partitions)}}
