"""Metrics registry: counters, gauges, timers, histograms (dropwizard parity).

The reference exports dropwizard meters/timers via JMX under
``kafka.cruisecontrol`` (``KafkaCruiseControlMain.java:71-73``; sensor table
``docs/wiki/User Guide/Sensors.md``). Here the registry is in-process and
exported two ways through the REST server:

- ``GET /metrics`` — flat JSON snapshot (:meth:`MetricsRegistry.snapshot`),
  the shape the tests and ad-hoc curl debugging read;
- ``GET /metrics?format=prometheus`` — spec-conformant Prometheus text
  exposition (:meth:`MetricsRegistry.prometheus`): ``# HELP``/``# TYPE``
  headers, ``_total`` counter suffix, timers as cumulative fixed-bucket
  histograms (``_bucket{le=...}``/``_sum``/``_count``), stable label
  ordering and escaped label values, deterministic line order.

Timers measure durations on an *injectable monotonic* clock — never
``time.time()``, whose NTP/virtual-clock steps corrupt deltas (the same
hazard graftlint G011 bans on control-plane paths) — and fold every
observation into a fixed-bucket :class:`Histogram` so p50/p99 are
deterministic functions of the bucket counts (no reservoir sampling).
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

LOG = logging.getLogger(__name__)

#: label set normalized to a hashable, deterministically-ordered key
LabelKey = Tuple[Tuple[str, str], ...]

#: fixed histogram bucket upper bounds (seconds) — spans sub-ms span
#: overhead through multi-minute greedy fallbacks; one implicit +Inf
DEFAULT_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def _label_key(labels: Optional[Dict[str, object]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class Histogram:
    """Fixed-bucket histogram with deterministic quantiles.

    Bucket counts are non-cumulative internally; quantiles report the
    upper bound of the bucket where the cumulative count crosses the
    rank — a deterministic, merge-friendly estimate (exactly what the
    Prometheus exposition encodes), not a sampled one.
    """

    def __init__(self, bounds: Sequence[float] = DEFAULT_BUCKETS_S):
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # [-1]=+Inf
        self.total = 0
        self.sum = 0.0

    def update(self, value: float) -> None:
        # caller (Timer) holds its lock; bare Histogram is single-writer
        idx = bisect.bisect_left(self.bounds, value)
        self.counts[idx] += 1
        self.total += 1
        self.sum += value

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket containing quantile ``q`` (0..1).
        The +Inf bucket reports the largest finite bound."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return self.bounds[i] if i < len(self.bounds) \
                    else self.bounds[-1]
        return self.bounds[-1]

    def cumulative(self) -> List[Tuple[float, int]]:
        """``(upper_bound, cumulative_count)`` pairs, +Inf last — the
        Prometheus ``_bucket{le=...}`` series."""
        out: List[Tuple[float, int]] = []
        cum = 0
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            out.append((bound, cum))
        out.append((float("inf"), cum + self.counts[-1]))
        return out


class Timer:
    """Duration metric: count/total/max plus a fixed-bucket histogram.

    Deltas come from an injectable *monotonic* clock (default
    ``time.monotonic``) so a wall-clock step — NTP slew in prod, the
    virtual clock jumping in tests — can't corrupt a measurement.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 bounds: Sequence[float] = DEFAULT_BUCKETS_S):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.hist = Histogram(bounds)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()

    def update(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)
            self.hist.update(seconds)

    def time(self):
        timer = self
        clock = self._clock

        class _Ctx:
            def __enter__(self):
                self.t0 = clock()
                return self

            def __exit__(self, *exc):
                timer.update(max(clock() - self.t0, 0.0))

        return _Ctx()

    @property
    def mean_s(self) -> float:
        with self._lock:
            return self.total_s / self.count if self.count else 0.0

    @property
    def p50_s(self) -> float:
        with self._lock:
            return self.hist.quantile(0.50)

    @property
    def p99_s(self) -> float:
        with self._lock:
            return self.hist.quantile(0.99)


#: ``# HELP`` strings for the sensors this codebase emits, keyed by the
#: registry name (pre-sanitization).  ``tools/gen_docs.py`` regenerates
#: ``docs/sensors.md`` from this table, so docs can't drift from code.
SENSOR_DOCS: Dict[str, str] = {
    "proposal-computation-timer":
        "Wall time of one full proposal computation (optimize() call).",
    "proposal-computation-fallback-rate":
        "Engine fallbacks taken (anneal -> greedy -> sequential).",
    "proposal.precompute.failures":
        "Background proposal precompute attempts that raised.",
    "proposal.incremental.refresh":
        "Warm proposal refreshes served from the incremental path.",
    "cluster-model-creation-timer":
        "Wall time to build or splice the cluster model.",
    "cluster-model-cache-hit-rate": "Cluster model cache hits.",
    "cluster-model-cache-miss-rate": "Cluster model cache misses.",
    "partition-samples-fetcher-timer":
        "Wall time of one partition metric sample fetch.",
    "partition-samples-fetcher-failure-rate":
        "Partition metric sample fetches that failed.",
    "adapter-call-retry-rate": "Executor adapter calls that were retried.",
    "executor-recovery-rate": "Executor journal recoveries performed.",
    "execution-finished-rate": "Proposal executions finished cleanly.",
    "execution-failed-rate": "Proposal executions that failed.",
    "execution-stopped-rate": "Proposal executions stopped by request.",
    "throttle-clear-failed-rate":
        "Replication throttle clears that failed.",
    "task-stuck-rate": "Executor tasks declared stuck past the timeout.",
    "task-dead-on-adapter-failure-rate":
        "Executor tasks killed by repeated adapter failures.",
    "anomaly-detector-error-rate": "Anomaly detector sweeps that raised.",
    "self-healing-fix-rate": "Self-healing fixes dispatched.",
    "gauge-errors": "Registered gauge callbacks that raised on read.",
    "observatory-jit-traces":
        "Jit traces observed by the compile observatory, per function.",
    "observatory-xla-compiles":
        "XLA compiles observed by the compile observatory, per function.",
    "observatory-steady-state-retraces":
        "Jit traces after the loop declared steady state, per function.",
    "observatory-compile-timer":
        "XLA compile wall time, per function.",
    "observatory-device-dispatches":
        "Device dispatches of jitted entry points, per callsite.",
    "observatory-transfer-guard-violations":
        "Implicit-transfer violations surfaced, per callsite.",
    "observatory-compile-wall-seconds":
        "Cumulative XLA compile wall time, per function (the labeled "
        "series behind the per-function compile-budget attribution; the "
        "compile timer histogram buckets the same durations).",
    "costmodel-programs-captured":
        "Compiled-program variants captured by the cost observatory, "
        "per program (one per new argument-shape signature).",
    "costmodel-device-bytes-in-use":
        "Device memory in use at the last graftwatch sample (backend "
        "memory_stats, or the live-array census on backends without "
        "allocator stats).",
    "costmodel-headroom-bytes":
        "Remaining device memory against the configured/backed HBM "
        "limit at the last headroom forecast.",
    "costmodel-next-step-bytes":
        "Forecast footprint of the next bucket-ladder rung (x1.25 "
        "growth) of the cluster model.",
    "costmodel-next-step-fits":
        "1 when the next bucket-ladder rung fits the remaining device "
        "memory, 0 when it does not (absent while no limit is known).",
    "healthwatch-active-alerts":
        "Alert rules currently firing (active, not yet resolved).",
    "healthwatch-alerts-fired":
        "Burn-rate alert fire transitions, per rule.",
    "healthwatch-alerts-suppressed":
        "Burn-rate alert decisions suppressed while already active, "
        "per rule.",
    "healthwatch-alerts-resolved":
        "Burn-rate alert resolve transitions, per rule.",
}

#: sensor families registered as callback gauges — the docs generator
#: (tools/gen_docs.py) classifies kinds by name, and gauges render on
#: the Prometheus scrape as the bare metric name (no ``_total`` suffix)
GAUGE_SENSORS = frozenset({
    "costmodel-device-bytes-in-use",
    "costmodel-headroom-bytes",
    "costmodel-next-step-bytes",
    "costmodel-next-step-fits",
    "healthwatch-active-alerts",
})


class MetricsRegistry:
    """Named counters / gauges / timers, labeled, snapshot-able, scrapable."""

    #: failures logged per gauge before going quiet (the capped rate)
    GAUGE_ERROR_LOG_CAP = 1

    def __init__(self, prefix: str = "kafka_cruisecontrol",
                 clock: Optional[Callable[[], float]] = None):
        self.prefix = prefix
        self._clock = clock or time.monotonic
        self._counters: Dict[str, Dict[LabelKey, float]] = {}
        self._gauges: Dict[str, Dict[LabelKey, Callable[[], float]]] = {}
        self._timers: Dict[str, Dict[LabelKey, Timer]] = {}
        self._gauge_error_logs: Dict[Tuple[str, LabelKey], int] = {}
        # RLock: snapshot() increments the gauge-errors counter while
        # already holding the lock (gauge callback raised mid-walk)
        self._lock = threading.RLock()

    def counter(self, name: str, inc: float = 1.0,
                labels: Optional[Dict[str, object]] = None):
        key = _label_key(labels)
        with self._lock:
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + inc

    def gauge(self, name: str, fn: Callable[[], float],
              labels: Optional[Dict[str, object]] = None):
        with self._lock:
            self._gauges.setdefault(name, {})[_label_key(labels)] = fn

    def timer(self, name: str,
              labels: Optional[Dict[str, object]] = None) -> Timer:
        key = _label_key(labels)
        with self._lock:
            series = self._timers.setdefault(name, {})
            t = series.get(key)
            if t is None:
                t = series[key] = Timer(clock=self._clock)
            return t

    # ------------------------------------------------------------ reads
    def _read_gauge(self, name: str, key: LabelKey,
                    fn: Callable[[], float]) -> Optional[float]:
        """Read one gauge; on failure count it, warn (capped), skip it.
        A gauge may return ``None`` to decline reporting (no sample yet,
        e.g. the headroom forecaster before its first geometry) — skipped
        without counting as an error.
        Caller holds ``self._lock`` (RLock — the counter bump re-enters)."""
        try:
            v = fn()
            return None if v is None else float(v)
        except Exception:
            self.counter("gauge-errors")
            logged = self._gauge_error_logs.get((name, key), 0)
            self._gauge_error_logs[(name, key)] = logged + 1
            if logged < self.GAUGE_ERROR_LOG_CAP:
                LOG.warning("gauge %r%s raised; excluded from snapshot "
                            "(logged once, counted in gauge-errors)",
                            name, dict(key) if key else "", exc_info=True)
            return None

    @staticmethod
    def _suffix(key: LabelKey) -> str:
        if not key:
            return ""
        return "{" + ",".join(f"{k}={v}" for k, v in key) + "}"

    def snapshot(self) -> dict:
        """Flat JSON view. Unlabeled series keep their bare name (the
        pre-labels format); labeled series append ``{k=v,...}``."""
        with self._lock:
            out: Dict[str, float] = {}
            # gauges first: a failure bumps gauge-errors, which the
            # counter walk below then reports in THIS snapshot
            gauge_vals: List[Tuple[str, LabelKey, float]] = []
            for name, series in self._gauges.items():
                for key, fn in list(series.items()):
                    val = self._read_gauge(name, key, fn)
                    if val is not None:
                        gauge_vals.append((name, key, val))
            for name, series in self._counters.items():
                for key, v in series.items():
                    out[f"{name}{self._suffix(key)}"] = v
            for name, key, val in gauge_vals:
                out[f"{name}{self._suffix(key)}"] = val
            for name, series in self._timers.items():
                for key, t in series.items():
                    base = f"{name}{self._suffix(key)}"
                    out[f"{base}-count"] = t.count
                    out[f"{base}-mean-s"] = round(t.mean_s, 6)
                    out[f"{base}-max-s"] = round(t.max_s, 6)
                    out[f"{base}-p50-s"] = round(t.p50_s, 6)
                    out[f"{base}-p99-s"] = round(t.p99_s, 6)
            return out

    # ------------------------------------------------------ prometheus
    def _metric_name(self, name: str) -> str:
        return f"{self.prefix}_{name}".replace(".", "_").replace("-", "_")

    @staticmethod
    def _render_labels(key: LabelKey, extra: str = "") -> str:
        parts = [f'{k}="{_escape_label_value(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def _header(self, lines: List[str], metric: str, name: str,
                mtype: str) -> None:
        help_text = SENSOR_DOCS.get(name)
        if help_text:
            lines.append(f"# HELP {metric} {_escape_help(help_text)}")
        lines.append(f"# TYPE {metric} {mtype}")

    @staticmethod
    def _fmt(value: float) -> str:
        if value == int(value) and abs(value) < 1e15:
            return str(int(value))
        return repr(float(value))

    def prometheus(self) -> str:
        """Prometheus text exposition (``text/plain; version=0.0.4``).

        Deterministic: families sorted by name, series by label key,
        label names pre-sorted, values escaped. Counters get the
        ``_total`` suffix; timers render as cumulative histograms.
        """
        lines: List[str] = []
        with self._lock:
            # read gauges first so a failure's gauge-errors bump lands
            # in this scrape's counter section
            gauge_vals: Dict[str, List[Tuple[LabelKey, float]]] = {}
            for name in sorted(self._gauges):
                for key in sorted(self._gauges[name]):
                    val = self._read_gauge(name, key,
                                           self._gauges[name][key])
                    if val is not None:
                        gauge_vals.setdefault(name, []).append((key, val))
            for name in sorted(self._counters):
                metric = self._metric_name(name) + "_total"
                self._header(lines, metric, name, "counter")
                for key in sorted(self._counters[name]):
                    lines.append(f"{metric}{self._render_labels(key)} "
                                 f"{self._fmt(self._counters[name][key])}")
            for name in sorted(gauge_vals):
                metric = self._metric_name(name)
                self._header(lines, metric, name, "gauge")
                for key, val in gauge_vals[name]:
                    lines.append(f"{metric}{self._render_labels(key)} "
                                 f"{self._fmt(val)}")
            for name in sorted(self._timers):
                metric = self._metric_name(name) + "_seconds"
                self._header(lines, metric, name, "histogram")
                for key in sorted(self._timers[name]):
                    t = self._timers[name][key]
                    with t._lock:
                        buckets = t.hist.cumulative()
                        total_s, count = t.total_s, t.count
                    for bound, cum in buckets:
                        le = "+Inf" if bound == float("inf") \
                            else self._fmt(bound)
                        labels = self._render_labels(key, f'le="{le}"')
                        lines.append(f"{metric}_bucket{labels} {cum}")
                    suffix = self._render_labels(key)
                    lines.append(f"{metric}_sum{suffix} "
                                 f"{repr(round(total_s, 9))}")
                    lines.append(f"{metric}_count{suffix} {count}")
        return "\n".join(lines) + "\n"

    def sensor_rows(self) -> List[dict]:
        """One row per registered sensor family (for docs generation)."""
        with self._lock:
            rows = []
            for name in sorted(self._counters):
                rows.append({"name": name, "kind": "counter"})
            for name in sorted(self._gauges):
                rows.append({"name": name, "kind": "gauge"})
            for name in sorted(self._timers):
                rows.append({"name": name, "kind": "timer"})
        for row in rows:
            row["help"] = SENSOR_DOCS.get(row["name"], "")
        return sorted(rows, key=lambda r: r["name"])


#: process-wide default registry (the reference's singleton MetricRegistry)
REGISTRY = MetricsRegistry()
