"""Metrics registry: counters, gauges, timers (dropwizard → JMX parity).

The reference exports dropwizard meters/timers via JMX under
``kafka.cruisecontrol`` (``KafkaCruiseControlMain.java:71-73``; sensor table
``docs/wiki/User Guide/Sensors.md``). Here the registry is in-process and
exported through the REST ``/metrics`` route in Prometheus text format —
the observability fabric this ecosystem actually scrapes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class Timer:
    """Wall-clock timer with count/total/max (dropwizard Timer parity)."""

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.time()
                return self

            def __exit__(self, *exc):
                timer.update(time.time() - self.t0)

        return _Ctx()

    @property
    def mean_s(self) -> float:
        with self._lock:
            return self.total_s / self.count if self.count else 0.0


class MetricsRegistry:
    """Named counters / gauges / timers, snapshot-able and scrapable."""

    def __init__(self, prefix: str = "kafka_cruisecontrol"):
        self.prefix = prefix
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, inc: float = 1.0):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + inc

    def gauge(self, name: str, fn: Callable[[], float]):
        with self._lock:
            self._gauges[name] = fn

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer()
            return t

    def snapshot(self) -> dict:
        with self._lock:
            out = {f"{k}": v for k, v in self._counters.items()}
            for k, fn in self._gauges.items():
                try:
                    out[k] = float(fn())
                except Exception:
                    pass
            for k, t in self._timers.items():
                out[f"{k}-count"] = t.count
                out[f"{k}-mean-s"] = round(t.mean_s, 6)
                out[f"{k}-max-s"] = round(t.max_s, 6)
            return out

    def prometheus(self) -> str:
        lines: List[str] = []
        for k, v in sorted(self.snapshot().items()):
            metric = f"{self.prefix}_{k}".replace(".", "_").replace("-", "_")
            lines.append(f"{metric} {v}")
        return "\n".join(lines) + "\n"


#: process-wide default registry (the reference's singleton MetricRegistry)
REGISTRY = MetricsRegistry()
