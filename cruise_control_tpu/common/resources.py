"""Resource taxonomy for the TPU-native cluster model.

Mirrors the semantics of the reference's resource enum
(``cruise-control/.../common/Resource.java:17-27``): four balanced resources with
fixed array ids, host-vs-broker scoping flags, and the float-summation epsilon
policy tuned for ~800K-replica models.

Array layout convention used across the whole framework: every per-entity load or
capacity tensor has a trailing axis of size ``NUM_RESOURCES`` indexed by these ids.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Resource ids — identical to Resource.java ids so dumps/diffs line up.
CPU = 0
NW_IN = 1
NW_OUT = 2
DISK = 3
NUM_RESOURCES = 4

# Extra broker-level metric column (not a balanced "Resource" in the reference,
# but KafkaMetricDef's LEADER_BYTES_IN model metric, used by
# LeaderBytesInDistributionGoal). Broker metric tensors that carry it use
# NUM_BROKER_METRICS columns.
LEADER_BYTES_IN = 4
NUM_BROKER_METRICS = 5

RESOURCE_NAMES = ("cpu", "networkInbound", "networkOutbound", "disk")

# Host-level resources: CPU, NW_IN, NW_OUT (capacity goals aggregate over the
# host for these); broker-level resources: CPU, DISK (Resource.java:18-21).
IS_HOST_RESOURCE = np.array([True, True, True, False])
IS_BROKER_RESOURCE = np.array([True, False, False, True])

# Absolute epsilon floor per resource (Resource.java:18-21 last ctor arg).
RESOURCE_EPSILON = np.array([0.001, 10.0, 10.0, 100.0])

# Relative epsilon: acceptable nuance from float summation, 0.08% of the sum of
# compared values (Resource.java:27).
EPSILON_PERCENT = 0.0008

# Priority order used by BalancingConstraint for resource balancing
# (BalancingConstraint.java:40): DISK, NW_IN, NW_OUT, CPU.
RESOURCE_BALANCE_PRIORITY = (DISK, NW_IN, NW_OUT, CPU)


def epsilon(resource: int, value1, value2):
    """Comparison tolerance for a resource, matching Resource.java:87-89."""
    return np.maximum(RESOURCE_EPSILON[resource], EPSILON_PERCENT * (value1 + value2))


@dataclasses.dataclass(frozen=True)
class BalancingConstraint:
    """Balance/capacity thresholds (analyzer/BalancingConstraint.java:22-66).

    Defaults mirror KafkaCruiseControlConfig.java:1344-1460: balance thresholds
    1.10 (topic-replica 3.00), capacity thresholds 0.8, low-utilization 0.0,
    max 10_000 replicas per broker, goal-violation distribution multiplier 1.0.
    Array fields are indexed by resource id.
    """

    resource_balance_percentage: tuple = (1.10, 1.10, 1.10, 1.10)
    capacity_threshold: tuple = (0.8, 0.8, 0.8, 0.8)
    low_utilization_threshold: tuple = (0.0, 0.0, 0.0, 0.0)
    replica_balance_percentage: float = 1.10
    leader_replica_balance_percentage: float = 1.10
    topic_replica_balance_percentage: float = 3.00
    goal_violation_distribution_threshold_multiplier: float = 1.00
    max_replicas_per_broker: int = 10_000

    def balance_percentage_array(self) -> np.ndarray:
        return np.asarray(self.resource_balance_percentage, dtype=np.float32)

    def capacity_threshold_array(self) -> np.ndarray:
        return np.asarray(self.capacity_threshold, dtype=np.float32)

    def low_utilization_threshold_array(self) -> np.ndarray:
        return np.asarray(self.low_utilization_threshold, dtype=np.float32)


DEFAULT_BALANCING_CONSTRAINT = BalancingConstraint()
