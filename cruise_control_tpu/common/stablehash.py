"""Process-stable hashing for deterministic seeds and bucketing.

Python's builtin ``hash()`` is randomized per process for ``str`` and
``bytes`` (PYTHONHASHSEED), so anything derived from
``hash((seed, topic, partition))`` — synthetic load rates, hot-group
assignment — silently differs between interpreter invocations.  Within
one process everything stays self-consistent, which is why the bug only
shows up when two runs of the *same seed* in *different processes* are
compared: the byte-identical-journal and bit-identical-convergence
guarantees (docs/operations.md) are cross-process statements, so they
must not depend on interpreter hash randomization.

:func:`stable_hash32` is the drop-in replacement: a CRC-32 over the
``repr`` of the parts, stable across processes, platforms, and Python
versions for the primitive types used here (ints and strings).
"""

from __future__ import annotations

import zlib

__all__ = ["stable_hash32"]


def stable_hash32(*parts) -> int:
    """A stable 32-bit hash of ``parts`` (ints/strings), suitable as an
    RNG seed or modulo bucket.  NOT cryptographic."""
    payload = "\x1f".join(repr(p) for p in parts).encode("utf-8")
    return zlib.crc32(payload) & 0xFFFFFFFF
