"""Runtime hazard sentinels: retrace counting + implicit-transfer guards.

graftlint's static rules (tools/graftlint) catch hazard *patterns*; these
sentinels catch the hazards themselves at runtime:

- :func:`no_implicit_transfers` — a ``jax.transfer_guard("disallow")``
  scope.  Inside it, any device transfer JAX inserts *implicitly* (a numpy
  array silently uploaded into a jit call, an eager op against a Python
  scalar, a device array silently pulled to host) raises immediately.
  Explicit transfers — ``jax.device_put``, ``jax.device_get``,
  ``jnp.asarray(np_array)`` — remain allowed, so fully-explicit
  host-sequencing passes untouched.  The annealer wraps its steady-state
  parallel-tempering dispatch in this scope.

- :func:`retrace_sentinel` — counts jit traces/compiles inside the scope
  (via ``jax_log_compiles`` log capture, which names the traced function),
  so a test or bench can assert that a *warmed* steady-state run performs
  zero retraces.

- :func:`check_steady_state` — compares a :class:`RetraceLog` against the
  checked-in runtime baseline (``tools/graftlint/runtime_baseline.json``):
  every steady-state retrace must either not happen or be listed there
  with a justification.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
from typing import Iterator, List, Optional, Tuple

import jax

RUNTIME_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "tools", "graftlint", "runtime_baseline.json")


def no_implicit_transfers():
    """``with no_implicit_transfers(): ...`` — implicit transfers raise.

    Wrap steady-state device dispatch (all-device-array arguments, statics
    hashed) in this scope.  Keep host glue — Python-scalar arithmetic,
    ``jnp.array([...])`` literals, numpy args to jit calls — outside, or
    make its transfers explicit via device_put/device_get.
    """
    return jax.transfer_guard("disallow")


def parse_compile_log(msg: str) -> Optional[Tuple[str, str, Optional[float]]]:
    """Classify one ``jax_log_compiles`` message.

    Returns ``(kind, function_name, seconds)`` where kind is ``"trace"``
    (tracing + transforming finished), ``"compile"`` (XLA compile
    started), or ``"compile_done"`` (XLA compile finished; ``seconds`` is
    the reported wall time) — or ``None`` for anything else.  Shared by
    the test-scoped :func:`retrace_sentinel` and the always-on
    production observatory (``cruise_control_tpu.obs.observatory``).
    """
    try:
        if msg.startswith("Finished tracing + transforming"):
            return "trace", msg.split()[4], None
        if msg.startswith("Compiling") and "with global shapes" in msg:
            return "compile", msg.split()[1], None
        if msg.startswith("Finished XLA compilation of"):
            parts = msg.split()
            fn = parts[4]
            if fn.startswith("jit(") and fn.endswith(")"):
                fn = fn[4:-1]      # "jit(f)" -> "f": match the trace name
            return "compile_done", fn, float(parts[6])
    except (IndexError, ValueError):
        return None
    return None


class RetraceLog:
    """Trace/compile events captured inside a :func:`retrace_sentinel`."""

    def __init__(self) -> None:
        self.traces: List[str] = []    # "Finished tracing + transforming X"
        self.compiles: List[str] = []  # "Compiling X with global shapes..."

    @property
    def count(self) -> int:
        """Number of traces observed (each cache miss traces once)."""
        return len(self.traces)

    def summary(self) -> str:
        if not self.traces and not self.compiles:
            return "0 retraces"
        names = self.traces or self.compiles
        return f"{len(names)} retrace(s): {', '.join(sorted(set(names)))}"


class _CaptureHandler(logging.Handler):
    def __init__(self, log: RetraceLog) -> None:
        super().__init__(level=logging.DEBUG)
        self._log = log

    def emit(self, record: logging.LogRecord) -> None:
        parsed = parse_compile_log(record.getMessage())
        if parsed is None:
            return
        kind, fn, _ = parsed
        if kind == "trace":
            self._log.traces.append(fn)
        elif kind == "compile":
            self._log.compiles.append(fn)


@contextlib.contextmanager
def retrace_sentinel() -> Iterator[RetraceLog]:
    """Count jit traces/compiles inside the scope.

    A warmed steady-state region must report ``log.count == 0``; anything
    else is a retrace storm (shape/dtype drift, a fresh jit wrapper, or a
    high-cardinality static) and ``log.summary()`` names the functions.
    """
    log = RetraceLog()
    handler = _CaptureHandler(log)
    logger = logging.getLogger("jax")
    prev = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    logger.addHandler(handler)
    try:
        yield log
    finally:
        logger.removeHandler(handler)
        jax.config.update("jax_log_compiles", prev)


def load_runtime_baseline(path: str = RUNTIME_BASELINE) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        return json.load(fh).get("allowed", [])


def check_steady_state(log: RetraceLog, path: str = RUNTIME_BASELINE,
                       strict: Optional[bool] = None) -> List[str]:
    """Return steady-state retraces NOT covered by the runtime baseline.

    Each baseline entry allows one trace of ``function`` (with a
    file:line + justification for the reader).  With ``strict`` (default:
    the GRAFT_STRICT_SENTINELS env var), uncovered retraces raise.
    """
    allowed: List[str] = []
    for entry in load_runtime_baseline(path):
        allowed.append(entry.get("function", ""))
    uncovered = list(log.traces)
    for fn in allowed:
        if fn in uncovered:
            uncovered.remove(fn)
    if strict is None:
        strict = bool(os.environ.get("GRAFT_STRICT_SENTINELS"))
    if uncovered and strict:
        raise AssertionError(
            f"steady state retraced {len(uncovered)} function(s) not in "
            f"runtime baseline: {sorted(set(uncovered))}")
    return uncovered
