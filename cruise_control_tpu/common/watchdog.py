"""Thread watchdog: heartbeat registry + bounded restart supervision.

Every long-lived background thread (monitor sampler, detector sweep,
proposal precompute, executor progress loop, sample-store flusher)
registers a named heartbeat and calls :meth:`Watchdog.beat` from its
loop.  :meth:`Watchdog.poll` — driven either by the watchdog's own
monitor thread (wall-clock deployments) or by the simulator tick loop
(virtual time) — flags heartbeats older than ``stall_ms`` and, for
threads registered with a ``restart_fn``, restarts them with
exponential backoff, up to ``max_restarts`` times.  A thread that
exhausts its restart budget is surfaced as degraded in ``/state``
rather than silently dead.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class _Heartbeat:
    name: str
    last_beat_ms: int
    restart_fn: Optional[Callable[[], None]] = None
    #: stall detection applies only while this returns True (e.g. the
    #: executor progress heartbeat is only live during an execution)
    active_fn: Optional[Callable[[], bool]] = None
    restarts: int = 0
    next_restart_ms: int = 0
    degraded: bool = False
    beats: int = 0
    last_error: str = ""


class Watchdog:
    """Heartbeat registry with stall detection and bounded restarts."""

    def __init__(self, now_ms: Callable[[], int] = None,
                 stall_ms: int = 30_000, max_restarts: int = 3,
                 backoff_ms: int = 1_000):
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self.stall_ms = int(stall_ms)
        self.max_restarts = int(max_restarts)
        self.backoff_ms = int(backoff_ms)
        self._lock = threading.Lock()
        self._threads: Dict[str, _Heartbeat] = {}
        self.total_restarts = 0

    # ------------------------------------------------------- registry

    def register(self, name: str,
                 restart_fn: Optional[Callable[[], None]] = None,
                 active_fn: Optional[Callable[[], bool]] = None) -> None:
        with self._lock:
            self._threads[name] = _Heartbeat(
                name=name, last_beat_ms=int(self._now_ms()),
                restart_fn=restart_fn, active_fn=active_fn)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._threads.pop(name, None)

    def beat(self, name: str) -> None:
        now = int(self._now_ms())
        with self._lock:
            hb = self._threads.get(name)
            if hb is None:
                return
            hb.last_beat_ms = now
            hb.beats += 1

    # ----------------------------------------------------- supervision

    def poll(self) -> List[str]:
        """Check all heartbeats; restart stalled restartable threads.

        Returns the names restarted this poll.
        """
        now = int(self._now_ms())
        restarted: List[str] = []
        with self._lock:
            candidates = list(self._threads.values())
        stalled = []
        for hb in candidates:
            if hb.active_fn is not None and not hb.active_fn():
                # idle: the stall clock starts when the thread goes active
                hb.last_beat_ms = now
                continue
            if now - hb.last_beat_ms > self.stall_ms and not hb.degraded:
                stalled.append(hb)
        for hb in stalled:
            if hb.restart_fn is None:
                continue
            if now < hb.next_restart_ms:
                continue
            if hb.restarts >= self.max_restarts:
                hb.degraded = True
                logger.error("Thread %s exhausted %d restarts; degraded",
                             hb.name, self.max_restarts)
                continue
            try:
                hb.restart_fn()
                hb.last_error = ""
            except Exception as exc:  # noqa: BLE001 - supervision boundary
                hb.last_error = f"{type(exc).__name__}: {exc}"
                logger.error("Restart of %s failed: %s",
                             hb.name, hb.last_error)
            hb.restarts += 1
            self.total_restarts += 1
            # exponential backoff: 1x, 2x, 4x ... of backoff_ms
            hb.next_restart_ms = now + self.backoff_ms * (
                2 ** (hb.restarts - 1))
            hb.last_beat_ms = now  # grace period after restart
            restarted.append(hb.name)
            logger.warning("Watchdog restarted stalled thread %s "
                           "(restart %d/%d)", hb.name, hb.restarts,
                           self.max_restarts)
        return restarted

    def snapshot(self) -> dict:
        """State for ``/state``: per-thread heartbeat age and health."""
        now = int(self._now_ms())
        with self._lock:
            entries = list(self._threads.values())
        threads = {}
        for hb in entries:
            active = hb.active_fn is None or bool(hb.active_fn())
            threads[hb.name] = {
                "ageMs": max(0, now - hb.last_beat_ms),
                "beats": hb.beats,
                "active": active,
                "stalled": (active
                            and now - hb.last_beat_ms > self.stall_ms),
                "restarts": hb.restarts,
                "restartable": hb.restart_fn is not None,
                "degraded": hb.degraded,
                **({"lastError": hb.last_error} if hb.last_error else {}),
            }
        return {
            "stallMs": self.stall_ms,
            "maxRestarts": self.max_restarts,
            "totalRestarts": self.total_restarts,
            "degraded": any(t["degraded"] for t in threads.values()),
            "threads": threads,
        }
