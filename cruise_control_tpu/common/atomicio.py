"""Crash-safe file primitives shared by the execution journal and the
sample store.

Two durability idioms live here:

* :func:`atomic_replace` — full-file replacement via write-to-temp +
  ``os.replace`` + fsync.  Readers observe either the old or the new
  complete file, never a torn write.
* :func:`fsync_file` / :func:`fsync_dir` — flush helpers for appenders
  that keep a long-lived fd (the journal) and need each record durable
  before acting on it.

Plus :func:`iter_jsonl`, a tolerant JSONL reader that skips corrupt or
truncated lines (a crash mid-append may leave a partial final line; the
write-ahead contract only requires the *prefix* to be replayable).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Iterator, Optional

LOG = logging.getLogger("cruise-control.atomicio")


def fsync_file(f) -> None:
    """Flush user-space buffers and fsync an open file object."""
    f.flush()
    os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename/creation inside it is durable.

    Best-effort: some filesystems/platforms refuse O_RDONLY dir fds.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform dependent
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - platform dependent
        pass
    finally:
        os.close(fd)


def atomic_replace(path: str, data: bytes, fsync: bool = True) -> None:
    """Atomically replace ``path`` with ``data``.

    Writes to a temp file in the same directory, fsyncs it, then
    ``os.replace``s over the target and fsyncs the directory.  A crash
    at any point leaves either the complete old file or the complete
    new file — never a truncated hybrid.
    """
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=directory,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            if fsync:
                fsync_file(f)
        os.replace(tmp, path)
        if fsync:
            fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def iter_jsonl(path: str) -> Iterator[dict]:
    """Yield parsed objects from a JSONL file, skipping corrupt lines.

    A truncated trailing line (crash mid-append) is skipped with a
    warning rather than raised, so any durable prefix replays cleanly.
    Missing file yields nothing.
    """
    if not os.path.exists(path):
        return
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                LOG.warning("Skipping corrupt line %d in %s", lineno, path)
                continue
            if isinstance(obj, dict):
                yield obj


def read_file(path: str) -> Optional[bytes]:
    """Read a whole file, returning ``None`` if it does not exist."""
    try:
        with open(path, "rb") as f:
            return f.read()
    except FileNotFoundError:
        return None
