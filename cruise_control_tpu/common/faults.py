"""Deterministic fault injection: chaos hooks + a faulty ClusterAdapter.

The reference earns its keep by surviving a misbehaving cluster: Executor.java
retries transient admin failures, detects stuck tasks, and contains failures
to the affected tasks. Those paths are untestable without a way to *produce*
the failures on demand, so this module provides two seams:

1. **Chaos hooks** — named injection points (``install_chaos_hook``) that
   production code threads values through via :func:`chaos`. A hook can
   mutate the value (e.g. poison a penalty total with NaN) or raise (e.g.
   simulate a device failure inside an engine). With no hook installed the
   call is an identity pass-through — zero behavior change.

2. **FaultyClusterAdapter** — a wrapper around any ``ClusterAdapter`` that
   injects faults according to a seeded :class:`FaultPlan`: transient
   ``AdapterTransientError``s, call latency, partial-batch submissions,
   reassignments that never converge (stuck tasks), permanently-failing
   partitions, and mid-execution broker/disk death. Every draw comes from
   one ``random.Random(seed)`` stream, so a failing chaos test reproduces
   exactly from its seed.

The wrapper is duck-typed rather than subclassing ``ClusterAdapter`` so this
module stays import-light (common/ must not depend on executor/).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Dict, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# Chaos hooks (analyzer/detector injection points)
# ---------------------------------------------------------------------------

_CHAOS_HOOKS: Dict[str, Callable] = {}


def install_chaos_hook(site: str, fn: Callable) -> None:
    """Install ``fn`` at ``site``. The hook receives the value passed to
    :func:`chaos` and its return value replaces it; raising from the hook
    simulates a failure at that site."""
    _CHAOS_HOOKS[site] = fn


def remove_chaos_hook(site: str) -> None:
    _CHAOS_HOOKS.pop(site, None)


def clear_chaos_hooks() -> None:
    _CHAOS_HOOKS.clear()


def chaos(site: str, value=None):
    """Thread ``value`` through the hook installed at ``site`` (identity
    when none is installed — the production fast path)."""
    fn = _CHAOS_HOOKS.get(site)
    return value if fn is None else fn(value)


# ---------------------------------------------------------------------------
# Adapter fault injection
# ---------------------------------------------------------------------------


class AdapterTransientError(RuntimeError):
    """A retriable cluster-side failure (the admin-API timeout /
    NOT_CONTROLLER / disconnect class the reference retries)."""


class ProcessCrashed(BaseException):
    """Simulated control-plane process death (the ``process_crash``
    simulator fault).

    Deliberately a ``BaseException``: a real crash is not containable,
    so it must blow through every ``except Exception`` containment layer
    (executor task containment, detector fix handling) and reach the
    scenario runner, which then rebuilds the app and exercises restart
    reconciliation."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Seeded schedule of fault events for :class:`FaultyClusterAdapter`.

    Rates are per guarded adapter call and drawn from one seeded RNG stream,
    so a given (plan, call sequence) always injects the same faults.
    """

    seed: int = 0
    #: probability a guarded call raises AdapterTransientError
    transient_error_rate: float = 0.0
    #: cap on back-to-back transient failures of one method — keeps a
    #: retrying caller convergent (set >= executor retries to starve it)
    max_consecutive_transients: int = 2
    #: probability a guarded call sleeps ``latency_s`` first
    latency_rate: float = 0.0
    latency_s: float = 0.0
    #: probability a reassignment batch is submitted only partially
    #: (prefix applied, then AdapterTransientError raised)
    partial_batch_rate: float = 0.0
    #: topic-partitions whose reassignments are accepted but never converge
    #: in current_replicas (the reference's stuck-task condition)
    stuck_partitions: Tuple[str, ...] = ()
    #: topic-partitions whose current_replicas ALWAYS raises (a permanently
    #: unreachable partition — exercises retry exhaustion / containment)
    poisoned_partitions: Tuple[str, ...] = ()
    #: kill this broker once the guarded-call counter passes the threshold
    #: (mid-execution broker death)
    kill_broker_id: Optional[int] = None
    kill_broker_after_calls: Optional[int] = None
    #: fail this (broker, logdir) once the counter passes the threshold
    fail_disk_broker_id: Optional[int] = None
    fail_disk_logdir: str = "/data/d0"
    fail_disk_after_calls: Optional[int] = None
    #: raise :class:`ProcessCrashed` once the guarded-call counter passes
    #: the threshold — simulated control-plane death mid-execution
    process_crash_after_calls: Optional[int] = None


class FaultyClusterAdapter:
    """Wraps any ClusterAdapter and injects the faults a :class:`FaultPlan`
    schedules. Unlisted attributes delegate to the inner adapter, so fake
    helpers (``kill_broker``, ``replicas``, ...) remain reachable."""

    def __init__(self, inner, plan: FaultPlan, sleep=time.sleep):
        self.inner = inner
        self.plan = plan
        self._rng = random.Random(plan.seed)
        self._sleep = sleep
        self.calls = 0
        #: per-kind injection tally (test observability)
        self.injected: Dict[str, int] = {
            "transient": 0, "latency": 0, "partial": 0,
            "broker_death": 0, "disk_death": 0}
        self._consecutive: Dict[str, int] = {}
        self._stuck_submitted: Set[str] = set()
        self._forced_dead: Set[int] = set()
        self._forced_bad_disks: Dict[int, Dict[str, bool]] = {}
        #: invoked once, just before ProcessCrashed is raised — the scenario
        #: runner freezes the execution journal here so the "dead" process
        #: writes nothing more (a real kill would not run finally blocks)
        self.on_crash: Optional[Callable[[], None]] = None
        self._crashed = False

    def set_plan(self, plan: FaultPlan) -> None:
        """Swap the active fault plan. ``self.plan`` is read per guarded
        call, so a scenario runner can retarget faults tick-by-tick (latency
        storms that start and end, a broker death armed mid-run) without
        rebuilding the wrapper — the call counter, consecutive-failure
        state, and injection tallies all carry across the swap."""
        self.plan = plan
        self._rng = random.Random(plan.seed)

    # -- fault machinery --
    def _guard(self, method: str) -> None:
        plan = self.plan
        self.calls += 1
        if (plan.process_crash_after_calls is not None
                and self.calls >= plan.process_crash_after_calls
                and not self._crashed):
            self._crashed = True
            self.injected["process_crash"] = (
                self.injected.get("process_crash", 0) + 1)
            if self.on_crash is not None:
                self.on_crash()
            raise ProcessCrashed(
                f"injected process crash in {method} (call {self.calls})")
        if (plan.kill_broker_after_calls is not None
                and plan.kill_broker_id is not None
                and self.calls >= plan.kill_broker_after_calls
                and plan.kill_broker_id not in self._forced_dead):
            self._forced_dead.add(plan.kill_broker_id)
            self.injected["broker_death"] += 1
            if hasattr(self.inner, "kill_broker"):
                self.inner.kill_broker(plan.kill_broker_id)
        if (plan.fail_disk_after_calls is not None
                and plan.fail_disk_broker_id is not None
                and self.calls >= plan.fail_disk_after_calls
                and plan.fail_disk_broker_id not in self._forced_bad_disks):
            self._forced_bad_disks[plan.fail_disk_broker_id] = {
                plan.fail_disk_logdir: False}
            self.injected["disk_death"] += 1
            if hasattr(self.inner, "fail_disk"):
                self.inner.fail_disk(plan.fail_disk_broker_id,
                                     plan.fail_disk_logdir)
        if plan.latency_rate and self._rng.random() < plan.latency_rate:
            self.injected["latency"] += 1
            self._sleep(plan.latency_s)
        if (plan.transient_error_rate
                and self._rng.random() < plan.transient_error_rate):
            if self._bump(method):
                self.injected["transient"] += 1
                raise AdapterTransientError(
                    f"injected transient failure in {method} "
                    f"(call {self.calls}, seed {plan.seed})")
        self._consecutive[method] = 0

    def _bump(self, key: str) -> bool:
        """True when another consecutive failure of ``key`` is allowed."""
        c = self._consecutive.get(key, 0)
        if c >= self.plan.max_consecutive_transients:
            return False
        self._consecutive[key] = c + 1
        return True

    # -- adapter API --
    def execute_replica_reassignments(self, tasks):
        self._guard("execute_replica_reassignments")
        stuck = set(self.plan.stuck_partitions)
        forward = []
        for t in tasks:
            tp = t.proposal.topic_partition
            if tp in stuck:
                # accepted but never applied: looks in-progress forever
                self._stuck_submitted.add(tp)
            else:
                forward.append(t)
        if (forward and len(forward) > 1 and self.plan.partial_batch_rate
                and self._rng.random() < self.plan.partial_batch_rate
                and self._bump("partial_batch")):
            half = max(1, len(forward) // 2)
            self.inner.execute_replica_reassignments(forward[:half])
            self.injected["partial"] += 1
            raise AdapterTransientError(
                f"injected partial-batch failure: submitted {half} of "
                f"{len(forward)} reassignments (seed {self.plan.seed})")
        self._consecutive["partial_batch"] = 0
        if forward:
            self.inner.execute_replica_reassignments(forward)

    def execute_preferred_leader_elections(self, tasks):
        self._guard("execute_preferred_leader_elections")
        self.inner.execute_preferred_leader_elections(tasks)

    def current_replicas(self, tp):
        if tp in self.plan.poisoned_partitions:
            self.calls += 1
            self.injected["transient"] += 1
            raise AdapterTransientError(
                f"injected permanent failure: current_replicas({tp!r})")
        self._guard("current_replicas")
        return self.inner.current_replicas(tp)

    def current_leader(self, tp):
        self._guard("current_leader")
        return self.inner.current_leader(tp)

    def in_progress_reassignments(self):
        self._guard("in_progress_reassignments")
        return set(self.inner.in_progress_reassignments()) | set(
            self._stuck_submitted)

    def cancel_reassignments(self, tasks):
        self._guard("cancel_reassignments")
        for t in tasks:
            self._stuck_submitted.discard(t.proposal.topic_partition)
        self.inner.cancel_reassignments(tasks)

    def set_broker_throttle_rate(self, broker_ids, rate):
        self._guard("set_broker_throttle_rate")
        self.inner.set_broker_throttle_rate(broker_ids, rate)

    def clear_broker_throttle_rate(self, broker_ids):
        self._guard("clear_broker_throttle_rate")
        self.inner.clear_broker_throttle_rate(broker_ids)

    def set_topic_throttled_replicas(self, topic, leader_entries,
                                     follower_entries):
        self._guard("set_topic_throttled_replicas")
        self.inner.set_topic_throttled_replicas(topic, leader_entries,
                                                follower_entries)

    def clear_topic_throttled_replicas(self, topic):
        self._guard("clear_topic_throttled_replicas")
        self.inner.clear_topic_throttled_replicas(topic)

    def dead_brokers(self):
        self._guard("dead_brokers")
        return set(self.inner.dead_brokers()) | set(self._forced_dead)

    def describe_logdirs(self):
        self._guard("describe_logdirs")
        out = {b: dict(d) for b, d in self.inner.describe_logdirs().items()}
        for b, dirs in self._forced_bad_disks.items():
            out.setdefault(b, {}).update(dirs)
        return out

    def alter_replica_logdirs(self, moves):
        self._guard("alter_replica_logdirs")
        self.inner.alter_replica_logdirs(moves)

    def __getattr__(self, name):
        # fake-adapter helpers (kill_broker, replicas, leaders, ...) and any
        # future adapter surface pass through un-faulted
        return getattr(self.inner, name)
