"""Runtime lock sanitizer: TSan-style acquisition-order + hold-time tracing.

graftlint's static concurrency family (G101-G105, ``tools/graftlint``)
proves lock *discipline* from the AST; this module observes the locks
*running*.  A :class:`TracedLock` wraps a ``threading.Lock``/``RLock`` and
reports every acquire/release to a :class:`LockSanitizer`, which maintains:

- the **acquisition-order graph** — a directed edge ``A -> B`` the first
  time any thread acquires B while holding A.  Acquiring B while holding A
  when the reverse edge ``B -> A`` was ever observed is a **lock-order
  inversion** (the runtime twin of static rule G102): two threads
  interleaving those paths can deadlock.
- per-lock **hold times** — a release after more than ``hold_threshold_s``
  is recorded as a long hold (the runtime twin of G105: something slow ran
  inside a critical section).
- per-lock **acquire counts** — lets a regression test assert that a
  method actually takes the lock it is documented to take.

Opt-in only: production code paths change ONLY when ``GRAFT_TSAN=1`` is in
the environment (:func:`tsan_enabled` — the app instruments its own locks
at startup and dumps a report at shutdown) or when a test wraps objects in
:func:`instrument_locks`.  With the variable unset nothing in this module
is imported by a hot path.

Reports are reproducible: sites are ``file:line`` of the acquiring frame,
and the report dict is JSON-serializable via :meth:`LockSanitizer.dump`.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

#: concrete lock types instrument_locks() looks for on objects
_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))

#: default long-hold threshold (seconds) — generous enough that CI noise
#: never trips it, small enough to catch a blocking RPC under a lock
DEFAULT_HOLD_THRESHOLD_S = 0.25

def tsan_enabled() -> bool:
    """True when GRAFT_TSAN=1: the app instruments its locks at startup."""
    return os.environ.get("GRAFT_TSAN") == "1"


def default_report_path() -> str:
    """Report path for the GRAFT_TSAN=1 app wiring (env-overridable)."""
    return os.environ.get("GRAFT_TSAN_REPORT", "graft_tsan_report.json")


def _call_site() -> str:
    """``file:line`` of the nearest frame outside this module."""
    frame = sys._getframe(1)
    while frame is not None and frame.f_code.co_filename == __file__:
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    fname = os.path.basename(frame.f_code.co_filename)
    return f"{fname}:{frame.f_lineno}"


class _ThreadHeld(threading.local):
    """Per-thread acquisition state: ordered held list + reentrancy depth."""

    def __init__(self) -> None:
        self.order: List[str] = []
        self.depth: Dict[str, int] = {}


class LockSanitizer:
    """Collects acquisition edges, inversions, hold times, acquire counts.

    Thread-safe; its own bookkeeping lock is a plain ``threading.Lock``
    that is never held while user code runs, so the sanitizer cannot
    introduce ordering of its own.
    """

    def __init__(self, hold_threshold_s: float = DEFAULT_HOLD_THRESHOLD_S):
        self.hold_threshold_s = hold_threshold_s
        self._internal = threading.Lock()
        self._held = _ThreadHeld()
        #: (held, acquired) -> "file:line" of the first site observing it
        self.edges: Dict[Tuple[str, str], str] = {}
        self.inversions: List[dict] = []
        self.long_holds: List[dict] = []
        self.acquire_counts: Dict[str, int] = {}
        #: base name -> how many locks claimed it (see unique_name)
        self._name_seq: Dict[str, int] = {}

    def unique_name(self, base: str) -> str:
        """Disambiguate ``base`` across lock instances: the first claimant
        keeps it, later ones get ``base#2``, ``base#3``, ...  Two instances
        of the same class must NOT share a name — the sanitizer would
        misread acquiring one while holding the other as a reentrant
        acquire and record no edge."""
        with self._internal:
            n = self._name_seq.get(base, 0) + 1
            self._name_seq[base] = n
        return base if n == 1 else f"{base}#{n}"

    # -- TracedLock callbacks --

    def note_acquired(self, name: str) -> None:
        held = self._held
        if held.depth.get(name, 0):          # reentrant RLock acquire
            held.depth[name] += 1
            return
        site = _call_site()
        with self._internal:
            self.acquire_counts[name] = self.acquire_counts.get(name, 0) + 1
            for h in held.order:
                if (name, h) in self.edges:
                    self.inversions.append({
                        "held": h, "acquiring": name,
                        "firstOrderSite": self.edges[(name, h)],
                        "site": site,
                        "thread": threading.current_thread().name,
                    })
                self.edges.setdefault((h, name), site)
        held.order.append(name)
        held.depth[name] = 1

    def note_released(self, name: str, held_for_s: float) -> None:
        held = self._held
        d = held.depth.get(name, 0)
        if d > 1:
            held.depth[name] = d - 1
            return
        held.depth.pop(name, None)
        if name in held.order:
            # remove the most recent occurrence (release order may not be
            # strict LIFO)
            for i in range(len(held.order) - 1, -1, -1):
                if held.order[i] == name:
                    del held.order[i]
                    break
        if held_for_s > self.hold_threshold_s:
            with self._internal:
                self.long_holds.append({
                    "lock": name, "heldForS": round(held_for_s, 6),
                    "site": _call_site(),
                    "thread": threading.current_thread().name,
                })

    # -- reporting --

    def report(self) -> dict:
        with self._internal:
            return {
                "inversions": list(self.inversions),
                "longHolds": list(self.long_holds),
                "acquireCounts": dict(self.acquire_counts),
                "edges": [{"held": a, "acquired": b, "site": s}
                          for (a, b), s in sorted(self.edges.items())],
            }

    def dump(self, path: Optional[str] = None) -> str:
        path = path or default_report_path()
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.report(), fh, indent=1)
            fh.write("\n")
        return path

    def check(self) -> None:
        """Raise if any lock-order inversion was observed."""
        with self._internal:
            inversions = list(self.inversions)
        if inversions:
            lines = [f"  {i['held']} -> {i['acquiring']} at {i['site']} "
                     f"(opposite order first seen at {i['firstOrderSite']})"
                     for i in inversions]
            raise AssertionError(
                "lock-order inversion(s) observed:\n" + "\n".join(lines))


class TracedLock:
    """Wraps a Lock/RLock, reporting acquire/release to a LockSanitizer.

    Drop-in: supports the context-manager protocol, ``acquire`` with
    ``blocking``/``timeout``, ``release``, and proxies anything else
    (``locked``, RLock internals) to the wrapped lock.
    """

    def __init__(self, lock, name: str, sanitizer: LockSanitizer):
        self._lock = lock
        self._name = name
        self._sanitizer = sanitizer
        self._acquired_at = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._sanitizer.note_acquired(self._name)
            state = self._acquired_at
            depth = getattr(state, "depth", 0)
            if depth == 0:
                # stamp only the OUTERMOST acquire — a reentrant RLock
                # acquire must not reset the clock, or holds spanning
                # reentrant sections get measured from the innermost one
                state.t = time.monotonic()
            state.depth = depth + 1
        return ok

    def release(self) -> None:
        state = self._acquired_at
        depth = getattr(state, "depth", 1)
        state.depth = depth - 1
        # held_for only matters on the final release (the sanitizer ignores
        # reentrant ones), measured from the outermost acquire
        held_for = (time.monotonic() - getattr(state, "t", time.monotonic())
                    if state.depth == 0 else 0.0)
        self._lock.release()
        self._sanitizer.note_released(self._name, held_for)

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, attr):
        return getattr(self._lock, attr)


def _instrument_object(obj, sanitizer: LockSanitizer) -> List[Tuple[str, object]]:
    """Replace every Lock/RLock attribute of ``obj`` with a TracedLock.
    Returns the (attr, original) pairs for restoration."""
    replaced: List[Tuple[str, object]] = []
    for attr, value in list(vars(obj).items()):
        if isinstance(value, _LOCK_TYPES):
            name = sanitizer.unique_name(f"{type(obj).__name__}.{attr}")
            setattr(obj, attr, TracedLock(value, name, sanitizer))
            replaced.append((attr, value))
    return replaced


def install_tracing(*objects, sanitizer: Optional[LockSanitizer] = None,
                    hold_threshold_s: float = DEFAULT_HOLD_THRESHOLD_S
                    ) -> LockSanitizer:
    """Permanently instrument ``objects``' lock attributes (GRAFT_TSAN=1
    app wiring — restoration is pointless when the process is exiting
    anyway).  Tests should prefer :func:`instrument_locks`."""
    san = sanitizer or LockSanitizer(hold_threshold_s=hold_threshold_s)
    for obj in objects:
        _instrument_object(obj, san)
    return san


@contextlib.contextmanager
def instrument_locks(*objects, sanitizer: Optional[LockSanitizer] = None,
                     hold_threshold_s: float = DEFAULT_HOLD_THRESHOLD_S
                     ) -> Iterator[LockSanitizer]:
    """``with instrument_locks(app, app.executor) as san:`` — every
    Lock/RLock attribute of the given objects is traced inside the scope
    and restored on exit.  Restoring while another thread still holds a
    TracedLock is safe: that thread releases through its own reference."""
    san = sanitizer or LockSanitizer(hold_threshold_s=hold_threshold_s)
    restore: List[Tuple[object, str, object]] = []
    for obj in objects:
        for attr, original in _instrument_object(obj, san):
            restore.append((obj, attr, original))
    try:
        yield san
    finally:
        for obj, attr, original in restore:
            setattr(obj, attr, original)
