"""Kafka-style typed configuration framework.

Rebuild of the core config machinery
(``cruise-control-core/.../common/config/ConfigDef.java`` — typed defines
with defaults, validators, importance, docs — and ``AbstractConfig.java``)
plus the service's config surface (``config/KafkaCruiseControlConfig.java``,
the keys that drive behavior in this framework). Reads Java-style
``.properties`` files or plain dicts.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Dict, List, Optional


class ConfigType(enum.Enum):
    BOOLEAN = "boolean"
    STRING = "string"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    LIST = "list"
    CLASS = "class"


class Importance(enum.Enum):
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"


class ConfigException(ValueError):
    pass


_NO_DEFAULT = object()


def at_least(n):
    def check(name, v):
        if v < n:
            raise ConfigException(f"{name} must be >= {n}, got {v}")
    return check


def between(lo, hi):
    def check(name, v):
        if not (lo <= v <= hi):
            raise ConfigException(f"{name} must be in [{lo}, {hi}], got {v}")
    return check


@dataclasses.dataclass
class ConfigKey:
    name: str
    type: ConfigType
    default: Any
    importance: Importance
    doc: str
    validator: Optional[Callable[[str, Any], None]] = None

    @property
    def has_default(self) -> bool:
        return self.default is not _NO_DEFAULT


class ConfigDef:
    """Typed schema: define keys, then parse a raw mapping."""

    def __init__(self):
        self._keys: Dict[str, ConfigKey] = {}

    def define(self, name: str, ctype: ConfigType, default: Any = _NO_DEFAULT,
               importance: Importance = Importance.MEDIUM, doc: str = "",
               validator: Optional[Callable] = None) -> "ConfigDef":
        if name in self._keys:
            raise ConfigException(f"duplicate config key {name}")
        self._keys[name] = ConfigKey(name, ctype, default, importance, doc,
                                     validator)
        return self

    @property
    def keys(self) -> Dict[str, ConfigKey]:
        return dict(self._keys)

    def parse_value(self, key: ConfigKey, raw: Any) -> Any:
        t = key.type
        try:
            if raw is None:
                return None
            if t == ConfigType.BOOLEAN:
                if isinstance(raw, bool):
                    return raw
                s = str(raw).strip().lower()
                if s in ("true", "1", "yes"):
                    return True
                if s in ("false", "0", "no"):
                    return False
                raise ConfigException(f"{key.name}: not a boolean: {raw!r}")
            if t in (ConfigType.INT, ConfigType.LONG):
                return int(str(raw).strip())
            if t == ConfigType.DOUBLE:
                return float(str(raw).strip())
            if t == ConfigType.LIST:
                if isinstance(raw, (list, tuple)):
                    return list(raw)
                s = str(raw).strip()
                return [x.strip() for x in s.split(",") if x.strip()] if s else []
            return str(raw)
        except ConfigException:
            raise
        except (TypeError, ValueError) as e:
            raise ConfigException(f"{key.name}: cannot parse {raw!r} as "
                                  f"{t.value}: {e}")

    def parse(self, raw: Dict[str, Any], allow_unknown: bool = True
              ) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, key in self._keys.items():
            if name in raw:
                v = self.parse_value(key, raw[name])
            elif key.has_default:
                v = key.default
            else:
                raise ConfigException(f"missing required config {name}")
            if key.validator is not None and v is not None:
                key.validator(name, v)
            out[name] = v
        if not allow_unknown:
            unknown = set(raw) - set(self._keys)
            if unknown:
                raise ConfigException(f"unknown configs: {sorted(unknown)}")
        return out


def resolve_pluggable(name: str, registry: Dict[str, Any],
                      base: Optional[type] = None):
    """Resolve a pluggable-class config value (Pluggable-Components.md
    parity): a bare name looks up the SPI's registry; a dotted path imports
    the attribute, so deployments can select ANY class without registering
    it first. ``base`` (when given) must be a superclass of the result."""
    if name in registry:
        out = registry[name]
    elif "." in name:
        import importlib
        mod, _, attr = name.rpartition(".")
        out = getattr(importlib.import_module(mod), attr)
    else:
        raise ValueError(
            f"unknown pluggable class {name!r}; register it or use a "
            f"dotted import path (have: {sorted(registry)})")
    if base is not None and isinstance(out, type) and not issubclass(out, base):
        raise ValueError(f"{name} must subclass {base.__name__}")
    return out


def load_properties(path: str) -> Dict[str, str]:
    """Minimal Java .properties reader (the boot-file format)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or line.startswith("!"):
                continue
            for sep in ("=", ":"):
                if sep in line:
                    k, _, v = line.partition(sep)
                    out[k.strip()] = v.strip()
                    break
    return out


# ---------------------------------------------------------------------------
# Service config (KafkaCruiseControlConfig.java keys that drive behavior)
# ---------------------------------------------------------------------------

def _service_config_def() -> ConfigDef:
    from cruise_control_tpu.analyzer import goals as G
    d = ConfigDef()
    T, I = ConfigType, Importance
    # goals (KafkaCruiseControlConfig.java:1521-1570)
    d.define("goals", T.LIST, list(G.DEFAULT_GOALS) + list(G.EXTRA_GOALS),
             I.HIGH, "Supported goals in priority order.")
    d.define("default.goals", T.LIST, list(G.DEFAULT_GOALS), I.HIGH,
             "Goals used when a request names none; also precompute goals.")
    d.define("hard.goals", T.LIST, sorted(G.HARD_GOALS), I.HIGH, "Hard goals.")
    d.define("self.healing.goals", T.LIST, [], I.HIGH,
             "Goals for self-healing; empty = default.goals.")
    d.define("anomaly.detection.goals", T.LIST,
             list(G.ANOMALY_DETECTION_GOALS), I.MEDIUM,
             "Goals the goal-violation detector checks.")
    d.define("intra.broker.goals", T.LIST,
             ["IntraBrokerDiskCapacityGoal", "IntraBrokerDiskUsageDistributionGoal"],
             I.HIGH, "Intra-broker (JBOD) goals.")
    # balancing constraint (BalancingConstraint.java defaults)
    for res_name in ("cpu", "disk", "network.inbound", "network.outbound"):
        d.define(f"{res_name}.balance.threshold", T.DOUBLE, 1.10, I.HIGH,
                 f"Balance band multiplier for {res_name}.", at_least(1.0))
        d.define(f"{res_name}.capacity.threshold", T.DOUBLE, 0.8, I.HIGH,
                 f"Capacity threshold for {res_name}.", between(0.0, 1.0))
        d.define(f"{res_name}.low.utilization.threshold", T.DOUBLE, 0.0,
                 I.LOW, f"Low-utilization threshold for {res_name}.",
                 between(0.0, 1.0))
    d.define("max.replicas.per.broker", T.LONG, 10_000, I.MEDIUM,
             "ReplicaCapacityGoal limit.", at_least(1))
    d.define("replica.count.balance.threshold", T.DOUBLE, 1.10, I.LOW,
             "Replica-count balance band.", at_least(1.0))
    d.define("leader.replica.count.balance.threshold", T.DOUBLE, 1.10, I.LOW,
             "Leader-replica-count balance band.", at_least(1.0))
    d.define("topic.replica.count.balance.threshold", T.DOUBLE, 3.00, I.LOW,
             "Per-topic replica balance band.", at_least(1.0))
    d.define("goal.violation.distribution.threshold.multiplier", T.DOUBLE,
             1.0, I.MEDIUM, "Detector relaxation multiplier.", at_least(1.0))
    d.define("goal.balancedness.priority.weight", T.DOUBLE, 1.1, I.LOW,
             "Balancedness priority weight.")
    d.define("goal.balancedness.strictness.weight", T.DOUBLE, 1.5, I.LOW,
             "Balancedness strictness weight.")
    # monitor
    d.define("num.partition.metrics.windows", T.INT, 5, I.HIGH,
             "Number of load windows.", at_least(1))
    d.define("partition.metrics.window.ms", T.LONG, 300_000, I.HIGH,
             "Window length ms.", at_least(1))
    d.define("min.samples.per.partition.metrics.window", T.INT, 1, I.MEDIUM,
             "Min samples for a valid window.", at_least(1))
    d.define("max.allowed.extrapolations.per.partition", T.INT, 5, I.LOW,
             "Max extrapolated windows per partition.", at_least(0))
    d.define("num.metric.fetchers", T.INT, 1, I.MEDIUM,
             "Parallel metric fetcher tasks; partitions are assigned "
             "round-robin across fetchers (MetricFetcherManager).",
             at_least(1))
    d.define("metric.sampling.interval.ms", T.LONG, 60_000, I.MEDIUM,
             "Sampler period.", at_least(1))
    d.define("min.valid.partition.ratio", T.DOUBLE, 0.95, I.MEDIUM,
             "Monitored-partition completeness ratio.", between(0.0, 1.0))
    d.define("broker.capacity.config.resolver.class", T.CLASS,
             "FileCapacityResolver", I.MEDIUM, "Capacity resolver class.")
    d.define("capacity.config.file", T.STRING, "config/capacity.json",
             I.MEDIUM, "Capacity file path.")
    d.define("sample.store.class", T.CLASS, "FileSampleStore", I.LOW,
             "Sample store implementation "
             "(NoopSampleStore | FileSampleStore | KafkaSampleStore).")
    d.define("sample.store.dir", T.STRING, "", I.LOW,
             "FileSampleStore directory ('' = disabled).")
    # KafkaSampleStore topic bootstrap (KafkaSampleStore.java:85)
    d.define("partition.metric.sample.store.topic", T.STRING,
             "__KafkaCruiseControlPartitionMetricSamples", I.LOW,
             "KafkaSampleStore partition-sample topic.")
    d.define("broker.metric.sample.store.topic", T.STRING,
             "__KafkaCruiseControlModelTrainingSamples", I.LOW,
             "KafkaSampleStore broker (model-training) sample topic.")
    d.define("sample.store.topic.replication.factor", T.INT, 2, I.LOW,
             "Replication factor for the sample store topics.", at_least(1))
    d.define("partition.sample.store.topic.partition.count", T.INT, 32,
             I.LOW, "Partition count of the partition-sample topic.",
             at_least(1))
    d.define("broker.sample.store.topic.partition.count", T.INT, 32,
             I.LOW, "Partition count of the broker-sample topic.",
             at_least(1))
    d.define("partition.sample.store.topic.retention.time.ms", T.LONG,
             14 * 24 * 3600 * 1000, I.LOW,
             "Retention of the sample store topics.", at_least(1))
    d.define("num.sample.loading.threads", T.INT, 8, I.LOW,
             "Sample replay deserialization parallelism on startup.",
             at_least(1))
    d.define("sample.store.bootstrap.servers", T.STRING, "", I.LOW,
             "Kafka cluster for the sample store topics "
             "('' = use bootstrap.servers).")
    d.define("metric.sampler.class", T.CLASS, "SyntheticLoadSampler", I.HIGH,
             "MetricSampler implementation.")
    d.define("partition.metric.sample.aggregator.completeness.cache.size",
             T.INT, 5, I.LOW,
             "Cached completeness computations in the partition aggregator.",
             at_least(0))
    d.define("broker.metric.sample.aggregator.completeness.cache.size",
             T.INT, 5, I.LOW,
             "Cached completeness computations in the broker aggregator.",
             at_least(0))
    d.define("sampling.allow.cpu.capacity.estimation", T.BOOLEAN, True,
             I.LOW, "Permit estimated broker CPU capacities during "
             "sampling; when false, model builds fail if any broker "
             "capacity had to be estimated.")
    # analyzer / optimizer engine
    d.define("proposal.expiration.ms", T.LONG, 900_000, I.MEDIUM,
             "Cached proposal staleness bound.", at_least(0))
    d.define("num.proposal.precompute.threads", T.INT, 1, I.LOW,
             "Proposal precompute workers.", at_least(0))
    d.define("proposal.cache.dirty.mass.threshold", T.DOUBLE, 0.5, I.MEDIUM,
             "Incremental tick path: largest fraction of monitored "
             "partitions allowed dirty for a precompute tick to revalidate "
             "the cached proposal with a goal rescore instead of a full "
             "anneal. 0 disables the incremental path.", between(0.0, 1.0))
    d.define("optimizer.engine", T.STRING, "auto", I.HIGH,
             "auto | greedy | anneal")
    d.define("optimizer.bucketing", T.STRING, "auto", I.MEDIUM,
             "Shape-bucketed model padding: auto | on | off. Padding the "
             "broker/partition axes to geometric bucket sizes lets cluster "
             "drift within a bucket reuse compiled programs (no retrace); "
             "proposals are identical either way. auto engages it for "
             "large single-device anneal runs (see "
             "analyzer.optimizer.engages_bucketing).")
    d.define("optimizer.mesh.enable", T.BOOLEAN, False, I.MEDIUM,
             "Shard the optimizer over a device mesh: chain-axis data "
             "parallelism for the parallel-tempering anneal plus "
             "replica-axis sharded exact rescore. Off (default) runs "
             "single-device, bit-identical to the unmeshed path.")
    d.define("optimizer.mesh.devices", T.INT, 0, I.MEDIUM,
             "Device count for the optimizer mesh; 0 = all visible "
             "devices. Requests beyond the visible count clamp with a "
             "warning. Ignored unless optimizer.mesh.enable.", at_least(0))
    d.define("anneal.num.chains", T.INT, 32, I.MEDIUM,
             "Parallel-tempering chains.", at_least(1))
    d.define("anneal.steps", T.INT, 2048, I.MEDIUM, "Annealer steps.",
             at_least(1))
    d.define("anneal.tries.move", T.INT, 32, I.LOW, "Move proposals/step.")
    d.define("anneal.tries.lead", T.INT, 8, I.LOW, "Leadership proposals/step.")
    d.define("anneal.tries.swap", T.INT, 16, I.LOW, "Swap proposals/step.")
    d.define("anneal.warm.fraction", T.DOUBLE, 0.0, I.MEDIUM,
             "Fraction of PT chains seeded from the previous accepted "
             "assignment on the cached default-goal computation (the rest "
             "stay cold for exploration). Engages only when the monitor's "
             "structural digest is unchanged since that assignment was "
             "accepted. 0 (the default) disables warm starts — chain inits "
             "then take the exact historical path; steady-state services "
             "should enable it (0.5 is the benched setting).",
             between(0.0, 1.0))
    d.define("anneal.telemetry.enable", T.BOOLEAN, False, I.LOW,
             "Collect per-ladder-slot acceptance rates, exchange rates and "
             "the best-energy descent curve as device-side aggregates in "
             "the annealer's scan carry (one extra fetch per run, zero "
             "retraces). Off (the default) runs the exact historical "
             "program — bit-identical proposals.")
    # observability (graftscope: docs/observability.md)
    d.define("obs.tracing.enable", T.BOOLEAN, False, I.LOW,
             "Span tracing of the control loop (tick stages, executor task "
             "lifecycle, recovery) into a bounded in-memory ring exported "
             "as Chrome-trace JSON. Disabled, the tracer is a shared no-op "
             "and behavior is bit-identical.")
    d.define("obs.tracing.buffer.spans", T.INT, 4096, I.LOW,
             "Capacity of the tracer's completed-span ring buffer; the "
             "oldest spans are dropped (and counted) past it.", at_least(1))
    d.define("obs.observatory.enable", T.BOOLEAN, True, I.LOW,
             "Always-on compile/retrace observatory: per-function jit "
             "trace/compile counts and compile wall time, steady-state "
             "retrace accounting and transfer-guard violation counters, "
             "surfaced in the metrics registry and GET /observatory.")
    d.define("obs.provenance.enable", T.BOOLEAN, False, I.LOW,
             "Per-move goal attribution on every proposal computation: one "
             "batched device evaluation over the decoded diff stamps each "
             "move's per-goal penalty delta onto the result (GET /explain). "
             "Off (the default) runs the exact historical program — "
             "bit-identical proposals.")
    d.define("obs.flightrec.enable", T.BOOLEAN, True, I.LOW,
             "Tick flight recorder: a bounded ring of decision records "
             "(inputs digest, dirty-mask summary, goal verdicts, engine/"
             "heal/decode path, fallback reason, top attributed moves, "
             "anomaly-detector decisions) exported as canonical JSONL via "
             "GET /flightrecorder. Pure observation on the injected clock; "
             "same-seed simulator runs export byte-identical logs.")
    d.define("obs.flightrec.ticks", T.INT, 256, I.LOW,
             "Capacity of the flight-recorder ring; the oldest records are "
             "dropped (and counted) past it.", at_least(1))
    d.define("obs.flightrec.top.moves", T.INT, 8, I.LOW,
             "How many of the most impactful attributed moves each tick "
             "record keeps (requires obs.provenance.enable).", at_least(0))
    d.define("obs.costmodel.enable", T.BOOLEAN, False, I.LOW,
             "graftwatch cost observatory: per-compiled-program cost/"
             "memory ledger, live device-buffer census, backend memory "
             "stats sampling and the bucket-ladder headroom forecaster "
             "(GET /headroom). Off (the default) the capture seam is one "
             "flag check — bit-identical proposals.")
    d.define("obs.costmodel.deep", T.BOOLEAN, False, I.LOW,
             "AOT-lower each newly captured program signature to pull "
             "XLA cost_analysis (flops, bytes accessed) and "
             "memory_analysis (arg/output/temp bytes) into the ledger. "
             "Doubles warmup compile work for the captured programs; "
             "steady state is untouched (capture memoizes signatures).")
    d.define("obs.costmodel.sample.interval.ms", T.LONG, 10_000, I.LOW,
             "Minimum spacing between device-memory samples (live-array "
             "census + backend memory_stats) on the injected clock.",
             at_least(1))
    d.define("obs.costmodel.hbm.limit.bytes", T.LONG, None, I.LOW,
             "Device memory budget for the headroom forecaster when the "
             "backend reports no bytes_limit (CPU; TPU/GPU report their "
             "own). None leaves headroom/fit verdicts null.")
    d.define("healthwatch.enable", T.BOOLEAN, False, I.LOW,
             "graftwatch health watch: per-tick health vectors in a "
             "device ring with vmapped fast/slow burn-rate alerting "
             "(GET /alerts), alert decisions audited to the flight "
             "recorder and fired through the anomaly notifier. Off (the "
             "default) the tick path is bit-identical.")
    d.define("healthwatch.ring.ticks", T.INT, 512, I.LOW,
             "Capacity of the device health ring (also the longest "
             "usable burn window).", at_least(1))
    d.define("healthwatch.tick.slo.ms", T.LONG, 30_000, I.LOW,
             "Tick wall-time SLO: ticks slower than this count as "
             "latency breaches in the health vector (matches the "
             "simulator SLOBudget default).", at_least(1))
    d.define("healthwatch.error.budget", T.DOUBLE, 0.02, I.LOW,
             "Allowed bad-tick fraction for the stock alert rules; burn "
             "rate = bad fraction / budget.", between(0.0, 1.0))
    d.define("healthwatch.fast.window.ticks", T.INT, 8, I.LOW,
             "Fast burn window (ticks) for the stock rules — fires "
             "quickly on sharp degradation.", at_least(1))
    d.define("healthwatch.slow.window.ticks", T.INT, 32, I.LOW,
             "Slow burn window (ticks) for the stock rules — the "
             "sustained-burn confirmation that keeps blips from paging.",
             at_least(1))
    d.define("healthwatch.fast.burn", T.DOUBLE, 10.0, I.LOW,
             "Burn-rate threshold over the fast window.", at_least(0.0))
    d.define("healthwatch.slow.burn", T.DOUBLE, 2.5, I.LOW,
             "Burn-rate threshold over the slow window.", at_least(0.0))
    d.define("healthwatch.rules", T.STRING, None, I.LOW,
             "JSON list of AlertRule overrides/additions (keys: name, "
             "signal, threshold, budget, fastWindowTicks, "
             "slowWindowTicks, fastBurn, slowBurn); same-name entries "
             "replace the stock rules.")
    # executor (Executor.java config surface)
    d.define("num.concurrent.partition.movements.per.broker", T.INT, 5,
             I.MEDIUM, "Per-broker reassignment concurrency.", at_least(1))
    d.define("num.concurrent.leader.movements", T.INT, 1000, I.MEDIUM,
             "Leadership movement batch size.", at_least(1))
    d.define("execution.progress.check.interval.ms", T.LONG, 10_000, I.LOW,
             "Executor poll period.", at_least(1))
    d.define("default.replication.throttle", T.LONG, None, I.MEDIUM,
             "Default replication throttle bytes/sec (None = off).")
    d.define("max.num.cluster.movements", T.INT, 1250, I.MEDIUM,
             "Cap on simultaneous movements.", at_least(1))
    d.define("executor.adapter.retries", T.INT, 3, I.MEDIUM,
             "Retries per adapter call before the affected task is marked "
             "DEAD (0 = fail fast).", at_least(0))
    d.define("executor.adapter.retry.backoff.ms", T.LONG, 100, I.LOW,
             "Initial adapter-retry backoff; doubles per attempt with "
             "jitter.", at_least(1))
    d.define("executor.adapter.retry.backoff.max.ms", T.LONG, 10_000, I.LOW,
             "Upper bound on the adapter-retry backoff.", at_least(1))
    d.define("executor.task.stuck.deadline.ms", T.LONG, 300_000, I.MEDIUM,
             "Abort an in-flight task whose cluster-observed progress has "
             "not changed for this long.", at_least(1))
    d.define("executor.journal.path", T.STRING, "", I.MEDIUM,
             "Write-ahead execution journal file (JSONL). Empty disables "
             "journaling and restart reconciliation.")
    d.define("executor.journal.fsync", T.BOOLEAN, True, I.LOW,
             "fsync the journal on every append (and its epoch sidecar on "
             "every replace). Disable only for tests/benchmarks.")
    d.define("executor.journal.epoch.path", T.STRING, "", I.LOW,
             "Override for the epoch/lease sidecar location (empty = "
             "'<executor.journal.path>.epoch'). A warm standby points its "
             "tailed replica journal at the leader's sidecar on shared "
             "storage so both incarnations fence against the same leased "
             "claim.")
    d.define("executor.journal.compact.records", T.LONG, 0, I.LOW,
             "Auto-compact the execution journal (fold history into one "
             "checkpoint record and truncate behind it) whenever the entry "
             "count reaches this. 0 disables compaction.", at_least(0))
    d.define("replication.lease.ms", T.LONG, 30_000, I.MEDIUM,
             "Leadership lease duration stamped into the epoch sidecar. A "
             "standby may only take over (advancing the epoch, fencing the "
             "ex-leader) once the expiry passes on its clock.", at_least(1))
    d.define("replication.lease.renew.ms", T.LONG, 10_000, I.LOW,
             "How often the leader re-stamps the lease expiry (atomic "
             "sidecar replace, same epoch). Must be well under "
             "replication.lease.ms to ride out transient stalls.",
             at_least(1))
    d.define("watchdog.stall.ms", T.LONG, 30_000, I.MEDIUM,
             "A background thread whose heartbeat is older than this is "
             "considered stalled.", at_least(1))
    d.define("watchdog.max.restarts", T.INT, 3, I.LOW,
             "Restart budget per supervised thread; past it the thread is "
             "reported degraded instead.", at_least(0))
    d.define("watchdog.backoff.ms", T.LONG, 1_000, I.LOW,
             "Initial restart backoff; doubles per restart.", at_least(1))
    d.define("watchdog.interval.ms", T.LONG, 5_000, I.LOW,
             "Watchdog poll period. 0 disables the monitor thread (the "
             "scenario simulator polls explicitly instead).", at_least(0))
    d.define("logdir.response.timeout.ms", T.LONG, 10_000, I.LOW,
             "DescribeLogDirs request timeout.", at_least(1))
    d.define("inter.broker.replica.movement.rate.alerting.threshold",
             T.DOUBLE, 0.1, I.LOW,
             "Alert when the achieved inter-broker movement rate (MB/s) "
             "falls below this.", at_least(0.0))
    d.define("intra.broker.replica.movement.rate.alerting.threshold",
             T.DOUBLE, 0.2, I.LOW,
             "Alert when the achieved intra-broker movement rate (MB/s) "
             "falls below this.", at_least(0.0))
    # anomaly detector
    d.define("anomaly.detection.interval.ms", T.LONG, 300_000, I.MEDIUM,
             "Detector sweep period.", at_least(1))
    d.define("anomaly.notifier.class", T.CLASS, "SelfHealingNotifier",
             I.LOW, "AnomalyNotifier implementation.")
    d.define("self.healing.enabled", T.BOOLEAN, False, I.HIGH,
             "Global self-healing master switch.")
    d.define("broker.failure.alert.threshold.ms", T.LONG, 900_000, I.MEDIUM,
             "Broker-failure alert delay.")
    d.define("broker.failure.self.healing.threshold.ms", T.LONG, 1_800_000,
             I.MEDIUM, "Broker-failure fix delay.")
    d.define("failed.brokers.file.path", T.STRING, "failed_brokers.json",
             I.LOW, "Persisted failed-broker record.")
    d.define("failed.brokers.zk.path", T.STRING, "", I.LOW,
             "Reference-compat alias for the failed-broker record location; "
             "when set it overrides failed.brokers.file.path (this rebuild "
             "persists to a file, not ZooKeeper).")
    # pluggable anomaly classes (AnomalyDetectorConfig *_CLASS_CONFIG):
    # names resolve through detector.ANOMALY_CLASS_REGISTRY, so a deployment
    # can register a subclass and select it here
    d.define("broker.failures.class", T.CLASS, "BrokerFailures", I.LOW,
             "Broker-failure anomaly payload class.")
    d.define("goal.violations.class", T.CLASS, "GoalViolations", I.LOW,
             "Goal-violation anomaly payload class.")
    d.define("disk.failures.class", T.CLASS, "DiskFailures", I.LOW,
             "Disk-failure anomaly payload class.")
    d.define("metric.anomaly.class", T.CLASS, "KafkaMetricAnomaly", I.LOW,
             "Metric anomaly payload class.")
    d.define("use.linear.regression.model", T.BOOLEAN, False, I.MEDIUM,
             "Use the trained linear-regression CPU model for partition CPU "
             "estimation after TRAIN completes.")
    d.define("anomaly.detection.recheck.delay.ms", T.LONG, None, I.LOW,
             "Delay before re-checking an anomaly deferred by an ongoing "
             "execution (None = anomaly.detection.interval.ms).")
    d.define("metric.anomaly.percentile.upper.threshold", T.DOUBLE, 95.0,
             I.LOW, "Percentile above which a broker metric is anomalous "
             "(PercentileMetricAnomalyFinder).")
    d.define("metric.anomaly.percentile.lower.threshold", T.DOUBLE, 2.0,
             I.LOW, "Percentile below which a broker metric is anomalous.")
    d.define("slow.broker.demotion.score", T.INT, 3, I.LOW,
             "Consecutive slow detections before demotion "
             "(SlowBrokerFinder escalation).")
    d.define("slow.broker.decommission.score", T.INT, 6, I.LOW,
             "Consecutive slow detections before removal.")
    # provisioner (provision/ProvisionRecommendation semantics): rightsizing
    # grid bounds + the capacity headroom a recommendation must preserve
    d.define("provision.headroom.margin", T.DOUBLE, 0.1, I.MEDIUM,
             "Fraction of thresholded capacity the rightsizer keeps free "
             "when judging a broker count feasible (0 = size to the limit).",
             between(0.0, 1.0))
    d.define("provision.max.added.brokers", T.INT, 16, I.MEDIUM,
             "Largest broker-addition scenario in the rightsizing grid.",
             at_least(1))
    d.define("provision.max.removed.brokers", T.INT, 8, I.MEDIUM,
             "Largest broker-removal scenario in the rightsizing grid "
             "(0 disables over-provisioning detection).", at_least(0))
    # webserver (KafkaCruiseControlMain/WebServerConfig)
    d.define("webserver.http.port", T.INT, 9090, I.HIGH, "REST port.")
    d.define("webserver.http.address", T.STRING, "127.0.0.1", I.HIGH,
             "REST bind address.")
    d.define("webserver.api.urlprefix", T.STRING, "/kafkacruisecontrol",
             I.LOW, "API prefix.")
    d.define("webserver.session.maxExpiryPeriodMs", T.LONG, 60_000, I.LOW,
             "Session expiry.")
    d.define("max.active.user.tasks", T.INT, 25, I.LOW,
             "Active user task cap.")
    d.define("completed.user.task.retention.time.ms", T.LONG, 86_400_000,
             I.LOW, "Completed task retention.")
    d.define("two.step.verification.enabled", T.BOOLEAN, False, I.MEDIUM,
             "Purgatory 2-step review for POSTs.")
    d.define("bootstrap.servers", T.STRING, "", I.HIGH,
             "Kafka bootstrap servers (Kafka-backed deployments).")
    d.define("zookeeper.connect", T.STRING, "", I.MEDIUM,
             "ZooKeeper connect string (legacy deployments). "
             "Reference-compat: this rebuild talks to Kafka via the admin "
             "adapter, not ZooKeeper; accepted for config-file parity, "
             "no effect.")
    # -- CPU estimation model (ModelParameters.java:21-29) ------------------
    d.define("leader.network.inbound.weight.for.cpu.util", T.DOUBLE, 0.7,
             I.LOW, "Static CPU attribution weight of leader bytes-in.")
    d.define("leader.network.outbound.weight.for.cpu.util", T.DOUBLE, 0.15,
             I.LOW, "Static CPU attribution weight of leader bytes-out.")
    d.define("follower.network.inbound.weight.for.cpu.util", T.DOUBLE, 0.15,
             I.LOW, "Static CPU attribution weight of follower bytes-in.")
    d.define("linear.regression.model.cpu.util.bucket.size", T.INT, 5, I.LOW,
             "CPU-utilization bucket width (percent) for LR training.")
    d.define("linear.regression.model.min.num.cpu.util.buckets", T.INT, 5,
             I.LOW, "Distinct CPU buckets required before the LR model "
             "is considered trained.")
    d.define("linear.regression.model.required.samples.per.bucket", T.INT,
             10, I.LOW, "Samples per CPU bucket required for LR training.")
    # -- broker-metric windows (separate aggregator) ------------------------
    d.define("num.broker.metrics.windows", T.INT, None, I.MEDIUM,
             "Broker metric sample aggregator window count "
             "(default: num.partition.metrics.windows).")
    d.define("broker.metrics.window.ms", T.LONG, None, I.MEDIUM,
             "Broker metric aggregation window span "
             "(default: partition.metrics.window.ms).")
    d.define("min.samples.per.broker.metrics.window", T.INT, None, I.LOW,
             "Minimum samples per broker window "
             "(default: min.samples.per.partition.metrics.window).")
    d.define("max.allowed.extrapolations.per.broker", T.INT, None, I.LOW,
             "Max extrapolations per broker entity "
             "(default: max.allowed.extrapolations.per.partition).")
    # -- per-detector schedules (AnomalyDetector.java:167-180) --------------
    d.define("goal.violation.detection.interval.ms", T.LONG, None, I.LOW,
             "Goal-violation sweep interval; default anomaly interval.")
    d.define("metric.anomaly.detection.interval.ms", T.LONG, None, I.LOW,
             "Metric-anomaly sweep interval; default anomaly interval.")
    d.define("disk.failure.detection.interval.ms", T.LONG, None, I.LOW,
             "Disk-failure sweep interval; default anomaly interval.")
    d.define("broker.failure.detection.backoff.ms", T.LONG, 300_000, I.LOW,
             "Backoff before re-reporting a persisting broker failure.")
    d.define("num.cached.recent.anomaly.states", T.INT, 10, I.LOW,
             "Recent anomalies kept per type in the state snapshot.")
    d.define("self.healing.exclude.recently.demoted.brokers", T.BOOLEAN,
             True, I.MEDIUM, "Self-healing avoids leadership on recently "
             "demoted brokers.")
    d.define("self.healing.exclude.recently.removed.brokers", T.BOOLEAN,
             True, I.MEDIUM, "Self-healing avoids replicas on recently "
             "removed brokers.")
    # -- executor -----------------------------------------------------------
    d.define("num.concurrent.intra.broker.partition.movements", T.INT, 2,
             I.MEDIUM, "Concurrent logdir moves per broker.")
    d.define("leader.movement.timeout.ms", T.LONG, 180_000, I.MEDIUM,
             "Leadership-movement batch timeout.")
    d.define("task.execution.alerting.threshold.ms", T.LONG, 90_000, I.LOW,
             "Warn when one execution task exceeds this duration.")
    d.define("replica.movement.strategies", T.LIST,
             ["PostponeUrpReplicaMovementStrategy",
              "PrioritizeLargeReplicaMovementStrategy",
              "PrioritizeSmallReplicaMovementStrategy"], I.LOW,
             "Replica movement strategies available per request.")
    d.define("default.replica.movement.strategies", T.LIST,
             ["BaseReplicaMovementStrategy"], I.LOW,
             "Strategy chain applied when a request names none.")
    d.define("demotion.history.retention.time.ms", T.LONG, 1_209_600_000,
             I.LOW, "How long a demoted broker counts as recently demoted.")
    d.define("removal.history.retention.time.ms", T.LONG, 1_209_600_000,
             I.LOW, "How long a removed broker counts as recently removed.")
    # -- monitor / sampling -------------------------------------------------
    d.define("skip.loading.samples", T.BOOLEAN, False, I.LOW,
             "Skip sample-store replay at startup.")
    d.define("anomaly.detection.allow.capacity.estimation", T.BOOLEAN, True,
             I.LOW, "Goal-violation detection may run on estimated broker "
             "capacities (default -1 entry); false skips the sweep instead.")
    d.define("topics.excluded.from.partition.movement", T.STRING, "", I.MEDIUM,
             "Regex of topics never moved by any optimization.")
    d.define("metric.sampler.partition.assignor.class", T.CLASS,
             "DefaultPartitionAssignor", I.LOW,
             "Partition→fetcher assignor implementation. Reference-compat: "
             "this rebuild assigns partitions round-robin inside "
             "MetricFetcherManager; accepted for parity, no effect.")
    d.define("topic.config.provider.class", T.CLASS,
             "StaticTopicConfigProvider", I.LOW,
             "Topic configuration provider implementation. Reference-"
             "compat: topic configs are read through the cluster adapter; "
             "accepted for parity, no effect.")
    # -- servlet / web ------------------------------------------------------
    d.define("two.step.purgatory.max.requests", T.INT, 25, I.LOW,
             "Max requests pending review in the purgatory.")
    d.define("two.step.purgatory.retention.time.ms", T.LONG, 1_209_600_000,
             I.LOW, "How long a reviewed request stays retrievable.")
    d.define("request.reason.required", T.BOOLEAN, False, I.LOW,
             "POST operations must carry a reason parameter.")
    d.define("max.cached.completed.user.tasks", T.INT, 100, I.LOW,
             "Completed user tasks kept for User-Task-ID polling.")
    for _etype, _label in (("cruise.control.admin", "CRUISE_CONTROL_ADMIN"),
                           ("cruise.control.monitor", "CRUISE_CONTROL_MONITOR"),
                           ("kafka.admin", "KAFKA_ADMIN"),
                           ("kafka.monitor", "KAFKA_MONITOR")):
        d.define(f"completed.{_etype}.user.task.retention.time.ms", T.LONG,
                 None, I.LOW, f"Retention for completed {_label} tasks "
                 "(default: the global retention).")
        d.define(f"max.cached.completed.{_etype}.user.tasks", T.INT, None,
                 I.LOW, f"Cache cap for completed {_label} tasks "
                 "(default: only the global cap applies).")
    d.define("webserver.accesslog.enabled", T.BOOLEAN, True, I.LOW,
             "Emit an NCSA-style access log line per request.")
    d.define("webserver.accesslog.path", T.STRING, "", I.LOW,
             "Access log file path ('' → service log stream).")
    d.define("webserver.http.cors.enabled", T.BOOLEAN, False, I.LOW,
             "Enable CORS headers on REST responses.")
    d.define("webserver.http.cors.origin", T.STRING, "*", I.LOW,
             "Access-Control-Allow-Origin value.")
    d.define("webserver.http.cors.allowmethods", T.STRING,
             "OPTIONS, GET, POST", I.LOW,
             "Access-Control-Allow-Methods value.")
    d.define("webserver.http.cors.exposeheaders", T.STRING, "User-Task-ID",
             I.LOW, "Access-Control-Expose-Headers value.")
    d.define("webserver.accesslog.retention.days", T.INT, 14, I.LOW,
             "Days of rotated access logs kept on disk.", at_least(1))
    d.define("webserver.session.path", T.STRING, "/", I.LOW,
             "Cookie path of the REST session cookie.")
    d.define("webserver.ui.diskpath", T.STRING, "", I.LOW,
             "Directory of static UI assets ('' = UI serving disabled).")
    d.define("webserver.ui.urlprefix", T.STRING, "/*", I.LOW,
             "URL prefix the static UI is served under.")
    d.define("zookeeper.security.enabled", T.BOOLEAN, False, I.LOW,
             "Reference-compat: secure ZK ACLs. This rebuild has no "
             "ZooKeeper dependency; accepted for config-file parity, "
             "no effect.")
    # -- pluggable classes --------------------------------------------------
    d.define("executor.notifier.class", T.CLASS, "LoggingExecutorNotifier",
             I.LOW, "ExecutorNotifier implementation.")
    d.define("metric.anomaly.finder.class", T.CLASS,
             "PercentileMetricAnomalyFinder", I.LOW,
             "MetricAnomalyFinder implementation.")
    d.define("network.client.provider.class", T.CLASS,
             "DefaultNetworkClientProvider", I.LOW,
             "Network client provider (Kafka adapter seam). Reference-"
             "compat: kafka-python owns client construction here; accepted "
             "for parity, no effect.")
    return d


class CruiseControlConfig:
    """AbstractConfig equivalent over the service schema."""

    _DEF: Optional[ConfigDef] = None

    @classmethod
    def definition(cls) -> ConfigDef:
        if cls._DEF is None:
            cls._DEF = _service_config_def()
        return cls._DEF

    def __init__(self, raw: Optional[Dict[str, Any]] = None,
                 properties_file: Optional[str] = None):
        merged: Dict[str, Any] = {}
        if properties_file:
            merged.update(load_properties(properties_file))
        if raw:
            merged.update(raw)
        self._values = self.definition().parse(merged)
        self.originals = merged

    def get(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise ConfigException(f"unknown config {name}")

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def balancing_constraint(self):
        from cruise_control_tpu.common.resources import BalancingConstraint
        g = self.get
        return BalancingConstraint(
            resource_balance_percentage=(
                g("cpu.balance.threshold"),
                g("network.inbound.balance.threshold"),
                g("network.outbound.balance.threshold"),
                g("disk.balance.threshold")),
            capacity_threshold=(
                g("cpu.capacity.threshold"),
                g("network.inbound.capacity.threshold"),
                g("network.outbound.capacity.threshold"),
                g("disk.capacity.threshold")),
            low_utilization_threshold=(
                g("cpu.low.utilization.threshold"),
                g("network.inbound.low.utilization.threshold"),
                g("network.outbound.low.utilization.threshold"),
                g("disk.low.utilization.threshold")),
            replica_balance_percentage=g("replica.count.balance.threshold"),
            leader_replica_balance_percentage=g(
                "leader.replica.count.balance.threshold"),
            topic_replica_balance_percentage=g(
                "topic.replica.count.balance.threshold"),
            goal_violation_distribution_threshold_multiplier=g(
                "goal.violation.distribution.threshold.multiplier"),
            max_replicas_per_broker=int(g("max.replicas.per.broker")),
        )
