"""Service entrypoint: properties file → config → app → REST server.

Rebuild of ``KafkaCruiseControlMain.java:38-125``: load the boot properties,
construct the application (monitor + analyzer + executor + anomaly
detector), start the REST server, block until shutdown.

Deployment modes:

- ``--demo``: a self-contained synthetic cluster (static metadata + the
  synthetic load sampler) — the zero-dependency way to drive the full
  service.
- Kafka mode: when ``bootstrap.servers`` is configured, the Kafka adapters
  (metadata source, metrics-topic sampler, admin executor) are loaded from
  :mod:`cruise_control_tpu.kafka_adapter`; they require a Kafka client
  library at runtime.

Usage::

    python -m cruise_control_tpu.main --config config/cruisecontrol.properties
    python -m cruise_control_tpu.main --demo --port 9090
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import Optional


def build_demo_app(config):
    """Synthetic single-process deployment (the CCEmbedded* analogue)."""
    from cruise_control_tpu.app import CruiseControlApp
    from cruise_control_tpu.executor.executor import FakeClusterAdapter
    from cruise_control_tpu.monitor.load_monitor import StaticMetadataSource
    from cruise_control_tpu.monitor.sampler import (
        BrokerMetadata,
        ClusterMetadata,
        PartitionMetadata,
        SyntheticLoadSampler,
    )
    num_brokers, num_parts, rf = 12, 120, 3
    brokers = [BrokerMetadata(i, rack=f"rack{i % 4}", host=f"host{i}")
               for i in range(num_brokers)]
    parts = [PartitionMetadata(
        f"topic{p % 8}", p // 8,
        leader=(p % num_brokers),
        replicas=tuple((p + j) % num_brokers for j in range(rf)))
        for p in range(num_parts)]
    metadata = ClusterMetadata(brokers=brokers, partitions=parts, generation=1)
    adapter = FakeClusterAdapter(
        {f"{p.topic}-{p.partition}": tuple(p.replicas) for p in parts})
    return CruiseControlApp(config, StaticMetadataSource(metadata),
                            SyntheticLoadSampler(seed=1),
                            cluster_adapter=adapter)


def build_kafka_app(config):
    from cruise_control_tpu import kafka_adapter
    from cruise_control_tpu.app import CruiseControlApp
    from cruise_control_tpu.monitor.capacity import FileCapacityResolver
    from cruise_control_tpu.monitor.sample_store import (
        FileSampleStore, KafkaSampleStore)
    source = kafka_adapter.KafkaMetadataSource(config)
    sampler = kafka_adapter.KafkaMetricsTopicSampler(config)
    adapter = kafka_adapter.KafkaClusterAdapter(config)
    store_cls = config.get("sample.store.class")
    store_dir = config.get("sample.store.dir")
    if store_cls == "KafkaSampleStore":
        store = KafkaSampleStore(config)
    elif store_cls == "FileSampleStore" and store_dir:
        store = FileSampleStore(store_dir)
    else:
        store = None
    return CruiseControlApp(
        config, source, sampler, cluster_adapter=adapter,
        capacity_resolver=FileCapacityResolver(
            config.get("capacity.config.file")),
        sample_store=store)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="cruise-control-tpu")
    parser.add_argument("--config", help="properties file path")
    parser.add_argument("--demo", action="store_true",
                        help="run against a synthetic in-process cluster")
    parser.add_argument("--port", type=int, help="REST port override")
    parser.add_argument("--no-sampling-loop", action="store_true",
                        help="do not start the periodic sampler thread")
    args = parser.parse_args(argv)

    # persistent XLA compile cache: a service restart reloads the compiled
    # proposal programs from disk instead of re-paying minutes of XLA
    # compile. Set through jax.config (not just the env var): backends whose
    # sitecustomize imports jax before this line would otherwise have
    # materialized the config default without the cache dir.
    import os

    import jax
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.getcwd(), ".jax_cache"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from cruise_control_tpu.common.config import CruiseControlConfig
    from cruise_control_tpu.server import rest
    config = CruiseControlConfig(properties_file=args.config)
    if args.demo or not config.get("bootstrap.servers"):
        app = build_demo_app(config)
        # prime a few windows so the model is immediately buildable. The
        # windows must END AT WALL TIME: the monitor clock is real time, so
        # epoch-anchored sample timestamps would all be ancient and every
        # model build would fail the completeness gate.
        import time as _time
        w = config.get("partition.metrics.window.ms")
        n = config.get("num.partition.metrics.windows")
        now = int(_time.time() * 1000)
        for i in range(n + 1):
            app.load_monitor.sample_once(now_ms=now - (n - i) * w)
    else:
        app = build_kafka_app(config)

    if not args.no_sampling_loop:
        app.startup()
    server = rest.serve(app, port=args.port)
    host, port = server.server_address[:2]
    print(f"cruise-control-tpu listening on http://{host}:{port}"
          f"{config.get('webserver.api.urlprefix')}/state", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        server.shutdown()
        app.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
