"""Vmapped what-if evaluator: score an entire scenario grid in ONE
compiled program.

Per scenario the evaluator produces the full as-is goal picture
(violation/cost vectors from ``full_goal_penalties``, the violated-goal
set, a balancedness score) PLUS assignment-invariant structural
feasibility bounds — exact necessary conditions no rebalance can work
around:

- rack bound:     Σ_p max(0, rf_p − #alive racks)
- replica bound:  max(0, R − #alive brokers · max_replicas_per_broker)
- capacity bound: per resource, max(0, total load − Σ_alive capacity·
  threshold·(1 − headroom))

A scenario failing a bound is PROVABLY infeasible for any assignment; a
scenario passing all bounds is a candidate fix. The optional "deep" mode
refines candidates with a short donated PT anneal per scenario
(constructive witness: post-rebalance violations + move counts). All
scenarios of a grid share one shape bucket (scenarios.compile_grid), so
the batched evaluation is a single jit trace and re-evaluating any grid
in the same bucket retraces nothing.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import objective as OBJ
from cruise_control_tpu.analyzer.annealer import AnnealConfig, optimize_anneal
from cruise_control_tpu.analyzer.optimizer import (
    MAX_BALANCEDNESS_SCORE,
    TOPIC_DENSE_LIMIT,
    balancedness_cost_by_goal,
)
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.obs import costmodel as CM
from cruise_control_tpu.common.resources import BalancingConstraint
from cruise_control_tpu.ops.aggregates import (
    compute_aggregates,
    device_topology,
    topic_totals,
)
from cruise_control_tpu.provisioner.scenarios import Scenario, ScenarioGrid

#: structural-bound order: rack, replica-count, then one per resource
BOUND_GOALS = ("RackAwareGoal", "ReplicaCapacityGoal", "CpuCapacityGoal",
               "NetworkInboundCapacityGoal", "NetworkOutboundCapacityGoal",
               "DiskCapacityGoal")
_BOUND_RESOURCES = (res.CPU, res.NW_IN, res.NW_OUT, res.DISK)

#: deep-mode default: a deliberately small PT ladder — the point is a
#: feasibility witness + move estimate per scenario, not a polished plan
DEEP_ANNEAL_CONFIG = AnnealConfig(num_chains=8, steps=256, swap_interval=32)


def _structural_bounds(dt, th, headroom: jax.Array) -> jax.Array:
    """f32[6] assignment-invariant infeasibility bounds (0 = satisfiable).

    Works on padded models: padded brokers are dead (excluded from every
    alive mask) and padded replicas/partitions carry weight 0."""
    B = dt.rack_of_broker.shape[0]
    alive_f = th.alive.astype(jnp.float32)
    w_r = (dt.replica_weight.astype(jnp.float32)
           if dt.replica_weight is not None
           else jnp.ones(dt.partition_of_replica.shape[0], jnp.float32))
    w_p = (dt.partition_weight.astype(jnp.float32)
           if dt.partition_weight is not None
           else jnp.ones(dt.topic_of_partition.shape[0], jnp.float32))

    # rack bound — rack ids are data; a rack is alive iff it holds an alive
    # broker. Rack ids are < B by construction (≤ one rack per broker).
    racks_alive = jax.ops.segment_sum(alive_f, dt.rack_of_broker,
                                      num_segments=B)
    n_racks = jnp.sum(racks_alive > 0).astype(jnp.float32)
    rf = dt.rf_of_partition.astype(jnp.float32)
    rack_bound = jnp.sum(jnp.maximum(rf - n_racks, 0.0) * w_p)

    # replica-count bound
    n_real = jnp.sum(w_r)
    repl_bound = jnp.maximum(
        n_real - th.n_alive * th.max_replicas_per_broker, 0.0)

    # capacity bounds — total load (follower base + leader extra) vs the
    # thresholded alive capacity shaved by the headroom margin
    total_load = (jnp.sum(dt.replica_base_load * w_r[:, None], axis=0)
                  + jnp.sum(dt.leader_extra * w_p[:, None], axis=0))  # [4]
    avail = jnp.sum(th.cap_limit_broker * alive_f[:, None], axis=0)
    avail = avail * (1.0 - headroom)
    cap_bound = jnp.maximum(total_load - avail, 0.0)                  # [4]

    return jnp.concatenate([
        jnp.stack([rack_bound, repl_bound]),
        cap_bound[jnp.asarray(_BOUND_RESOURCES)],
    ])


@partial(jax.jit,
         static_argnames=("num_topics", "goal_names", "constraint",
                          "sparse_topic"))
def _eval_grid(dts, assigns, headroom, num_topics: int,
               goal_names: Tuple[str, ...],
               constraint: BalancingConstraint, sparse_topic: bool):
    """One compiled program scoring every scenario of the stacked grid."""

    def _one(dt, assign):
        agg = compute_aggregates(dt, assign,
                                 1 if sparse_topic else num_topics)
        th = G.compute_thresholds(
            dt, constraint, agg,
            topic_total=(topic_totals(dt, num_topics)
                         if sparse_topic else None))
        pen = G.full_goal_penalties(dt, assign, th, num_topics, goal_names,
                                    initial_broker_of=assign.broker_of,
                                    agg=agg, sparse_topic=sparse_topic)
        return pen.violations, pen.cost, _structural_bounds(dt, th, headroom)

    return jax.vmap(_one)(dts, assigns)


# ---------------------------------------------------------------------------
# Host-side result fold
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ScenarioScore:
    """Everything the grid evaluation learned about one scenario."""

    scenario: Scenario
    num_brokers: int                 # real brokers in the mutated model
    num_alive_brokers: int
    violations: np.ndarray           # f32[G+1] per-goal violation measures
    costs: np.ndarray                # f32[G+1] per-goal soft costs
    violated_goals: Tuple[str, ...]  # as-is violated goal names
    offline_replicas: float          # the appended self-healing term
    structural_bounds: np.ndarray    # f32[6], BOUND_GOALS order
    infeasible_goals: Tuple[str, ...]  # goals whose bound fires (no
    #                                    assignment can satisfy them)
    balancedness: float
    # deep-mode extras (None unless evaluated deep)
    post_rebalance_violations: Optional[float] = None
    estimated_replica_moves: Optional[int] = None
    estimated_leadership_moves: Optional[int] = None

    @property
    def feasible(self) -> bool:
        """No structural bound fires — some assignment satisfies every
        bounded hard goal (deep mode refines this to a witness)."""
        return not self.infeasible_goals

    def to_dict(self) -> dict:
        d = {
            "scenario": self.scenario.name,
            "numBrokers": self.num_brokers,
            "numAliveBrokers": self.num_alive_brokers,
            "violatedGoals": list(self.violated_goals),
            "offlineReplicas": self.offline_replicas,
            "structurallyInfeasibleGoals": list(self.infeasible_goals),
            "feasible": self.feasible,
            "balancedness": self.balancedness,
        }
        if self.post_rebalance_violations is not None:
            d["postRebalanceViolations"] = self.post_rebalance_violations
            d["estimatedReplicaMoves"] = self.estimated_replica_moves
            d["estimatedLeadershipMoves"] = self.estimated_leadership_moves
        return d


@dataclasses.dataclass(frozen=True)
class WhatIfResult:
    goal_names: Tuple[str, ...]
    headroom_margin: float
    scores: Tuple[ScenarioScore, ...]

    def score_of(self, name: str) -> ScenarioScore:
        for s in self.scores:
            if s.scenario.name == name:
                return s
        raise KeyError(name)

    def to_dict(self) -> dict:
        return {
            "goals": list(self.goal_names),
            "headroomMargin": self.headroom_margin,
            "scenarios": [s.to_dict() for s in self.scores],
        }


def _balancedness(goal_names, violations, weights=None) -> float:
    pw, sw = weights if weights is not None else (None, None)
    costs = balancedness_cost_by_goal(goal_names, priority_weight=pw,
                                      strictness_weight=sw)
    score = MAX_BALANCEDNESS_SCORE
    for g, v in zip(goal_names, violations):
        if v > 0:
            score -= costs[g]
    return float(max(score, 0.0))


def evaluate_grid(grid: ScenarioGrid, constraint: BalancingConstraint,
                  goal_names: Sequence[str], headroom: float = 0.0,
                  balancedness_weights=None,
                  sparse_topic: Optional[bool] = None,
                  deep: bool = False,
                  anneal_config: Optional[AnnealConfig] = None,
                  seed: int = 0) -> WhatIfResult:
    """Score every scenario of a compiled grid in one vmapped call.

    ``sparse_topic=None`` auto-selects the sort-based topic scoring above
    ``TOPIC_DENSE_LIMIT`` B·T cells (a dense [S, B, T] histogram at
    LinkedIn scale would be tens of GB). ``deep=True`` additionally runs a
    short PT anneal per bound-feasible scenario; the shared grid bucket
    means every anneal reuses one compiled program."""
    goal_names = tuple(goal_names)
    if sparse_topic is None:
        max_real_b = max(c.info.num_brokers for c in grid.compiled)
        sparse_topic = max_real_b * grid.num_topics > TOPIC_DENSE_LIMIT
    viol, cost, bounds = _eval_grid(
        grid.dts, grid.assigns, jnp.float32(headroom),
        num_topics=grid.num_topics, goal_names=goal_names,
        constraint=constraint, sparse_topic=bool(sparse_topic))
    CM.capture_program(
        "whatif-grid", _eval_grid,
        (grid.dts, grid.assigns, jnp.float32(headroom)),
        (viol, cost, bounds),
        {"num_topics": grid.num_topics, "goal_names": goal_names,
         "constraint": constraint, "sparse_topic": bool(sparse_topic)})
    viol = np.asarray(jax.device_get(viol))      # f32[S, G+1]
    cost = np.asarray(jax.device_get(cost))
    bounds = np.asarray(jax.device_get(bounds))  # f32[S, 6]

    bounded = [g for g in BOUND_GOALS if g in goal_names]
    scores = []
    for i, c in enumerate(grid.compiled):
        alive = np.asarray(c.topo.broker_alive)
        present = np.asarray(c.topo.broker_present)
        infeasible = tuple(
            g for j, g in enumerate(BOUND_GOALS)
            if bounds[i, j] > 0 and g in bounded)
        scores.append(ScenarioScore(
            scenario=c.scenario,
            num_brokers=c.info.num_brokers,
            num_alive_brokers=int(np.sum(alive & present)),
            violations=viol[i],
            costs=cost[i],
            violated_goals=tuple(
                g for j, g in enumerate(goal_names) if viol[i, j] > 0),
            offline_replicas=float(viol[i, -1]),
            structural_bounds=bounds[i],
            infeasible_goals=infeasible,
            balancedness=_balancedness(goal_names, viol[i],
                                       balancedness_weights),
        ))
    if deep:
        scores = _deep_refine(grid, scores, constraint, goal_names,
                              anneal_config or DEEP_ANNEAL_CONFIG, seed)
    return WhatIfResult(goal_names=goal_names,
                        headroom_margin=float(headroom),
                        scores=tuple(scores))


def _deep_refine(grid: ScenarioGrid, scores, constraint, goal_names,
                 config: AnnealConfig, seed: int):
    """Anneal each bound-feasible scenario briefly; report the witness.

    Host loop — every scenario shares the grid bucket, so after the first
    anneal compiles, the rest reuse the same program."""
    weights = OBJ.build_weights(goal_names)
    out = []
    for i, (c, sc) in enumerate(zip(grid.compiled, scores)):
        if not sc.feasible:
            out.append(sc)
            continue
        dt = device_topology(c.topo)
        agg = compute_aggregates(dt, c.assign, grid.num_topics)
        th = G.compute_thresholds(dt, constraint, agg)
        init_bo = c.assign.broker_of          # already a device int32 array
        result = optimize_anneal(dt, c.assign, th, weights, c.options,
                                 grid.num_topics, config=config,
                                 seed=seed + i, goal_names=goal_names,
                                 initial_broker_of=init_bo)
        pen = G.full_goal_penalties(dt, result.assignment, th,
                                    grid.num_topics, goal_names,
                                    initial_broker_of=init_bo)
        post = np.asarray(jax.device_get(pen.violations))
        R, P = c.info.num_replicas, c.info.num_partitions
        bo0 = np.asarray(jax.device_get(c.assign.broker_of))[:R]
        bo1 = np.asarray(jax.device_get(result.assignment.broker_of))[:R]
        lo0 = np.asarray(jax.device_get(c.assign.leader_of))[:P]
        lo1 = np.asarray(jax.device_get(result.assignment.leader_of))[:P]
        out.append(dataclasses.replace(
            sc,
            post_rebalance_violations=float(post[:-1].sum() + post[-1]),
            estimated_replica_moves=int(np.sum(bo0 != bo1)),
            estimated_leadership_moves=int(np.sum(lo0 != lo1)),
        ))
    return out
