"""Provisioner: batched counterfactual what-if engine + rightsizing.

The reference Cruise Control ships a Provisioner subsystem
(``provision/Provisioner.java``, ``ProvisionRecommendation.java``): goals
report UNDER/OVER_PROVISIONED status and the detector turns "no feasible
fix" into an add-capacity recommendation. This package is the TPU-shaped
port: a counterfactual is just a mutated :class:`ClusterTopology`, so an
entire grid of scenarios pads into ONE shared shape bucket and scores as a
single vmapped ``full_goal_penalties`` call — the reference's one-at-a-time
simulation becomes one compiled batch.

- :mod:`.scenarios` — declarative scenario spec + host-side grid compiler
- :mod:`.whatif` — vmapped grid evaluator (+ optional deep anneal mode)
- :mod:`.provisioner` — recommendation fold + detector/service surface
"""

from cruise_control_tpu.provisioner.provisioner import (  # noqa: F401
    ProvisionRecommendation,
    Provisioner,
    RIGHT_SIZED,
    OVER_PROVISIONED,
    UNDER_PROVISIONED,
)
from cruise_control_tpu.provisioner.scenarios import (  # noqa: F401
    Scenario,
    ScenarioGrid,
    add_brokers,
    add_partitions,
    apply_scenario,
    compile_grid,
    fail_rack,
    remove_brokers,
    scale_capacity,
)
from cruise_control_tpu.provisioner.whatif import (  # noqa: F401
    ScenarioScore,
    WhatIfResult,
    evaluate_grid,
)
