"""Declarative what-if scenarios + the host-side grid compiler.

A :class:`Scenario` is a named tuple of ops applied in order to the frozen
(:class:`ClusterTopology`, :class:`Assignment`) pair on the HOST — the same
mutation idiom the service uses for real operations (``app.remove_brokers``):
removed/failed brokers flip to dead and their replicas go offline, added
brokers enter as empty-but-alive rows on fresh failure domains.

``compile_grid`` pads every mutated scenario of a grid into ONE shared
bucket (``pad_topology`` with explicit per-axis targets) so the broker /
host / partition / replica axes agree across the batch and the whole grid
stacks into a single vmapped program. For a singleton grid the shared
targets collapse to exactly the stock ``pad_topology`` bucket choice, so a
one-scenario grid is bit-identical to padding the mutated model directly.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.models.cluster import (
    BROKER_BUCKET_FLOOR,
    HOST_BUCKET_FLOOR,
    PARTITION_BUCKET_FLOOR,
    REPLICA_BUCKET_FLOOR,
    Assignment,
    ClusterTopology,
    PaddingInfo,
    bucket_size,
    pad_topology,
)
from cruise_control_tpu.ops.aggregates import DeviceTopology, device_topology

# ---------------------------------------------------------------------------
# Scenario spec
# ---------------------------------------------------------------------------

ADD_BROKERS = "ADD_BROKERS"
REMOVE_BROKERS = "REMOVE_BROKERS"
SCALE_CAPACITY = "SCALE_CAPACITY"
FAIL_RACK = "FAIL_RACK"
ADD_PARTITIONS = "ADD_PARTITIONS"

#: resource spelling accepted by SCALE_CAPACITY — canonical names
#: (``res.RESOURCE_NAMES``) plus the short aliases operators actually type
_RESOURCE_ALIASES = {
    "cpu": res.CPU,
    "networkinbound": res.NW_IN,
    "nw_in": res.NW_IN,
    "networkoutbound": res.NW_OUT,
    "nw_out": res.NW_OUT,
    "disk": res.DISK,
}


def resolve_resource(name: str) -> int:
    key = str(name).strip().lower()
    if key not in _RESOURCE_ALIASES:
        raise ValueError(
            f"unknown resource {name!r}: use one of "
            f"{sorted(set(_RESOURCE_ALIASES))}")
    return _RESOURCE_ALIASES[key]


@dataclasses.dataclass(frozen=True)
class ScenarioOp:
    """One mutation step; use the module-level constructors below."""

    kind: str
    count: int = 0
    rack: Optional[str] = None
    broker_ids: Tuple[int, ...] = ()
    resource: Optional[str] = None
    factor: float = 1.0
    topic: Optional[str] = None


def add_brokers(count: int, rack: Optional[str] = None) -> ScenarioOp:
    """``count`` empty-but-alive brokers. ``rack=None`` puts each on its OWN
    new rack and host (conservative new-failure-domain assumption — what a
    capacity request would actually provision); a named rack targets that
    existing rack, or one shared new rack if the name is unknown."""
    if count < 1:
        raise ValueError(f"add_brokers needs count >= 1, got {count}")
    return ScenarioOp(ADD_BROKERS, count=int(count), rack=rack)


def remove_brokers(broker_ids: Sequence[int]) -> ScenarioOp:
    """Flip the listed brokers dead + their replicas offline (the exact
    ``app.remove_brokers`` decommission semantics)."""
    ids = tuple(int(b) for b in broker_ids)
    if not ids:
        raise ValueError("remove_brokers needs at least one broker id")
    return ScenarioOp(REMOVE_BROKERS, broker_ids=ids)


def scale_capacity(resource: str, factor: float) -> ScenarioOp:
    """Scale one capacity column by ``factor`` (e.g. disk 0.5 = half-size
    volumes, cpu 2.0 = doubled cores)."""
    if not factor > 0:
        raise ValueError(f"scale_capacity factor must be > 0, got {factor}")
    resolve_resource(resource)
    return ScenarioOp(SCALE_CAPACITY, resource=str(resource),
                      factor=float(factor))


def fail_rack(rack: str) -> ScenarioOp:
    """Kill every broker in the rack (rack name, or rack index when the
    model carries no rack names)."""
    return ScenarioOp(FAIL_RACK, rack=str(rack))


def add_partitions(topic: str, count: int) -> ScenarioOp:
    """Grow a topic by ``count`` partitions at the topic's typical rf,
    placed rack-diverse on the least-loaded alive brokers, with loads set
    to the topic's per-partition mean."""
    if count < 1:
        raise ValueError(f"add_partitions needs count >= 1, got {count}")
    return ScenarioOp(ADD_PARTITIONS, topic=str(topic), count=int(count))


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A named, ordered composition of ops. Empty ops = the baseline."""

    name: str
    ops: Tuple[ScenarioOp, ...] = ()


BASELINE = Scenario("baseline", ())


# ---------------------------------------------------------------------------
# Host-side application of one scenario to the frozen model
# ---------------------------------------------------------------------------


def _apply_add_brokers(topo: ClusterTopology, bo: np.ndarray, lo: np.ndarray,
                       op: ScenarioOp):
    n = op.count
    B, H, K = topo.num_brokers, topo.num_hosts, topo.num_racks
    alive = np.asarray(topo.broker_alive)
    cap_src = topo.capacity[alive] if alive.any() else topo.capacity
    cap_row = np.asarray(cap_src, np.float32).mean(axis=0)

    rack_names = tuple(topo.rack_names)
    if op.rack is None:
        new_racks = K + np.arange(n)
        if rack_names:
            rack_names += tuple(f"provision-rack-{K + i}" for i in range(n))
    else:
        if rack_names and op.rack in rack_names:
            r = rack_names.index(op.rack)
        else:
            try:
                r = int(op.rack)
            except ValueError:
                r = -1
            if not 0 <= r < K:
                r = K  # one shared new rack under the requested name
                if rack_names:
                    rack_names += (str(op.rack),)
        new_racks = np.full(n, r)
    new_hosts = H + np.arange(n)
    host_names = tuple(topo.host_names)
    if host_names:
        host_names += tuple(f"provision-host-{H + i}" for i in range(n))

    broker_ids = topo.broker_ids
    if broker_ids is not None:
        ids = np.asarray(broker_ids)
        start = int(ids.max()) + 1
        broker_ids = np.concatenate(
            [ids, np.arange(start, start + n, dtype=ids.dtype)])

    def _app(arr, fill):
        arr = np.asarray(arr)
        pad = np.full((n,) + arr.shape[1:], fill, dtype=arr.dtype)
        return np.concatenate([arr, pad], axis=0)

    topo = dataclasses.replace(
        topo,
        rack_of_broker=np.concatenate(
            [np.asarray(topo.rack_of_broker),
             new_racks.astype(topo.rack_of_broker.dtype)]),
        host_of_broker=np.concatenate(
            [np.asarray(topo.host_of_broker),
             new_hosts.astype(topo.host_of_broker.dtype)]),
        capacity=np.concatenate(
            [np.asarray(topo.capacity),
             np.tile(cap_row, (n, 1)).astype(topo.capacity.dtype)], axis=0),
        broker_alive=_app(topo.broker_alive, True),
        broker_new=_app(topo.broker_new, False),
        broker_demoted=_app(topo.broker_demoted, False),
        broker_bad_disks=_app(topo.broker_bad_disks, False),
        broker_ids=broker_ids,
        host_names=host_names,
        rack_names=rack_names,
    )
    return topo, bo, lo


def _broker_rows(topo: ClusterTopology, ids: Sequence[int]) -> List[int]:
    """External broker ids → topology rows (``app.remove_brokers`` idiom)."""
    if topo.broker_ids is not None:
        idx = {int(b): i for i, b in enumerate(topo.broker_ids)}
    else:
        idx = {i: i for i in range(topo.num_brokers)}
    rows = []
    for b in ids:
        if int(b) not in idx:
            raise ValueError(f"unknown broker id {b}")
        rows.append(idx[int(b)])
    return rows


def _kill_rows(topo: ClusterTopology, bo: np.ndarray, rows: Sequence[int]):
    alive = np.asarray(topo.broker_alive).copy()
    offline = np.asarray(topo.replica_offline).copy()
    for r_i in rows:
        alive[r_i] = False
        offline |= bo == r_i
    return dataclasses.replace(topo, broker_alive=alive,
                               replica_offline=offline)


def _apply_remove_brokers(topo, bo, lo, op: ScenarioOp):
    rows = _broker_rows(topo, op.broker_ids)
    return _kill_rows(topo, bo, rows), bo, lo


def _apply_fail_rack(topo, bo, lo, op: ScenarioOp):
    rack_names = tuple(topo.rack_names)
    if rack_names and op.rack in rack_names:
        k = rack_names.index(op.rack)
    else:
        try:
            k = int(op.rack)
        except ValueError:
            raise ValueError(f"unknown rack {op.rack!r}") from None
        if not 0 <= k < topo.num_racks:
            raise ValueError(f"rack index {k} out of range "
                             f"[0, {topo.num_racks})")
    rows = np.flatnonzero(np.asarray(topo.rack_of_broker) == k)
    return _kill_rows(topo, bo, rows), bo, lo


def _apply_scale_capacity(topo, bo, lo, op: ScenarioOp):
    r = resolve_resource(op.resource)
    cap = np.asarray(topo.capacity).copy()
    cap[:, r] *= op.factor
    return dataclasses.replace(topo, capacity=cap), bo, lo


def _apply_add_partitions(topo: ClusterTopology, bo: np.ndarray,
                          lo: np.ndarray, op: ScenarioOp):
    names = tuple(topo.topic_names)
    if names and op.topic in names:
        t = names.index(op.topic)
    else:
        try:
            t = int(op.topic)
        except ValueError:
            raise ValueError(f"unknown topic {op.topic!r}") from None
        if not 0 <= t < topo.num_topics:
            raise ValueError(f"topic index {t} out of range "
                             f"[0, {topo.num_topics})")
    t_parts = np.flatnonzero(np.asarray(topo.topic_of_partition) == t)
    if t_parts.size == 0:
        raise ValueError(f"topic {op.topic!r} has no partitions to model "
                         "the new ones after")
    t_reps_mask = np.isin(np.asarray(topo.partition_of_replica), t_parts)
    rfs = np.asarray(topo.rf_of_partition)[t_parts]
    rf = int(np.bincount(rfs).argmax())  # the topic's typical rf
    alive_rows = np.flatnonzero(np.asarray(topo.broker_alive))
    if rf > alive_rows.size:
        raise ValueError(
            f"topic rf {rf} exceeds {alive_rows.size} alive brokers")

    n = op.count
    B, P, R = topo.num_brokers, topo.num_partitions, topo.num_replicas
    rack = np.asarray(topo.rack_of_broker)
    counts = np.bincount(bo, minlength=B).astype(np.int64)
    lead_extra_row = np.asarray(
        topo.leader_extra[t_parts], np.float32).mean(axis=0)
    lbi = float(np.asarray(topo.leader_bytes_in[t_parts]).mean())
    base_row = np.asarray(
        topo.replica_base_load[t_reps_mask], np.float32).mean(axis=0)

    # rack-diverse least-loaded placement, deterministic (ties by row)
    placements = []
    for _ in range(n):
        chosen: List[int] = []
        used_racks: set = set()
        for _slot in range(rf):
            order = sorted(alive_rows, key=lambda b: (counts[b], b))
            pick = next((b for b in order
                         if b not in chosen and rack[b] not in used_racks),
                        None)
            if pick is None:
                pick = next(b for b in order if b not in chosen)
            chosen.append(int(pick))
            used_racks.add(int(rack[pick]))
            counts[pick] += 1
        placements.append(chosen)

    m = topo.max_rf
    reps_new = np.full((n, m), -1, dtype=topo.replicas_of_partition.dtype)
    new_rep_brokers = []
    off = 0
    for i, chosen in enumerate(placements):
        reps_new[i, :rf] = R + off + np.arange(rf)
        new_rep_brokers.extend(chosen)
        off += rf
    n_new_reps = off

    part_index = topo.partition_index
    if part_index is not None:
        nxt = int(np.max(np.asarray(part_index)[t_parts])) + 1
        part_index = np.concatenate(
            [np.asarray(part_index),
             np.arange(nxt, nxt + n, dtype=np.asarray(part_index).dtype)])

    def _rep_rows(arr, row):
        arr = np.asarray(arr)
        new = np.broadcast_to(row, (n_new_reps,) + arr.shape[1:])
        return np.concatenate([arr, new.astype(arr.dtype)], axis=0)

    def _part_rows(arr, row):
        arr = np.asarray(arr)
        new = np.broadcast_to(row, (n,) + arr.shape[1:])
        return np.concatenate([arr, new.astype(arr.dtype)], axis=0)

    win_r = topo.replica_base_load_windows
    if win_r is not None:
        win_r = _rep_rows(win_r, np.asarray(
            win_r[t_reps_mask], np.float32).mean(axis=0))
    win_p = topo.leader_extra_windows
    if win_p is not None:
        win_p = _part_rows(win_p, np.asarray(
            win_p[t_parts], np.float32).mean(axis=0))

    topo = dataclasses.replace(
        topo,
        partition_of_replica=np.concatenate(
            [np.asarray(topo.partition_of_replica),
             np.repeat(P + np.arange(n), rf).astype(
                 topo.partition_of_replica.dtype)]),
        topic_of_partition=_part_rows(topo.topic_of_partition, t),
        replicas_of_partition=np.concatenate(
            [np.asarray(topo.replicas_of_partition), reps_new], axis=0),
        rf_of_partition=_part_rows(topo.rf_of_partition, rf),
        initial_leader_slot=_part_rows(topo.initial_leader_slot, 0),
        replica_offline=_rep_rows(topo.replica_offline, False),
        replica_base_load=_rep_rows(topo.replica_base_load, base_row),
        leader_extra=_part_rows(topo.leader_extra, lead_extra_row),
        leader_bytes_in=_part_rows(topo.leader_bytes_in, lbi),
        replica_base_load_windows=win_r,
        leader_extra_windows=win_p,
        partition_index=part_index,
        disk_of_replica=(_rep_rows(topo.disk_of_replica, -1)
                         if topo.disk_of_replica is not None else None),
    )
    bo = np.concatenate([bo, np.asarray(new_rep_brokers, np.int32)])
    lo = np.concatenate(
        [lo, (R + np.arange(0, n_new_reps, rf)).astype(np.int32)])
    return topo, bo, lo


_APPLY = {
    ADD_BROKERS: _apply_add_brokers,
    REMOVE_BROKERS: _apply_remove_brokers,
    SCALE_CAPACITY: _apply_scale_capacity,
    FAIL_RACK: _apply_fail_rack,
    ADD_PARTITIONS: _apply_add_partitions,
}


def apply_scenario(topo: ClusterTopology, assign: Assignment,
                   scenario: Scenario
                   ) -> Tuple[ClusterTopology, Assignment]:
    """Apply a scenario's ops in order; returns the mutated UNPADDED pair.

    Pure host-side — the inputs are never modified (frozen dataclass +
    copy-on-write arrays)."""
    if topo.replica_weight is not None:
        raise ValueError("apply_scenario expects an unpadded model "
                         "(got bucketing sentinels)")
    bo = np.asarray(jax.device_get(assign.broker_of), np.int32)
    lo = np.asarray(jax.device_get(assign.leader_of), np.int32)
    for op in scenario.ops:
        if op.kind not in _APPLY:
            raise ValueError(f"unknown scenario op kind {op.kind!r}")
        topo, bo, lo = _APPLY[op.kind](topo, bo, lo, op)
    return topo, Assignment(broker_of=jnp.asarray(bo),
                            leader_of=jnp.asarray(lo))


# ---------------------------------------------------------------------------
# Grid compiler: pad every scenario into ONE shared bucket and stack
# ---------------------------------------------------------------------------


def _widen_rf(topo: ClusterTopology, m: int) -> ClusterTopology:
    """Widen the replica-slot axis to ``m`` columns (-1 fill — the valid
    mask every per-partition walk already applies)."""
    cur = topo.max_rf
    if cur >= m:
        return topo
    reps = np.full((topo.num_partitions, m), -1,
                   dtype=topo.replicas_of_partition.dtype)
    reps[:, :cur] = np.asarray(topo.replicas_of_partition)
    return dataclasses.replace(topo, replicas_of_partition=reps)


@dataclasses.dataclass(frozen=True)
class CompiledScenario:
    """One padded scenario of a grid (host handle for decode/deep mode)."""

    scenario: Scenario
    topo: ClusterTopology           # padded, shared bucket
    assign: Assignment              # padded
    options: G.DeviceOptions        # padded
    info: PaddingInfo               # real sizes of THIS scenario


@dataclasses.dataclass(frozen=True)
class ScenarioGrid:
    """A compiled grid: per-scenario handles + the stacked device batch."""

    compiled: Tuple[CompiledScenario, ...]
    dts: DeviceTopology             # every leaf stacked on a leading S axis
    assigns: Assignment             # stacked
    options: G.DeviceOptions        # stacked
    num_topics: int
    bucket: Tuple[int, int, int, int]  # (B_pad, H_pad, P_pad, R_pad)

    @property
    def num_scenarios(self) -> int:
        return len(self.compiled)

    @property
    def scenarios(self) -> Tuple[Scenario, ...]:
        return tuple(c.scenario for c in self.compiled)


def grid_targets(mutated: Sequence[ClusterTopology]
                 ) -> Tuple[int, int, int, int]:
    """Shared bucket targets covering every scenario of a grid.

    Chosen so a singleton grid reproduces stock ``pad_topology`` exactly:
    the replica target is sized off the worst-case padded-partition count
    (``R_i + (P_pad - P_i)`` — one sentinel replica per padded partition)."""
    B_t = bucket_size(max(t.num_brokers for t in mutated) + 1,
                      BROKER_BUCKET_FLOOR)
    H_t = bucket_size(max(t.num_hosts for t in mutated) + 1,
                      HOST_BUCKET_FLOOR)
    P_t = bucket_size(max(t.num_partitions for t in mutated) + 1,
                      PARTITION_BUCKET_FLOOR)
    R_t = bucket_size(max(t.num_replicas - t.num_partitions
                          for t in mutated) + P_t, REPLICA_BUCKET_FLOOR)
    return B_t, H_t, P_t, R_t


def compile_grid(topo: ClusterTopology, assign: Assignment,
                 scenarios: Sequence[Scenario]) -> ScenarioGrid:
    """Apply every scenario, pad all of them into one shared bucket, and
    stack the device mirrors into a single leading-axis batch."""
    if not scenarios:
        raise ValueError("compile_grid needs at least one scenario")
    mutated = [apply_scenario(topo, assign, s) for s in scenarios]
    m = max(t.max_rf for t, _ in mutated)
    mutated = [(_widen_rf(t, m), a) for t, a in mutated]
    B_t, H_t, P_t, R_t = grid_targets([t for t, _ in mutated])

    compiled = []
    for s, (t, a) in zip(scenarios, mutated):
        opts = G.default_options(t)
        t_p, a_p, info = pad_topology(
            t, a, broker_target=B_t, host_target=H_t,
            partition_target=P_t, replica_target=R_t)
        opts_p = G.pad_options(opts, R_t, B_t)
        compiled.append(CompiledScenario(
            scenario=s, topo=t_p, assign=a_p, options=opts_p, info=info))

    dts = [device_topology(c.topo) for c in compiled]
    stack = lambda *xs: jnp.stack(xs)  # noqa: E731 — tree.map thunk
    return ScenarioGrid(
        compiled=tuple(compiled),
        dts=jax.tree.map(stack, *dts),
        assigns=jax.tree.map(stack, *[c.assign for c in compiled]),
        options=jax.tree.map(stack, *[c.options for c in compiled]),
        num_topics=topo.num_topics,
        bucket=(B_t, H_t, P_t, R_t),
    )
