"""Rightsizing: fold a what-if grid into a ProvisionRecommendation.

Port of the reference Provisioner surface (``provision/
ProvisionRecommendation.java``, ``RightsizeOptions.java``): classify the
cluster UNDER/OVER/RIGHT_SIZED against the hard goals, find the minimum
broker count that satisfies all of them under a configurable headroom
margin, and report the cheapest feasible scenario + an estimate of the
moves a subsequent rebalance needs.

Classification runs on the assignment-invariant structural bounds from
:mod:`.whatif` — an as-is violation that some assignment could fix is a
job for self-healing, not for provisioning; only a bound that NO
assignment can satisfy makes the cluster under-provisioned.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer.annealer import AnnealConfig
from cruise_control_tpu.common.resources import BalancingConstraint
from cruise_control_tpu.models.cluster import Assignment, ClusterTopology
from cruise_control_tpu.provisioner.scenarios import (
    BASELINE,
    Scenario,
    add_brokers,
    compile_grid,
    remove_brokers,
)
from cruise_control_tpu.provisioner.whatif import (
    ScenarioScore,
    WhatIfResult,
    evaluate_grid,
)

UNDER_PROVISIONED = "UNDER_PROVISIONED"
OVER_PROVISIONED = "OVER_PROVISIONED"
RIGHT_SIZED = "RIGHT_SIZED"


@dataclasses.dataclass(frozen=True)
class ProvisionRecommendation:
    """The operator-facing verdict (ProvisionRecommendation.java)."""

    status: str
    num_brokers: int                       # alive brokers today
    recommended_brokers: Optional[int]     # min/target alive broker count
    headroom_margin: float
    unfixable_goals: Tuple[str, ...]       # hard goals no assignment fixes
    cheapest_feasible_scenario: Optional[str]
    moves_required: Optional[int]          # replica moves (estimate)
    reason: str

    @property
    def delta_brokers(self) -> int:
        if self.recommended_brokers is None:
            return 0
        return self.recommended_brokers - self.num_brokers

    def to_dict(self) -> dict:
        return {
            "status": self.status,
            "numBrokers": self.num_brokers,
            "recommendedBrokers": self.recommended_brokers,
            "deltaBrokers": self.delta_brokers,
            "headroomMargin": self.headroom_margin,
            "unfixableGoals": list(self.unfixable_goals),
            "cheapestFeasibleScenario": self.cheapest_feasible_scenario,
            "movesRequired": self.moves_required,
            "reason": self.reason,
        }


def _move_estimate(score: ScenarioScore,
                   goal_names: Sequence[str]) -> int:
    """Replica moves a fix needs, from the scenario's as-is picture: every
    offline replica must move, plus the as-is rack-excess replicas (each
    excess is one misplaced replica). A lower bound — deep mode replaces
    it with the anneal witness."""
    if score.estimated_replica_moves is not None:
        return score.estimated_replica_moves
    rack_excess = 0.0
    goal_names = tuple(goal_names)
    if "RackAwareGoal" in goal_names:
        rack_excess = float(
            score.violations[goal_names.index("RackAwareGoal")])
    return int(np.ceil(score.offline_replicas + rack_excess))


class Provisioner:
    """Batched rightsizing engine over the what-if grid evaluator."""

    def __init__(self, constraint: Optional[BalancingConstraint] = None,
                 goal_names: Optional[Sequence[str]] = None,
                 headroom_margin: float = 0.1,
                 max_added_brokers: int = 16,
                 max_removed_brokers: int = 8,
                 balancedness_weights=None,
                 anneal_config: Optional[AnnealConfig] = None,
                 tracer=None):
        from cruise_control_tpu.obs.tracing import NOOP_TRACER
        self._constraint = constraint or BalancingConstraint()
        self._goals = tuple(goal_names or G.ANOMALY_DETECTION_GOALS)
        self._headroom = float(headroom_margin)
        self._max_added = int(max_added_brokers)
        self._max_removed = int(max_removed_brokers)
        self._balancedness_weights = balancedness_weights
        self._anneal_config = anneal_config
        #: graftscope tracer — the what-if grid and the rightsize fold
        #: record `whatif-grid` / `rightsize` spans (and thereby stage
        #: timers in the registry); None = shared no-op
        self._tracer = tracer or NOOP_TRACER

    # -- ad-hoc what-if (the WHAT_IF endpoint) ---------------------------

    def what_if(self, topo: ClusterTopology, assign: Assignment,
                scenarios: Sequence[Scenario], deep: bool = False,
                headroom: Optional[float] = None,
                seed: int = 0) -> WhatIfResult:
        with self._tracer.span("whatif-grid",
                               scenarios=len(scenarios)) as sp:
            grid = compile_grid(topo, assign, tuple(scenarios))
            out = evaluate_grid(
                grid, self._constraint, self._goals,
                headroom=(self._headroom if headroom is None
                          else float(headroom)),
                balancedness_weights=self._balancedness_weights,
                deep=deep, anneal_config=self._anneal_config, seed=seed)
            sp.set("deep", bool(deep))
        return out

    # -- rightsizing (detector + RIGHTSIZE endpoint) ---------------------

    def _least_loaded_alive(self, topo: ClusterTopology,
                            assign: Assignment, k: int) -> Tuple[int, ...]:
        """External ids of the k least-loaded alive brokers (ties by id)."""
        bo = np.asarray(jax.device_get(assign.broker_of))
        counts = np.bincount(bo, minlength=topo.num_brokers)
        rows = sorted(np.flatnonzero(np.asarray(topo.broker_alive)),
                      key=lambda b: (counts[b], b))[:k]
        if topo.broker_ids is not None:
            return tuple(int(topo.broker_ids[r]) for r in rows)
        return tuple(int(r) for r in rows)

    def recommend(self, topo: ClusterTopology, assign: Assignment,
                  headroom_margin: Optional[float] = None,
                  max_added_brokers: Optional[int] = None,
                  max_removed_brokers: Optional[int] = None,
                  deep: bool = False, seed: int = 0,
                  ) -> Tuple[ProvisionRecommendation, WhatIfResult]:
        """Classify the cluster and return (recommendation, full grid).

        One compiled batch scores the baseline plus every add/remove
        candidate; the fold below is pure host logic."""
        with self._tracer.span("rightsize"):
            return self._recommend(topo, assign, headroom_margin,
                                   max_added_brokers, max_removed_brokers,
                                   deep, seed)

    def _recommend(self, topo, assign, headroom_margin, max_added_brokers,
                   max_removed_brokers, deep, seed
                   ) -> Tuple[ProvisionRecommendation, WhatIfResult]:
        headroom = (self._headroom if headroom_margin is None
                    else float(headroom_margin))
        max_add = (self._max_added if max_added_brokers is None
                   else int(max_added_brokers))
        max_rm = (self._max_removed if max_removed_brokers is None
                  else int(max_removed_brokers))
        n_alive = int(np.sum(np.asarray(topo.broker_alive)))
        max_rm = min(max_rm, max(n_alive - 1, 0))

        scenarios = [BASELINE]
        scenarios += [Scenario(f"add-{n}", (add_brokers(n),))
                      for n in range(1, max_add + 1)]
        remove_ks = list(range(1, max_rm + 1))
        for k in remove_ks:
            ids = self._least_loaded_alive(topo, assign, k)
            scenarios.append(Scenario(f"remove-{k}", (remove_brokers(ids),)))

        result = self.what_if(topo, assign, scenarios, deep=deep,
                              headroom=headroom, seed=seed)
        base = result.scores[0]
        adds = {n: result.score_of(f"add-{n}")
                for n in range(1, max_add + 1)}
        removes = {k: result.score_of(f"remove-{k}") for k in remove_ks}

        if not base.feasible:
            fix_n = next((n for n in sorted(adds) if adds[n].feasible), None)
            if fix_n is None:
                return ProvisionRecommendation(
                    status=UNDER_PROVISIONED,
                    num_brokers=n_alive,
                    recommended_brokers=None,
                    headroom_margin=headroom,
                    unfixable_goals=base.infeasible_goals,
                    cheapest_feasible_scenario=None,
                    moves_required=None,
                    reason=(f"no assignment satisfies "
                            f"{', '.join(base.infeasible_goals)} even "
                            f"after adding {max_add} brokers"),
                ), result
            chosen = adds[fix_n]
            return ProvisionRecommendation(
                status=UNDER_PROVISIONED,
                num_brokers=n_alive,
                recommended_brokers=n_alive + fix_n,
                headroom_margin=headroom,
                unfixable_goals=base.infeasible_goals,
                cheapest_feasible_scenario=chosen.scenario.name,
                moves_required=_move_estimate(chosen, self._goals),
                reason=(f"{', '.join(base.infeasible_goals)} cannot be "
                        f"satisfied by any assignment on {n_alive} alive "
                        f"brokers; adding {fix_n} restores feasibility "
                        f"with {headroom:.0%} headroom"),
            ), result

        shrink = max((k for k in remove_ks if removes[k].feasible),
                     default=0)
        if shrink > 0:
            chosen = removes[shrink]
            return ProvisionRecommendation(
                status=OVER_PROVISIONED,
                num_brokers=n_alive,
                recommended_brokers=n_alive - shrink,
                headroom_margin=headroom,
                unfixable_goals=(),
                cheapest_feasible_scenario=chosen.scenario.name,
                moves_required=_move_estimate(chosen, self._goals),
                reason=(f"all hard goals stay satisfiable with "
                        f"{headroom:.0%} headroom after removing the "
                        f"{shrink} least-loaded broker(s)"),
            ), result

        return ProvisionRecommendation(
            status=RIGHT_SIZED,
            num_brokers=n_alive,
            recommended_brokers=n_alive,
            headroom_margin=headroom,
            unfixable_goals=(),
            cheapest_feasible_scenario=BASELINE.name,
            moves_required=0,
            reason=(f"hard goals satisfiable on the current {n_alive} "
                    f"alive brokers; no removal candidate keeps "
                    f"{headroom:.0%} headroom"),
        ), result
