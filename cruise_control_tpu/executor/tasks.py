"""Execution tasks: lifecycle, planning, and movement strategies.

Mirrors ``executor/ExecutionTask.java`` (state machine PENDING → IN_PROGRESS
→ {COMPLETED, ABORTING → ABORTED, DEAD}), ``executor/ExecutionTaskPlanner.java:44-110``
(per-broker sorted pending task sets ordered by a pluggable strategy chain)
and ``executor/strategy/*.java`` (Base, PostponeUrp, PrioritizeLarge,
PrioritizeSmall).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set

from cruise_control_tpu.analyzer.proposals import ExecutionProposal


class TaskState(enum.Enum):
    PENDING = "PENDING"
    IN_PROGRESS = "IN_PROGRESS"
    ABORTING = "ABORTING"
    ABORTED = "ABORTED"
    DEAD = "DEAD"
    COMPLETED = "COMPLETED"


class TaskType(enum.Enum):
    INTER_BROKER_REPLICA_ACTION = "INTER_BROKER_REPLICA_ACTION"
    INTRA_BROKER_REPLICA_ACTION = "INTRA_BROKER_REPLICA_ACTION"
    LEADER_ACTION = "LEADER_ACTION"


_VALID_TRANSITIONS = {
    TaskState.PENDING: {TaskState.IN_PROGRESS},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD,
                            TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.ABORTED: set(),
    TaskState.DEAD: set(),
    TaskState.COMPLETED: set(),
}


@dataclasses.dataclass
class ExecutionTask:
    """One unit of work the executor drives to completion."""

    execution_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: int = -1
    end_time_ms: int = -1
    alert_time_ms: int = -1

    def transition(self, to: TaskState, now_ms: int = -1):
        if to not in _VALID_TRANSITIONS[self.state]:
            raise ValueError(f"illegal transition {self.state} -> {to} "
                             f"for task {self.execution_id}")
        self.state = to
        if to == TaskState.IN_PROGRESS:
            self.start_time_ms = now_ms
        elif to in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD):
            self.end_time_ms = now_ms

    @property
    def done(self) -> bool:
        return self.state in (TaskState.COMPLETED, TaskState.ABORTED,
                              TaskState.DEAD)

    def brokers_involved(self) -> Set[int]:
        return set(self.proposal.old_replicas) | set(self.proposal.new_replicas)


# ---------------------------------------------------------------------------
# Movement strategies (executor/strategy/*.java)
# ---------------------------------------------------------------------------


class ReplicaMovementStrategy:
    """Orders inter-broker movement tasks; chained like the reference's
    ``chain(...)`` (AbstractReplicaMovementStrategy)."""

    name = "BaseReplicaMovementStrategy"

    def sort_key(self, task: ExecutionTask, urp: Set[str]):
        return task.execution_id

    def chain(self, nxt: "ReplicaMovementStrategy") -> "ReplicaMovementStrategy":
        outer = self

        class _Chained(ReplicaMovementStrategy):
            name = f"{outer.name}->{nxt.name}"

            def sort_key(self, task, urp):
                return (outer.sort_key(task, urp), nxt.sort_key(task, urp))

        return _Chained()


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """Execution-id order (executor/strategy/BaseReplicaMovementStrategy.java)."""


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move partitions with no under-replicated state first
    (PostponeUrpReplicaMovementStrategy.java)."""

    name = "PostponeUrpReplicaMovementStrategy"

    def sort_key(self, task, urp):
        return 1 if task.proposal.topic_partition in urp else 0


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    """Large replicas first (PrioritizeLargeReplicaMovementStrategy.java)."""

    name = "PrioritizeLargeReplicaMovementStrategy"

    def sort_key(self, task, urp):
        return -task.proposal.data_size


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    """Small replicas first (PrioritizeSmallReplicaMovementStrategy.java)."""

    name = "PrioritizeSmallReplicaMovementStrategy"

    def sort_key(self, task, urp):
        return task.proposal.data_size


STRATEGIES = {cls.name if hasattr(cls, "name") else cls.__name__: cls
              for cls in (BaseReplicaMovementStrategy,
                          PostponeUrpReplicaMovementStrategy,
                          PrioritizeLargeReplicaMovementStrategy,
                          PrioritizeSmallReplicaMovementStrategy)}


# ---------------------------------------------------------------------------
# Planner (executor/ExecutionTaskPlanner.java)
# ---------------------------------------------------------------------------


class ExecutionTaskPlanner:
    """Splits proposals into replica-move / leadership task pools and hands
    out per-round batches honoring per-broker concurrency."""

    def __init__(self, strategy: Optional[ReplicaMovementStrategy] = None,
                 id_start: int = 0):
        # ``id_start`` fences the execution epoch into every task ID
        # (``epoch << 32 | seq``): journaled records from different process
        # incarnations can never collide, and a zombie's stale IDs are
        # recognizable on sight.
        self._strategy = strategy or BaseReplicaMovementStrategy()
        self._id_gen = itertools.count(id_start)
        self.replica_tasks: List[ExecutionTask] = []
        self.leadership_tasks: List[ExecutionTask] = []
        self.intra_broker_tasks: List[ExecutionTask] = []

    def add_proposals(self, proposals: Iterable[ExecutionProposal],
                      urp: Optional[Set[str]] = None):
        urp = urp or set()
        # device-decoded proposal sets (analyzer.proposals.LazyProposals)
        # carry per-proposal action masks computed by the diff kernel in the
        # same compact transfer as the movement stats — consume those
        # instead of re-deriving has_replica_action / has_leader_action as
        # ~150K Python set comparisons. Duck-typed so the executor layer
        # stays import-free of the analyzer.
        rep_mask = lead_mask = None
        if hasattr(proposals, "replica_action_mask"):
            rep_mask = proposals.replica_action_mask
            lead_mask = proposals.leader_action_mask
        for i, p in enumerate(proposals):
            if (p.has_replica_action if rep_mask is None
                    else bool(rep_mask[i])):
                self.replica_tasks.append(ExecutionTask(
                    next(self._id_gen), p, TaskType.INTER_BROKER_REPLICA_ACTION))
            # A leadership task is created for EVERY proposal with a leader
            # action, including those that also move replicas: reassignment
            # alone does not transfer leadership while the old leader remains
            # in the replica set (ExecutionTaskPlanner.java:250-258,
            # maybeAddLeaderChangeTasks).
            if (p.has_leader_action if lead_mask is None
                    else bool(lead_mask[i])):
                self.leadership_tasks.append(ExecutionTask(
                    next(self._id_gen), p, TaskType.LEADER_ACTION))
        self.replica_tasks.sort(
            key=lambda t: (self._strategy.sort_key(t, urp), t.execution_id))

    def next_replica_batch(self, concurrency_per_broker: int,
                           in_flight_by_broker: Dict[int, int]) -> List[ExecutionTask]:
        """Pending movement tasks whose brokers have spare concurrency
        (ExecutionTaskPlanner.getInterBrokerReplicaMovementTasks)."""
        batch: List[ExecutionTask] = []
        counts = dict(in_flight_by_broker)
        for t in self.replica_tasks:
            if t.state != TaskState.PENDING:
                continue
            brokers = t.brokers_involved()
            if all(counts.get(b, 0) < concurrency_per_broker for b in brokers):
                for b in brokers:
                    counts[b] = counts.get(b, 0) + 1
                batch.append(t)
        return batch

    def next_leadership_batch(self, max_batch: int) -> List[ExecutionTask]:
        out = [t for t in self.leadership_tasks
               if t.state == TaskState.PENDING][:max_batch]
        return out

    @property
    def remaining(self) -> int:
        return sum(1 for t in itertools.chain(
            self.replica_tasks, self.leadership_tasks, self.intra_broker_tasks)
            if not t.done)


# ---------------------------------------------------------------------------
# Tracker (executor/ExecutionTaskManager.java / ExecutionTaskTracker.java)
# ---------------------------------------------------------------------------


class ExecutionTaskTracker:
    """Counts tasks by (type, state) and in-flight per broker."""

    def __init__(self):
        self._lock = threading.Lock()
        self.by_state: Dict[TaskType, Dict[TaskState, int]] = {
            t: {s: 0 for s in TaskState} for t in TaskType}
        self.in_flight_by_broker: Dict[int, int] = {}
        self.finished_data_movement_mb = 0.0

    def mark(self, task: ExecutionTask, frm: TaskState):
        with self._lock:
            self.by_state[task.task_type][frm] -= 1 if self.by_state[
                task.task_type][frm] > 0 else 0
            self.by_state[task.task_type][task.state] += 1
            if task.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
                delta = (1 if task.state == TaskState.IN_PROGRESS
                         else -1 if frm == TaskState.IN_PROGRESS else 0)
                if delta:
                    for b in task.brokers_involved():
                        self.in_flight_by_broker[b] = max(
                            0, self.in_flight_by_broker.get(b, 0) + delta)
            if (task.state == TaskState.COMPLETED
                    and task.task_type == TaskType.INTER_BROKER_REPLICA_ACTION):
                self.finished_data_movement_mb += task.proposal.inter_broker_data_to_move()

    def register(self, tasks: Iterable[ExecutionTask]):
        with self._lock:
            for t in tasks:
                self.by_state[t.task_type][t.state] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                t.value: {s.value: n for s, n in states.items() if n}
                for t, states in self.by_state.items()}
