"""Executor: drives accepted proposals against the live cluster.

Rebuild of ``executor/Executor.java:69-1100``: three phases per execution —
inter-broker replica moves (batched by per-broker concurrency,
``Executor.java:932``), intra-broker moves (:995), leadership moves (:1050) —
with progress polling, graceful/forced stop, replication throttling, and
notifier callbacks. The cluster-side apply API is the pluggable
:class:`ClusterAdapter` — the seam the reference implements with the Scala
ZK bridge (``ExecutorUtils.scala:22-34``) + AdminClient; tests use
:class:`FakeClusterAdapter`.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import random
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

logger = logging.getLogger(__name__)

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.journal import ExecutionJournal, StaleEpochError
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskPlanner,
    ExecutionTaskTracker,
    ReplicaMovementStrategy,
    TaskState,
    TaskType,
)

#: journal states that need no reconciliation on restart
_TERMINAL_TASK_STATES = frozenset({
    TaskState.COMPLETED.value, TaskState.ABORTED.value, TaskState.DEAD.value})


class ExecutorState(enum.Enum):
    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = \
        "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = \
        "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


class ClusterAdapter:
    """The cluster-side apply seam (ExecutorUtils.scala / ExecutorAdminUtils).

    A Kafka implementation submits reassignments via the admin/ZK API; the
    fake applies them after a configurable number of polls.
    """

    def execute_replica_reassignments(self, tasks: Sequence[ExecutionTask]) -> None:
        raise NotImplementedError

    def execute_preferred_leader_elections(self, tasks: Sequence[ExecutionTask]) -> None:
        raise NotImplementedError

    def current_replicas(self, topic_partition: str) -> Tuple[int, ...]:
        raise NotImplementedError

    def current_leader(self, topic_partition: str) -> int:
        raise NotImplementedError

    def in_progress_reassignments(self) -> Set[str]:
        raise NotImplementedError

    def cancel_reassignments(self, tasks: Sequence[ExecutionTask]) -> None:
        """Actively cancel the in-flight reassignments of ``tasks``, rolling
        each partition back to a safe (pre-move) target — the adapter-side
        half of a graceful abort (Executor.java abort handling +
        ExecutorUtils.scala:22-34; KIP-455 cancellation post-2.4). Adapters
        that cannot cancel may leave this unimplemented; the executor then
        falls back to bookkeeping-only aborts."""
        raise NotImplementedError

    # -- replication throttling (ReplicationThrottleHelper.java:29-79 seam):
    # per-broker leader/follower rates + per-topic throttled replica lists.
    def set_broker_throttle_rate(self, broker_ids: Sequence[int],
                                 rate_bytes_per_sec: int) -> None:
        """Set leader.replication.throttled.rate and
        follower.replication.throttled.rate on each broker."""

    def clear_broker_throttle_rate(self, broker_ids: Sequence[int]) -> None:
        pass

    def set_topic_throttled_replicas(self, topic: str,
                                     leader_entries: Sequence[str],
                                     follower_entries: Sequence[str]) -> None:
        """Set {leader,follower}.replication.throttled.replicas on the topic;
        entries are "partition:brokerId" strings."""

    def clear_topic_throttled_replicas(self, topic: str) -> None:
        pass

    def dead_brokers(self) -> Set[int]:
        return set()

    def describe_logdirs(self) -> Dict[int, Dict[str, bool]]:
        """Logdir liveness per broker (AdminClient describeLogDirs — the
        DiskFailureDetector.java:35-85 seam): {broker_id: {logdir: alive}}."""
        return {}

    def alter_replica_logdirs(self, moves) -> None:
        """Apply intra-broker logdir moves (AdminClient alterReplicaLogDirs,
        Executor.java:995 seam)."""
        raise NotImplementedError


class FakeClusterAdapter(ClusterAdapter):
    """In-memory cluster: reassignments complete after ``latency_polls``
    polls — the test double standing in for the embedded-broker harness."""

    def __init__(self, replicas_by_tp: Dict[str, Tuple[int, ...]],
                 leaders_by_tp: Optional[Dict[str, int]] = None,
                 latency_polls: int = 1):
        self.replicas: Dict[str, Tuple[int, ...]] = dict(replicas_by_tp)
        self.leaders: Dict[str, int] = dict(leaders_by_tp or {
            tp: reps[0] for tp, reps in replicas_by_tp.items()})
        self.latency = latency_polls
        self._pending: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
        self._pending_ple: Dict[str, Tuple[int, int]] = {}
        self.broker_throttle_rates: Dict[int, int] = {}
        self.topic_throttled_replicas: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        self._dead: Set[int] = set()
        self.logdir_state: Dict[int, Dict[str, bool]] = {}

    # -- adapter API --
    def execute_replica_reassignments(self, tasks):
        for t in tasks:
            self._pending[t.proposal.topic_partition] = (
                self.latency, t.proposal.new_replicas)

    def execute_preferred_leader_elections(self, tasks):
        for t in tasks:
            self._pending_ple[t.proposal.topic_partition] = (
                self.latency, t.proposal.new_replicas)

    def current_replicas(self, tp):
        self._tick(tp)
        return self.replicas.get(tp, ())

    def current_leader(self, tp):
        self._tick(tp)
        return self.leaders.get(tp, -1)

    def in_progress_reassignments(self):
        return set(self._pending)

    def cancel_reassignments(self, tasks):
        """Stop the pending moves: the partition keeps its current replica
        set (the old assignment — the fake applies atomically on completion)."""
        for t in tasks:
            self._pending.pop(t.proposal.topic_partition, None)

    def set_broker_throttle_rate(self, broker_ids, rate):
        for b in broker_ids:
            self.broker_throttle_rates[int(b)] = rate

    def clear_broker_throttle_rate(self, broker_ids):
        for b in broker_ids:
            self.broker_throttle_rates.pop(int(b), None)

    def set_topic_throttled_replicas(self, topic, leader_entries,
                                     follower_entries):
        self.topic_throttled_replicas[topic] = {
            "leader": tuple(leader_entries),
            "follower": tuple(follower_entries)}

    def clear_topic_throttled_replicas(self, topic):
        self.topic_throttled_replicas.pop(topic, None)

    def dead_brokers(self):
        return set(self._dead)

    def kill_broker(self, broker_id: int):
        self._dead.add(broker_id)

    def describe_logdirs(self):
        return {b: dict(dirs) for b, dirs in self.logdir_state.items()}

    def fail_disk(self, broker_id: int, logdir: str):
        self.logdir_state.setdefault(int(broker_id), {})[logdir] = False

    def alter_replica_logdirs(self, moves):
        for m in moves:
            self.logdir_by_tp_broker = getattr(self, "logdir_by_tp_broker", {})
            self.logdir_by_tp_broker[
                (f"{m.topic}-{m.partition}", m.broker_id)] = m.to_logdir

    def _tick(self, tp):
        if tp in self._pending:
            n, target = self._pending[tp]
            if n <= 1:
                self.replicas[tp] = target
                if self.leaders.get(tp) not in target:
                    self.leaders[tp] = target[0]
                del self._pending[tp]
            else:
                self._pending[tp] = (n - 1, target)
        if tp in self._pending_ple:
            n, new_order = self._pending_ple[tp]
            if n <= 1:
                self.leaders[tp] = new_order[0]
                # the real adapter writes the FULL proposal order before the
                # election; mirror it exactly when it is a pure reorder
                reps = self.replicas.get(tp)
                if reps and set(reps) == set(new_order):
                    self.replicas[tp] = tuple(new_order)
                del self._pending_ple[tp]
            else:
                self._pending_ple[tp] = (n - 1, new_order)


#: adapter API methods the executor wraps in retry-with-backoff — the full
#: cluster-facing surface of :class:`ClusterAdapter`
_ADAPTER_RETRY_METHODS = frozenset({
    "execute_replica_reassignments", "execute_preferred_leader_elections",
    "current_replicas", "current_leader", "in_progress_reassignments",
    "cancel_reassignments", "set_broker_throttle_rate",
    "clear_broker_throttle_rate", "set_topic_throttled_replicas",
    "clear_topic_throttled_replicas", "dead_brokers", "describe_logdirs",
    "alter_replica_logdirs",
})


class RetryingClusterAdapter:
    """Retry-with-exponential-backoff+jitter shim over a ClusterAdapter.

    The reference retries transient admin failures (timeouts, controller
    handoffs, disconnects) before giving up on a task; this wrapper gives
    every adapter call that discipline, governed by ``executor.adapter.
    retries`` / ``executor.adapter.retry.backoff.ms`` / ``executor.adapter.
    retry.backoff.max.ms``. ``NotImplementedError`` passes straight through —
    it is a capability signal (e.g. an adapter that cannot cancel), not a
    failure. Config is read per call so per-instance tuning after
    construction takes effect.
    """

    def __init__(self, inner: ClusterAdapter, config: "ExecutorConfig",
                 on_retry: Optional[Callable[[str], None]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        self._inner = inner
        self._config = config
        self._on_retry = on_retry
        self._sleep = sleep
        self._rng = rng or random.Random()

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in _ADAPTER_RETRY_METHODS or not callable(attr):
            return attr

        def call(*args, **kwargs):
            retries = max(0, self._config.adapter_retries)
            backoff_s = max(self._config.adapter_retry_backoff_ms, 1) / 1000.0
            cap_s = max(self._config.adapter_retry_backoff_max_ms, 1) / 1000.0
            for attempt in range(retries + 1):
                try:
                    return attr(*args, **kwargs)
                except NotImplementedError:
                    raise
                except Exception:
                    if attempt >= retries:
                        raise
                    delay = min(cap_s, backoff_s * (2 ** attempt))
                    # full-jitter lower half: [0.5, 1.0) of the nominal delay
                    delay *= 0.5 + self._rng.random() * 0.5
                    logger.warning(
                        "adapter call %s failed (attempt %d/%d); retrying "
                        "in %.3f s", name, attempt + 1, retries + 1, delay,
                        exc_info=True)
                    if self._on_retry is not None:
                        self._on_retry(name)
                    self._sleep(delay)

        call.__name__ = name
        return call


class ReplicationThrottleHelper:
    """Sets/clears leader+follower throttled rates and per-topic throttled
    replica lists around an execution (ReplicationThrottleHelper.java:29-79):

    - every broker participating in a move gets the throttled *rate*;
    - each moved partition's topic gets ``leader.replication.throttled.replicas``
      entries "partition:broker" for the OLD replicas (they lead/serve the
      transfer) and ``follower.replication.throttled.replicas`` entries for
      the ADDED replicas (they fetch), and both are removed afterwards.
    """

    def __init__(self, adapter: ClusterAdapter, rate_bytes_per_sec: int):
        self.adapter = adapter
        self.rate = rate_bytes_per_sec
        self._brokers: Set[int] = set()
        self._topics: Set[str] = set()

    def set_throttles(self, proposals: Sequence[ExecutionProposal]) -> None:
        leader_entries: Dict[str, List[str]] = {}
        follower_entries: Dict[str, List[str]] = {}
        for p in proposals:
            if not p.replicas_to_add:
                continue
            leader_entries.setdefault(p.topic, []).extend(
                f"{p.partition}:{b}" for b in p.old_replicas)
            follower_entries.setdefault(p.topic, []).extend(
                f"{p.partition}:{b}" for b in p.replicas_to_add)
            self._brokers |= set(p.old_replicas) | set(p.new_replicas)
        if self._brokers:
            self.adapter.set_broker_throttle_rate(sorted(self._brokers),
                                                  self.rate)
        for topic in leader_entries:
            self._topics.add(topic)
            self.adapter.set_topic_throttled_replicas(
                topic, sorted(leader_entries[topic]),
                sorted(follower_entries.get(topic, [])))

    def clear_throttles(self) -> None:
        if self._brokers:
            self.adapter.clear_broker_throttle_rate(sorted(self._brokers))
        for topic in sorted(self._topics):
            self.adapter.clear_topic_throttled_replicas(topic)
        self._brokers.clear()
        self._topics.clear()


class ExecutorNotifier:
    """SPI (executor/ExecutorNotifier.java)."""

    def on_execution_finished(self, summary: dict):
        pass

    def on_execution_stopped(self, summary: dict):
        pass


class LoggingExecutorNotifier(ExecutorNotifier):
    """Default notifier: executions land in the operation log (the
    reference's OPERATION_LOGGER discipline, Executor.java:71)."""

    def on_execution_finished(self, summary: dict):
        logger.info("execution finished: %s", summary)

    def on_execution_stopped(self, summary: dict):
        logger.warning("execution stopped: %s", summary)


#: ``executor.notifier.class`` registry (ExecutorNotifier SPI).
EXECUTOR_NOTIFIER_REGISTRY = {
    "ExecutorNotifier": ExecutorNotifier,
    "LoggingExecutorNotifier": LoggingExecutorNotifier,
}


@dataclasses.dataclass
class ExecutorConfig:
    num_concurrent_partition_movements_per_broker: int = 5
    num_concurrent_intra_broker_partition_movements: int = 2
    num_concurrent_leader_movements: int = 1000
    #: max.num.cluster.movements — hard cap on ongoing movement tasks in
    #: one execution (None = unlimited)
    max_num_cluster_movements: Optional[int] = None
    execution_progress_check_interval_ms: int = 10
    max_execution_progress_check_rounds: int = 10_000
    #: executor.adapter.retries / executor.adapter.retry.backoff{,.max}.ms —
    #: per-adapter-call retry budget with exponential backoff + jitter
    adapter_retries: int = 3
    adapter_retry_backoff_ms: int = 100
    adapter_retry_backoff_max_ms: int = 10_000
    #: executor.task.stuck.deadline.ms — abort an in-flight task whose
    #: adapter-observed progress has not changed for this long (the
    #: reference's task-stuck condition; None disables the check)
    task_stuck_deadline_ms: Optional[int] = 300_000
    default_replication_throttle: Optional[int] = None
    #: leader.movement.timeout.ms — wall-clock bound on one leadership batch;
    #: the round budget is derived from the EFFECTIVE check interval at
    #: execution time so a per-request interval override cannot stretch it
    leader_movement_timeout_ms: int = 180_000
    #: warn when a single task stays in flight past this
    #: (task.execution.alerting.threshold.ms)
    task_execution_alerting_threshold_ms: int = 90_000
    #: how long removed/demoted brokers stay in the recently-* sets
    #: ({removal,demotion}.history.retention.time.ms)
    removal_history_retention_ms: int = 1_209_600_000
    demotion_history_retention_ms: int = 1_209_600_000
    #: alert when the achieved movement rate (MB/s) falls below these
    #: ({inter,intra}.broker.replica.movement.rate.alerting.threshold)
    inter_broker_movement_rate_alerting_threshold: float = 0.1
    intra_broker_movement_rate_alerting_threshold: float = 0.2


class Executor:
    """Applies proposals; one execution at a time (Executor.java:383)."""

    def __init__(self, adapter: ClusterAdapter,
                 config: Optional[ExecutorConfig] = None,
                 notifier: Optional[ExecutorNotifier] = None,
                 strategy: Optional[ReplicaMovementStrategy] = None,
                 clock: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep,
                 journal: Optional[ExecutionJournal] = None,
                 heartbeat: Optional[Callable[[], None]] = None,
                 tracer=None):
        from cruise_control_tpu.obs.tracing import NOOP_TRACER
        self.adapter = adapter
        self.config = config or ExecutorConfig()
        self.notifier = notifier or ExecutorNotifier()
        self._strategy = strategy
        # graftscope spans: execution phases + restart reconciliation
        self._tracer = tracer or NOOP_TRACER
        # write-ahead execution journal (None = journaling disabled) and the
        # watchdog heartbeat the progress loop checks into every poll round
        self._journal = journal
        self._beat = heartbeat or (lambda: None)
        self.recovering = False
        self._last_recovery: Optional[dict] = None
        # virtual-time seam: every deadline/timestamp decision (stuck tasks,
        # alerting thresholds, history retention) reads ``clock``; every
        # poll-interval and retry-backoff wait goes through ``sleep``. A
        # scenario run passes a VirtualClock so a simulated latency storm
        # costs zero wall time.
        self._clock = clock
        self._sleep_fn = sleep
        self._state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = threading.Event()
        self._force_stop = threading.Event()
        self._timed_out = False
        # RLock: state transitions inside execute_proposals happen while the
        # acquisition check (which also takes it) may sit on the same stack
        self._lock = threading.RLock()
        self.tracker = ExecutionTaskTracker()
        self._interval_override_ms: Optional[int] = None
        self._planner: Optional[ExecutionTaskPlanner] = None
        self._history_lock = threading.Lock()
        self._removal_history: Dict[int, float] = {}   # broker → record ts (s)
        self._demotion_history: Dict[int, float] = {}
        self._execution_history: List[dict] = []
        # per-execution fault-tolerance tallies (reset in execute_proposals)
        self._exec_retries = 0
        self._exec_task_failures = 0
        self._exec_stuck = 0

    @property
    def _adapter(self) -> RetryingClusterAdapter:
        """The retrying view of ``self.adapter`` — built per access so a
        swapped-in adapter (tests) is always the one retried."""
        return RetryingClusterAdapter(self.adapter, self.config,
                                      on_retry=self._note_retry,
                                      sleep=self._sleep_fn)

    def _note_retry(self, method: str) -> None:
        self._exec_retries += 1
        from cruise_control_tpu.common.metrics import REGISTRY
        REGISTRY.counter("adapter-call-retry-rate")

    # -- removal/demotion history (Executor.java:123-127 with the
    # {removal,demotion}.history.retention.time.ms windows). Readers prune
    # in place, so every access goes through the history lock — REST
    # threads, ADMIN drops, and executions touch these concurrently.
    def _pruned_history(self, hist: Dict[int, float],
                        retention_ms: int) -> Set[int]:
        with self._history_lock:
            cutoff = self._clock() - retention_ms / 1000.0
            for b in [b for b, ts in hist.items() if ts < cutoff]:
                del hist[b]
            return set(hist)

    @property
    def recently_removed_brokers(self) -> Set[int]:
        # the dict reference is created once in __init__ and never rebound;
        # _pruned_history takes the history lock before touching its contents
        return self._pruned_history(
            self._removal_history,  # graftlint: disable=G101
            self.config.removal_history_retention_ms)

    @property
    def recently_demoted_brokers(self) -> Set[int]:
        return self._pruned_history(
            self._demotion_history,  # graftlint: disable=G101
            self.config.demotion_history_retention_ms)

    def record_history(self, removed_brokers=(), demoted_brokers=()):
        now = self._clock()
        with self._history_lock:
            self._removal_history.update(
                {int(b): now for b in removed_brokers})
            self._demotion_history.update(
                {int(b): now for b in demoted_brokers})

    def drop_history(self, removed: bool = False, demoted: bool = False):
        """ADMIN drop_recently_removed/demoted_brokers."""
        with self._history_lock:
            if removed:
                self._removal_history.clear()
            if demoted:
                self._demotion_history.clear()

    # -- state --
    @property
    def state(self) -> ExecutorState:
        with self._lock:
            return self._state

    @property
    def has_ongoing_execution(self) -> bool:
        with self._lock:
            return self._state != ExecutorState.NO_TASK_IN_PROGRESS

    def state_snapshot(self) -> dict:
        out = {
            "state": self.state.value,
            "taskCounts": self.tracker.snapshot(),
            "finishedDataMovementMB": self.tracker.finished_data_movement_mb,
            "recentlyRemovedBrokers": sorted(self.recently_removed_brokers),
            "recentlyDemotedBrokers": sorted(self.recently_demoted_brokers),
            "executorRecovery": {
                "recovering": self.recovering,
                "lastRecovery": self._last_recovery,
            },
        }
        if self._journal is not None:
            out["journalPath"] = self._journal.path
            out["journalEntries"] = self._journal.entries
            last = self._journal.last_append_ms
            out["journalLagMs"] = (
                max(0, int(self._clock() * 1000) - last)
                if last is not None else None)
        if self._last_recovery is not None:
            out["lastRecovery"] = self._last_recovery
        return out

    # -- write-ahead journal --
    def _journal_task(self, task: ExecutionTask) -> None:
        """Append a task transition — BEFORE the corresponding cluster
        effect (write-ahead). A :class:`StaleEpochError` here means this
        process has been superseded; it propagates and aborts the
        execution before any further adapter mutation."""
        if self._journal is not None:
            self._journal.log_task(task.execution_id, task.task_type.value,
                                   task.proposal.topic_partition,
                                   task.state.value)

    # -- restart reconciliation --
    def _proposal_finished(self, p: ExecutionProposal) -> bool:
        tp = p.topic_partition
        if p.has_replica_action and not p.is_completed(
                self._adapter.current_replicas(tp)):
            return False
        if (p.has_leader_action
                and self._adapter.current_leader(tp) != p.new_replicas[0]):
            return False
        return True

    def attach_journal(self, journal) -> None:
        """Swap in a write-ahead journal (warm-standby promotion: the
        follower's tailed replica becomes the authoritative journal)."""
        self._journal = journal

    def recover(self, advance: bool = True, replay=None) -> dict:
        """Restart reconciliation (Executor.java onActivation semantics).

        Replays the write-ahead journal, claims a new execution epoch
        (fencing out any zombie pre-crash incarnation), classifies each
        journaled open task against live cluster metadata —

        ========================  =================================
        observation               action
        ========================  =================================
        terminal in journal       nothing (already resolved)
        target reached            completed — finish tracking
        adapter still moving it   still-moving — resume in new epoch
        journaled in-progress,    orphaned — cancel any stray move,
        neither of the above      then roll forward in new epoch
        journaled pending only    pending — re-execute
        ========================  =================================

        — then synchronously re-executes every unfinished proposal
        through the normal execution path (the adapters converge on
        re-submission). Returns (and stores for ``/state``) a summary.

        The warm-standby takeover path passes ``advance=False`` (the
        replication lease already advanced the epoch when it fenced the
        ex-leader — the journal *adopts* that epoch instead of double-
        fencing) and ``replay=<tailed state>`` (the follower accumulated
        the replay incrementally while tailing, so takeover skips the
        full-journal parse a cold restart pays).
        """
        if self._journal is None:
            return {"performed": False}
        with self._tracer.span("recover",
                               mode="cold" if advance else "warm") as _sp:
            summary = self._recover_impl(advance, replay)
            _sp.set("resumed", summary.get("resumed", 0))
            return summary

    def _recover_impl(self, advance: bool, replay) -> dict:
        t0 = self._clock()
        if replay is None:
            replay = self._journal.replay()
        self.recovering = True
        try:
            new_epoch = (self._journal.advance_epoch() if advance
                         else self._journal.adopt_epoch())
            counts = {"completed": 0, "stillMoving": 0, "orphaned": 0,
                      "pending": 0}
            unfinished: List[ExecutionProposal] = []
            rolled_back = 0
            open_exec = replay.open_execution
            if open_exec is not None:
                try:
                    in_prog = set(self._adapter.in_progress_reassignments())
                except NotImplementedError:
                    in_prog = set()
                for p in open_exec.proposals:
                    tp = p.topic_partition
                    states = {
                        open_exec.task_states.get(
                            (TaskType.INTER_BROKER_REPLICA_ACTION.value, tp)),
                        open_exec.task_states.get(
                            (TaskType.LEADER_ACTION.value, tp))}
                    states.discard(None)
                    if states and states <= _TERMINAL_TASK_STATES:
                        # every journaled leg reached a terminal state
                        # before the crash; nothing to reconcile
                        continue
                    if self._proposal_finished(p):
                        counts["completed"] += 1
                        continue
                    if tp in in_prog:
                        counts["stillMoving"] += 1
                    elif TaskState.IN_PROGRESS.value in states:
                        # submitted (journal says so) but the cluster shows
                        # neither progress nor completion: orphaned. Cancel
                        # any stray reassignment, then roll forward below.
                        counts["orphaned"] += 1
                        rolled_back += 1
                        orphan = ExecutionTask(
                            0, p, TaskType.INTER_BROKER_REPLICA_ACTION)
                        try:
                            self._adapter.cancel_reassignments([orphan])
                        except NotImplementedError:
                            pass
                        except Exception:
                            logger.exception(
                                "rollback of orphaned reassignment %s "
                                "failed; re-executing anyway", tp)
                    else:
                        counts["pending"] += 1
                    unfinished.append(p)
            resume_summary = None
            if unfinished:
                resume_summary = self.execute_proposals(
                    unfinished,
                    removed_brokers=open_exec.removed_brokers,
                    demoted_brokers=open_exec.demoted_brokers)
            remaining = [p for p in unfinished
                         if not self._proposal_finished(p)]
            summary = {
                "performed": True,
                "mode": "cold" if advance else "warm",
                "epoch": new_epoch,
                "journalEntries": replay.entries,
                "openExecution": open_exec is not None,
                "classified": counts,
                "resumed": len(unfinished),
                "rolledBack": rolled_back,
                "orphanedRemaining": len(remaining),
                "durationMs": round((self._clock() - t0) * 1000.0, 3),
            }
            if resume_summary is not None:
                summary["resumeStopped"] = resume_summary.get("stopped", False)
            self._last_recovery = summary
            from cruise_control_tpu.common.metrics import REGISTRY
            REGISTRY.counter("executor-recovery-rate")
            return summary
        finally:
            self.recovering = False

    def stop_execution(self, forced: bool = False):
        """Stop the ongoing execution (Executor.java:94-99 stopExecution):
        graceful — in-flight tasks drain/abort, pending are cancelled;
        forced — in-flight tasks are dropped (marked DEAD) without waiting."""
        if forced:
            self._force_stop.set()
        self._stop_requested.set()
        # check-then-act under the lock: an execution finishing between the
        # check and the write would otherwise wedge the executor in
        # STOPPING_EXECUTION with no task to ever clear it
        with self._lock:
            if self._state != ExecutorState.NO_TASK_IN_PROGRESS:
                self._state = ExecutorState.STOPPING_EXECUTION

    # -- execution --
    def execute_proposals(self, proposals: Sequence[ExecutionProposal],
                          removed_brokers: Iterable[int] = (),
                          demoted_brokers: Iterable[int] = (),
                          replication_throttle: Optional[int] = None,
                          concurrency: Optional[int] = None,
                          leader_concurrency: Optional[int] = None,
                          progress_check_interval_ms: Optional[int] = None,
                          strategy_names: Sequence[str] = (),
                          logdir_moves: Sequence = ()) -> dict:
        """Synchronous execution of a proposal set; returns the summary.
        (The async layer runs this in an operation thread.)

        One execution runs all three phases (Executor.java:734): inter-broker
        replica moves, intra-broker logdir moves (``logdir_moves``), then
        leadership moves.
        """
        # per-request overrides (ParameterUtils: replica_movement_strategies,
        # execution_progress_check_interval_ms, concurrent_leader_movements).
        # Resolved BEFORE any state transition: an unknown strategy name must
        # reject the request, not wedge the executor in STARTING_EXECUTION.
        strategy = self._strategy
        if strategy_names:
            from cruise_control_tpu.executor.tasks import STRATEGIES
            chain = None
            for name in strategy_names:
                cls = STRATEGIES.get(name)
                if cls is None:
                    raise ValueError(f"unknown replica movement strategy "
                                     f"{name!r}; valid: {sorted(STRATEGIES)}")
                chain = cls() if chain is None else chain.chain(cls())
            strategy = chain
        # max.num.cluster.movements (Executor sanity cap): refuse an
        # execution whose total task count exceeds the configured bound —
        # BEFORE any state transition, like the strategy check above
        cap = self.config.max_num_cluster_movements
        total_tasks = len(proposals) + len(logdir_moves)
        if cap is not None and total_tasks > cap:
            raise ValueError(
                f"execution of {total_tasks} movements exceeds "
                f"max.num.cluster.movements={cap}")
        with self._lock:
            if self.has_ongoing_execution:
                raise RuntimeError("An execution is already in progress")
            self._state = ExecutorState.STARTING_EXECUTION
        try:
            # any setup failure (malformed proposal, history/notifier error)
            # must release STARTING_EXECUTION — not just the strategy check
            self._stop_requested.clear()
            self._force_stop.clear()
            self._timed_out = False
            self._exec_retries = 0
            self._exec_task_failures = 0
            self._exec_stuck = 0
            t0 = self._clock()
            self._interval_override_ms = progress_check_interval_ms
            # epoch-fenced task IDs: epoch << 32 | seq (journal.py fencing)
            id_start = (self._journal.epoch << 32
                        if self._journal is not None else 0)
            planner = ExecutionTaskPlanner(strategy, id_start=id_start)
            planner.add_proposals(proposals)
            with self._lock:
                self._planner = planner
            self.tracker = ExecutionTaskTracker()
            self.tracker.register(planner.replica_tasks)
            self.tracker.register(planner.leadership_tasks)
            self.record_history(removed_brokers, demoted_brokers)
            # write-ahead: the full reassignment payload is durable before
            # any cluster mutation, so a crash from here on is recoverable
            if self._journal is not None:
                self._journal.log_execution_start(
                    proposals, removed_brokers, demoted_brokers,
                    generation=getattr(self.adapter, "generation", -1))

            throttle = (replication_throttle
                        if replication_throttle is not None
                        else self.config.default_replication_throttle)
            helper = (ReplicationThrottleHelper(self._adapter, throttle)
                      if throttle is not None else None)
        except BaseException:
            with self._lock:        # match the acquisition path's discipline
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
                self._planner = None
            raise
        intra_moves_applied = 0
        crashed = True      # cleared on the clean path through the try
        try:
            # inside the try: a partial throttle-set failure must still clear
            # what was applied and release the executor state
            from cruise_control_tpu.server.async_ops import report_progress
            with self._tracer.span(
                    "execute", numProposals=len(proposals),
                    numLogdirMoves=len(logdir_moves)) as _exec_sp:
                if helper is not None:
                    helper.set_throttles(
                        [t.proposal for t in planner.replica_tasks])
                with self._lock:
                    self._state = ExecutorState.\
                        INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
                report_progress(
                    f"Executing {len(planner.replica_tasks)} inter-broker "
                    f"replica movements")
                with self._tracer.span("execute-replica-moves",
                                       tasks=len(planner.replica_tasks)):
                    self._move_replicas(planner, concurrency)
                if logdir_moves and not self._stop_requested.is_set():
                    with self._lock:
                        self._state = ExecutorState.\
                            INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
                    report_progress(f"Executing {len(logdir_moves)} "
                                    f"intra-broker logdir movements")
                    with self._tracer.span("execute-logdir-moves",
                                           moves=len(logdir_moves)):
                        for lb in self._logdir_batches(logdir_moves):
                            self._adapter.alter_replica_logdirs(lb)
                            intra_moves_applied += len(lb)
                            if self._stop_requested.is_set():
                                break
                with self._lock:
                    self._state = ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS
                report_progress(
                    f"Executing {len(planner.leadership_tasks)} leadership "
                    f"movements")
                with self._tracer.span(
                        "execute-leader-moves",
                        tasks=len(planner.leadership_tasks)):
                    self._move_leadership(planner, leader_concurrency)
                _exec_sp.set("stopped", self._stop_requested.is_set())
                crashed = False
        finally:
            from cruise_control_tpu.common.metrics import REGISTRY
            if helper is not None:
                try:
                    helper.clear_throttles()
                except Exception:
                    # the summary/state release below must still run; the
                    # leaked throttle is the operator's signal to clean up
                    logger.exception(
                        "failed to clear replication throttles after "
                        "execution (adapter retries exhausted)")
                    REGISTRY.counter("throttle-clear-failed-rate")
            duration_s = self._clock() - t0
            summary = {
                "stopped": self._stop_requested.is_set(),
                "forcedStop": self._force_stop.is_set(),
                "timedOut": self._timed_out,
                "taskCounts": self.tracker.snapshot(),
                "intraBrokerMoves": intra_moves_applied,
                "durationSeconds": round(duration_s, 3),
            }
            # fault-tolerance tallies are reported only when nonzero so a
            # fault-free execution's summary is unchanged from older builds
            if self._exec_retries:
                summary["adapterRetries"] = self._exec_retries
            if self._exec_task_failures:
                summary["tasksDeadOnAdapterFailure"] = self._exec_task_failures
            if self._exec_stuck:
                summary["stuckTasksAborted"] = self._exec_stuck
            # movement-rate alert ({inter,intra}.broker.replica.movement.
            # rate.alerting.threshold): a healthy execution sustains at
            # least the configured MB/s of ACTUALLY FINISHED movement (the
            # tracker's figure — planned data would mis-rate stopped or
            # timed-out runs); below it, flag the execution so the
            # notifier/operator can investigate throttles or slow disks
            data_mb = self.tracker.finished_data_movement_mb
            # gate on PLANNED movement: a fully-stalled run (0 MB finished)
            # is the slowest possible and must alert; leadership-only runs
            # and deliberately stopped/timed-out runs stay exempt
            if (not crashed and planner.replica_tasks
                    and not self._stop_requested.is_set()
                    and not self._timed_out and duration_s > 0
                    and (data_mb / duration_s)
                    < self.config.inter_broker_movement_rate_alerting_threshold):
                summary["slowInterBrokerMovementRateMBps"] = round(
                    data_mb / duration_s, 6)
            self._execution_history.append(summary)
            if self._journal is not None:
                try:
                    self._journal.log_execution_end(
                        "crashed" if crashed
                        else "stopped" if self._stop_requested.is_set()
                        else "completed")
                except StaleEpochError:
                    # a fenced-out zombie must not mask the original error
                    pass
            with self._lock:
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
                self._planner = None
            if crashed:
                REGISTRY.counter("execution-failed-rate")
                self.notifier.on_execution_stopped(summary)
            elif self._stop_requested.is_set():
                REGISTRY.counter("execution-stopped-rate")
                self.notifier.on_execution_stopped(summary)
            else:
                REGISTRY.counter("execution-finished-rate")
                self.notifier.on_execution_finished(summary)
        return summary

    def execute_logdir_moves(self, moves) -> dict:
        """Phase 2 (Executor.java:995): intra-broker logdir moves, batched
        per broker by num.concurrent.intra.broker.partition.movements."""
        with self._lock:
            if self.has_ongoing_execution:
                raise RuntimeError("An execution is already in progress")
            self._state = ExecutorState.INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
        t0 = self._clock()
        applied = 0
        data_mb = 0.0
        try:
            for batch in self._logdir_batches(moves):
                self._adapter.alter_replica_logdirs(batch)
                applied += len(batch)
                # intra rate counts the APPLIED batches' sizes only (a
                # stopped run must not have its rate inflated by the
                # unexecuted tail; batches are round-robin, not a prefix
                # of `moves`)
                data_mb += sum(float(getattr(m, "data_size", 0.0))
                               for m in batch)
                if self._stop_requested.is_set():
                    break
            dur = self._clock() - t0
            out = {"intraBrokerMoves": applied,
                   "stopped": applied < len(moves),
                   "durationSeconds": round(dur, 3)}
            # intra.broker.replica.movement.rate.alerting.threshold
            if (data_mb > 0 and dur > 0 and (data_mb / dur)
                    < self.config.intra_broker_movement_rate_alerting_threshold):
                out["slowIntraBrokerMovementRateMBps"] = round(
                    data_mb / dur, 6)
            return out
        finally:
            with self._lock:
                self._state = ExecutorState.NO_TASK_IN_PROGRESS

    def _logdir_batches(self, moves) -> Iterable[list]:
        """Round-robin batches with at most N in-flight logdir moves per
        broker per round."""
        per_broker = max(
            1, self.config.num_concurrent_intra_broker_partition_movements)
        queues: Dict[int, list] = {}
        for m in moves:
            queues.setdefault(getattr(m, "broker_id", 0), []).append(m)
        while any(queues.values()):
            batch = []
            for b, q in queues.items():
                batch.extend(q[:per_broker])
                queues[b] = q[per_broker:]
            if not batch:
                break
            yield batch

    # -- phases --
    def _move_replicas(self, planner: ExecutionTaskPlanner,
                       concurrency: Optional[int]):
        """Phase 1 (Executor.java:932): batches bounded by per-broker
        concurrency; poll until batch completes; dead-broker tasks die."""
        per_broker = (concurrency
                      or self.config.num_concurrent_partition_movements_per_broker)
        while not self._stop_requested.is_set():
            batch = planner.next_replica_batch(
                per_broker, self.tracker.in_flight_by_broker)
            if not batch:
                break
            now = int(self._clock() * 1000)
            for t in batch:
                t.transition(TaskState.IN_PROGRESS, now)
                self._journal_task(t)   # write-ahead: durable before submit
                self.tracker.mark(t, TaskState.PENDING)
            batch = self._submit_contained(
                batch, self._adapter.execute_replica_reassignments)
            if batch:
                self._wait_for(batch, self._replica_task_status)

    def _move_leadership(self, planner: ExecutionTaskPlanner,
                         concurrency: Optional[int] = None):
        """Phase 3 (Executor.java:1050); leadership movements time out on
        their own (shorter) round budget."""
        while not self._stop_requested.is_set():
            batch = planner.next_leadership_batch(
                concurrency
                or self.config.num_concurrent_leader_movements)
            if not batch:
                break
            now = int(self._clock() * 1000)
            for t in batch:
                t.transition(TaskState.IN_PROGRESS, now)
                self._journal_task(t)   # write-ahead: durable before submit
                self.tracker.mark(t, TaskState.PENDING)
            batch = self._submit_contained(
                batch, self._adapter.execute_preferred_leader_elections)
            if batch:
                self._wait_for(batch, self._leader_task_status,
                               max_rounds=self._leadership_round_budget(),
                               cancelable=False)

    def _effective_check_interval_ms(self) -> int:
        return (self._interval_override_ms
                if self._interval_override_ms is not None
                else self.config.execution_progress_check_interval_ms)

    def _leadership_round_budget(self) -> int:
        """leader.movement.timeout.ms ÷ the EFFECTIVE per-round interval —
        a per-request progress_check_interval_ms override changes the sleep,
        so computing rounds at init would let the override stretch the
        wall-clock timeout (Executor.java bounds it in time, not rounds)."""
        return max(1, int(self.config.leader_movement_timeout_ms
                          // max(self._effective_check_interval_ms(), 1)))

    def _submit_contained(self, batch: List[ExecutionTask],
                          submit: Callable[[Sequence[ExecutionTask]], None]
                          ) -> List[ExecutionTask]:
        """Submit a batch through the retrying adapter; on retry exhaustion
        fall back to per-task submission and mark only the tasks that STILL
        fail DEAD — the rest of the execution continues (the reference
        contains admin failures to the affected tasks, it does not abort
        whole rebalances). Returns the tasks that were actually submitted."""
        try:
            submit(batch)
            return list(batch)
        except NotImplementedError:
            raise
        except Exception:
            logger.exception(
                "batch submission of %d tasks failed after retries; "
                "retrying tasks individually", len(batch))
        survivors: List[ExecutionTask] = []
        for t in batch:
            try:
                submit([t])
                survivors.append(t)
            except Exception:
                logger.exception(
                    "task %s failed to submit after retries; marking it DEAD",
                    t.proposal.topic_partition)
                self._fail_task(t, int(self._clock() * 1000))
        return survivors

    def _fail_task(self, task: ExecutionTask, now_ms: int) -> None:
        """Adapter-failure containment: this task dies, the run survives."""
        prev = task.state
        task.transition(TaskState.DEAD, now_ms)
        self._journal_task(task)
        self.tracker.mark(task, prev)
        self._exec_task_failures += 1
        from cruise_control_tpu.common.metrics import REGISTRY
        REGISTRY.counter("task-dead-on-adapter-failure-rate")

    def _replica_task_status(
            self, task: ExecutionTask) -> Tuple[Optional[TaskState], object]:
        """One progress probe; returns (outcome, observed replica set). The
        probe value feeds stuck detection: no change within the deadline
        means the reassignment is wedged cluster-side."""
        tp = task.proposal.topic_partition
        current = self._adapter.current_replicas(tp)
        if task.proposal.is_completed(current):
            return TaskState.COMPLETED, current
        dead = self._adapter.dead_brokers()
        if dead & set(task.proposal.new_replicas):
            return TaskState.DEAD, current
        return None, current

    def _leader_task_status(
            self, task: ExecutionTask) -> Tuple[Optional[TaskState], object]:
        tp = task.proposal.topic_partition
        leader = self._adapter.current_leader(tp)
        if leader == task.proposal.new_replicas[0]:
            return TaskState.COMPLETED, leader
        if leader in self._adapter.dead_brokers():
            return TaskState.DEAD, leader
        return None, leader

    def _wait_for(self, batch: List[ExecutionTask],
                  status_fn: Callable[[ExecutionTask],
                                      Tuple[Optional[TaskState], object]],
                  max_rounds: Optional[int] = None,
                  cancelable: bool = True):
        """Progress polling (Executor.java waitForExecutionTaskToFinish).

        Graceful stop aborts what can be aborted and drains the rest; forced
        stop (Executor.java:94-99) drops in-flight tasks immediately (DEAD).
        Exhausting the round budget also marks the stragglers DEAD — leaving
        them IN_PROGRESS would corrupt per-broker concurrency accounting for
        the next batch — and surfaces ``timedOut`` in the summary.

        Per-task failure containment (the reference's task-stuck semantics):
        a status probe that still fails after adapter retries kills only that
        task; a task whose adapter-observed progress has not changed within
        ``task_stuck_deadline_ms`` is individually cancelled and ABORTED
        (``cancelable=False`` phases — leadership — mark it DEAD instead).
        """
        rounds = 0
        budget = (max_rounds if max_rounds is not None
                  else self.config.max_execution_progress_check_rounds)
        open_tasks = list(batch)
        batch_t0 = self._clock()
        alerted = False
        deadline_ms = self.config.task_stuck_deadline_ms
        # per-task (last probe, wall time it last changed)
        progress: Dict[int, Tuple[object, float]] = {
            id(t): (None, batch_t0) for t in open_tasks}
        while open_tasks and rounds < budget:
            self._beat()    # executor-progress watchdog heartbeat
            if (not alerted and (self._clock() - batch_t0) * 1000
                    > self.config.task_execution_alerting_threshold_ms):
                # task.execution.alerting.threshold.ms: surface slow batches
                alerted = True
                logger.warning(
                    "%d execution tasks still in flight after %.0f s "
                    "(alerting threshold %.0f s)", len(open_tasks),
                    self._clock() - batch_t0,
                    self.config.task_execution_alerting_threshold_ms / 1000.0)
            rounds += 1
            now = int(self._clock() * 1000)
            wall = self._clock()
            still = []
            aborting: List[ExecutionTask] = []
            stuck: List[ExecutionTask] = []
            stopping = self._stop_requested.is_set()
            forced = self._force_stop.is_set()
            for t in open_tasks:
                try:
                    outcome, probe = status_fn(t)
                except NotImplementedError:
                    raise
                except Exception:
                    # the probe itself is failing past the retry budget:
                    # contain the failure to this task and keep polling
                    logger.exception(
                        "progress check for %s failed after retries; "
                        "marking the task DEAD",
                        t.proposal.topic_partition)
                    self._fail_task(t, now)
                    continue
                prev_probe, since = progress[id(t)]
                if probe != prev_probe:
                    progress[id(t)] = (probe, wall)
                elif (outcome is None and not stopping
                        and deadline_ms is not None
                        and (wall - since) * 1000.0 > deadline_ms):
                    stuck.append(t)
                    continue
                if outcome is None and forced:
                    outcome = TaskState.DEAD
                elif outcome is None and stopping:
                    # graceful stop: abort what can be aborted
                    if t.proposal.can_be_aborted(
                            self._adapter.current_replicas(
                                t.proposal.topic_partition)):
                        t.transition(TaskState.ABORTING, now)
                        self._journal_task(t)   # before the adapter cancel
                        self.tracker.mark(t, TaskState.IN_PROGRESS)
                        aborting.append(t)
                        continue
                if outcome is None:
                    still.append(t)
                else:
                    prev = t.state
                    t.transition(outcome, now)
                    self._journal_task(t)
                    self.tracker.mark(t, prev)
            if stuck:
                from cruise_control_tpu.common.metrics import REGISTRY
                for t in stuck:
                    logger.warning(
                        "task %s made no progress for %.0f ms (deadline "
                        "%d ms); %s it individually",
                        t.proposal.topic_partition,
                        (wall - progress[id(t)][1]) * 1000.0, deadline_ms,
                        "aborting" if cancelable else "killing")
                    self._exec_stuck += 1
                    REGISTRY.counter("task-stuck-rate")
                if cancelable:
                    aborting.extend(stuck)
                    for t in stuck:
                        t.transition(TaskState.ABORTING, now)
                        self._journal_task(t)
                        self.tracker.mark(t, TaskState.IN_PROGRESS)
                else:
                    for t in stuck:
                        prev = t.state
                        t.transition(TaskState.DEAD, now)
                        self._journal_task(t)
                        self.tracker.mark(t, prev)
            if aborting:
                # adapter-side cancel BEFORE marking ABORTED: a graceful
                # abort rewrites the in-flight reassignment to a safe
                # target, it does not merely stop the bookkeeping (forced
                # stop is the drop-without-cancel path)
                try:
                    self._adapter.cancel_reassignments(aborting)
                except NotImplementedError:
                    logger.warning(
                        "%s cannot cancel reassignments; aborting %d tasks "
                        "in bookkeeping only", type(self.adapter).__name__,
                        len(aborting))
                except Exception:
                    # a transient admin-API failure must not crash the stop:
                    # the tasks still transition to ABORTED (the tracker's
                    # per-broker accounting depends on it) and the operator
                    # sees the failure in the log
                    logger.exception(
                        "cancel_reassignments failed for %d tasks during "
                        "abort; marking them ABORTED anyway",
                        len(aborting))
                for t in aborting:
                    t.transition(TaskState.ABORTED, now)
                    self._journal_task(t)
                    self.tracker.mark(t, TaskState.ABORTING)
            open_tasks = still
            if open_tasks:
                self._sleep_fn(self._effective_check_interval_ms() / 1000.0)
        if open_tasks:   # round budget exhausted
            self._timed_out = True
            now = int(self._clock() * 1000)
            for t in open_tasks:
                prev = t.state
                t.transition(TaskState.DEAD, now)
                self._journal_task(t)
                self.tracker.mark(t, prev)
