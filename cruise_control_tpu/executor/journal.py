"""Write-ahead execution journal.

Append-only JSONL record of everything the :class:`Executor` is about
to do and has done: execution starts (with full reassignment payloads),
per-task state transitions, and execution ends.  The journal is written
*before* the corresponding cluster mutation (write-ahead discipline),
flushed + fsynced per append, and is replayable after any prefix
truncation — a torn final line is skipped, everything before it is
authoritative.

Epoch fencing
-------------
Each journal carries a monotonically increasing *execution epoch*
persisted in an atomically-replaced sidecar file (``<path>.epoch``).  A
restarted process calls :meth:`ExecutionJournal.advance_epoch` before
acting; any zombie pre-crash process still holding the old epoch gets
:class:`StaleEpochError` on its next append and therefore never submits
another mutation (appends happen before effects).  The epoch is also
fenced into task IDs (``execution_id = epoch << 32 | seq``) so journaled
records from different incarnations can never collide.

Record format (deterministic: sorted keys, compact separators, virtual
timestamps only) — see docs/operations.md for the full table::

    {"type": "epoch", "epoch": N, "ts": ms}
    {"type": "execution_start", "epoch": N, "ts": ms, "generation": g,
     "proposals": [...], "removedBrokers": [...], "demotedBrokers": [...]}
    {"type": "task", "epoch": N, "ts": ms, "executionId": id,
     "taskType": "INTER_BROKER_REPLICA_ACTION", "tp": "t-0",
     "state": "IN_PROGRESS"}
    {"type": "execution_end", "epoch": N, "ts": ms, "result": "completed"}
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..analyzer.proposals import ExecutionProposal
from ..common.atomicio import atomic_replace, fsync_file, iter_jsonl

LOG = logging.getLogger("cruise-control.journal")


class StaleEpochError(RuntimeError):
    """Raised when a journal writer's epoch has been superseded.

    The holder is a zombie pre-crash incarnation; it must abandon the
    operation without touching the cluster.
    """


def proposal_to_record(p: ExecutionProposal) -> dict:
    return {
        "topic": p.topic,
        "partition": p.partition,
        "oldLeader": p.old_leader,
        "oldReplicas": list(p.old_replicas),
        "newReplicas": list(p.new_replicas),
        "dataSize": p.data_size,
    }


def proposal_from_record(r: dict) -> ExecutionProposal:
    return ExecutionProposal(
        topic=r["topic"],
        partition=int(r["partition"]),
        old_leader=int(r["oldLeader"]),
        old_replicas=tuple(int(b) for b in r["oldReplicas"]),
        new_replicas=tuple(int(b) for b in r["newReplicas"]),
        data_size=float(r["dataSize"]),
    )


@dataclass
class OpenExecution:
    """An execution_start with no matching execution_end in the journal."""

    epoch: int
    generation: int
    proposals: List[ExecutionProposal] = field(default_factory=list)
    removed_brokers: Tuple[int, ...] = ()
    demoted_brokers: Tuple[int, ...] = ()
    #: latest journaled state keyed by (taskType, "topic-partition")
    task_states: Dict[Tuple[str, str], str] = field(default_factory=dict)

    def proposal_for(self, tp: str) -> Optional[ExecutionProposal]:
        for p in self.proposals:
            if p.topic_partition == tp:
                return p
        return None


@dataclass
class JournalReplay:
    """Result of replaying a journal from disk."""

    epoch: int = 0
    entries: int = 0
    open_execution: Optional[OpenExecution] = None


class ExecutionJournal:
    """Append-only, fsynced, epoch-fenced execution journal."""

    def __init__(self, path: str, fsync: bool = True,
                 now_ms: Callable[[], int] = None):
        self._path = path
        self._epoch_path = path + ".epoch"
        self._fsync = fsync
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self._epoch = self._read_epoch_file()
        self._entries = sum(1 for _ in iter_jsonl(path))
        self._fh = None
        self._last_append_ms: Optional[int] = None
        self._frozen = False

    # ----------------------------------------------------------- epoch

    @property
    def path(self) -> str:
        return self._path

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def entries(self) -> int:
        return self._entries

    @property
    def last_append_ms(self) -> Optional[int]:
        return self._last_append_ms

    def _read_epoch_file(self) -> int:
        try:
            with open(self._epoch_path, "r", encoding="utf-8") as f:
                return int(json.loads(f.read())["epoch"])
        except (OSError, ValueError, KeyError, TypeError):
            return 0

    def advance_epoch(self) -> int:
        """Claim the next execution epoch, fencing out all prior holders.

        Persisted via atomic replace *before* the epoch record is
        appended, so a crash between the two still leaves older
        incarnations fenced.
        """
        self._epoch = self._read_epoch_file() + 1
        payload = json.dumps({"epoch": self._epoch},
                             sort_keys=True, separators=(",", ":"))
        atomic_replace(self._epoch_path, payload.encode("utf-8"),
                       fsync=self._fsync)
        self._append({"type": "epoch"})
        return self._epoch

    def _check_epoch(self) -> None:
        if self._read_epoch_file() != self._epoch:
            raise StaleEpochError(
                f"journal epoch {self._epoch} superseded "
                f"(current {self._read_epoch_file()}); refusing to act")

    def freeze(self) -> None:
        """Simulate process death: refuse every subsequent append.

        Used by the simulator's ``process_crash`` fault — a killed
        process writes nothing more, including the ``finally``-path
        execution_end a normal interpreter would still reach (the
        executor swallows that one ``StaleEpochError`` so the original
        crash propagates unmasked).  Appends after death *raise* rather
        than silently succeed: a frozen journal no-op would let a dead
        incarnation start a whole new execution without ever hitting the
        epoch check — the write-ahead fence only works if every append
        either lands or refuses.
        """
        self._frozen = True
        self.close()

    # ---------------------------------------------------------- append

    def _append(self, record: dict) -> None:
        if self._frozen:
            raise StaleEpochError(
                "journal frozen (process death); refusing to act")
        self._check_epoch()
        record = dict(record)
        record["epoch"] = self._epoch
        record["ts"] = int(self._now_ms())
        line = json.dumps(record, sort_keys=True, separators=(",", ":"))
        if self._fh is None:
            self._fh = open(self._path, "a", encoding="utf-8")
        self._fh.write(line + "\n")
        if self._fsync:
            fsync_file(self._fh)
        else:
            self._fh.flush()
        self._entries += 1
        self._last_append_ms = record["ts"]

    def log_execution_start(self, proposals, removed_brokers=(),
                            demoted_brokers=(), generation: int = -1) -> None:
        self._append({
            "type": "execution_start",
            "generation": int(generation),
            "proposals": [proposal_to_record(p) for p in proposals],
            "removedBrokers": sorted(int(b) for b in removed_brokers),
            "demotedBrokers": sorted(int(b) for b in demoted_brokers),
        })

    def log_task(self, execution_id: int, task_type: str, tp: str,
                 state: str) -> None:
        self._append({
            "type": "task",
            "executionId": int(execution_id),
            "taskType": task_type,
            "tp": tp,
            "state": state,
        })

    def log_execution_end(self, result: str) -> None:
        self._append({"type": "execution_end", "result": result})

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:  # pragma: no cover
                pass
            self._fh = None

    # ---------------------------------------------------------- replay

    def replay(self) -> JournalReplay:
        """Parse the journal into its net effect.

        Tolerates a torn trailing line; the durable prefix is
        authoritative.  Only the *last* execution_start can be open —
        an execution_start implicitly closes any predecessor (the
        executor is single-flight).
        """
        out = JournalReplay(epoch=self._read_epoch_file())
        open_exec: Optional[OpenExecution] = None
        for rec in iter_jsonl(self._path):
            out.entries += 1
            rtype = rec.get("type")
            if rtype == "epoch":
                continue
            if rtype == "execution_start":
                try:
                    props = [proposal_from_record(r)
                             for r in rec.get("proposals", [])]
                except (KeyError, ValueError, TypeError):
                    LOG.warning("Unreadable execution_start in %s; skipping",
                                self._path)
                    continue
                open_exec = OpenExecution(
                    epoch=int(rec.get("epoch", 0)),
                    generation=int(rec.get("generation", -1)),
                    proposals=props,
                    removed_brokers=tuple(rec.get("removedBrokers", ())),
                    demoted_brokers=tuple(rec.get("demotedBrokers", ())),
                )
            elif rtype == "task" and open_exec is not None:
                key = (str(rec.get("taskType")), str(rec.get("tp")))
                open_exec.task_states[key] = str(rec.get("state"))
            elif rtype == "execution_end":
                open_exec = None
        out.open_execution = open_exec
        return out
