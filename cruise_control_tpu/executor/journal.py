"""Write-ahead execution journal.

Append-only JSONL record of everything the :class:`Executor` is about
to do and has done: execution starts (with full reassignment payloads),
per-task state transitions, and execution ends.  The journal is written
*before* the corresponding cluster mutation (write-ahead discipline),
flushed + fsynced per append, and is replayable after any prefix
truncation — a torn final line is skipped, everything before it is
authoritative.

Epoch fencing
-------------
Each journal carries a monotonically increasing *execution epoch*
persisted in an atomically-replaced sidecar file (``<path>.epoch``).  A
restarted process calls :meth:`ExecutionJournal.advance_epoch` before
acting; any zombie pre-crash process still holding the old epoch gets
:class:`StaleEpochError` on its next append and therefore never submits
another mutation (appends happen before effects).  The epoch is also
fenced into task IDs (``execution_id = epoch << 32 | seq``) so journaled
records from different incarnations can never collide.

The sidecar doubles as the replication lease (see
:mod:`cruise_control_tpu.replication.lease`): a leased holder writes
``{"epoch": N, "holder": id, "leaseExpiryMs": ms}`` — this module only
ever reads the ``epoch`` key, so legacy and leased sidecars are
interchangeable.  A warm standby that tailed the journal takes over with
:meth:`adopt_epoch` (the lease manager already advanced the epoch;
re-advancing would double-fence).

Compaction
----------
:meth:`compact` folds the journal's durable prefix into one
``checkpoint`` record (the reconciled snapshot of the open execution, if
any) and atomically truncates behind it, bounding both the replay cost
and the tail a replication shipper must stream.  Replaying a compacted
journal is *classification-equivalent* to replaying the full history by
construction: both feed the same :class:`ReplayAccumulator`.

Record format (deterministic: sorted keys, compact separators, virtual
timestamps only) — see docs/operations.md for the full table::

    {"type": "epoch", "epoch": N, "ts": ms}
    {"type": "execution_start", "epoch": N, "ts": ms, "generation": g,
     "proposals": [...], "removedBrokers": [...], "demotedBrokers": [...]}
    {"type": "task", "epoch": N, "ts": ms, "executionId": id,
     "taskType": "INTER_BROKER_REPLICA_ACTION", "tp": "t-0",
     "state": "IN_PROGRESS"}
    {"type": "execution_end", "epoch": N, "ts": ms, "result": "completed"}
    {"type": "checkpoint", "epoch": N, "ts": ms, "entriesFolded": k,
     "open": null | {"generation": g, "epoch": e, "proposals": [...],
                     "removedBrokers": [...], "demotedBrokers": [...],
                     "taskStates": {"TYPE|t-0": "IN_PROGRESS", ...}}}
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..analyzer.proposals import ExecutionProposal
from ..common.atomicio import atomic_replace, fsync_file, iter_jsonl

LOG = logging.getLogger("cruise-control.journal")


class StaleEpochError(RuntimeError):
    """Raised when a journal writer's epoch has been superseded.

    The holder is a zombie pre-crash incarnation; it must abandon the
    operation without touching the cluster.
    """


def proposal_to_record(p: ExecutionProposal) -> dict:
    return {
        "topic": p.topic,
        "partition": p.partition,
        "oldLeader": p.old_leader,
        "oldReplicas": list(p.old_replicas),
        "newReplicas": list(p.new_replicas),
        "dataSize": p.data_size,
    }


def proposal_from_record(r: dict) -> ExecutionProposal:
    return ExecutionProposal(
        topic=r["topic"],
        partition=int(r["partition"]),
        old_leader=int(r["oldLeader"]),
        old_replicas=tuple(int(b) for b in r["oldReplicas"]),
        new_replicas=tuple(int(b) for b in r["newReplicas"]),
        data_size=float(r["dataSize"]),
    )


@dataclass
class OpenExecution:
    """An execution_start with no matching execution_end in the journal."""

    epoch: int
    generation: int
    proposals: List[ExecutionProposal] = field(default_factory=list)
    removed_brokers: Tuple[int, ...] = ()
    demoted_brokers: Tuple[int, ...] = ()
    #: latest journaled state keyed by (taskType, "topic-partition")
    task_states: Dict[Tuple[str, str], str] = field(default_factory=dict)

    def proposal_for(self, tp: str) -> Optional[ExecutionProposal]:
        for p in self.proposals:
            if p.topic_partition == tp:
                return p
        return None


def open_execution_to_record(oe: Optional[OpenExecution]) -> Optional[dict]:
    """Checkpoint payload for an open execution (``None`` stays ``None``).

    Task-state keys are flattened to ``"TYPE|tp"`` strings so the record
    round-trips through JSON deterministically."""
    if oe is None:
        return None
    return {
        "epoch": int(oe.epoch),
        "generation": int(oe.generation),
        "proposals": [proposal_to_record(p) for p in oe.proposals],
        "removedBrokers": sorted(int(b) for b in oe.removed_brokers),
        "demotedBrokers": sorted(int(b) for b in oe.demoted_brokers),
        "taskStates": {f"{t}|{tp}": s
                       for (t, tp), s in sorted(oe.task_states.items())},
    }


def open_execution_from_record(rec: Optional[dict]) -> Optional[OpenExecution]:
    if rec is None:
        return None
    try:
        props = [proposal_from_record(r) for r in rec.get("proposals", [])]
        states = {}
        for key, state in rec.get("taskStates", {}).items():
            task_type, _, tp = str(key).partition("|")
            states[(task_type, tp)] = str(state)
        return OpenExecution(
            epoch=int(rec.get("epoch", 0)),
            generation=int(rec.get("generation", -1)),
            proposals=props,
            removed_brokers=tuple(rec.get("removedBrokers", ())),
            demoted_brokers=tuple(rec.get("demotedBrokers", ())),
            task_states=states,
        )
    except (KeyError, ValueError, TypeError, AttributeError):
        LOG.warning("Unreadable checkpoint open-execution payload; skipping")
        return None


@dataclass
class JournalReplay:
    """Result of replaying a journal from disk."""

    epoch: int = 0
    entries: int = 0
    open_execution: Optional[OpenExecution] = None


class ReplayAccumulator:
    """Incremental journal replay: feed records one at a time.

    The single classification authority for journal contents —
    :meth:`ExecutionJournal.replay` folds a file through it, a
    replication tailer feeds it shipped records as they arrive, and
    :meth:`ExecutionJournal.compact` serializes its state into a
    checkpoint record.  Because every consumer shares this accumulator,
    replay-from-checkpoint is classification-equivalent to full replay
    by construction.
    """

    def __init__(self) -> None:
        self.entries = 0
        self.open_execution: Optional[OpenExecution] = None

    def feed(self, rec: dict) -> None:
        self.entries += 1
        rtype = rec.get("type")
        if rtype == "epoch":
            return
        if rtype == "checkpoint":
            self.open_execution = open_execution_from_record(rec.get("open"))
        elif rtype == "execution_start":
            try:
                props = [proposal_from_record(r)
                         for r in rec.get("proposals", [])]
            except (KeyError, ValueError, TypeError):
                LOG.warning("Unreadable execution_start record; skipping")
                return
            self.open_execution = OpenExecution(
                epoch=int(rec.get("epoch", 0)),
                generation=int(rec.get("generation", -1)),
                proposals=props,
                removed_brokers=tuple(rec.get("removedBrokers", ())),
                demoted_brokers=tuple(rec.get("demotedBrokers", ())),
            )
        elif rtype == "task" and self.open_execution is not None:
            key = (str(rec.get("taskType")), str(rec.get("tp")))
            self.open_execution.task_states[key] = str(rec.get("state"))
        elif rtype == "execution_end":
            self.open_execution = None

    def result(self, epoch: int = 0) -> JournalReplay:
        return JournalReplay(epoch=epoch, entries=self.entries,
                             open_execution=self.open_execution)


class ExecutionJournal:
    """Append-only, fsynced, epoch-fenced execution journal.

    ``epoch_path`` overrides the fencing-sidecar location (default
    ``<path>.epoch``): a standby's tailed replica journal points it at
    the *leader's* sidecar on shared storage so both incarnations fence
    against the same leased claim.  ``entries_hint`` skips the initial
    entry count for a caller that already knows it (a tailer hands its
    replica over at takeover without re-parsing the file).
    ``compact_records`` > 0 auto-compacts whenever the entry count
    reaches the threshold.
    """

    def __init__(self, path: str, fsync: bool = True,
                 now_ms: Callable[[], int] = None,
                 epoch_path: Optional[str] = None,
                 entries_hint: Optional[int] = None,
                 compact_records: int = 0):
        self._path = path
        self._epoch_path = epoch_path or (path + ".epoch")
        self._fsync = fsync
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._compact_records = int(compact_records or 0)
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self._epoch = self._read_epoch_file()
        self._entries = (int(entries_hint) if entries_hint is not None
                         else sum(1 for _ in iter_jsonl(path)))
        self._fh = None
        self._last_append_ms: Optional[int] = None
        self._frozen = False
        self._compactions = 0

    # ----------------------------------------------------------- epoch

    @property
    def path(self) -> str:
        return self._path

    @property
    def epoch_path(self) -> str:
        return self._epoch_path

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def entries(self) -> int:
        return self._entries

    @property
    def compactions(self) -> int:
        """Times this incarnation truncated behind a checkpoint — a
        replication shipper includes it so tailers detect the rewrite
        and re-sync from offset 0."""
        return self._compactions

    @property
    def last_append_ms(self) -> Optional[int]:
        return self._last_append_ms

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self._path)
        except OSError:
            return 0

    def _read_epoch_file(self) -> int:
        try:
            with open(self._epoch_path, "r", encoding="utf-8") as f:
                return int(json.loads(f.read())["epoch"])
        except (OSError, ValueError, KeyError, TypeError):
            return 0

    def advance_epoch(self) -> int:
        """Claim the next execution epoch, fencing out all prior holders.

        Persisted via atomic replace *before* the epoch record is
        appended, so a crash between the two still leaves older
        incarnations fenced.
        """
        self._epoch = self._read_epoch_file() + 1
        payload = json.dumps({"epoch": self._epoch},
                             sort_keys=True, separators=(",", ":"))
        atomic_replace(self._epoch_path, payload.encode("utf-8"),
                       fsync=self._fsync)
        self._append({"type": "epoch"})
        return self._epoch

    def adopt_epoch(self) -> int:
        """Adopt the epoch already claimed in the sidecar without
        advancing it.

        The warm-takeover path: the replication lease manager advanced
        the epoch when it acquired leadership (fencing the ex-leader),
        so the promoted incarnation must append under *that* epoch —
        advancing again here would fence the lease itself out.
        """
        self._epoch = self._read_epoch_file()
        self._append({"type": "epoch"})
        return self._epoch

    def _check_epoch(self) -> None:
        if self._read_epoch_file() != self._epoch:
            raise StaleEpochError(
                f"journal epoch {self._epoch} superseded "
                f"(current {self._read_epoch_file()}); refusing to act")

    def freeze(self) -> None:
        """Simulate process death: refuse every subsequent append.

        Used by the simulator's ``process_crash`` fault — a killed
        process writes nothing more, including the ``finally``-path
        execution_end a normal interpreter would still reach (the
        executor swallows that one ``StaleEpochError`` so the original
        crash propagates unmasked).  Appends after death *raise* rather
        than silently succeed: a frozen journal no-op would let a dead
        incarnation start a whole new execution without ever hitting the
        epoch check — the write-ahead fence only works if every append
        either lands or refuses.
        """
        self._frozen = True
        self.close()

    # ---------------------------------------------------------- append

    def _append(self, record: dict) -> None:
        if self._frozen:
            raise StaleEpochError(
                "journal frozen (process death); refusing to act")
        self._check_epoch()
        record = dict(record)
        record["epoch"] = self._epoch
        record["ts"] = int(self._now_ms())
        line = json.dumps(record, sort_keys=True, separators=(",", ":"))
        if self._fh is None:
            self._fh = open(self._path, "a", encoding="utf-8")
        self._fh.write(line + "\n")
        if self._fsync:
            fsync_file(self._fh)
        else:
            self._fh.flush()
        self._entries += 1
        self._last_append_ms = record["ts"]
        if self._compact_records and self._entries >= self._compact_records:
            self.compact()

    def log_execution_start(self, proposals, removed_brokers=(),
                            demoted_brokers=(), generation: int = -1) -> None:
        self._append({
            "type": "execution_start",
            "generation": int(generation),
            "proposals": [proposal_to_record(p) for p in proposals],
            "removedBrokers": sorted(int(b) for b in removed_brokers),
            "demotedBrokers": sorted(int(b) for b in demoted_brokers),
        })

    def log_task(self, execution_id: int, task_type: str, tp: str,
                 state: str) -> None:
        self._append({
            "type": "task",
            "executionId": int(execution_id),
            "taskType": task_type,
            "tp": tp,
            "state": state,
        })

    def log_execution_end(self, result: str) -> None:
        self._append({"type": "execution_end", "result": result})

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:  # pragma: no cover
                pass
            self._fh = None

    # --------------------------------------------------------- compact

    def compact(self) -> dict:
        """Fold the durable prefix into one checkpoint record and
        atomically truncate behind it.

        The checkpoint carries the reconciled snapshot of the open
        execution (full proposals + latest task states), so replaying
        the compacted journal classifies identically to replaying the
        full history — and a replication shipper only ever has a bounded
        tail to stream.  Refuses (like any append) when frozen or
        fenced.
        """
        if self._frozen:
            raise StaleEpochError(
                "journal frozen (process death); refusing to compact")
        self._check_epoch()
        replay = self.replay()
        record = {
            "type": "checkpoint",
            "epoch": self._epoch,
            "ts": int(self._now_ms()),
            "entriesFolded": replay.entries,
            "open": open_execution_to_record(replay.open_execution),
        }
        line = json.dumps(record, sort_keys=True, separators=(",", ":"))
        self.close()
        atomic_replace(self._path, (line + "\n").encode("utf-8"),
                       fsync=self._fsync)
        self._entries = 1
        self._compactions += 1
        self._last_append_ms = record["ts"]
        return {"entriesFolded": replay.entries,
                "openExecution": replay.open_execution is not None}

    # ---------------------------------------------------------- replay

    def replay(self) -> JournalReplay:
        """Parse the journal into its net effect.

        Tolerates a torn trailing line; the durable prefix is
        authoritative.  Only the *last* execution_start can be open —
        an execution_start implicitly closes any predecessor (the
        executor is single-flight).  A leading checkpoint record seeds
        the state that the truncated history folded into.
        """
        acc = ReplayAccumulator()
        for rec in iter_jsonl(self._path):
            acc.feed(rec)
        return acc.result(epoch=self._read_epoch_file())
