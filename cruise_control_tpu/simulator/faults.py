"""Time-indexed fault schedules for scenario runs.

PR 2's ``FaultPlan`` expresses *rates* over one adapter's whole lifetime;
a scenario needs faults pinned to the time axis: "broker 2 dies at tick
100", "a 5-tick latency storm starts at tick 40", "the next execution loses
a broker 30 adapter calls in". :class:`FaultSchedule` is the bridge — the
runner applies direct events at their tick and compiles the transient
windows active at each tick into a fresh seeded ``FaultPlan`` for the
``FaultyClusterAdapter`` wrapper (``set_plan`` swaps it per tick; the plan
is read per guarded call, so mid-tick swaps are safe).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from cruise_control_tpu.common.faults import FaultPlan

#: events the runner applies directly against the simulated cluster/app
DIRECT_KINDS = frozenset({
    "kill_broker", "restore_broker", "fail_disk", "restore_disk",
    "kill_broker_mid_execution", "stop_execution", "process_crash",
})

#: events that open a [tick, tick+duration) window of per-call fault rates
WINDOW_KINDS = frozenset({
    "latency_storm", "partial_batches", "transient_storm",
})

VALID_KINDS = DIRECT_KINDS | WINDOW_KINDS


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.

    ``tick`` indexes the scenario loop (virtual time = tick × tick_ms).
    Direct kinds fire once at their tick; window kinds stay active for
    ``duration_ticks``. ``kill_broker_mid_execution`` arms the chaos
    adapter to kill ``broker_id`` after ``calls_after`` more guarded
    adapter calls — landing the death inside that tick's execution batch
    rather than between ticks. ``process_crash`` arms the adapter the same
    way but kills the *control plane*: after ``calls_after`` more guarded
    calls the wrapper freezes the execution journal and raises
    ``ProcessCrashed``; the runner tears the app down and rebuilds it
    against the same simulated cluster, exercising restart reconciliation
    (the Scorecard records the recovery tick). With a warm standby
    attached (``Scenario.warm_standby``) the same event kills the
    *leader*: the standby keeps tailing, the lease expires, and takeover
    is scored instead of a cold rebuild.
    """

    tick: int
    kind: str
    broker_id: Optional[int] = None
    logdir: str = "/data/d0"
    duration_ticks: int = 1
    rate: float = 1.0
    latency_s: float = 0.0
    calls_after: int = 10

    def __post_init__(self):
        if self.kind not in VALID_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"valid: {sorted(VALID_KINDS)}")
        if self.tick < 0:
            raise ValueError(f"fault tick must be >= 0, got {self.tick}")
        if self.kind in WINDOW_KINDS and self.duration_ticks < 1:
            raise ValueError("window faults need duration_ticks >= 1")


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """The scenario's full fault timeline."""

    events: Tuple[FaultEvent, ...] = ()
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "events", tuple(self.events))

    def direct_at(self, tick: int) -> Tuple[FaultEvent, ...]:
        """Direct events that fire exactly at ``tick``."""
        return tuple(e for e in self.events
                     if e.kind in DIRECT_KINDS and e.tick == tick)

    def windows_at(self, tick: int) -> Tuple[FaultEvent, ...]:
        """Window events whose [tick, tick+duration) covers ``tick``."""
        return tuple(e for e in self.events if e.kind in WINDOW_KINDS
                     and e.tick <= tick < e.tick + e.duration_ticks)

    def plan_for_tick(self, tick: int) -> FaultPlan:
        """Compile the windows active at ``tick`` into one FaultPlan.

        The seed mixes the schedule seed with the tick so each tick's
        injection draws are independent of how many adapter calls earlier
        ticks made — the property the byte-identical scorecard test pins.
        Overlapping windows of one kind combine by max rate.
        """
        latency_rate = latency_s = partial = transient = 0.0
        for e in self.windows_at(tick):
            if e.kind == "latency_storm":
                latency_rate = max(latency_rate, e.rate)
                latency_s = max(latency_s, e.latency_s)
            elif e.kind == "partial_batches":
                partial = max(partial, e.rate)
            elif e.kind == "transient_storm":
                transient = max(transient, e.rate)
        return FaultPlan(
            seed=self.seed * 1_000_003 + tick,
            latency_rate=latency_rate, latency_s=latency_s,
            partial_batch_rate=partial,
            transient_error_rate=transient)

    def kill_broker_events(self) -> Tuple[FaultEvent, ...]:
        """Broker-death events (both kinds), in tick order — the scorecard's
        self-heal ground truth."""
        return tuple(sorted(
            (e for e in self.events
             if e.kind in ("kill_broker", "kill_broker_mid_execution")),
            key=lambda e: e.tick))

    def process_crash_events(self) -> Tuple[FaultEvent, ...]:
        """Control-plane death events, in tick order — the runner
        provisions a journal (and, with ``Scenario.warm_standby``, the
        standby pair) iff any are scheduled."""
        return tuple(sorted(
            (e for e in self.events if e.kind == "process_crash"),
            key=lambda e: e.tick))
