"""Time-varying workload generators behind the MetricSampler SPI.

Each generator extends the SyntheticLoadSampler recipe (stable seeded
per-partition base rates + per-call jitter) with a deterministic
**intensity** factor ``intensity(t_ms, topic, partition)`` — a pure function
of virtual time and identity, so the same (seed, scenario) always emits the
same sample stream. Because samples are built against the *current* cluster
metadata (leaders included), executor-applied movements change which broker
carries a partition's load on the next tick — the loop the one-shot chaos
harness never closed.

Shapes provided:

- :class:`DiurnalWorkload` — sinusoidal day/night cycle.
- :class:`SpikeWorkload` — a flat multiplier inside a time window.
- :class:`FlashCrowdWorkload` — sudden ramp + exponential decay on a hot
  topic set (the "everyone piles onto one topic" incident shape).
- :class:`TopicGrowthWorkload` — compounding growth on matching topics.
- :class:`HotspotDriftWorkload` — a rotating hot partition subset, so the
  *location* of load drifts even when the total is flat.
- :class:`CompositeWorkload` — product of component intensities.
- :class:`TraceReplayWorkload` — JSONL trace replay (FileMetricSampler
  format); :func:`record_trace` writes such traces from any sampler.
"""

from __future__ import annotations

import json
import math
from typing import Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.common.stablehash import stable_hash32
from cruise_control_tpu.monitor import metricdef as md
from cruise_control_tpu.monitor.sampler import (
    BrokerMetricSample,
    ClusterMetadata,
    FileMetricSampler,
    MetricSampler,
    PartitionMetricSample,
    estimate_partition_cpu,
)


class WorkloadGenerator(MetricSampler):
    """Base generator: seeded stable rates × time-varying intensity.

    Subclasses override :meth:`intensity`; everything else (per-partition
    base rates, jitter, broker roll-ups, CPU attribution) follows the
    SyntheticLoadSampler recipe so windows fill with consistent,
    extrapolation-friendly data.
    """

    def __init__(self, seed: int = 0, mean_nw_in: float = 100.0,
                 mean_nw_out: float = 100.0, mean_disk: float = 500.0,
                 jitter: float = 0.02):
        self._seed = seed
        self._means = (mean_nw_in, mean_nw_out, mean_disk)
        self._jitter = jitter

    # -- the time axis ----------------------------------------------------
    def intensity(self, t_ms: int, topic: str, partition: int) -> float:
        """Multiplier applied to the partition's base rates at time t."""
        return 1.0

    # -- SyntheticLoadSampler recipe --------------------------------------
    def _base_rates(self, topic: str, partition: int) -> np.ndarray:
        h = stable_hash32(self._seed, topic, partition)
        rng = np.random.default_rng(h)
        return np.array([rng.exponential(self._means[0]),
                         rng.exponential(self._means[1]),
                         rng.exponential(self._means[2])])

    def get_samples(self, metadata: ClusterMetadata, start_ms: int,
                    end_ms: int):
        rng = np.random.default_rng((self._seed, start_ms & 0xffffffff))
        t = (start_ms + end_ms) // 2
        psamples, leader_totals = [], {}
        per_part = []
        for pm in metadata.partitions:
            if pm.leader < 0:
                continue
            scale = float(self.intensity(t, pm.topic, pm.partition))
            rates = self._base_rates(pm.topic, pm.partition) * scale * (
                1.0 + self._jitter * rng.standard_normal(3))
            nw_in, nw_out, disk = (max(rates[0], 0.0), max(rates[1], 0.0),
                                   max(rates[2], 0.0))
            per_part.append((pm, nw_in, nw_out, disk))
            agg = leader_totals.setdefault(pm.leader, [0.0, 0.0])
            agg[0] += nw_in
            agg[1] += nw_out
        bsamples = []
        broker_cpu = {}
        for b in metadata.brokers:
            lbi, lbo = leader_totals.get(b.broker_id, (0.0, 0.0))
            # follower bytes-in ≈ replication in; approximate with lbi
            cpu = min(90.0, 0.0008 * (0.7 * lbi + 0.15 * lbo + 0.15 * lbi))
            broker_cpu[b.broker_id] = (cpu, lbi, lbo)
            if b.alive:
                bsamples.append(BrokerMetricSample(
                    broker_id=b.broker_id, time_ms=t, cpu_util=cpu,
                    leader_bytes_in=lbi, leader_bytes_out=lbo,
                    replication_bytes_in=lbi, replication_bytes_out=0.0))
        for pm, nw_in, nw_out, disk in per_part:
            cpu, blbi, blbo = broker_cpu.get(pm.leader, (0.0, 0.0, 0.0))
            pcpu = float(estimate_partition_cpu(
                np.array(nw_in), np.array(nw_out), cpu, blbi, blbo, blbi))
            metrics = np.full(md.NUM_MODEL_METRICS, np.nan)
            metrics[md.ModelMetric.CPU_USAGE] = pcpu
            metrics[md.ModelMetric.DISK_USAGE] = disk
            metrics[md.ModelMetric.LEADER_BYTES_IN] = nw_in
            metrics[md.ModelMetric.LEADER_BYTES_OUT] = nw_out
            psamples.append(PartitionMetricSample(
                topic=pm.topic, partition=pm.partition,
                leader_broker=pm.leader, time_ms=t, metrics=metrics))
        return psamples, bsamples


class DiurnalWorkload(WorkloadGenerator):
    """Sinusoidal day/night cycle: 1 + amplitude·sin(2π(t-phase)/period)."""

    def __init__(self, seed: int = 0, period_ms: int = 86_400_000,
                 amplitude: float = 0.5, phase_ms: int = 0, **kw):
        super().__init__(seed=seed, **kw)
        self._period = max(int(period_ms), 1)
        self._amplitude = amplitude
        self._phase = phase_ms

    def intensity(self, t_ms, topic, partition):
        x = 2.0 * math.pi * ((t_ms - self._phase) % self._period) / self._period
        return max(1.0 + self._amplitude * math.sin(x), 0.05)


class SpikeWorkload(WorkloadGenerator):
    """Flat multiplier inside [start_ms, end_ms); optionally topic-scoped."""

    def __init__(self, seed: int = 0, start_ms: int = 0, end_ms: int = 0,
                 multiplier: float = 3.0,
                 topics: Optional[Sequence[str]] = None, **kw):
        super().__init__(seed=seed, **kw)
        self._window = (start_ms, end_ms)
        self._multiplier = multiplier
        self._topics = frozenset(topics) if topics is not None else None

    def intensity(self, t_ms, topic, partition):
        lo, hi = self._window
        if lo <= t_ms < hi and (self._topics is None or topic in self._topics):
            return self._multiplier
        return 1.0


class FlashCrowdWorkload(WorkloadGenerator):
    """Sudden onset + linear ramp + exponential decay on hot topics."""

    def __init__(self, seed: int = 0, onset_ms: int = 0,
                 ramp_ms: int = 60_000, decay_ms: int = 300_000,
                 peak_multiplier: float = 5.0,
                 hot_topics: Sequence[str] = (), **kw):
        super().__init__(seed=seed, **kw)
        self._onset = onset_ms
        self._ramp = max(int(ramp_ms), 1)
        self._decay = max(int(decay_ms), 1)
        self._peak = peak_multiplier
        self._hot = frozenset(hot_topics)

    def intensity(self, t_ms, topic, partition):
        if self._hot and topic not in self._hot:
            return 1.0
        dt = t_ms - self._onset
        if dt < 0:
            return 1.0
        if dt < self._ramp:
            return 1.0 + (self._peak - 1.0) * dt / self._ramp
        return 1.0 + (self._peak - 1.0) * math.exp(
            -(dt - self._ramp) / self._decay)


class TopicGrowthWorkload(WorkloadGenerator):
    """Compounding growth: matching topics multiply by ``growth_per_period``
    every ``period_ms`` (the organic-adoption shape the provisioner must
    eventually flag as under-provisioned)."""

    def __init__(self, seed: int = 0, growth_per_period: float = 1.3,
                 period_ms: int = 3_600_000,
                 topic_prefix: str = "", **kw):
        super().__init__(seed=seed, **kw)
        self._growth = growth_per_period
        self._period = max(int(period_ms), 1)
        self._prefix = topic_prefix

    def intensity(self, t_ms, topic, partition):
        if self._prefix and not topic.startswith(self._prefix):
            return 1.0
        return self._growth ** (t_ms / self._period)


class HotspotDriftWorkload(WorkloadGenerator):
    """A rotating hot partition subset: every ``rotation_ms`` the hot group
    advances, so total load is flat while its *placement* keeps moving —
    the shape that punishes a rebalancer for chasing transients."""

    def __init__(self, seed: int = 0, rotation_ms: int = 600_000,
                 num_groups: int = 4, multiplier: float = 4.0, **kw):
        super().__init__(seed=seed, **kw)
        self._rotation = max(int(rotation_ms), 1)
        self._groups = max(int(num_groups), 1)
        self._multiplier = multiplier

    def intensity(self, t_ms, topic, partition):
        group = stable_hash32(topic, partition) % self._groups
        hot = (t_ms // self._rotation) % self._groups
        return self._multiplier if group == hot else 1.0


class CompositeWorkload(WorkloadGenerator):
    """Product of component intensities (e.g. diurnal × flash-crowd). Base
    rates/jitter/seed come from this instance; components contribute only
    their ``intensity``."""

    def __init__(self, components: Sequence[WorkloadGenerator],
                 seed: int = 0, **kw):
        super().__init__(seed=seed, **kw)
        self._components = tuple(components)

    def intensity(self, t_ms, topic, partition):
        out = 1.0
        for c in self._components:
            out *= c.intensity(t_ms, topic, partition)
        return out


class TraceReplayWorkload(FileMetricSampler):
    """Replay a recorded JSONL trace through the monitor ingest path — the
    same format FileMetricSampler reads (``kind``-tagged sample objects, one
    per line)."""


def record_trace(path: str, sampler: MetricSampler,
                 metadata: ClusterMetadata, start_ms: int, end_ms: int,
                 step_ms: int) -> int:
    """Materialize a sampler's output as a replayable JSONL trace.

    Writes one ``kind``-tagged JSON object per sample (the tag is what
    FileMetricSampler dispatches on; the samples' own ``to_json`` omits it).
    Returns the number of lines written.
    """
    n = 0
    with open(path, "w") as f:
        t = start_ms
        while t < end_ms:
            ps, bs = sampler.get_samples(metadata, t, min(t + step_ms, end_ms))
            for s in ps:
                f.write(json.dumps({"kind": "partition", **s.to_json()}) + "\n")
                n += 1
            for s in bs:
                f.write(json.dumps({"kind": "broker", **s.to_json()}) + "\n")
                n += 1
            t += step_ms
    return n


#: generator registry for ``metric.sampler.class``-style lookup
WORKLOAD_REGISTRY = {
    "DiurnalWorkload": DiurnalWorkload,
    "SpikeWorkload": SpikeWorkload,
    "FlashCrowdWorkload": FlashCrowdWorkload,
    "TopicGrowthWorkload": TopicGrowthWorkload,
    "HotspotDriftWorkload": HotspotDriftWorkload,
    "CompositeWorkload": CompositeWorkload,
    "TraceReplayWorkload": TraceReplayWorkload,
}
