"""Scenario runner: the real control loop over virtual time, scored.

A :class:`Scenario` is a deterministic, seeded description of "a cluster +
a workload + a fault timeline + SLO budgets". :func:`run_scenario` builds a
:class:`~cruise_control_tpu.simulator.cluster.SimulatedKafkaCluster`, wraps
it in the PR 2 chaos adapter (plan swapped per tick from the
:class:`~cruise_control_tpu.simulator.faults.FaultSchedule`), boots a real
``CruiseControlApp`` on a :class:`~cruise_control_tpu.simulator.clock.
VirtualClock`, and steps the monitor→detector→analyzer→executor loop for
``ticks`` virtual windows. Executed proposals mutate the simulated cluster,
so the next tick's model reflects them — convergence, churn, and self-heal
latency are measured on a genuinely closed loop.

The :class:`Scorecard` separates two channels:

- a **deterministic core** (pure function of the scenario: convergence
  tick, movement totals, churn, goal-violation ticks, fault tallies,
  self-heal virtual latencies, provisioner statuses) — serialized by
  ``canonical_json()``, the byte-identical determinism contract;
- a **wall section** (tick p50/p99, self-heal wall vs the PR 7 <10 s
  budget, SLO violation counts, sentinel results) that depends on the host.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time as _time
from contextlib import nullcontext
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from cruise_control_tpu.simulator import score as SC
from cruise_control_tpu.simulator.clock import VirtualClock
from cruise_control_tpu.simulator.cluster import SimulatedKafkaCluster
from cruise_control_tpu.simulator.faults import FaultEvent, FaultSchedule
from cruise_control_tpu.simulator.workloads import DiurnalWorkload


@dataclasses.dataclass(frozen=True)
class SLOBudget:
    """Per-scenario service-level objectives."""

    #: wall-clock budget for one control-loop tick
    tick_wall_ms: float = 30_000.0
    #: wall-clock budget for a self-heal optimize (the PR 7 <10 s contract)
    self_heal_wall_ms: float = 10_000.0
    #: virtual ticks allowed from broker death to full evacuation
    heal_convergence_ticks: int = 10


@dataclasses.dataclass(frozen=True)
class Scenario:
    """Deterministic scenario spec; see docs/simulation.md."""

    name: str
    seed: int = 0
    ticks: int = 60
    tick_ms: int = 60_000
    num_brokers: int = 4
    num_racks: int = 2
    topics: Tuple[str, ...] = ("T0", "T1")
    partitions_per_topic: int = 4
    rf: int = 2
    #: MetricSampler; None → DiurnalWorkload over half the scenario span
    workload: Optional[object] = None
    faults: FaultSchedule = dataclasses.field(default_factory=FaultSchedule)
    slo: SLOBudget = dataclasses.field(default_factory=SLOBudget)
    #: control-loop ticks run before measurement starts (programs warm,
    #: windows full) — the sentinel only wraps the measured ticks
    warmup_ticks: int = 4
    #: ground truth for provisioner-accuracy scoring (None = not scored)
    expected_provision: Optional[str] = None
    config_overrides: Tuple[Tuple[str, object], ...] = ()
    latency_polls: int = 1
    #: attach a live warm standby (leased leadership + journal tailing);
    #: a ``process_crash`` then kills the *leader* and the scorecard
    #: measures lease-expiry takeover instead of a cold restart
    warm_standby: bool = False


@dataclasses.dataclass
class Scorecard:
    """Scenario verdict: deterministic core + host-dependent wall section."""

    core: dict
    wall: dict
    #: Chrome-trace export of the measured ticks (virtual-clock µs);
    #: carried out-of-band — not part of to_json()/the /state surface
    trace: Optional[dict] = None
    #: canonical flight-recorder JSONL of the measured ticks (out-of-band,
    #: like the trace); the core carries its digest + record count —
    #: tools/replay_tick.py consumes this log for deterministic replay
    flight_log: Optional[str] = None

    def canonical_json(self) -> str:
        """Byte-stable serialization of the deterministic core — two runs
        of the same (seed, scenario) must produce identical strings."""
        return json.dumps(self.core, sort_keys=True, separators=(",", ":"))

    def trace_json(self) -> Optional[str]:
        """Canonical Chrome-trace JSON of the measured ticks (None when
        tracing was disabled) — byte-stable for a deterministic run."""
        if self.trace is None:
            return None
        return json.dumps(self.trace, sort_keys=True, separators=(",", ":"))

    def to_json(self) -> dict:
        return {**self.core, "wall": self.wall}


def _scenario_config(sc: Scenario):
    """Virtual-time-friendly service config: one metrics window per tick,
    detector/notifier thresholds measured in ticks, anneal engine pinned."""
    from cruise_control_tpu.common.config import CruiseControlConfig
    W = sc.tick_ms
    base = {
        "optimizer.engine": "anneal",
        "anneal.num.chains": 4,
        "anneal.steps": 64,
        "anneal.tries.move": 16,
        "anneal.tries.lead": 4,
        "anneal.tries.swap": 8,
        "partition.metrics.window.ms": W,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "min.samples.per.partition.metrics.window": 1,
        "metric.sampling.interval.ms": W,
        "execution.progress.check.interval.ms": 10,
        "failed.brokers.file.path": "",
        "proposal.expiration.ms": 4 * W,
        "num.proposal.precompute.threads": 0,
        "anomaly.detection.interval.ms": W,
        "anomaly.detection.recheck.delay.ms": W,
        "self.healing.enabled": True,
        "broker.failure.alert.threshold.ms": W,
        "broker.failure.self.healing.threshold.ms": 2 * W,
        "broker.failure.detection.backoff.ms": W,
        # no watchdog monitor thread under virtual time — the tick loop
        # calls watchdog.poll() itself
        "watchdog.interval.ms": 0,
        # graftscope: span the measured ticks on the virtual clock so the
        # scorecard's per-stage breakdown (and the exported Chrome trace)
        # is a deterministic function of the scenario
        "obs.tracing.enable": True,
        # graftwatch: burn-rate alerting on the virtual clock — the
        # scorecard's alert timeline is a deterministic function of the
        # seed (fast window in tick units so short scenarios can fire)
        "healthwatch.enable": True,
        "healthwatch.fast.window.ticks": 4,
        "healthwatch.slow.window.ticks": 16,
    }
    if sc.warm_standby:
        # lease timing in tick units: the leader renews every tick, so a
        # one-tick lease expires on the first tick it misses — takeover
        # lands at crash tick + 1 without weakening the lease guarantee
        base["replication.lease.ms"] = W
        base["replication.lease.renew.ms"] = max(W // 4, 1)
    base.update(dict(sc.config_overrides))
    return CruiseControlConfig(base)


def _apply_direct(ev: FaultEvent, cluster: SimulatedKafkaCluster,
                  wrapper, app) -> None:
    """Fire a direct fault event against the simulated cluster/app."""
    if ev.kind == "kill_broker":
        cluster.kill_broker(ev.broker_id)
    elif ev.kind == "restore_broker":
        cluster.restore_broker(ev.broker_id)
    elif ev.kind == "fail_disk":
        cluster.fail_disk(ev.broker_id, ev.logdir)
    elif ev.kind == "restore_disk":
        cluster.restore_disk(ev.broker_id, ev.logdir)
    elif ev.kind == "kill_broker_mid_execution":
        # arm the chaos adapter: the death lands ``calls_after`` guarded
        # adapter calls from now — i.e. inside this tick's execution batch
        wrapper.set_plan(dataclasses.replace(
            wrapper.plan,
            kill_broker_id=ev.broker_id,
            kill_broker_after_calls=wrapper.calls + ev.calls_after))
    elif ev.kind == "stop_execution":
        app.executor.stop_execution(forced=True)
    elif ev.kind == "process_crash":
        # arm the chaos adapter: ``calls_after`` guarded calls from now the
        # wrapper freezes the execution journal (simulating kill -9 — no
        # shutdown hooks run) and raises ProcessCrashed; the runner's tick
        # loop catches it, rebuilds the app against the same simulated
        # cluster, and runs restart reconciliation
        wrapper._crashed = False
        wrapper.on_crash = (app.journal.freeze
                            if app.journal is not None else None)
        wrapper.set_plan(dataclasses.replace(
            wrapper.plan,
            process_crash_after_calls=wrapper.calls + ev.calls_after))


def build_app(sc: Scenario, clock=None, cluster=None, wrapper=None,
              sampler=None):
    """Construct (clock, cluster, chaos wrapper, app) for a scenario —
    exposed separately so tests can drive partial loops. Pass existing
    ``clock``/``cluster``/``wrapper``/``sampler`` to rebuild only the app
    (the ``process_crash`` restart path: same simulated world, fresh
    control plane)."""
    from cruise_control_tpu.app import CruiseControlApp
    from cruise_control_tpu.common.faults import FaultyClusterAdapter

    clock = clock or VirtualClock()
    cluster = cluster or SimulatedKafkaCluster.build(
        num_brokers=sc.num_brokers, num_racks=sc.num_racks,
        topics=sc.topics, partitions_per_topic=sc.partitions_per_topic,
        rf=sc.rf, latency_polls=sc.latency_polls)
    wrapper = wrapper or FaultyClusterAdapter(
        cluster, sc.faults.plan_for_tick(-1), sleep=clock.sleep)
    workload = sampler or sc.workload or DiurnalWorkload(
        seed=sc.seed, period_ms=max(sc.ticks * sc.tick_ms // 2, sc.tick_ms))
    app = CruiseControlApp(_scenario_config(sc), metadata_source=cluster,
                           sampler=workload, cluster_adapter=wrapper,
                           now_fn=clock.now_s, sleep_fn=clock.sleep)
    return clock, cluster, wrapper, app


def _build_standby(sc: Scenario, clock, cluster, wrapper, leader_app):
    """Attach a replicated control plane to a scenario: the leader takes
    the leadership lease over its journal's epoch sidecar, and a second
    full app — own monitor windows, no journal until promotion — tails
    the leader's journal on the same simulated world. Returns
    ``(controller, standby, standby_app)``."""
    from cruise_control_tpu.replication import (JournalTailer, LeaderLease,
                                                ReplicationController,
                                                WarmStandby)
    config = leader_app.config
    lease_ms = config.get("replication.lease.ms")
    renew_ms = config.get("replication.lease.renew.ms")
    epoch_path = leader_app.journal.epoch_path
    controller = ReplicationController(
        LeaderLease(epoch_path, holder="leader", now_ms=clock.now_ms,
                    lease_ms=lease_ms, renew_ms=renew_ms, fsync=False),
        journal=leader_app.journal)
    controller.attach()
    leader_app.attach_replication(controller)
    overrides = dict(sc.config_overrides)
    replica_path = overrides["executor.journal.path"] + ".standby"
    overrides["executor.journal.path"] = ""
    sc_follower = dataclasses.replace(
        sc, config_overrides=tuple(overrides.items()))
    standby_sampler = sc.workload or DiurnalWorkload(
        seed=sc.seed, period_ms=max(sc.ticks * sc.tick_ms // 2, sc.tick_ms))
    _, _, _, standby_app = build_app(
        sc_follower, clock=clock, cluster=cluster, wrapper=wrapper,
        sampler=standby_sampler)
    standby = WarmStandby(
        controller.shipper,
        JournalTailer(replica_path),
        LeaderLease(epoch_path, holder="standby", now_ms=clock.now_ms,
                    lease_ms=lease_ms, renew_ms=renew_ms, fsync=False),
        now_ms=clock.now_ms,
        executor=standby_app.executor,
        # the existing warm path: a precompute traces/compiles the
        # anneal + escape kernels via OPT.warm_kernels before takeover
        warm_fn=standby_app.precompute_tick)
    standby.register_watchdog(standby_app.watchdog)
    standby_app.attach_replication(standby)
    return controller, standby, standby_app


def run_scenario(sc: Scenario, use_sentinel: bool = False,
                 score_goals: bool = True) -> Scorecard:
    """Run one scenario end-to-end; returns its :class:`Scorecard`.

    ``use_sentinel`` wraps the measured ticks in ``retrace_sentinel()``
    (warmup stays outside) and reports uncovered retraces in the wall
    section. ``score_goals=False`` skips the per-tick model snapshots and
    the batched goal scoring (faster, for bench sweeps that only need
    convergence/churn).
    """
    from cruise_control_tpu.common import sentinels as SENT
    from cruise_control_tpu.common.faults import ProcessCrashed
    from cruise_control_tpu.executor.journal import StaleEpochError
    from cruise_control_tpu.monitor.load_monitor import (
        NotEnoughValidWindowsError)

    # a process_crash scenario needs a journal to reconcile from; provision
    # a temp one when the scenario doesn't pin its own path (no fsync — the
    # crash is simulated above the filesystem, and virtual time shouldn't
    # pay real disk latency)
    auto_journal_dir = None
    if (sc.faults.process_crash_events()
            and "executor.journal.path" not in dict(sc.config_overrides)):
        import tempfile
        auto_journal_dir = tempfile.mkdtemp(prefix="cc-scenario-journal-")
        sc = dataclasses.replace(sc, config_overrides=sc.config_overrides + (
            ("executor.journal.path",
             os.path.join(auto_journal_dir, "execution.journal")),
            ("executor.journal.fsync", False)))

    clock, cluster, wrapper, app = build_app(sc)
    W = sc.tick_ms
    config = app.config
    goal_names = tuple(config.get("anomaly.detection.goals"))
    full_windows = config.get("num.partition.metrics.windows")

    def valid_windows(a) -> int:
        """Monitoring completeness of one app's aggregator — a cold
        restart refills from zero (one window per tick); a warm standby
        sampled every tick, so its windows never emptied."""
        from cruise_control_tpu.monitor.aggregator import (
            ModelCompletenessRequirements)
        try:
            return int(a.load_monitor.partition_aggregator.completeness(
                clock.now_ms(),
                ModelCompletenessRequirements()).num_valid_windows)
        except Exception:  # pragma: no cover  # graftlint: disable=G009 a starved aggregator (no samples yet) simply has zero valid windows
            return 0

    standby = standby_app = None
    leader_dead = False
    dead_app = None
    dead_tick: Optional[int] = None
    zombie_fenced: Optional[bool] = None
    if sc.warm_standby and app.journal is not None:
        _, standby, standby_app = _build_standby(sc, clock, cluster,
                                                 wrapper, app)

    def ingest():
        if not leader_dead:
            app.load_monitor.sample_once(now_ms=clock.now_ms() + W // 2)
        if standby_app is not None and standby_app is not app:
            standby_app.load_monitor.sample_once(
                now_ms=clock.now_ms() + W // 2)
        clock.advance_ms(W)

    def replication_tick():
        """Leader renews its lease, follower tails the journal — the
        per-tick replication duties while both incarnations live."""
        if standby is None or standby.role != "follower":
            return
        if not leader_dead:
            try:
                app.replication.tick()
            except StaleEpochError:  # pragma: no cover - superseded leader
                pass
        standby.poll()
        standby_app.watchdog.poll()

    def loop_once():
        replication_tick()
        app.precompute_tick()
        app.anomaly_detector.sweep()
        app.anomaly_detector.handle_pending()

    # ---- warmup: fill windows, then run real ticks so every program the
    # measured loop dispatches (model build, anneal, detector scoring,
    # provisioner grid, self-healing rebalance) is traced before the
    # sentinel opens
    for _ in range(config.get("num.partition.metrics.windows")):
        ingest()
    for _ in range(max(sc.warmup_ticks, 1)):
        ingest()
        loop_once()
    # heal-shaped programs: optimize-with-options traces a different
    # program than the default-goal path, so warm the exact routes the
    # scheduled faults will take. Warmup failures are expected shapes (a
    # plan with nothing to fix returns None, tiny models can reject a
    # remove), not scenario errors — log and continue; the measured run's
    # own assertions catch anything real. The self-healing rebalance
    # executes (self_healing forces dryrun off), so warmup may move
    # replicas — all before the measurement baselines are taken.
    kills = sc.faults.kill_broker_events()
    if kills:
        try:
            app.remove_brokers([kills[0].broker_id], dryrun=True)
        except Exception:
            logger.debug("warmup remove_brokers skipped", exc_info=True)
    if any(e.kind == "fail_disk" for e in sc.faults.events):
        try:
            app.fix_offline_replicas(dryrun=True)
        except Exception:
            logger.debug("warmup fix_offline_replicas skipped", exc_info=True)
    try:
        app.rebalance(dryrun=True, self_healing=True)
    except Exception:
        logger.debug("warmup self-healing rebalance skipped", exc_info=True)
    # fault drill: a broker death changes compiled shapes downstream — the
    # provisioner what-if grid is composed from the *alive* broker set and
    # the post-death rebalance dispatches batched-apply programs the
    # healthy loop never traces. Dry runs can't reach those, so rehearse
    # the first scheduled kill against the live cluster: kill, run ticks
    # until the loop settles, restore, re-settle. Deterministic (same
    # drill every run) and excluded from the baselines taken below.
    if kills:
        def settle(max_ticks: int = 6) -> None:
            # tick until the loop stops moving replicas/leadership — the
            # post-death cleanup (heal moves, then the repair engine's
            # leadership phase) spans several ticks, and each stage
            # dispatches programs the healthy loop never traces
            for _ in range(max_ticks):
                m0 = cluster.moves_applied
                l0 = cluster.leadership_moves_applied
                ingest()
                loop_once()
                if (cluster.moves_applied == m0
                        and cluster.leadership_moves_applied == l0):
                    return
        drill = kills[0].broker_id
        cluster.kill_broker(drill)
        settle()
        cluster.restore_broker(drill)
        settle()

    # ---- measurement baselines (warmup movement must not count)
    # warmup spans out of the ring: the scorecard's stage breakdown (and
    # the exported trace) covers exactly the measured ticks. The flight
    # recorder resets on the same boundary — its export (and digest) then
    # covers exactly the measured ticks, and same-seed runs produce
    # byte-identical logs (everything in a record is a deterministic
    # function of the seed; timestamps come from the virtual clock).
    app.tracer.clear()
    app.flightrec.clear()
    # graftwatch shares the boundary: the alert timeline (and its digest
    # in the scorecard core) covers exactly the measured ticks
    if app.healthwatch is not None:
        app.healthwatch.reset()
    # replay pin: a scenario fully described by scalar spec fields (no
    # workload object, no faults, no standby) embeds the spec so
    # tools/replay_tick.py can rebuild it from the log alone
    replay_spec = None
    if (sc.workload is None and not sc.faults.events and not sc.warm_standby
            and sc.expected_provision is None):
        replay_spec = {
            "name": sc.name, "seed": sc.seed, "ticks": sc.ticks,
            "tick_ms": sc.tick_ms, "num_brokers": sc.num_brokers,
            "num_racks": sc.num_racks, "topics": list(sc.topics),
            "partitions_per_topic": sc.partitions_per_topic, "rf": sc.rf,
            "warmup_ticks": sc.warmup_ticks, "latency_polls": sc.latency_polls,
            "config_overrides": [list(kv) for kv in sc.config_overrides],
        }
    app.flightrec.set_context(source=f"scenario:{sc.name}", seed=sc.seed,
                              scenarioSpec=replay_spec)
    base_moves = cluster.moves_applied
    base_lmoves = cluster.leadership_moves_applied
    base_churn = dict(cluster.move_count_by_tp)
    base_injected = dict(wrapper.injected)
    with app._cache_lock:
        last_fb = app._last_fallback

    records: List[dict] = []
    snapshots: List[Optional[dict]] = []
    tick_walls: List[float] = []
    provision_statuses: List[str] = []
    evac_tick: Dict[int, int] = {}
    base_topo = None
    fallback_events = 0
    fallback_reasons: List[str] = []
    direct_fired = 0
    crash_recoveries: List[dict] = []
    recovery_walls: List[float] = []

    ctx = SENT.retrace_sentinel() if use_sentinel else nullcontext()
    with ctx as rlog:
        for tick in range(sc.ticks):
            # one span per measured tick, opened BEFORE ingest (the virtual
            # clock advances one window inside it) so the exported timeline
            # covers the tick's full virtual duration
            with app.tracer.span("tick", tick=tick) as _tick_sp:
                for ev in sc.faults.direct_at(tick):
                    _apply_direct(ev, cluster, wrapper, app)
                    direct_fired += 1
                if not sc.faults.direct_at(tick):
                    # per-tick transient windows (a mid-execution kill armed
                    # above must not be clobbered by the window plan this tick)
                    plan = sc.faults.plan_for_tick(tick)
                    if (wrapper.plan.process_crash_after_calls is not None
                            and not wrapper._crashed):
                        # an armed-but-unfired process crash persists across
                        # window swaps: the process dies at its Nth guarded
                        # call whichever tick that lands in
                        plan = dataclasses.replace(
                            plan, process_crash_after_calls=(
                                wrapper.plan.process_crash_after_calls))
                    wrapper.set_plan(plan)
                ingest()
                if not leader_dead:
                    replication_tick()
                m0 = cluster.moves_applied
                l0 = cluster.leadership_moves_applied
                t0 = _time.perf_counter()
                if leader_dead:
                    # the leader is down and a standby exists: no control
                    # plane serves this tick. The standby keeps tailing the
                    # (frozen) journal and watches the lease; once it expires
                    # the standby advances the epoch and takes over from its
                    # already-tailed state — no cold rebuild, no full replay.
                    computed = False
                    rec_t0 = _time.perf_counter()
                    standby.poll()
                    takeover = standby.maybe_takeover()
                    if takeover is not None:
                        app = standby_app
                        app.journal = standby.journal
                        wrapper.on_crash = standby.journal.freeze
                        recovery_walls.append(
                            round((_time.perf_counter() - rec_t0) * 1000.0, 3))
                        crash_recoveries.append({
                            **takeover, "tick": dead_tick, "takeoverTick": tick,
                            "takeoverTicks": tick - dead_tick,
                            "mode": "warm_takeover"})
                        # the fenced ex-leader provably cannot mutate: its
                        # next append refuses with StaleEpochError and its
                        # held epoch predates the lease-claimed one
                        try:
                            dead_app.journal.log_execution_end("zombie-probe")
                            zombie_fenced = False
                        except StaleEpochError:
                            zombie_fenced = (dead_app.journal.epoch
                                             < standby.journal.epoch)
                        leader_dead = False
                        computed = bool(app.precompute_tick())
                        app.anomaly_detector.sweep()
                        app.anomaly_detector.handle_pending()
                else:
                    try:
                        computed = app.precompute_tick()
                        app.anomaly_detector.sweep()
                        app.anomaly_detector.handle_pending()
                    except ProcessCrashed:
                        computed = False
                        if standby is not None and standby.role == "follower":
                            # leader killed with a live standby attached:
                            # leave the corpse fenced and let the lease run
                            # out (scored as takeoverTicks)
                            leader_dead = True
                            dead_tick = tick
                            dead_app = app
                        else:
                            # no standby: the PR 10 path. Rebuild the app
                            # against the SAME simulated cluster/clock/chaos
                            # wrapper — a new process on the same host — and
                            # run cold restart reconciliation (full replay).
                            rec_t0 = _time.perf_counter()
                            _, _, _, app = build_app(
                                sc, clock=clock, cluster=cluster,
                                wrapper=wrapper,
                                sampler=app.load_monitor._sampler)
                            wrapper.on_crash = (app.journal.freeze
                                                if app.journal is not None
                                                else None)
                            recovery = (app.executor.recover()
                                        if app.journal is not None
                                        else {"performed": False})
                            recovery_walls.append(round(
                                (_time.perf_counter() - rec_t0) * 1000.0, 3))
                            crash_recoveries.append(
                                {**recovery, "tick": tick,
                                 "mode": "cold_restart"})
                app.watchdog.poll()
                wall_ms = (_time.perf_counter() - t0) * 1000.0
                tick_walls.append(wall_ms)
                with app._cache_lock:
                    res = (app._proposal_cache.result
                           if app._proposal_cache is not None else None)
                    fb = app._last_fallback
                    pr = app._last_provision_recommendation
                if fb is not None and fb is not last_fb:
                    fallback_events += 1
                    if fb.get("reason") and fb["reason"] not in fallback_reasons:
                        fallback_reasons.append(fb["reason"])
                last_fb = fb
                status = (pr or {}).get("status")
                if status and (not provision_statuses
                               or provision_statuses[-1] != status):
                    provision_statuses.append(status)
                records.append({
                    "tick": tick,
                    "computed": bool(computed),
                    "engine": res.engine if res is not None else None,
                    "replicaMoves": cluster.moves_applied - m0,
                    "leadershipMoves": cluster.leadership_moves_applied - l0,
                    "validWindows": valid_windows(app),
                })
                for ev in kills:
                    if ev.broker_id in evac_tick or ev.tick > tick:
                        continue
                    if not cluster.replicas_on_broker(ev.broker_id):
                        evac_tick[ev.broker_id] = tick
                if score_goals:
                    try:
                        topo, assign = app._model()
                        snap = SC.snapshot_model(topo, assign)
                        if base_topo is None:
                            base_topo = topo
                            base_shapes = {k: v.shape for k, v in snap.items()}
                        if {k: v.shape for k, v in snap.items()} == base_shapes:
                            snapshots.append(snap)
                        else:
                            # the valid-partition set shrank this tick (e.g. the
                            # monitor starved through a latency storm): a
                            # different-shaped model cannot join the vmapped
                            # timeline stack — count the tick as unscored
                            snapshots.append(None)
                    except NotEnoughValidWindowsError:
                        snapshots.append(None)
                _tick_sp.set("computed", bool(computed))
    uncovered = SENT.check_steady_state(rlog) if use_sentinel else None

    # ---- batched scoring of the whole timeline (outside the sentinel:
    # the stacked [T, ...] shapes are a new program by construction)
    scored = [s for s in snapshots if s is not None]
    if score_goals and base_topo is not None and scored:
        viol = SC.batched_goal_violations(base_topo, scored, goal_names)
        vticks = SC.violation_ticks(viol, goal_names)
    else:
        vticks = {"goalViolationTicks": None, "hardViolationTicks": None,
                  "offlineTicks": None}

    # ---- fold into the scorecard
    move_ticks = [r["tick"] for r in records if r["replicaMoves"] > 0]
    last_move_tick = move_ticks[-1] if move_ticks else None
    churn = sum(
        max(cluster.move_count_by_tp.get(tp, 0) - base_churn.get(tp, 0) - 1, 0)
        for tp in cluster.move_count_by_tp)
    heal = []
    for ev in kills:
        e_tick = evac_tick.get(ev.broker_id)
        heal_ticks = (e_tick - ev.tick) if e_tick is not None else None
        heal.append({
            "brokerId": ev.broker_id,
            "faultTick": ev.tick,
            "evacuatedTick": e_tick,
            "healTicks": heal_ticks,
            "withinTickBudget": (heal_ticks is not None
                                 and heal_ticks <= sc.slo.heal_convergence_ticks),
        })
    engines = sorted({r["engine"] for r in records if r["engine"]})
    injected = {k: wrapper.injected[k] - base_injected.get(k, 0)
                for k in wrapper.injected}
    # recovery ticks: crash tick → first tick the control plane computes
    # again at FULL monitoring completeness. A cold restart refills its
    # metric windows from zero (one per tick); a warm standby's windows
    # never emptied, so takeover + one tick suffices.
    for entry in crash_recoveries:
        rec_tick = next((r["tick"] for r in records
                         if r["tick"] >= entry["tick"] and r["computed"]
                         and r["validWindows"] >= full_windows),
                        None)
        entry["recoveryTicks"] = (rec_tick - entry["tick"]
                                  if rec_tick is not None else None)
    takeover_ticks = next(
        (e["takeoverTicks"] for e in crash_recoveries
         if e.get("mode") == "warm_takeover"), None)
    provision_accurate = (None if sc.expected_provision is None
                          else sc.expected_provision in provision_statuses)
    core = {
        "scenario": sc.name,
        "seed": sc.seed,
        "ticks": sc.ticks,
        "tickMs": sc.tick_ms,
        "brokers": sc.num_brokers,
        "partitions": len(sc.topics) * sc.partitions_per_topic,
        "computeTicks": sum(1 for r in records if r["computed"]),
        "engines": engines,
        "fallbackEvents": fallback_events,
        "fallbackReasons": fallback_reasons,
        "totalReplicaMoves": cluster.moves_applied - base_moves,
        "totalLeadershipMoves": cluster.leadership_moves_applied - base_lmoves,
        "moveChurn": churn,
        "lastMoveTick": last_move_tick,
        "convergenceTick": (last_move_tick + 1
                            if last_move_tick is not None else 0),
        "converged": last_move_tick is None or last_move_tick < sc.ticks - 1,
        "scoredTicks": len(scored),
        **vticks,
        "selfHeal": heal,
        "healTicksBudget": sc.slo.heal_convergence_ticks,
        "sloHealTickViolations": sum(
            1 for h in heal if not h["withinTickBudget"]),
        "faultsInjected": injected,
        "directFaultEvents": direct_fired,
        "provisionStatuses": provision_statuses,
        "expectedProvision": sc.expected_provision,
        "provisionAccurate": provision_accurate,
        "processCrashes": len(crash_recoveries),
        "recoveryTick": (crash_recoveries[0]["tick"]
                         if crash_recoveries else None),
        "crashRecoveries": crash_recoveries,
        "warmStandby": sc.warm_standby,
        "takeoverTicks": takeover_ticks,
        "zombieFenced": zombie_fenced,
        "standbyLagRecords": (standby.lag_records
                              if standby is not None else None),
        "watchdogRestarts": app.watchdog.total_restarts,
        # digest of the final replica assignment + leaders: the crash-
        # recovery acceptance check compares this across a crashing run and
        # its uninterrupted twin (bit-identical convergence)
        "finalAssignmentDigest": hashlib.sha256(json.dumps(
            {"replicas": cluster.replicas, "leaders": cluster.leaders},
            sort_keys=True, separators=(",", ":")).encode()).hexdigest(),
        # flight-recorder attachment: record count + digest of the canonical
        # JSONL export over the measured ticks. In the deterministic core on
        # purpose — a same-seed rerun must reproduce the decision log
        # byte-for-byte (tools/replay_tick.py replays individual records)
        "flightRecorder": {"records": len(app.flightrec.records()),
                           "digest": app.flightrec.export_digest()},
        # graftwatch attachment: burn-rate alert counts + digest of the
        # canonical alert timeline. Also in the deterministic core — every
        # signal in a health vector derives from seed-determined state and
        # the virtual clock, so same-seed runs reproduce the timeline
        # byte-for-byte
        "alerts": (dict(app.healthwatch.alert_counts(),
                        timelineDigest=hashlib.sha256(
                            app.healthwatch.export_timeline().encode()
                        ).hexdigest())
                   if app.healthwatch is not None else
                   {"fired": 0, "suppressed": 0, "resolved": 0,
                    "firstFiringTick": None, "timelineDigest": None}),
    }
    walls = np.asarray(tick_walls) if tick_walls else np.zeros(1)
    with app._cache_lock:
        self_heal_wall = app.last_self_heal_ms
        heal_path = app.self_heal_path
    wall = {
        "tickWallMsP50": round(float(np.percentile(walls, 50)), 3),
        "tickWallMsP99": round(float(np.percentile(walls, 99)), 3),
        "tickWallMsMax": round(float(walls.max()), 3),
        "sloTickWallMs": sc.slo.tick_wall_ms,
        "sloTickViolations": int((walls > sc.slo.tick_wall_ms).sum()),
        "selfHealWallMs": self_heal_wall,
        "selfHealPath": heal_path,
        "sloSelfHealWallMs": sc.slo.self_heal_wall_ms,
        "sloSelfHealViolations": int(
            self_heal_wall is not None
            and self_heal_wall > sc.slo.self_heal_wall_ms),
    }
    if recovery_walls:
        wall["recoveryWallMs"] = recovery_walls
    if uncovered is not None:
        wall["uncoveredRetraces"] = [str(u) for u in uncovered]
    # per-stage breakdown from the measured ticks' spans: counts + virtual
    # durations are deterministic (scorecard core); wall percentiles are
    # host-dependent (wall section). The raw Chrome trace rides out-of-band
    # on the Scorecard object.
    trace = None
    spans = app.tracer.finished()
    if spans:
        from cruise_control_tpu.obs import tracing as TR
        core["stageBreakdown"] = TR.stage_breakdown(spans)
        wall["stageWallPercentiles"] = TR.stage_wall_percentiles(spans)
        trace = app.tracer.chrome_trace()
    card = Scorecard(core=core, wall=wall, trace=trace,
                     flight_log=app.flightrec.export_jsonl())
    app.record_simulation_scorecard(card.to_json())
    if standby is not None:
        standby.stop()
        if standby.journal is not None:
            standby.journal.close()
    if auto_journal_dir is not None:
        if app.journal is not None:
            app.journal.close()
        if dead_app is not None and dead_app.journal is not None:
            dead_app.journal.close()
        import shutil
        shutil.rmtree(auto_journal_dir, ignore_errors=True)
    return card
