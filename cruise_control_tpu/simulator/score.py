"""Batched goal scoring over a scenario timeline.

A scenario records one model snapshot per tick (load columns + placement +
liveness). Scoring them one-by-one would pay per-tick dispatch for hundreds
of ticks; since the topology *structure* (partition/replica layout indices,
capacities, racks) is tick-invariant in a scenario, the whole timeline
stacks along a leading axis and every tick scores in ONE compiled vmapped
program — the same aggregates→thresholds→``full_goal_penalties`` pipeline
the GoalViolationDetector runs per tick (all documented jit/vmap-safe).

Output: violations ``f32[T, G+1]`` — per-goal totals plus the trailing
offline/self-healing term, exactly the detector's per-tick verdict vector.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from cruise_control_tpu.common.resources import BalancingConstraint

#: snapshot keys, in the order the vmapped scorer consumes them
SNAPSHOT_KEYS = ("replica_base_load", "leader_extra", "leader_bytes_in",
                 "broker_alive", "replica_offline", "broker_of", "leader_of")


def snapshot_model(topo, assign) -> Dict[str, np.ndarray]:
    """Host-side per-tick snapshot of the leaves that vary over a scenario."""
    import jax
    return {
        "replica_base_load": np.asarray(topo.replica_base_load, np.float32),
        "leader_extra": np.asarray(topo.leader_extra, np.float32),
        "leader_bytes_in": np.asarray(topo.leader_bytes_in, np.float32),
        "broker_alive": np.asarray(topo.broker_alive, bool),
        "replica_offline": np.asarray(topo.replica_offline, bool),
        "broker_of": np.asarray(jax.device_get(assign.broker_of), np.int32),
        "leader_of": np.asarray(jax.device_get(assign.leader_of), np.int32),
    }


def batched_goal_violations(base_topo,
                            snapshots: Sequence[Dict[str, np.ndarray]],
                            goal_names: Sequence[str],
                            constraint: Optional[BalancingConstraint] = None,
                            ) -> np.ndarray:
    """Score every tick's model in one vmapped compiled call.

    ``base_topo`` supplies the tick-invariant structure; each snapshot (from
    :func:`snapshot_model`) supplies that tick's load/placement/liveness.
    Returns ``f32[T, G+1]`` violation totals (trailing entry = the
    offline/self-healing term).
    """
    import jax
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer import goals as G
    from cruise_control_tpu.ops.aggregates import (
        compute_aggregates, device_topology)

    if not snapshots:
        return np.zeros((0, len(goal_names) + 1), np.float32)
    constraint = constraint or BalancingConstraint()
    gn = tuple(goal_names)
    num_topics = base_topo.num_topics
    dt0 = device_topology(base_topo)
    stacked = {k: jnp.asarray(np.stack([s[k] for s in snapshots]))
               for k in SNAPSHOT_KEYS}

    def _score_one(base_load, leader_extra, lbi, alive, offline,
                   broker_of, leader_of):
        from cruise_control_tpu.models.cluster import Assignment
        dt = dt0._replace(replica_base_load=base_load,
                          leader_extra=leader_extra,
                          leader_bytes_in=lbi,
                          broker_alive=alive,
                          replica_offline=offline)
        assign = Assignment(broker_of=broker_of, leader_of=leader_of)
        agg = compute_aggregates(dt, assign, num_topics)
        th = G.compute_thresholds(dt, constraint, agg)
        pen = G.full_goal_penalties(dt, assign, th, num_topics, gn,
                                    initial_broker_of=broker_of, agg=agg)
        return pen.violations

    out = jax.vmap(_score_one)(*(stacked[k] for k in SNAPSHOT_KEYS))
    return np.asarray(jax.device_get(out), np.float32)


def violation_ticks(violations: np.ndarray,
                    goal_names: Sequence[str]) -> Dict[str, int]:
    """Collapse the [T, G+1] matrix into scorecard counters."""
    from cruise_control_tpu.analyzer import goals as G
    if violations.size == 0:
        return {"goalViolationTicks": 0, "hardViolationTicks": 0,
                "offlineTicks": 0}
    per_goal = violations[:, :-1]
    hard_idx = [i for i, g in enumerate(goal_names) if G.is_hard(g)]
    hard = (per_goal[:, hard_idx].sum(axis=1) > 0 if hard_idx
            else np.zeros(len(violations), bool))
    return {
        "goalViolationTicks": int((per_goal.sum(axis=1) > 0).sum()),
        "hardViolationTicks": int(np.asarray(hard).sum()),
        "offlineTicks": int((violations[:, -1] > 0).sum()),
    }
