"""Virtual time for the scenario simulator.

Every time-dependent seam in the control loop is injectable — the monitor,
the detectors, the self-healing notifier, the executor's deadlines, and the
fault adapter's latency sleeps all take ``now_fn``/``sleep`` callables. A
:class:`VirtualClock` closes them over one mutable timestamp, so a simulated
week of diurnal traffic (or a 30 s latency storm inside an execution) costs
zero wall time while every deadline/backoff/threshold computation sees the
same consistent timeline.

The clock only moves forward, and only when the scenario runner advances it
(tick boundaries) or a component "sleeps" (executor poll intervals, retry
backoffs, injected latency). That makes a scenario a deterministic function
of (seed, schedule): there is no wall-clock leakage into any recorded
virtual timestamp.
"""

from __future__ import annotations


class VirtualClock:
    """A monotonically-advancing simulated clock.

    ``now_s``/``now_ms`` are drop-in replacements for ``time.time`` and the
    millisecond ``now_fn`` seams; ``sleep`` replaces ``time.sleep`` and
    advances virtual time instead of blocking.
    """

    def __init__(self, start_ms: int = 0):
        self._now_ms = float(start_ms)

    def now_ms(self) -> int:
        return int(self._now_ms)

    def now_s(self) -> float:
        """``time.time`` replacement (seconds, float)."""
        return self._now_ms / 1000.0

    def advance_ms(self, ms: float) -> None:
        if ms < 0:
            raise ValueError(f"cannot advance a clock backwards ({ms} ms)")
        self._now_ms += float(ms)

    def sleep(self, seconds: float) -> None:
        """``time.sleep`` replacement: advancing time IS the sleep."""
        if seconds > 0:
            self._now_ms += float(seconds) * 1000.0
