"""SimulatedKafkaCluster: one mutable state behind both loop seams.

The control loop touches the cluster through two interfaces: the monitor
reads ``MetadataSource.get_metadata()`` and the executor applies movements
through ``ClusterAdapter``. FakeClusterAdapter only implements the second,
so in every existing test the *model* the analyzer optimizes is frozen
metadata — proposals never feed back. This class holds topology and
liveness as one mutable, generation-stamped state: a reassignment the
executor completes changes the PartitionMetadata the monitor reads on the
next tick, and a ``kill_broker`` fault changes both the metadata (the
BrokerFailureDetector's input) and the adapter view (``dead_brokers``) at
the same instant, exactly like a real cluster.

Reassignments follow FakeClusterAdapter's poll discipline: submitted moves
apply after ``latency_polls`` progress probes of that partition, so the
executor's batching/abort/stuck logic is exercised for real.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.monitor.sampler import (
    BrokerMetadata,
    ClusterMetadata,
    PartitionMetadata,
)


class SimulatedKafkaCluster:
    """Mutable in-memory cluster: MetadataSource + ClusterAdapter in one."""

    def __init__(self, brokers: Sequence[BrokerMetadata],
                 partitions: Sequence[PartitionMetadata],
                 latency_polls: int = 1):
        self._brokers: Dict[int, BrokerMetadata] = {
            b.broker_id: dataclasses.replace(b) for b in brokers}
        self._parts: Dict[str, PartitionMetadata] = {}
        self._order: List[str] = []
        for p in partitions:
            tp = f"{p.topic}-{p.partition}"
            self._parts[tp] = dataclasses.replace(p)
            self._order.append(tp)
        self.latency = latency_polls
        self.generation = 1
        self._pending: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
        self._pending_ple: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
        self.broker_throttle_rates: Dict[int, int] = {}
        self.topic_throttled_replicas: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        self.logdir_state: Dict[int, Dict[str, bool]] = {
            b.broker_id: {"/data/d0": True} for b in brokers}
        #: movement tallies + per-partition move counts (scorecard churn)
        self.moves_applied = 0
        self.leadership_moves_applied = 0
        self.move_count_by_tp: Dict[str, int] = {}

    # ------------------------------------------------------------ factory
    @classmethod
    def build(cls, num_brokers: int, num_racks: int = 2,
              topics: Sequence[str] = ("T0", "T1"),
              partitions_per_topic: int = 4, rf: int = 2,
              latency_polls: int = 1) -> "SimulatedKafkaCluster":
        """Deterministic small-cluster layout: brokers round-robin across
        racks, replica sets striped so every broker leads something."""
        rf = min(rf, num_brokers)
        brokers = [BrokerMetadata(i, rack=f"r{i % num_racks}", host=f"h{i}")
                   for i in range(num_brokers)]
        partitions = []
        for ti, topic in enumerate(topics):
            for p in range(partitions_per_topic):
                lead = (ti + p) % num_brokers
                reps = tuple((lead + k) % num_brokers for k in range(rf))
                partitions.append(PartitionMetadata(
                    topic, p, leader=lead, replicas=reps, isr=reps))
        return cls(brokers, partitions, latency_polls=latency_polls)

    # ----------------------------------------------------- MetadataSource
    def get_metadata(self) -> ClusterMetadata:
        """Generation-stamped snapshot of the current simulated state."""
        return ClusterMetadata(
            brokers=[dataclasses.replace(self._brokers[b])
                     for b in sorted(self._brokers)],
            partitions=[dataclasses.replace(self._parts[tp])
                        for tp in self._order],
            generation=self.generation)

    # -------------------------------------------------- fake-compat views
    @property
    def replicas(self) -> Dict[str, Tuple[int, ...]]:
        return {tp: p.replicas for tp, p in self._parts.items()}

    @property
    def leaders(self) -> Dict[str, int]:
        return {tp: p.leader for tp, p in self._parts.items()}

    def replicas_on_broker(self, broker_id: int) -> Set[str]:
        return {tp for tp, p in self._parts.items()
                if broker_id in p.replicas}

    # ------------------------------------------------------ fault surface
    def kill_broker(self, broker_id: int) -> None:
        """Broker death: metadata alive=False, leadership fails over to the
        first surviving replica, stranded replicas go offline."""
        b = self._brokers.get(int(broker_id))
        if b is None or not b.alive:
            return
        b.alive = False
        for p in self._parts.values():
            if broker_id in p.replicas:
                off = set(p.offline_replicas) | {broker_id}
                p.offline_replicas = tuple(sorted(off))
                p.isr = tuple(r for r in p.isr if r != broker_id)
            if p.leader == broker_id:
                survivors = [r for r in p.replicas
                             if self._brokers.get(r) is not None
                             and self._brokers[r].alive]
                p.leader = survivors[0] if survivors else -1
        self.generation += 1

    def restore_broker(self, broker_id: int) -> None:
        b = self._brokers.get(int(broker_id))
        if b is None or b.alive:
            return
        b.alive = True
        for p in self._parts.values():
            if broker_id in p.offline_replicas:
                p.offline_replicas = tuple(
                    r for r in p.offline_replicas if r != broker_id)
                p.isr = tuple(sorted(set(p.isr) | {broker_id}))
            if p.leader < 0 and broker_id in p.replicas:
                p.leader = broker_id
        self.generation += 1

    def fail_disk(self, broker_id: int, logdir: str = "/data/d0") -> None:
        self.logdir_state.setdefault(int(broker_id), {})[logdir] = False

    def restore_disk(self, broker_id: int, logdir: str = "/data/d0") -> None:
        self.logdir_state.setdefault(int(broker_id), {})[logdir] = True

    # -------------------------------------------------- ClusterAdapter API
    def execute_replica_reassignments(self, tasks) -> None:
        for t in tasks:
            self._pending[t.proposal.topic_partition] = (
                self.latency, t.proposal.new_replicas)

    def execute_preferred_leader_elections(self, tasks) -> None:
        for t in tasks:
            self._pending_ple[t.proposal.topic_partition] = (
                self.latency, t.proposal.new_replicas)

    def current_replicas(self, tp: str) -> Tuple[int, ...]:
        self._tick(tp)
        p = self._parts.get(tp)
        return p.replicas if p is not None else ()

    def current_leader(self, tp: str) -> int:
        self._tick(tp)
        p = self._parts.get(tp)
        return p.leader if p is not None else -1

    def in_progress_reassignments(self) -> Set[str]:
        return set(self._pending)

    def cancel_reassignments(self, tasks) -> None:
        for t in tasks:
            self._pending.pop(t.proposal.topic_partition, None)

    def set_broker_throttle_rate(self, broker_ids, rate) -> None:
        for b in broker_ids:
            self.broker_throttle_rates[int(b)] = rate

    def clear_broker_throttle_rate(self, broker_ids) -> None:
        for b in broker_ids:
            self.broker_throttle_rates.pop(int(b), None)

    def set_topic_throttled_replicas(self, topic, leader_entries,
                                     follower_entries) -> None:
        self.topic_throttled_replicas[topic] = {
            "leader": tuple(leader_entries),
            "follower": tuple(follower_entries)}

    def clear_topic_throttled_replicas(self, topic) -> None:
        self.topic_throttled_replicas.pop(topic, None)

    def dead_brokers(self) -> Set[int]:
        return {b for b, meta in self._brokers.items() if not meta.alive}

    def describe_logdirs(self) -> Dict[int, Dict[str, bool]]:
        return {b: dict(dirs) for b, dirs in self.logdir_state.items()}

    def alter_replica_logdirs(self, moves) -> None:
        self.logdir_by_tp_broker = getattr(self, "logdir_by_tp_broker", {})
        for m in moves:
            self.logdir_by_tp_broker[
                (f"{m.topic}-{m.partition}", m.broker_id)] = m.to_logdir

    # ---------------------------------------------------------- mechanics
    def _tick(self, tp: str) -> None:
        """Apply a pending movement once its poll latency elapses — and,
        unlike the fake, fold the result back into the metadata the monitor
        reads (replica set, leader, offline flags, generation)."""
        if tp in self._pending:
            n, target = self._pending[tp]
            if n <= 1:
                del self._pending[tp]
                self._apply_reassignment(tp, target)
            else:
                self._pending[tp] = (n - 1, target)
        if tp in self._pending_ple:
            n, new_order = self._pending_ple[tp]
            if n <= 1:
                del self._pending_ple[tp]
                self._apply_leadership(tp, new_order)
            else:
                self._pending_ple[tp] = (n - 1, new_order)

    def _apply_reassignment(self, tp: str,
                            target: Tuple[int, ...]) -> None:
        p = self._parts.get(tp)
        if p is None:
            return
        p.replicas = tuple(target)
        alive = [r for r in target
                 if self._brokers.get(r) is not None
                 and self._brokers[r].alive]
        p.isr = tuple(alive)
        p.offline_replicas = tuple(r for r in target if r not in alive)
        if p.leader not in alive:
            p.leader = alive[0] if alive else -1
        self.moves_applied += 1
        self.move_count_by_tp[tp] = self.move_count_by_tp.get(tp, 0) + 1
        self.generation += 1

    def _apply_leadership(self, tp: str,
                          new_order: Tuple[int, ...]) -> None:
        p = self._parts.get(tp)
        if p is None:
            return
        lead = new_order[0]
        b = self._brokers.get(lead)
        if b is None or not b.alive:
            return               # election against a dead broker: no-op
        p.leader = lead
        # the real adapter writes the FULL proposal order before the
        # election; mirror it exactly when it is a pure reorder
        if set(p.replicas) == set(new_order):
            p.replicas = tuple(new_order)
        self.leadership_moves_applied += 1
        self.generation += 1
