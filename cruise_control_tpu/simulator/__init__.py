"""Time-axis scenario simulator: workloads + fault schedules through the
real control loop, scored against SLOs. See docs/simulation.md."""

from cruise_control_tpu.simulator.clock import VirtualClock
from cruise_control_tpu.simulator.cluster import SimulatedKafkaCluster
from cruise_control_tpu.simulator.faults import (
    DIRECT_KINDS,
    WINDOW_KINDS,
    FaultEvent,
    FaultSchedule,
)
from cruise_control_tpu.simulator.scenario import (
    Scenario,
    Scorecard,
    SLOBudget,
    build_app,
    run_scenario,
)
from cruise_control_tpu.simulator.score import (
    batched_goal_violations,
    snapshot_model,
    violation_ticks,
)
from cruise_control_tpu.simulator.workloads import (
    WORKLOAD_REGISTRY,
    CompositeWorkload,
    DiurnalWorkload,
    FlashCrowdWorkload,
    HotspotDriftWorkload,
    SpikeWorkload,
    TopicGrowthWorkload,
    TraceReplayWorkload,
    WorkloadGenerator,
    record_trace,
)

__all__ = [
    "VirtualClock", "SimulatedKafkaCluster", "FaultEvent", "FaultSchedule",
    "DIRECT_KINDS", "WINDOW_KINDS", "Scenario", "SLOBudget", "Scorecard",
    "build_app", "run_scenario", "snapshot_model", "batched_goal_violations",
    "violation_ticks", "WorkloadGenerator", "DiurnalWorkload",
    "SpikeWorkload", "FlashCrowdWorkload", "TopicGrowthWorkload",
    "HotspotDriftWorkload", "CompositeWorkload", "TraceReplayWorkload",
    "record_trace", "WORKLOAD_REGISTRY",
]
