"""graftwatch cost observatory: compiled-program cost/memory accounting,
live device-buffer census, and the bucket-ladder headroom forecaster.

Three concerns, one ledger:

- **Program capture.** Every cached compiled program on the hot path
  (anneal PT run, chain rescore, what-if grid, fused shed/lead escapes,
  provenance attribution, device proposal decode) reports itself through
  :func:`capture_program` the first time a given argument-shape signature
  executes.  The ledger records argument/output bytes from the concrete
  leaves (``.nbytes`` — no tracing, no transfers, so steady-state stays
  zero-retrace) and, when ``obs.costmodel.deep`` is set, AOT-lowers the
  same signature to pull XLA ``cost_analysis()`` (flops, bytes accessed)
  and ``memory_analysis()`` (argument/output/temp/codegen bytes — the
  compiler's own peak-footprint estimate).  Compile wall time arrives
  per function through the PR 13 observatory's compile listener.
- **Device memory.** :meth:`CostObservatory.live_buffer_census` groups
  ``jax.live_arrays()`` by (shape, dtype); :meth:`memory_snapshot`
  prefers the backend's ``memory_stats()`` (HBM ``bytes_in_use`` /
  ``bytes_limit`` on TPU/GPU) and falls back to the census total plus
  the configured ``obs.costmodel.hbm.limit.bytes`` on backends (CPU)
  that report none.  Sampling happens on the injected clock at a
  bounded cadence (:meth:`maybe_sample`) — never per dispatch.
- **Headroom forecasting.** The bucket ladder (``models/cluster.py``,
  ×1.25 growth) means the *next* retrace after cluster drift allocates a
  predictably larger model.  :func:`model_bytes` prices a bucketed
  geometry analytically from the ``DeviceTopology`` field table, and
  :meth:`headroom_forecast` prices the next rung on every axis against
  ``bytes_limit - bytes_in_use`` — answering "will the next bucket step
  fit?" *before* anything compiles or allocates.  The transition peak is
  conservative: the next rung must fit while the current one is still
  resident, because the old buffers are only freed after the splice.

Everything here is pure observation: with ``obs.costmodel.enable`` off
(the default) the seam is a single attribute check and the optimizer's
program is bit-identical to the historical one.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

LOG = logging.getLogger(__name__)

__all__ = [
    "CostObservatory", "COSTS", "capture_program", "model_bytes",
    "geometry_from_counts", "geometry_from_topology", "next_bucket_step",
]

#: bytes per element for the dtypes the model tensors use
_ITEMSIZE = {"int32": 4, "float32": 4, "bool": 1}

#: analytic footprint table for one bucketed cluster model: every
#: device-resident ``DeviceTopology`` field plus the assignment arrays,
#: as (field, axes, dtype) with axes drawn from the bucketed geometry —
#: B brokers, H hosts, P partitions, R replicas, M max-rf, 4 resources.
#: Mirrors ``ops/aggregates.DeviceTopology`` / ``models/cluster``; the
#: LinkedIn-fixture parity test pins this table against the concrete
#: arrays, so drift between the two fails loudly.
MODEL_FIELD_TABLE: Tuple[Tuple[str, Tuple[str, ...], str], ...] = (
    ("rack_of_broker", ("B",), "int32"),
    ("host_of_broker", ("B",), "int32"),
    ("capacity", ("B", "RES"), "float32"),
    ("host_capacity", ("H", "RES"), "float32"),
    ("broker_alive", ("B",), "bool"),
    ("broker_new", ("B",), "bool"),
    ("broker_demoted", ("B",), "bool"),
    ("partition_of_replica", ("R",), "int32"),
    ("topic_of_partition", ("P",), "int32"),
    ("replicas_of_partition", ("P", "M"), "int32"),
    ("rf_of_partition", ("P",), "int32"),
    ("replica_offline", ("R",), "bool"),
    ("replica_base_load", ("R", "RES"), "float32"),
    ("leader_extra", ("P", "RES"), "float32"),
    ("leader_bytes_in", ("P",), "float32"),
    # bucketing sentinels — None on unpadded models, but production
    # models are always padded, so they price into the footprint
    ("replica_weight", ("R",), "int32"),
    ("partition_weight", ("P",), "int32"),
    ("broker_present", ("B",), "bool"),
    # assignment (broker_of / leader_of)
    ("assignment.broker_of", ("R",), "int32"),
    ("assignment.leader_of", ("P",), "int32"),
)

#: per-chain annealer working state priced per parallel-tempering chain:
#: an assignment copy plus per-broker load aggregates
_CHAIN_FIELD_TABLE: Tuple[Tuple[str, Tuple[str, ...], str], ...] = (
    ("chain.broker_of", ("R",), "int32"),
    ("chain.leader_of", ("P",), "int32"),
    ("chain.broker_load", ("B", "RES"), "float32"),
)


def _axis_size(geom: Dict[str, int], axis: str) -> int:
    if axis == "RES":
        return 4
    key = {"B": "brokers", "H": "hosts", "P": "partitions",
           "R": "replicas", "M": "maxRf"}[axis]
    return int(geom[key])


def model_bytes(geom: Dict[str, int]) -> int:
    """Analytic device footprint (bytes) of one bucketed cluster model.

    ``geom`` holds *bucketed* axis sizes (``brokers``/``hosts``/
    ``partitions``/``replicas``/``maxRf``) plus optional ``chains`` for
    the annealer's per-chain working state."""
    total = 0
    for _name, axes, dtype in MODEL_FIELD_TABLE:
        n = _ITEMSIZE[dtype]
        for axis in axes:
            n *= _axis_size(geom, axis)
        total += n
    chains = int(geom.get("chains", 0))
    if chains:
        per_chain = 0
        for _name, axes, dtype in _CHAIN_FIELD_TABLE:
            n = _ITEMSIZE[dtype]
            for axis in axes:
                n *= _axis_size(geom, axis)
            per_chain += n
        total += chains * per_chain
    return total


def geometry_from_counts(num_brokers: int, num_hosts: int,
                         num_partitions: int, num_replicas: int,
                         max_rf: int, chains: int = 0) -> Dict[str, int]:
    """Bucketed geometry for a *logical* cluster size — applies the same
    n+1 bucket-ladder rule ``pad_topology`` uses, so the result matches
    the shapes the next model build will actually allocate."""
    from cruise_control_tpu.models import cluster as C
    b = C.bucket_size(num_brokers + 1, C.BROKER_BUCKET_FLOOR)
    h = C.bucket_size(num_hosts + 1, C.HOST_BUCKET_FLOOR)
    p = C.bucket_size(num_partitions + 1, C.PARTITION_BUCKET_FLOOR)
    n_pp = p - num_partitions
    r = C.bucket_size(num_replicas + n_pp, C.REPLICA_BUCKET_FLOOR)
    return {"brokers": b, "hosts": h, "partitions": p, "replicas": r,
            "maxRf": int(max_rf), "chains": int(chains)}


def geometry_from_topology(dt, chains: int = 0) -> Dict[str, int]:
    """Bucketed geometry read off an already-padded ``DeviceTopology``
    (array shapes are the buckets — no ladder math needed)."""
    return {
        "brokers": int(dt.rack_of_broker.shape[0]),
        "hosts": int(dt.host_capacity.shape[0]),
        "partitions": int(dt.topic_of_partition.shape[0]),
        "replicas": int(dt.partition_of_replica.shape[0]),
        "maxRf": int(dt.replicas_of_partition.shape[1]),
        "chains": int(chains),
    }


def next_bucket_step(geom: Dict[str, int]) -> Dict[str, int]:
    """The geometry one rung up the ladder on every bucketed axis
    (``ceil(bucket × 1.25)`` — ``BUCKET_GROWTH``); max-rf and chain
    count carry over unchanged."""
    from cruise_control_tpu.models.cluster import BUCKET_GROWTH
    out = dict(geom)
    for key in ("brokers", "hosts", "partitions", "replicas"):
        out[key] = int(math.ceil(int(geom[key]) * BUCKET_GROWTH))
    return out


def _leaf_bytes_and_signature(tree) -> Tuple[int, Tuple]:
    """Sum concrete array bytes and build a hashable shape signature for
    a pytree of call arguments — reads metadata only, never traces."""
    import jax
    total = 0
    sig: List = []
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        nbytes = getattr(leaf, "nbytes", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(int(s) for s in shape), str(dtype)))
            if nbytes is not None:
                total += int(nbytes)
        else:
            sig.append(("scalar", type(leaf).__name__))
    return total, tuple(sig)


class CostObservatory:
    """Process-lifetime ledger of compiled-program cost and device memory.

    Disabled (the default) every entry point returns after one flag
    check; the app enables and configures it from ``obs.costmodel.*``.
    """

    def __init__(self, registry=None,
                 now_ms_fn: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self.enabled = False
        self._deep = False
        self._sample_interval_ms = 10_000.0
        self._hbm_limit_bytes: Optional[int] = None
        self._registry = registry
        self._now_ms = now_ms_fn or (lambda: 0.0)
        self._programs: Dict[str, List[dict]] = {}
        self._signatures: set = set()
        self._compiles: Dict[str, dict] = {}
        self._last_census: Optional[dict] = None
        self._last_memory: Optional[dict] = None
        self._last_forecast: Optional[dict] = None
        self._last_sample_ms: Optional[float] = None
        self._samples = 0
        self._capture_errors = 0

    # ------------------------------------------------------- lifecycle
    def configure(self, *, enabled: bool, deep: bool = False,
                  sample_interval_ms: float = 10_000.0,
                  hbm_limit_bytes: Optional[int] = None,
                  registry=None,
                  now_ms_fn: Optional[Callable[[], float]] = None) -> None:
        # configuration happens at app startup, before the control loop
        # spawns — plain assignments, no lock (the lock guards only the
        # mutable ledger/sample state below)
        self.enabled = bool(enabled)
        self._deep = bool(deep)
        self._sample_interval_ms = float(sample_interval_ms)
        self._hbm_limit_bytes = (
            None if hbm_limit_bytes is None else int(hbm_limit_bytes))
        if registry is not None:
            self._registry = registry
        if now_ms_fn is not None:
            self._now_ms = now_ms_fn
        if self.enabled and self._registry is not None:
            self._register_gauges()

    def reset(self) -> None:
        """Drop all captured state (tests / standby takeover)."""
        with self._lock:
            self._programs.clear()
            self._signatures.clear()
            self._compiles.clear()
            self._last_census = None
            self._last_memory = None
            self._last_forecast = None
            self._last_sample_ms = None
            self._samples = 0
            self._capture_errors = 0

    def _register_gauges(self) -> None:
        reg = self._registry

        def _val(key):
            def read():
                with self._lock:
                    mem = self._last_memory or {}
                    fc = self._last_forecast or {}
                    vals = {
                        "inUse": mem.get("bytesInUse"),
                        "headroom": fc.get("headroomBytes"),
                        "nextStep": fc.get("nextModelBytes"),
                        "fits": fc.get("fits"),
                    }
                v = vals.get(key)
                if v is None:
                    return None
                return float(v)
            return read

        reg.gauge("costmodel-device-bytes-in-use", _val("inUse"))
        reg.gauge("costmodel-headroom-bytes", _val("headroom"))
        reg.gauge("costmodel-next-step-bytes", _val("nextStep"))
        reg.gauge("costmodel-next-step-fits", _val("fits"))

    # --------------------------------------------------------- capture
    def capture(self, name: str, fn: Optional[Callable], args: tuple,
                out: Any, statics: Optional[dict] = None) -> bool:
        """Record one compiled-program variant; memoized per (name,
        argument-shape signature) so steady-state is a set lookup."""
        if not self.enabled:
            return False
        arg_bytes, sig = _leaf_bytes_and_signature(args)
        # array-valued kwargs (dynamic device scalars like movable
        # counts) key by shape, not value — a changing count must not
        # mint a new ledger variant every tick
        static_sig = tuple(sorted(
            (k, str(tuple(v.shape)) + str(v.dtype))
            if hasattr(v, "shape") and hasattr(v, "dtype") else (k, str(v))
            for k, v in (statics or {}).items()))
        key = (name, sig, static_sig)
        with self._lock:
            if key in self._signatures:
                return False
            self._signatures.add(key)
        out_bytes, _ = _leaf_bytes_and_signature(out)
        entry = {
            "signature": [list(map(str, s)) for s in sig[:16]],
            "argLeaves": len(sig),
            "argBytes": int(arg_bytes),
            "outBytes": int(out_bytes),
        }
        if static_sig:
            entry["statics"] = {k: v for k, v in static_sig}
        if self._deep and fn is not None:
            entry.update(self._deep_price(fn, args, statics))
        with self._lock:
            self._programs.setdefault(name, []).append(entry)
        if self._registry is not None:
            self._registry.counter("costmodel-programs-captured",
                                   labels={"program": name})
        return True

    def _deep_price(self, fn: Callable, args: tuple,
                    statics: Optional[dict]) -> dict:
        """AOT-lower and compile the captured signature to pull XLA's
        own cost and memory analyses.  A second compile of an
        already-cached program — warmup-only by construction (capture is
        memoized per signature), so the steady-state retrace budget is
        untouched."""
        try:
            lowered = fn.lower(*args, **(statics or {}))
            compiled = lowered.compile()
            out: dict = {}
            cost = compiled.cost_analysis()
            if cost:
                first = cost[0] if isinstance(cost, (list, tuple)) else cost
                if "flops" in first:
                    out["flops"] = float(first["flops"])
                if "bytes accessed" in first:
                    out["bytesAccessed"] = float(first["bytes accessed"])
            mem = compiled.memory_analysis()
            if mem is not None:
                out["compiledArgBytes"] = int(mem.argument_size_in_bytes)
                out["compiledOutBytes"] = int(mem.output_size_in_bytes)
                out["compiledTempBytes"] = int(mem.temp_size_in_bytes)
                out["compiledCodeBytes"] = int(
                    mem.generated_code_size_in_bytes)
            return out
        except Exception as exc:  # graftlint: disable=G009 — deep pricing
            # is best-effort diagnostics; a backend that can't AOT-price a
            # program must not break the capture path
            with self._lock:
                self._capture_errors += 1
            return {"deepError": f"{type(exc).__name__}: {exc}"}

    def on_compile(self, fn: str, seconds: float) -> None:
        """Observatory compile-listener sink: per-function compile wall
        tallies folded into the ledger (the PR 13 hook)."""
        if not self.enabled:
            return
        with self._lock:
            row = self._compiles.setdefault(fn, {"count": 0, "seconds": 0.0})
            row["count"] += 1
            row["seconds"] += float(seconds)

    # --------------------------------------------------- device memory
    def live_buffer_census(self, top: int = 12) -> dict:
        """Live device buffers grouped by (shape, dtype), largest first."""
        import jax
        groups: Dict[Tuple, List[int]] = {}
        total = 0
        count = 0
        for arr in jax.live_arrays():
            try:
                key = (tuple(int(s) for s in arr.shape), str(arr.dtype))
                nbytes = int(arr.nbytes)
            except Exception:  # graftlint: disable=G009 — a deleted/donated
                # buffer mid-iteration must not break the census
                continue
            row = groups.setdefault(key, [0, 0])
            row[0] += 1
            row[1] += nbytes
            total += nbytes
            count += 1
        rows = sorted(groups.items(), key=lambda kv: (-kv[1][1], kv[0]))
        return {
            "totalArrays": count,
            "totalBytes": total,
            "groups": [
                {"shape": list(shape), "dtype": dtype,
                 "count": c, "bytes": b}
                for (shape, dtype), (c, b) in rows[:top]],
        }

    def memory_snapshot(self) -> dict:
        """Backend ``memory_stats()`` when the platform reports them
        (TPU/GPU HBM), else the live-array census total with the
        configured limit — same shape either way."""
        import jax
        per_device = []
        in_use = limit = 0
        have_backend = False
        for dev in jax.local_devices():
            stats = None
            try:
                stats = dev.memory_stats()
            except Exception:  # graftlint: disable=G009 — optional API;
                # platforms without allocator stats fall through to census
                stats = None
            if stats:
                have_backend = True
                b = int(stats.get("bytes_in_use", 0))
                lim = int(stats.get("bytes_limit", 0))
                in_use += b
                limit += lim
                per_device.append({"device": str(dev), "bytesInUse": b,
                                   "bytesLimit": lim or None})
        if have_backend:
            snap = {"source": "backend", "bytesInUse": in_use,
                    "bytesLimit": limit or self._hbm_limit_bytes,
                    "perDevice": per_device}
        else:
            census = self.live_buffer_census(top=0)
            snap = {"source": "census",
                    "bytesInUse": census["totalBytes"],
                    "bytesLimit": self._hbm_limit_bytes,
                    "perDevice": []}
        with self._lock:
            self._last_memory = snap
        return snap

    def maybe_sample(self, now_ms: Optional[float] = None) -> bool:
        """Bounded-cadence sampling hook (the app calls this per tick on
        the injected clock); returns True when a sample was taken."""
        if not self.enabled:
            return False
        now = self._now_ms() if now_ms is None else float(now_ms)
        with self._lock:
            due = (self._last_sample_ms is None or
                   now - self._last_sample_ms >= self._sample_interval_ms)
            if not due:
                return False
            self._last_sample_ms = now
            self._samples += 1
        census = self.live_buffer_census()
        with self._lock:
            self._last_census = census
        self.memory_snapshot()
        return True

    # ------------------------------------------------------ forecasting
    def headroom_forecast(self, geom: Optional[Dict[str, int]] = None
                          ) -> dict:
        """Price the next bucket-ladder rung against remaining memory.

        ``fits`` is the production question: can the next rung's full
        model materialize while the current one is still resident (the
        realistic transition peak — old buffers free only after the
        splice)?  ``None`` when no byte limit is known."""
        snap = self.memory_snapshot()
        fc: dict = {
            "bytesInUse": snap["bytesInUse"],
            "bytesLimit": snap["bytesLimit"],
            "source": snap["source"],
        }
        if geom is not None:
            nxt = next_bucket_step(geom)
            cur_b = model_bytes(geom)
            nxt_b = model_bytes(nxt)
            fc.update({
                "geometry": dict(geom), "nextGeometry": nxt,
                "currentModelBytes": cur_b, "nextModelBytes": nxt_b,
                "deltaBytes": nxt_b - cur_b,
            })
            if snap["bytesLimit"]:
                headroom = int(snap["bytesLimit"]) - int(snap["bytesInUse"])
                fc["headroomBytes"] = headroom
                fc["fits"] = bool(nxt_b <= headroom)
            else:
                fc["headroomBytes"] = None
                fc["fits"] = None
        with self._lock:
            self._last_forecast = fc
        return fc

    # ---------------------------------------------------------- reading
    def snapshot(self) -> dict:
        """JSON view for ``/state`` and ``GET /observatory``."""
        with self._lock:
            programs = {
                name: [dict(e) for e in entries]
                for name, entries in sorted(self._programs.items())}
            compiles = {
                fn: {"count": row["count"],
                     "seconds": round(row["seconds"], 3)}
                for fn, row in sorted(self._compiles.items())}
            return {
                "enabled": self.enabled,
                "deep": self._deep,
                "programs": programs,
                "programVariants": sum(
                    len(v) for v in programs.values()),
                "compiles": compiles,
                "census": self._last_census,
                "memory": self._last_memory,
                "forecast": self._last_forecast,
                "samples": self._samples,
                "captureErrors": self._capture_errors,
            }


#: process-wide cost observatory (configured by the app from
#: ``obs.costmodel.*``; disabled it never touches the hot path)
COSTS = CostObservatory()


def capture_program(name: str, fn: Optional[Callable] = None,
                    args: tuple = (), out: Any = None,
                    statics: Optional[dict] = None) -> None:
    """Hot-path seam: record a compiled-program execution in the cost
    ledger.  One flag check when disabled; memoized per argument-shape
    signature when enabled, so steady-state cost is a set lookup."""
    if not COSTS.enabled:
        return
    try:
        COSTS.capture(name, fn, args, out, statics)
    except Exception:  # graftlint: disable=G009 — observation must never
        # break the optimizer's hot path
        LOG.debug("costmodel capture failed for %s", name, exc_info=True)
