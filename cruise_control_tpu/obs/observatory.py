"""Compile/retrace observatory: always-on jit compile accounting.

``common/sentinels.py`` counts retraces inside a test-scoped context
manager; that catches regressions in CI but says nothing about a
production control loop that starts retracing at 3am because a topic's
partition count drifted past a bucket boundary.  The observatory is the
production promotion: one log handler installed for the process lifetime
that attributes every jit trace / XLA compile to the function it came
from, accumulates compile wall-time, and — once the loop declares itself
*steady* (first successful proposal computed) — counts further traces as
steady-state retraces.  A steady-state retrace in prod is the PR 8
silent-degradation class: each one is a multi-second stall on the tick
path, and enough of them turn a 2-second anneal into a 45-minute greedy
fallback.  The counters surface through the metrics registry (Prometheus
``/metrics``) and ``GET /observatory``.

The observatory also owns two host-side tallies the log can't see:
device-dispatch counts per callsite (how often each jitted entry point
actually runs) and transfer-guard violations per callsite (an implicit
host↔device transfer attempted inside a ``no_implicit_transfers`` scope
— surfaced by the optimizer's engine-fallback handler).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from cruise_control_tpu.common.metrics import REGISTRY
from cruise_control_tpu.common.sentinels import parse_compile_log


class _ObservatoryHandler(logging.Handler):
    """Routes jax compile-log records into the owning observatory."""

    def __init__(self, obs: "Observatory") -> None:
        super().__init__(level=logging.DEBUG)
        self._obs = obs

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._obs._on_message(record.getMessage())
        except Exception:  # graftlint: disable=G009 — surfaced as the
            # handlerErrors counter in snapshot(); a broken metric must
            # never break jax logging
            self._obs._emit_errors += 1


class _CompileLogSpamFilter(logging.Filter):
    """Drops ``jax_log_compiles`` chatter from jax's own stderr handler
    while the observatory is installed — the observatory consumes those
    records; one WARNING line per trace/compile would otherwise flood the
    log for the process lifetime. Non-compile jax messages pass through."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            msg = record.getMessage()
            if parse_compile_log(msg) is not None:
                return False
            # intermediate lowering stage the observatory doesn't count,
            # but still jax_log_compiles chatter
            return "jaxpr to MLIR module conversion" not in msg
        except Exception:  # graftlint: disable=G009 — a filter must never
            # break logging; failing open just re-admits one log line
            return True


class Observatory:
    """Per-function jit compile accounting for the process lifetime."""

    def __init__(self, registry=REGISTRY,
                 now_fn: Callable[[], float] = time.monotonic):
        self._registry = registry
        self._now = now_fn
        self._lock = threading.Lock()
        self._handler: Optional[_ObservatoryHandler] = None
        self._prev_log_compiles: Optional[bool] = None
        self._prev_propagate = True
        self._filtered_handlers: list = []
        self._installed_at_s: Optional[float] = None
        self._emit_errors = 0
        # per-function accounting (fn name -> count / seconds)
        self._traces: Dict[str, int] = {}
        self._compiles: Dict[str, int] = {}
        self._compile_s: Dict[str, float] = {}
        self._steady_retraces: Dict[str, int] = {}
        self._steady = False
        # host-side tallies (callsite label -> count)
        self._dispatches: Dict[str, int] = {}
        self._transfer_violations: Dict[str, int] = {}
        # compile listeners: (fn, seconds) sinks fed on compile_done —
        # the costmodel ledger subscribes here
        self._compile_listeners: list = []

    # -------------------------------------------------------- lifecycle
    @property
    def installed(self) -> bool:
        with self._lock:
            return self._handler is not None

    def install(self) -> None:
        """Attach the compile-log handler (idempotent, process-wide).

        ``jax_log_compiles`` emits at WARNING, so an always-on observatory
        would spam one stderr line per trace/compile for the process
        lifetime: jax attaches its own ``StreamHandler`` directly to the
        ``jax`` logger, which child-logger records reach regardless of
        ``propagate``.  A :class:`_CompileLogSpamFilter` is therefore
        attached to every handler already present on ``jax`` (never to the
        observatory's own), and propagation to any root sinks is stopped;
        genuine jax warnings still flow everywhere.  Both are undone by
        :meth:`uninstall`.
        """
        import jax
        jax_logger = logging.getLogger("jax")
        with self._lock:
            if self._handler is not None:
                return
            handler = self._handler = _ObservatoryHandler(self)
            self._prev_log_compiles = bool(jax.config.jax_log_compiles)
            self._prev_propagate = jax_logger.propagate
            self._installed_at_s = self._now()
            spam_filter = _CompileLogSpamFilter()
            filtered = self._filtered_handlers = [
                (h, spam_filter) for h in list(jax_logger.handlers)
                if not isinstance(h, _ObservatoryHandler)]
        jax.config.update("jax_log_compiles", True)
        for h, f in filtered:
            h.addFilter(f)
        jax_logger.addHandler(handler)
        jax_logger.propagate = False

    def uninstall(self) -> None:
        import jax
        with self._lock:
            handler, self._handler = self._handler, None
            prev, self._prev_log_compiles = self._prev_log_compiles, None
            prev_prop = getattr(self, "_prev_propagate", True)
            filtered = getattr(self, "_filtered_handlers", [])
            self._filtered_handlers = []
        if handler is not None:
            for h, f in filtered:
                h.removeFilter(f)
            logging.getLogger("jax").removeHandler(handler)
            logging.getLogger("jax").propagate = prev_prop
            jax.config.update("jax_log_compiles", bool(prev))

    # ------------------------------------------------------- accounting
    def _on_message(self, msg: str) -> None:
        parsed = parse_compile_log(msg)
        if parsed is None:
            return
        kind, fn, seconds = parsed
        with self._lock:
            if kind == "trace":
                self._traces[fn] = self._traces.get(fn, 0) + 1
                if self._steady:
                    self._steady_retraces[fn] = \
                        self._steady_retraces.get(fn, 0) + 1
            elif kind == "compile":
                self._compiles[fn] = self._compiles.get(fn, 0) + 1
            elif kind == "compile_done" and seconds is not None:
                self._compile_s[fn] = self._compile_s.get(fn, 0.0) + seconds
            steady = self._steady
        if self._registry is not None:
            if kind == "trace":
                self._registry.counter("observatory-jit-traces",
                                       labels={"function": fn})
                if steady:
                    self._registry.counter(
                        "observatory-steady-state-retraces",
                        labels={"function": fn})
            elif kind == "compile":
                self._registry.counter("observatory-xla-compiles",
                                       labels={"function": fn})
            elif kind == "compile_done" and seconds is not None:
                self._registry.timer("observatory-compile-timer",
                                     labels={"function": fn}).update(seconds)
                # labeled cumulative wall-time series: the histogram above
                # buckets durations per function, this answers "which
                # function owns the compile budget" in one Prometheus query
                self._registry.counter("observatory-compile-wall-seconds",
                                       inc=float(seconds),
                                       labels={"function": fn})
        if kind == "compile_done" and seconds is not None:
            with self._lock:
                listeners = list(self._compile_listeners)
            for cb in listeners:
                try:
                    cb(fn, seconds)
                except Exception:  # graftlint: disable=G009 — a listener
                    # must never break the log-handler path
                    with self._lock:
                        self._emit_errors += 1

    def add_compile_listener(self, cb) -> None:
        """Subscribe a ``(function_name, seconds)`` sink to compile
        completions (idempotent per callable)."""
        with self._lock:
            if cb not in self._compile_listeners:
                self._compile_listeners.append(cb)

    def remove_compile_listener(self, cb) -> None:
        with self._lock:
            if cb in self._compile_listeners:
                self._compile_listeners.remove(cb)

    def mark_steady(self) -> None:
        """Declare warmup over: traces from now on are steady-state
        retraces (the app calls this after its first full proposal)."""
        with self._lock:
            self._steady = True

    def mark_warming(self) -> None:
        """Re-enter warmup (topology change, standby takeover): expected
        recompiles stop counting against the steady-state budget."""
        with self._lock:
            self._steady = False

    def record_dispatch(self, site: str) -> None:
        """Count one device dispatch of a jitted entry point."""
        with self._lock:
            self._dispatches[site] = self._dispatches.get(site, 0) + 1
        if self._registry is not None:
            self._registry.counter("observatory-device-dispatches",
                                   labels={"site": site})

    def record_transfer_guard_violation(self, site: str) -> None:
        """Count an implicit-transfer violation surfaced at ``site``."""
        with self._lock:
            self._transfer_violations[site] = \
                self._transfer_violations.get(site, 0) + 1
        if self._registry is not None:
            self._registry.counter("observatory-transfer-guard-violations",
                                   labels={"site": site})

    # ---------------------------------------------------------- reading
    def steady_retrace_count(self) -> int:
        with self._lock:
            return sum(self._steady_retraces.values())

    def snapshot(self) -> dict:
        """JSON view for ``GET /observatory`` (deterministic ordering)."""
        with self._lock:
            fns = sorted(set(self._traces) | set(self._compiles)
                         | set(self._compile_s) | set(self._steady_retraces))
            per_fn = {fn: {
                "traces": self._traces.get(fn, 0),
                "compiles": self._compiles.get(fn, 0),
                "compileSeconds": round(self._compile_s.get(fn, 0.0), 3),
                "steadyStateRetraces": self._steady_retraces.get(fn, 0),
            } for fn in fns}
            return {
                "installed": self._handler is not None,
                "steady": self._steady,
                "totalTraces": sum(self._traces.values()),
                "totalCompiles": sum(self._compiles.values()),
                "totalCompileSeconds": round(
                    sum(self._compile_s.values()), 3),
                "steadyStateRetraces": sum(self._steady_retraces.values()),
                "perFunction": per_fn,
                "deviceDispatches": dict(sorted(self._dispatches.items())),
                "transferGuardViolations": dict(
                    sorted(self._transfer_violations.items())),
                "handlerErrors": self._emit_errors,
            }


#: process-wide observatory (installed by the app when
#: ``obs.observatory.enable`` is true; host-side tallies always count)
OBSERVATORY = Observatory()
