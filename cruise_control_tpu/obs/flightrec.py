"""Tick flight recorder: a bounded, deterministic decision audit log.

Span tracing (obs/tracing.py) answers *how long* each control-loop stage
took; the flight recorder answers *what the loop decided and why*: one
record per proposal tick (inputs digest, dirty-mask summary, per-goal
verdicts before/after, engine / heal / decode path, fallback reason, top-k
attributed moves) plus one record per anomaly-detector decision (fired /
suppressed / self-heal routed, with the triggering context).

Determinism is the contract that makes the log an *audit* log: timestamps
come from the injected clock (the simulator's virtual clock in scenarios),
sequence numbers are process-local counters, and every recorded value is a
deterministic function of the scenario seed — so two same-seed runs export
byte-identical JSONL (the PR 10 journal discipline), and
``tools/replay_tick.py`` can re-run any recorded tick from its digest-pinned
inputs and assert the proposal reproduces bit-identically.

The ring is bounded (``obs.flightrec.ticks`` records); export is canonical
JSONL — ``json.dumps(record, sort_keys=True, separators=(",", ":"))`` per
line — served by ``GET /flightrecorder`` and attached (as a digest + record
count) to the simulator scorecard.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Callable, Dict, List, Optional


def canonical_record(record: dict) -> str:
    """The one serialization every consumer (export, digest, replay
    comparison) uses — key-sorted, no whitespace."""
    return json.dumps(record, sort_keys=True, separators=(",", ":"))


def assignment_digest(broker_of, leader_of) -> str:
    """sha256 over the raw placement + leadership arrays — the bit-identity
    pin for deterministic replay (two proposals match iff their digests
    match)."""
    import numpy as np
    h = hashlib.sha256()
    for arr in (broker_of, leader_of):
        a = np.ascontiguousarray(np.asarray(arr, dtype=np.int64))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class FlightRecorder:
    """Bounded ring of decision records on an injected clock.

    ``record()`` stamps ``seq`` (monotonic, never reused even after ring
    drops) and ``tsMs`` (from ``now_fn``) onto a copy of the payload.
    A disabled recorder records nothing and exports an empty log — zero
    behavior change, like the disabled tracer."""

    def __init__(self, now_fn: Callable[[], float] = time.time,
                 capacity: int = 256, enabled: bool = True,
                 top_moves: int = 8):
        self._now = now_fn
        self._capacity = max(int(capacity), 1)
        self.enabled = bool(enabled)
        self.top_moves = int(top_moves)
        self._lock = threading.Lock()
        self._records: List[dict] = []
        self._seq = 0
        self._dropped = 0
        #: static context merged into every record (e.g. the simulator sets
        #: ``{"source": "scenario:<name>", "seed": <seed>}`` so replay knows
        #: how to rebuild the inputs); None values are omitted
        self._context: Dict[str, object] = {}

    # ------------------------------------------------------------- recording
    def set_context(self, **context) -> None:
        with self._lock:
            self._context = {k: v for k, v in context.items() if v is not None}

    def record(self, kind: str, payload: dict) -> Optional[dict]:
        """Append one record; returns it (with seq/ts stamped), or None when
        disabled. ``payload`` must be JSON-serializable and deterministic —
        no wall-clock durations, no host-dependent values."""
        if not self.enabled:
            return None
        with self._lock:
            rec = {"seq": self._seq,
                   "tsMs": int(round(self._now() * 1000.0)),
                   "kind": kind, **self._context, **payload}
            self._seq += 1
            self._records.append(rec)
            if len(self._records) > self._capacity:
                drop = len(self._records) - self._capacity
                del self._records[:drop]
                self._dropped += drop
            return rec

    # --------------------------------------------------------------- reading
    def records(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            recs = list(self._records)
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return recs

    def export_jsonl(self) -> str:
        """Canonical JSONL of the ring, oldest first. Byte-identical across
        same-seed runs on an injected clock — the determinism contract
        tests/test_provenance.py pins across two processes."""
        recs = self.records()
        if not recs:
            return ""
        return "\n".join(canonical_record(r) for r in recs) + "\n"

    def export_digest(self) -> str:
        """sha256 of the canonical JSONL export (scorecard attachment)."""
        return hashlib.sha256(self.export_jsonl().encode()).hexdigest()

    def summary(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "capacity": self._capacity,
                    "records": len(self._records), "dropped": self._dropped,
                    "lastSeq": self._seq - 1}

    def clear(self) -> None:
        """Drop buffered records (seq keeps counting — cleared history must
        not let two different ticks share a sequence number)."""
        with self._lock:
            self._records.clear()
            self._dropped = 0


def load_jsonl(text: str) -> List[dict]:
    """Parse an exported flight-recorder log back into records."""
    return [json.loads(line) for line in text.splitlines() if line.strip()]


#: shared disabled recorder (the NOOP_TRACER idiom)
NOOP_FLIGHT_RECORDER = FlightRecorder(enabled=False)
