"""graftscope: control-loop tracing, a compile observatory, and decision
provenance (per-move goal attribution + the tick flight recorder).

Always-available primitives (docs/observability.md):

- :mod:`~cruise_control_tpu.obs.tracing` — lightweight spans over an
  injected clock (wall or the simulator's virtual clock), a bounded ring
  buffer of completed spans, and Chrome-trace/Perfetto JSON export.  A
  disabled tracer is a shared no-op: zero records, zero behavior change
  (the bit-parity contract the fixture tests pin).
- :mod:`~cruise_control_tpu.obs.observatory` — the production promotion of
  the test-only retrace sentinels (common/sentinels.py): per-callsite jit
  trace/compile counts and compile wall-time, steady-state retrace
  accounting, transfer-guard violation and device-dispatch counters,
  surfaced through the metrics registry and ``GET /observatory``.
- :mod:`~cruise_control_tpu.obs.costmodel` — graftwatch's cost
  observatory: per-compiled-program cost/memory ledger, live
  device-buffer census, backend memory-stats sampling, and the
  bucket-ladder headroom forecaster (``GET /headroom``).
- :mod:`~cruise_control_tpu.obs.healthwatch` — graftwatch's health
  watch: a device ring of per-tick health vectors with vmapped
  fast/slow SRE burn-rate alerting (``GET /alerts``), decisions audited
  to the flight recorder and fired through the anomaly notifier.
"""

from cruise_control_tpu.obs.costmodel import COSTS, CostObservatory
from cruise_control_tpu.obs.flightrec import (NOOP_FLIGHT_RECORDER,
                                              FlightRecorder)
from cruise_control_tpu.obs.observatory import OBSERVATORY, Observatory
from cruise_control_tpu.obs.tracing import (NOOP_SPAN, NOOP_TRACER, Span,
                                            Tracer)

# obs.provenance is imported lazily by its callers (the optimizer's gated
# attribution block): it pulls in the analyzer/goal kernels, which this
# package must not load eagerly.  obs.healthwatch is likewise lazy — it
# pulls ops/health (jax) and the detector's anomaly vocabulary.

__all__ = ["Tracer", "Span", "NOOP_SPAN", "NOOP_TRACER", "Observatory",
           "OBSERVATORY", "FlightRecorder", "NOOP_FLIGHT_RECORDER",
           "CostObservatory", "COSTS"]
