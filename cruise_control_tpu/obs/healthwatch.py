"""graftwatch health watch: per-tick health vectors, SRE burn-rate
alerting, and the deterministic alert timeline.

Every control-loop tick the app reports one health vector (tick latency
vs SLO, monitor readiness, engine/fallback flags, heal wall, cache hit
ratio, watchdog restarts, replication lag, goal verdicts — the column
layout is ``ops/health.HEALTH_FIELDS``).  The vectors land in a
device-resident ring and an :class:`AlertRule` registry evaluates every
rule's fast/slow burn windows in one compiled vmapped program
(``ops/health.burn_rates``) — multiwindow multi-burn-rate alerting in
the SRE-workbook sense, with config-driven error budgets.

Alert lifecycle (fire → suppress-while-active → resolve) runs on the
host over the kernel's firing flags.  Every decision:

- lands in the PR 14 flight recorder through the same ``decision_sink``
  seam the anomaly detector audits through,
- fires through the existing notifier seam
  (``detector/anomalies.SelfHealingNotifier.alert``) as a
  :class:`~cruise_control_tpu.detector.anomalies.SLOBurnAnomaly`,
- appends to a canonical in-memory timeline (``export_timeline``) —
  everything is driven by the injected clock, so same-seed simulator
  scenarios produce byte-identical alert timelines.

Disabled (the default) the watch is never constructed and the tick path
is bit-identical to the historical program.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from cruise_control_tpu.ops import health as H

LOG = logging.getLogger(__name__)

__all__ = ["AlertRule", "HealthWatch", "default_rules"]

#: timeline safety cap — a runaway alert storm must not grow host memory
#: without bound; drops are counted, never silent
_TIMELINE_CAP = 65_536


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One burn-rate alert: fires when the bad-tick fraction of
    ``signal`` (> ``threshold``) burns the error budget faster than
    ``fast_burn``× over the fast window AND ``slow_burn``× over the
    slow window."""
    name: str
    signal: str                 # column in ops/health.HEALTH_FIELDS
    threshold: float = 0.5      # signal > threshold counts as a bad tick
    budget: float = 0.02        # allowed bad-tick fraction (error budget)
    fast_window_ticks: int = 8
    slow_window_ticks: int = 32
    fast_burn: float = 10.0
    slow_burn: float = 2.5

    def table_row(self):
        return (H.FIELD_INDEX[self.signal], self.threshold, self.budget,
                self.fast_window_ticks, self.slow_window_ticks,
                self.fast_burn, self.slow_burn)

    def describe(self) -> dict:
        return {
            "name": self.name, "signal": self.signal,
            "threshold": self.threshold, "budget": self.budget,
            "fastWindowTicks": self.fast_window_ticks,
            "slowWindowTicks": self.slow_window_ticks,
            "fastBurn": self.fast_burn, "slowBurn": self.slow_burn,
        }


def default_rules(budget: float, fast_w: int, slow_w: int,
                  fast_burn: float, slow_burn: float) -> List[AlertRule]:
    """The stock rule set: tick degradation, hard-goal violations and
    engine fallbacks, all on the shared windows/budget."""
    mk = lambda name, signal: AlertRule(  # noqa: E731
        name=name, signal=signal, budget=budget,
        fast_window_ticks=fast_w, slow_window_ticks=slow_w,
        fast_burn=fast_burn, slow_burn=slow_burn)
    return [
        mk("tick-slo-burn", "degraded"),
        mk("hard-violation-burn", "hardViolations"),
        mk("fallback-burn", "fallback"),
    ]


def rules_from_config(config) -> List[AlertRule]:
    """Stock rules on the configured windows/budget, plus/overridden by
    the ``healthwatch.rules`` JSON list (entries are keyword dicts in
    ``AlertRule.describe`` key style; same-name entries replace)."""
    budget = float(config.get("healthwatch.error.budget"))
    fast_w = int(config.get("healthwatch.fast.window.ticks"))
    slow_w = int(config.get("healthwatch.slow.window.ticks"))
    fast_b = float(config.get("healthwatch.fast.burn"))
    slow_b = float(config.get("healthwatch.slow.burn"))
    rules = {r.name: r for r in default_rules(
        budget, fast_w, slow_w, fast_b, slow_b)}
    raw = config.get("healthwatch.rules")
    if raw:
        for entry in json.loads(raw):
            rule = AlertRule(
                name=str(entry["name"]), signal=str(entry["signal"]),
                threshold=float(entry.get("threshold", 0.5)),
                budget=float(entry.get("budget", budget)),
                fast_window_ticks=int(
                    entry.get("fastWindowTicks", fast_w)),
                slow_window_ticks=int(
                    entry.get("slowWindowTicks", slow_w)),
                fast_burn=float(entry.get("fastBurn", fast_b)),
                slow_burn=float(entry.get("slowBurn", slow_b)))
            if rule.signal not in H.FIELD_INDEX:
                raise ValueError(
                    f"healthwatch.rules: unknown signal {rule.signal!r}; "
                    f"known: {', '.join(H.HEALTH_FIELDS)}")
            rules[rule.name] = rule
    return [rules[name] for name in sorted(rules)]


class HealthWatch:
    """Device health ring + alert lifecycle for one app instance."""

    def __init__(self, rules: List[AlertRule], *, ring_ticks: int = 512,
                 tick_slo_ms: float = 30_000.0,
                 now_ms_fn: Optional[Callable[[], float]] = None,
                 registry=None,
                 decision_sink: Optional[Callable[[dict], None]] = None,
                 notifier=None):
        if not rules:
            raise ValueError("HealthWatch needs at least one AlertRule")
        self._rules = list(rules)
        self._ring_ticks = int(ring_ticks)
        self.tick_slo_ms = float(tick_slo_ms)
        self._now_ms = now_ms_fn or (lambda: 0.0)
        self._registry = registry
        self._decision_sink = decision_sink or (lambda payload: None)
        self._notifier = notifier
        self._lock = threading.Lock()
        self._tables = H.rule_tables(r.table_row() for r in self._rules)
        self._ring, self._count = H.new_ring(self._ring_ticks)
        self._active: Dict[str, int] = {}      # rule -> firing-since tick
        self._fired = 0
        self._suppressed = 0
        self._resolved = 0
        self._first_firing_tick: Optional[int] = None
        self._timeline: List[dict] = []
        self._timeline_dropped = 0
        self._last_burns: Dict[str, dict] = {}
        if registry is not None:
            registry.gauge("healthwatch-active-alerts",
                           lambda: float(len(self._active)))

    # ------------------------------------------------------------ clear
    def reset(self) -> None:
        """Fresh ring and empty timeline (simulator measurement
        baseline — mirrors ``tracer.clear()`` / ``flightrec.clear()``)."""
        with self._lock:
            self._ring, self._count = H.new_ring(self._ring_ticks)
            self._active.clear()
            self._fired = self._suppressed = self._resolved = 0
            self._first_firing_tick = None
            self._timeline.clear()
            self._timeline_dropped = 0
            self._last_burns.clear()

    # ---------------------------------------------------------- observe
    def observe(self, sample: Dict[str, float]) -> List[dict]:
        """Fold one tick's health sample into the ring and run every
        alert rule; returns this tick's alert decisions (possibly [])."""
        vec = np.zeros(len(H.HEALTH_FIELDS), np.float32)
        for name, value in sample.items():
            vec[H.FIELD_INDEX[name]] = np.float32(value)
        latency = float(vec[H.FIELD_INDEX["latencyMs"]])
        vec[H.FIELD_INDEX["latencyBreach"]] = np.float32(
            1.0 if latency > self.tick_slo_ms else 0.0)
        vec[H.FIELD_INDEX["degraded"]] = max(
            vec[H.FIELD_INDEX["latencyBreach"]],
            vec[H.FIELD_INDEX["notReady"]],
            vec[H.FIELD_INDEX["failed"]],
            vec[H.FIELD_INDEX["fallback"]])
        with self._lock:
            tick = int(np.asarray(self._count))
            self._ring, self._count = H.push(self._ring, self._count, vec)
            burn_fast, burn_slow, _ff, _fs, firing = (
                np.asarray(a) for a in H.burn_rates(
                    self._ring, self._count, *self._tables))
            decisions = self._transition(tick, burn_fast, burn_slow, firing)
        for payload in decisions:
            self._emit(payload)
        return decisions

    def _transition(self, tick: int, burn_fast, burn_slow,
                    firing) -> List[dict]:
        ts_ms = int(self._now_ms())
        decisions: List[dict] = []
        for i, rule in enumerate(self._rules):
            bf = round(float(burn_fast[i]), 6)
            bs = round(float(burn_slow[i]), 6)
            self._last_burns[rule.name] = {"fast": bf, "slow": bs}
            is_firing = bool(firing[i])
            was_active = rule.name in self._active
            if is_firing and not was_active:
                decision = "fired"
                self._active[rule.name] = tick
                self._fired += 1
                if self._first_firing_tick is None:
                    self._first_firing_tick = tick
            elif is_firing and was_active:
                decision = "suppressed"
                self._suppressed += 1
            elif was_active:
                decision = "resolved"
                del self._active[rule.name]
                self._resolved += 1
            else:
                continue
            decisions.append({
                "tick": tick, "rule": rule.name, "signal": rule.signal,
                "decision": decision, "burnFast": bf, "burnSlow": bs,
                "tsMs": ts_ms,
            })
        for payload in decisions:
            if len(self._timeline) < _TIMELINE_CAP:
                self._timeline.append(payload)
            else:
                self._timeline_dropped += 1
        return decisions

    def _emit(self, payload: dict) -> None:
        if self._registry is not None:
            self._registry.counter(
                f"healthwatch-alerts-{payload['decision']}",
                labels={"rule": payload["rule"]})
        try:
            self._decision_sink(dict(payload))
        except Exception:  # graftlint: disable=G009 — an audit sink must
            # never break the tick path
            LOG.debug("healthwatch decision sink failed", exc_info=True)
        if payload["decision"] == "fired" and self._notifier is not None:
            try:
                from cruise_control_tpu.detector.anomalies import (
                    AnomalyType, SLOBurnAnomaly)
                anomaly = SLOBurnAnomaly(
                    anomaly_type=AnomalyType.METRIC_ANOMALY,
                    detection_time_ms=payload["tsMs"],
                    rule=payload["rule"], signal=payload["signal"],
                    burn_fast=payload["burnFast"],
                    burn_slow=payload["burnSlow"])
                alert = getattr(self._notifier, "alert", None)
                if alert is not None:
                    alert(anomaly, auto_fix_triggered=False)
            except Exception:  # graftlint: disable=G009 — notification is
                # fire-and-forget; a broken webhook must not break ticks
                LOG.warning("healthwatch notifier failed", exc_info=True)

    # ---------------------------------------------------------- reading
    def alert_counts(self) -> dict:
        with self._lock:
            return {
                "fired": self._fired,
                "suppressed": self._suppressed,
                "resolved": self._resolved,
                "firstFiringTick": self._first_firing_tick,
            }

    def active_alerts(self) -> List[dict]:
        with self._lock:
            return [{"rule": name, "sinceTick": since,
                     **self._last_burns.get(name, {})}
                    for name, since in sorted(self._active.items())]

    def snapshot(self, history: int = 32) -> dict:
        """JSON view for ``/state`` and ``GET /alerts``."""
        with self._lock:
            return {
                "enabled": True,
                "ticks": int(np.asarray(self._count)),
                "ringTicks": self._ring_ticks,
                "tickSloMs": self.tick_slo_ms,
                "rules": [r.describe() for r in self._rules],
                "active": [
                    {"rule": name, "sinceTick": since,
                     **self._last_burns.get(name, {})}
                    for name, since in sorted(self._active.items())],
                "burns": {name: dict(v) for name, v in
                          sorted(self._last_burns.items())},
                "counts": {
                    "fired": self._fired,
                    "suppressed": self._suppressed,
                    "resolved": self._resolved,
                    "firstFiringTick": self._first_firing_tick,
                },
                "history": [dict(p) for p in self._timeline[-history:]],
                "timelineDropped": self._timeline_dropped,
            }

    def export_timeline(self) -> str:
        """Canonical JSONL of every alert decision since the last reset —
        the byte-identical same-seed contract surface."""
        with self._lock:
            rows = [dict(p) for p in self._timeline]
        return "\n".join(
            json.dumps(r, sort_keys=True, separators=(",", ":"))
            for r in rows)
