"""Span tracer: the control-loop timeline over an injected clock.

A :class:`Tracer` produces *spans* — named, attributed intervals arranged
in a tree — and keeps the completed ones in a bounded ring buffer.  Two
clocks run side by side:

- ``now_fn`` (injected; the app passes its virtual-time seam) stamps span
  start/duration — under a ``VirtualClock`` the exported timeline is a
  pure function of the scenario, byte-identical across same-seed runs;
- ``time.monotonic`` measures the span's *wall* duration, which feeds the
  per-stage timers in the metrics registry (``stage-<name>-timer``) — the
  operational signal Prometheus scrapes.

Context propagation is thread-safe: each thread keeps its own open-span
stack, and a tracer-level *ambient* parent (set by the app around each
control-loop tick) lets spans opened on background threads — executor
progress polling, detector fixes, the escape-kernel warm thread — parent
to the tick span that caused them.  Explicit ``parent=`` wins over both.

A disabled tracer returns the shared :data:`NOOP_SPAN` from every call:
no allocation, no records, no timing — the bit-parity contract (tracing
off ⇒ behavior identical) that the fixture parity tests pin.

Spans MUST be used as context managers (``with tracer.span(...) as sp:``);
graftlint G012 flags bare ``span()``/``start_span()`` calls that could
leak an open span on an exception path.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class Span:
    """One completed span (immutable record in the tracer's ring buffer)."""

    __slots__ = ("name", "span_id", "parent_id", "thread", "start_s",
                 "dur_s", "wall_dur_s", "attrs")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 thread: str, start_s: float, dur_s: float,
                 wall_dur_s: float, attrs: Dict[str, Any]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.thread = thread
        self.start_s = start_s
        self.dur_s = dur_s
        self.wall_dur_s = wall_dur_s
        self.attrs = attrs

    def to_json(self) -> dict:
        """Deterministic dict: clock fields are now_fn units only (the
        wall duration is host-dependent and stays out on purpose)."""
        return {
            "name": self.name,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "thread": self.thread,
            "startS": round(self.start_s, 6),
            "durS": round(self.dur_s, 6),
            "attrs": {k: self.attrs[k] for k in sorted(self.attrs)},
        }


class _NoopSpan:
    """Shared do-nothing span: what a disabled tracer hands out."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, key: str, value: Any) -> None:
        pass

    @property
    def span_id(self) -> None:
        return None


#: the one no-op span instance (identity-comparable in tests)
NOOP_SPAN = _NoopSpan()


class _ActiveSpan:
    """An open span; records itself into the tracer on ``__exit__``."""

    __slots__ = ("_tracer", "name", "span_id", "parent_id", "attrs",
                 "_start_s", "_wall_t0")

    def __init__(self, tracer: "Tracer", name: str, span_id: int,
                 parent_id: Optional[int], attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self._start_s = 0.0
        self._wall_t0 = 0.0

    def set(self, key: str, value: Any) -> None:
        """Attach/overwrite one attribute while the span is open."""
        self.attrs[key] = value

    def __enter__(self) -> "_ActiveSpan":
        self._start_s = self._tracer._now()
        self._wall_t0 = time.monotonic()
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self._tracer._pop(self)
        self._tracer._record(Span(
            self.name, self.span_id, self.parent_id,
            threading.current_thread().name, self._start_s,
            max(self._tracer._now() - self._start_s, 0.0),
            max(time.monotonic() - self._wall_t0, 0.0),
            self.attrs))
        return False


class Tracer:
    """Bounded-buffer span tracer with cross-thread context propagation."""

    def __init__(self, now_fn: Optional[Callable[[], float]] = None,
                 capacity: int = 4096, enabled: bool = True,
                 registry=None):
        self._now = now_fn or time.monotonic
        self.enabled = bool(enabled)
        self.capacity = max(int(capacity), 1)
        #: metrics registry the per-stage timers derive into (None = off)
        self._registry = registry
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._ring: List[Span] = []
        self._ring_start = 0          # index of the oldest retained span
        self._dropped = 0
        self._local = threading.local()
        self._ambient: Optional[int] = None

    # ------------------------------------------------------------ spans
    def span(self, name: str, parent: Optional[object] = None,
             **attrs: Any):
        """Open a span (context manager).  Parent resolution: explicit
        ``parent`` (an open span or a span id) > this thread's innermost
        open span > the tracer's ambient parent."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is not None:
            parent_id = parent if isinstance(parent, int) \
                else getattr(parent, "span_id", None)
        else:
            stack = getattr(self._local, "stack", None)
            parent_id = stack[-1].span_id if stack else self._ambient
        with self._lock:
            span_id = next(self._ids)
        return _ActiveSpan(self, name, span_id, parent_id, dict(attrs))

    def current_id(self) -> Optional[int]:
        """Id of this thread's innermost open span (None outside any)."""
        stack = getattr(self._local, "stack", None)
        return stack[-1].span_id if stack else None

    # ambient parent: the cross-thread handoff. The app sets it to the
    # open tick span so background threads' spans join the tick's tree.
    def set_ambient(self, span: Optional[object]) -> None:
        self._ambient = span if isinstance(span, (int, type(None))) \
            else getattr(span, "span_id", None)

    def clear_ambient(self) -> None:
        self._ambient = None

    # ------------------------------------------------------- internals
    def _push(self, span: _ActiveSpan) -> None:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(span)

    def _pop(self, span: _ActiveSpan) -> None:
        stack = getattr(self._local, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()
        elif stack and span in stack:     # exited out of order: drop above
            del stack[stack.index(span):]

    def _record(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            if len(self._ring) > self.capacity:
                # amortized ring: drop the oldest half in one slice
                drop = len(self._ring) - self.capacity
                del self._ring[:drop]
                self._dropped += drop
                self._ring_start += drop
        if self._registry is not None:
            self._registry.timer(f"stage-{span.name}-timer").update(
                span.wall_dur_s)

    # --------------------------------------------------------- reading
    def finished(self) -> List[Span]:
        """Completed spans, oldest first (bounded by ``capacity``)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    def summary(self) -> dict:
        """Cheap JSON-able view for /observatory and /state."""
        with self._lock:
            spans = list(self._ring)
            dropped = self._dropped
        by_name: Dict[str, int] = {}
        for s in spans:
            by_name[s.name] = by_name.get(s.name, 0) + 1
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "bufferedSpans": len(spans),
            "droppedSpans": dropped,
            "spanCounts": {k: by_name[k] for k in sorted(by_name)},
        }

    # ---------------------------------------------------------- export
    def chrome_trace(self) -> dict:
        """Chrome-trace (``chrome://tracing`` / Perfetto) JSON object.

        Timestamps/durations are ``now_fn`` microseconds, so a virtual-
        clock run exports a deterministic timeline.  Thread ids are
        assigned by first appearance (stable for a deterministic run).
        """
        spans = self.finished()
        tids: Dict[str, int] = {}
        events: List[dict] = []
        for s in spans:
            if s.thread not in tids:
                tids[s.thread] = len(tids)
                events.append({
                    "name": "thread_name", "ph": "M", "pid": 0,
                    "tid": tids[s.thread], "args": {"name": s.thread}})
            args = {k: s.attrs[k] for k in sorted(s.attrs)}
            args["spanId"] = s.span_id
            if s.parent_id is not None:
                args["parentId"] = s.parent_id
            events.append({
                "name": s.name, "ph": "X", "pid": 0, "tid": tids[s.thread],
                "ts": round(s.start_s * 1e6, 3),
                "dur": round(s.dur_s * 1e6, 3),
                "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def chrome_trace_json(self) -> str:
        """Canonical serialization of :meth:`chrome_trace` (byte-stable
        for deterministic runs — the simulator determinism contract)."""
        return json.dumps(self.chrome_trace(), sort_keys=True,
                          separators=(",", ":"))


#: shared disabled tracer: the default for every ``tracer=None`` seam —
#: callers write ``tracer = tracer or NOOP_TRACER`` and instrument
#: unconditionally; the disabled path allocates nothing
NOOP_TRACER = Tracer(enabled=False)


def stage_breakdown(spans: List[Span]) -> Dict[str, dict]:
    """Fold span records into a per-stage table: count + total virtual
    duration (deterministic — scorecard core) keyed by span name."""
    out: Dict[str, Dict[str, float]] = {}
    for s in spans:
        ent = out.setdefault(s.name, {"count": 0, "virtualMsTotal": 0.0})
        ent["count"] += 1
        ent["virtualMsTotal"] += s.dur_s * 1000.0
    return {name: {"count": ent["count"],
                   "virtualMsTotal": round(ent["virtualMsTotal"], 3)}
            for name, ent in sorted(out.items())}


def stage_wall_percentiles(spans: List[Span]) -> Dict[str, dict]:
    """Host-dependent per-stage wall percentiles (scorecard wall section)."""
    by_name: Dict[str, List[float]] = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s.wall_dur_s * 1000.0)
    out = {}
    for name, vals in sorted(by_name.items()):
        vals.sort()
        def pct(p: float) -> float:
            idx = min(int(len(vals) * p), len(vals) - 1)
            return round(vals[idx], 3)
        out[name] = {"wallMsP50": pct(0.50), "wallMsP99": pct(0.99),
                     "wallMsMax": round(vals[-1], 3)}
    return out
