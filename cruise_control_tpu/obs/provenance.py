"""Per-move goal attribution: why did the optimizer make each move?

The optimizer's headline verdicts (``violatedGoalsBefore/After``,
``GoalSummary`` rows) say *whether* the proposal helped each goal; they
cannot say which of its ten thousand moves did the helping. This module
answers that: for every move in a proposal — a partition whose replica set
or leadership differs between the initial and final assignment — it computes
the per-goal ``(violations, cost)`` delta the move contributes to the final
objective, defined as::

    delta(move) = penalties(final) - penalties(final with that move reverted)

so a negative entry means the move *removed* penalty from that goal (the
reason the optimizer chose it) and a positive entry means the move paid
penalty there (collateral the other goals outvoted).

Evaluating ``full_goal_penalties`` per reverted state would be O(moves x
replicas) — hopeless at LinkedIn scale. Instead the kernel exploits the same
decomposition the greedy engine's hypothetical evals use: every goal term is
a sum over brokers, hosts, (broker, topic) cells, or the moved partition
itself, and one move touches at most ``2 * max_rf`` brokers. One batched
device evaluation vmaps the per-move local delta over all moves:

- broker terms via :func:`analyzer.goals.broker_terms` on gathered
  final-aggregate rows with the move's exact aggregate delta applied
  (same accounting as :func:`ops.aggregates.compute_aggregates`);
- host terms likewise on the touched hosts;
- the topic band from exact per-cell counts answered by binary search over
  one shared sort of (broker, topic) keys — the sort-based counting trick of
  :func:`analyzer.goals.sparse_topic_penalty`, reused as a lookup structure
  so neither mode materializes the [B, T] histogram;
- rack, preferred-leader, and self-healing terms analytically for the moved
  partition.

The move axis is padded to power-of-two buckets (:func:`ops.windows.
bucket_len`) with the partition-axis length as the drop sentinel — the same
discipline as the rescore splice kernels — so steady-state drift in the move
count reuses one compiled program per bucket and the retrace sentinel stays
quiet. Attribution runs strictly *after* the proposal is final and touches no
optimizer state: with ``obs.provenance.enable=false`` the code path is never
entered and the historical program is bit-identical by construction.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import objective as OBJ
from cruise_control_tpu.analyzer import proposals as PR
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.obs import costmodel as CM
from cruise_control_tpu.ops import aggregates as AGG
from cruise_control_tpu.ops.windows import bucket_len


@dataclasses.dataclass(frozen=True)
class AttributionResult:
    """Host-side per-move attribution over ``goals`` (goal_names + the
    synthetic self-healing term, the same [G+1] axis as GoalPenalties)."""

    goals: Tuple[str, ...]
    partitions: np.ndarray        # i32[M] model/real partition ids
    violations_delta: np.ndarray  # f32[M, G+1]
    cost_delta: np.ndarray        # f32[M, G+1]

    @property
    def num_moves(self) -> int:
        return int(self.partitions.shape[0])

    def scores(self) -> np.ndarray:
        """f32[M] two-channel lexicographic impact (violations dominate via
        VIOL_SCALE, the objective's own channel folding). More negative =
        more beneficial move."""
        return (OBJ.VIOL_SCALE * self.violations_delta.sum(axis=1)
                + self.cost_delta.sum(axis=1))

    def to_json(self, topo, top_k: Optional[int] = None) -> dict:
        """JSON-ready attribution: every move (or the ``top_k`` most
        impactful), most beneficial first, with per-goal deltas."""
        order = np.argsort(self.scores(), kind="stable")
        if top_k is not None:
            order = order[:top_k]
        t_of_p = np.asarray(topo.topic_of_partition)
        p_index = np.asarray(topo.partition_index)
        moves = []
        for i in order:
            p = int(self.partitions[i])
            topic = topo.topic_names[int(t_of_p[p])]
            moves.append({
                "topicPartition": f"{topic}-{int(p_index[p])}",
                "partition": p,
                "violationsDelta": [round(float(v), 6)
                                    for v in self.violations_delta[i]],
                "costDelta": [round(float(c), 6)
                              for c in self.cost_delta[i]],
            })
        return {"goals": list(self.goals), "numMoves": self.num_moves,
                "moves": moves}


@partial(jax.jit, static_argnames=("num_topics", "goal_names",
                                   "sparse_topic", "has_init"))
def _attribution_kernel(dt: AGG.DeviceTopology, final, base, th, agg,
                        init_broker, pids, num_topics: int,
                        goal_names: Tuple[str, ...], sparse_topic: bool,
                        has_init: bool):
    """[Mp] padded move pids -> ([Mp, G+1], [Mp, G+1]) per-goal deltas.

    ``agg`` must be the FINAL state's aggregates and ``th`` the frozen
    thresholds the optimization ran under. Sentinel pids (== num_partitions)
    produce zero rows. ``sparse_topic`` only mirrors the caller's routing for
    program identity — the cell-count lookup is mode-independent.
    """
    del sparse_topic  # counts come from the shared sort in both modes
    P = dt.num_partitions
    T = num_topics
    live = (pids < P).astype(jnp.float32)
    p_safe = jnp.minimum(pids, P - 1)

    # shared lookup structure: sorted (broker, topic) keys of the FINAL
    # placement; dead-broker / padding replicas park in the sentinel bin
    # exactly as sparse_topic_penalty bins them. count(b, t) is then one
    # binary-searched run length — no [B, T] histogram in either mode.
    t_of_r = dt.topic_of_partition[dt.partition_of_replica]
    countable = (dt.broker_alive[final.broker_of]
                 & (AGG.replica_count_weights(dt) > 0))
    BT = dt.num_brokers * T
    sorted_keys = jnp.sort(jnp.where(countable,
                                     final.broker_of * T + t_of_r, BT))

    host_col = {g: i for i, g in enumerate(G.HOST_TERM_GOALS)}
    bt_col = {g: i for i, g in enumerate(G.BROKER_TERM_GOALS)}

    def one_move(p):
        reps = dt.replicas_of_partition[p]                     # i32[m]
        valid = reps >= 0
        r = jnp.clip(reps, 0)
        a = final.broker_of[r]             # chosen placement per slot
        b = base.broker_of[r]              # placement if the move is reverted
        lf = final.leader_of[p]
        li = base.leader_of[p]
        base_rows = dt.replica_base_load[r]                    # f32[m, 4]
        ex = dt.leader_extra[p]                                # f32[4]
        eff_fin = base_rows + jnp.where((r == lf)[:, None], ex[None, :], 0.0)
        eff_rev = base_rows + jnp.where((r == li)[:, None], ex[None, :], 0.0)
        # potentialLeadershipLoad rows follow the partition's CURRENT leader
        pot_fin = ex[res.NW_OUT] + dt.replica_base_load[lf, res.NW_OUT]
        pot_rev = ex[res.NW_OUT] + dt.replica_base_load[li, res.NW_OUT]
        lbi = dt.leader_bytes_in[p]
        lb_fin = final.broker_of[lf]
        lb_rev = base.broker_of[li]

        # touched brokers: current + reverted placement of every slot. A
        # candidate's delta is a function of its broker id alone, so
        # duplicate candidates compute identical deltas and the first-
        # occurrence mask counts each broker (and host) exactly once.
        cand = jnp.concatenate([a, b])                         # i32[2m]
        m2 = cand.shape[0]
        hits_a = (cand[:, None] == a[None, :]) & valid[None, :]
        hits_b = (cand[:, None] == b[None, :]) & valid[None, :]
        fa = hits_a.astype(jnp.float32)
        fb = hits_b.astype(jnp.float32)
        d_load = (fb[:, :, None] * eff_rev[None, :, :]
                  - fa[:, :, None] * eff_fin[None, :, :]).sum(axis=1)
        d_rc = (hits_b.astype(jnp.int32) - hits_a.astype(jnp.int32)).sum(axis=1)
        d_lead = ((cand == lb_rev).astype(jnp.int32)
                  - (cand == lb_fin).astype(jnp.int32))
        d_pot = (fb * pot_rev - fa * pot_fin).sum(axis=1)
        d_lbi = ((cand == lb_rev).astype(jnp.float32)
                 - (cand == lb_fin).astype(jnp.float32)) * lbi

        earlier = (jnp.arange(m2)[:, None] > jnp.arange(m2)[None, :])
        uniq = (~jnp.any((cand[None, :] == cand[:, None]) & earlier,
                         axis=1)).astype(jnp.float32)

        th_c = OBJ.gather_thresholds(th, cand)
        rows = (agg.broker_load[cand], agg.replica_count[cand],
                agg.leader_count[cand], agg.potential_nw_out[cand],
                agg.leader_bytes_in[cand])
        bt_fin = G.broker_terms(th_c, *rows)
        bt_rev = G.broker_terms(th_c, rows[0] + d_load, rows[1] + d_rc,
                                rows[2] + d_lead, rows[3] + d_pot,
                                rows[4] + d_lbi)
        d_bt_v = (uniq[:, None] * (bt_rev.violations - bt_fin.violations)).sum(axis=0)
        d_bt_c = (uniq[:, None] * (bt_rev.cost - bt_fin.cost)).sum(axis=0)

        # host-scope capacity terms: fold the unique brokers' load deltas
        # onto their hosts, then score each unique touched host once
        hostc = dt.host_of_broker[cand]
        same_host = (hostc[None, :] == hostc[:, None]).astype(jnp.float32)
        d_host = jnp.matmul(same_host, uniq[:, None] * d_load)
        uniq_h = (~jnp.any((hostc[None, :] == hostc[:, None]) & earlier,
                           axis=1)).astype(jnp.float32)
        th_h = th._replace(cap_limit_host=th.cap_limit_host[hostc])
        hv_fin, hc_fin = G.host_terms(th_h, agg.host_load[hostc])
        hv_rev, hc_rev = G.host_terms(th_h, agg.host_load[hostc] + d_host)
        d_h_v = (uniq_h[:, None] * (hv_rev - hv_fin)).sum(axis=0)
        d_h_c = (uniq_h[:, None] * (hc_rev - hc_fin)).sum(axis=0)

        # topic band: only the (touched broker, this topic) cells change
        t_p = dt.topic_of_partition[p]
        key_c = cand * T + t_p
        c_fin = (jnp.searchsorted(sorted_keys, key_c, side="right")
                 - jnp.searchsorted(sorted_keys, key_c, side="left")
                 ).astype(jnp.float32)
        d_cnt = (fb - fa).sum(axis=1)
        tu = th.topic_upper[t_p]
        tl = th.topic_lower[t_p]
        alive_c = th_c.alive.astype(jnp.float32)
        band_fin = G.band_cost(c_fin, tu, tl)
        band_rev = G.band_cost(c_fin + d_cnt, tu, tl)
        d_topic_v = (uniq * alive_c
                     * ((band_rev > 0).astype(jnp.float32)
                        - (band_fin > 0).astype(jnp.float32))).sum()
        d_topic_c = (uniq * alive_c * (band_rev - band_fin)).sum()

        # rack excess for the moved partition (partition_rack_excess, one row)
        def excess(rk):
            same = rk[None, :] == rk[:, None]
            ear = (jnp.arange(rk.shape[0])[:, None]
                   > jnp.arange(rk.shape[0])[None, :])
            dup = jnp.any(same & ear & valid[None, :], axis=1) & valid
            return dup.astype(jnp.float32).sum()

        d_rack = excess(dt.rack_of_broker[b]) - excess(dt.rack_of_broker[a])

        head = dt.replicas_of_partition[p, 0]
        d_ple = ((li != head).astype(jnp.float32)
                 - (lf != head).astype(jnp.float32))

        if has_init:
            off = dt.replica_offline[r] & valid
            ib = init_broker[r]
            d_unmoved = (
                (off & (b == ib) & dt.broker_alive[b]).astype(jnp.float32).sum()
                - (off & (a == ib) & dt.broker_alive[a]).astype(jnp.float32).sum())
        else:
            d_unmoved = jnp.float32(0.0)

        # assemble the [G+1] axis exactly as full_goal_penalties does
        viols, costs = [], []
        for g in goal_names:
            if g == "RackAwareGoal":
                v = c = d_rack
            elif g == "TopicReplicaDistributionGoal":
                v, c = d_topic_v, d_topic_c
            elif g == "PreferredLeaderElectionGoal":
                v = c = d_ple
            elif g in bt_col:
                v, c = d_bt_v[bt_col[g]], d_bt_c[bt_col[g]]
                if g in host_col:
                    v = v + d_h_v[host_col[g]]
                    c = c + d_h_c[host_col[g]]
            else:
                raise ValueError(f"unknown goal {g}")
            viols.append(v)
            costs.append(c)
        dead_v = d_bt_v[bt_col["_DeadBrokerPlacement"]] + d_unmoved
        dead_c = d_bt_c[bt_col["_DeadBrokerPlacement"]] + d_unmoved
        viols.append(dead_v)
        costs.append(dead_c)
        # deltas above are (reverted - final); the move's contribution to
        # the final objective is the negation
        return -jnp.stack(viols), -jnp.stack(costs)

    vd, cd = jax.vmap(one_move)(p_safe)
    return vd * live[:, None], cd * live[:, None]


def attribute_proposal(dt: AGG.DeviceTopology, final, base, th, agg,
                       init_broker, goal_names, num_topics: int,
                       sparse_topic: bool) -> AttributionResult:
    """Attribute every move of ``final`` (vs ``base``) at model shapes.

    ``agg`` is the final state's aggregates, ``th`` the frozen thresholds —
    both already on device from the optimizer's after-eval, so the only new
    work is the one vmapped delta kernel (plus one [R] key sort) per padded
    move-bucket size.
    """
    goal_names = tuple(goal_names)
    names_ext = goal_names + (G.SELF_HEALING_TERM,)
    changed = np.asarray(jax.device_get(
        PR.changed_partitions(dt, final, base)))
    pids = np.nonzero(changed)[0].astype(np.int32)
    M = int(pids.shape[0])
    gp1 = len(names_ext)
    if M == 0:
        return AttributionResult(
            goals=names_ext, partitions=pids,
            violations_delta=np.zeros((0, gp1), np.float32),
            cost_delta=np.zeros((0, gp1), np.float32))
    P = dt.num_partitions
    padded = np.full(bucket_len(M), P, np.int32)
    padded[:M] = pids
    vd, cd = _attribution_kernel(
        dt, final, base, th, agg,
        init_broker if init_broker is not None else final.broker_of,
        jnp.asarray(padded), num_topics, goal_names, sparse_topic,
        init_broker is not None)
    CM.capture_program(
        "provenance-attribution", _attribution_kernel,
        (dt, final, base, th, agg,
         init_broker if init_broker is not None else final.broker_of,
         jnp.asarray(padded), num_topics, goal_names, sparse_topic,
         init_broker is not None),
        (vd, cd))
    return AttributionResult(
        goals=names_ext, partitions=pids,
        violations_delta=np.asarray(jax.device_get(vd))[:M],
        cost_delta=np.asarray(jax.device_get(cd))[:M])
