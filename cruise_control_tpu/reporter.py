"""Metrics reporter: the in-broker agent shipping raw metrics.

Rebuild of the ``cruise-control-metrics-reporter`` module
(``CruiseControlMetricsReporter.java:41-172``): a reporter co-located with
each broker samples the broker's metrics every reporting interval and ships
serialized ``CruiseControlMetric`` records (63 raw types,
``metric/RawMetricType.java``) to a transport. The reference's transport is
the ``__CruiseControlMetrics`` Kafka topic; here the transport is pluggable
(Kafka producer adapter, JSONL file, or HTTP POST to the service), with the
same record schema either way.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

from cruise_control_tpu.monitor.metricdef import MetricScope, RAW_METRIC_TYPES


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    """One raw metric record (metric/CruiseControlMetric.java serde schema)."""

    raw_metric_type: str
    time_ms: int
    broker_id: int
    value: float
    topic: Optional[str] = None
    partition: Optional[int] = None

    def __post_init__(self):
        scope = RAW_METRIC_TYPES.get(self.raw_metric_type)
        if scope is None:
            raise ValueError(f"unknown raw metric {self.raw_metric_type}")
        if scope == MetricScope.TOPIC and self.topic is None:
            raise ValueError(f"{self.raw_metric_type} requires a topic")
        if scope == MetricScope.PARTITION and (self.topic is None
                                               or self.partition is None):
            raise ValueError(f"{self.raw_metric_type} requires topic+partition")

    def to_json(self) -> dict:
        out = {"type": self.raw_metric_type, "time": self.time_ms,
               "brokerId": self.broker_id, "value": self.value}
        if self.topic is not None:
            out["topic"] = self.topic
        if self.partition is not None:
            out["partition"] = self.partition
        return out

    @classmethod
    def from_json(cls, d: dict) -> "CruiseControlMetric":
        return cls(d["type"], d["time"], d["brokerId"], d["value"],
                   d.get("topic"), d.get("partition"))


class MetricsTransport:
    """Where records go (the metrics-topic producer seam)."""

    def send(self, records: Iterable[CruiseControlMetric]) -> None:
        raise NotImplementedError

    def close(self):
        pass


class FileMetricsTransport(MetricsTransport):
    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()

    def send(self, records):
        with self._lock, open(self._path, "a") as f:
            for r in records:
                f.write(json.dumps(r.to_json()) + "\n")


class InMemoryMetricsTransport(MetricsTransport):
    def __init__(self):
        self.records: List[CruiseControlMetric] = []

    def send(self, records):
        self.records.extend(records)


class BrokerMetricsSource:
    """Reads the co-located broker's current metric values:
    {raw_metric_type: value} for broker metrics and
    {(type, topic[, partition]): value} for topic/partition metrics
    (YammerMetricProcessor seam)."""

    def broker_metrics(self) -> Dict[str, float]:
        raise NotImplementedError

    def topic_metrics(self) -> Dict[tuple, float]:
        return {}

    def partition_metrics(self) -> Dict[tuple, float]:
        return {}


class MetricsReporter:
    """The reporting loop (CruiseControlMetricsReporter.run, :172)."""

    def __init__(self, broker_id: int, source: BrokerMetricsSource,
                 transport: MetricsTransport,
                 reporting_interval_ms: int = 60_000,
                 now_fn=lambda: int(time.time() * 1000)):
        self.broker_id = broker_id
        self.source = source
        self.transport = transport
        self.interval_ms = reporting_interval_ms
        self._now = now_fn
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def report_once(self) -> int:
        now = self._now()
        records: List[CruiseControlMetric] = []
        for mtype, value in self.source.broker_metrics().items():
            records.append(CruiseControlMetric(mtype, now, self.broker_id,
                                               float(value)))
        for (mtype, topic), value in self.source.topic_metrics().items():
            records.append(CruiseControlMetric(mtype, now, self.broker_id,
                                               float(value), topic=topic))
        for (mtype, topic, part), value in self.source.partition_metrics().items():
            records.append(CruiseControlMetric(mtype, now, self.broker_id,
                                               float(value), topic=topic,
                                               partition=part))
        self.transport.send(records)
        return len(records)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"cc-metrics-reporter-{self.broker_id}")
        self._thread.start()

    def _run(self):
        while not self._shutdown.wait(self.interval_ms / 1000.0):
            try:
                self.report_once()
            except Exception:
                pass

    def close(self):
        self._shutdown.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.transport.close()
