"""Metrics reporter: the in-broker agent shipping raw metrics.

Rebuild of the ``cruise-control-metrics-reporter`` module
(``CruiseControlMetricsReporter.java:41-172``): a reporter co-located with
each broker samples the broker's metrics every reporting interval and ships
serialized ``CruiseControlMetric`` records (63 raw types,
``metric/RawMetricType.java``) to a transport. The reference's transport is
the ``__CruiseControlMetrics`` Kafka topic; here the transport is pluggable
(Kafka producer adapter, JSONL file, or HTTP POST to the service), with the
same record schema either way.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

from cruise_control_tpu.monitor.metricdef import MetricScope, RAW_METRIC_TYPES


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    """One raw metric record (metric/CruiseControlMetric.java serde schema)."""

    raw_metric_type: str
    time_ms: int
    broker_id: int
    value: float
    topic: Optional[str] = None
    partition: Optional[int] = None

    def __post_init__(self):
        scope = RAW_METRIC_TYPES.get(self.raw_metric_type)
        if scope is None:
            raise ValueError(f"unknown raw metric {self.raw_metric_type}")
        if scope == MetricScope.TOPIC and self.topic is None:
            raise ValueError(f"{self.raw_metric_type} requires a topic")
        if scope == MetricScope.PARTITION and (self.topic is None
                                               or self.partition is None):
            raise ValueError(f"{self.raw_metric_type} requires topic+partition")

    def to_json(self) -> dict:
        out = {"type": self.raw_metric_type, "time": self.time_ms,
               "brokerId": self.broker_id, "value": self.value}
        if self.topic is not None:
            out["topic"] = self.topic
        if self.partition is not None:
            out["partition"] = self.partition
        return out

    @classmethod
    def from_json(cls, d: dict) -> "CruiseControlMetric":
        return cls(d["type"], d["time"], d["brokerId"], d["value"],
                   d.get("topic"), d.get("partition"))


class MetricsTransport:
    """Where records go (the metrics-topic producer seam)."""

    def send(self, records: Iterable[CruiseControlMetric]) -> None:
        raise NotImplementedError

    def close(self):
        pass


class FileMetricsTransport(MetricsTransport):
    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()

    def send(self, records):
        with self._lock, open(self._path, "a") as f:
            for r in records:
                f.write(json.dumps(r.to_json()) + "\n")


class InMemoryMetricsTransport(MetricsTransport):
    def __init__(self):
        self.records: List[CruiseControlMetric] = []

    def send(self, records):
        self.records.extend(records)


class HttpMetricsTransport(MetricsTransport):
    """POSTs each batch as a JSON array to a collector URL. Send failures
    raise to the caller — the reporting loop already drops a failed
    interval and carries on (CruiseControlMetricsReporter.run swallows and
    logs per-interval errors the same way)."""

    def __init__(self, url: str, timeout_s: float = 10.0):
        self.url = url
        self.timeout_s = timeout_s

    def send(self, records):
        import urllib.request
        data = json.dumps([r.to_json() for r in records]).encode()
        req = urllib.request.Request(
            self.url, data=data, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()


class BrokerMetricsSource:
    """Reads the co-located broker's current metric values:
    {raw_metric_type: value} for broker metrics and
    {(type, topic[, partition]): value} for topic/partition metrics
    (YammerMetricProcessor seam)."""

    def begin_report(self) -> None:
        """Called once by the reporter at the start of each reporting
        interval — sources that snapshot/reset state do it here so the
        three getters read one consistent collection."""

    def broker_metrics(self) -> Dict[str, float]:
        raise NotImplementedError

    def topic_metrics(self) -> Dict[tuple, float]:
        return {}

    def partition_metrics(self) -> Dict[tuple, float]:
        return {}


class Meter:
    """Event-rate meter: mark() events, read events/sec since last tick
    (Yammer Meter one-minute-rate seam, YammerMetricProcessor.java)."""

    def __init__(self, now_fn=time.time):
        self._now = now_fn
        self._count = 0.0
        self._last_ts = now_fn()
        self._rate = 0.0
        self._lock = threading.Lock()

    def mark(self, n: float = 1.0):
        with self._lock:
            self._count += n

    def tick(self) -> float:
        """Rate over the elapsed interval; resets the interval window."""
        with self._lock:
            now = self._now()
            dt = max(now - self._last_ts, 1e-9)
            self._rate = self._count / dt
            self._count = 0.0
            self._last_ts = now
            return self._rate

    @property
    def rate(self) -> float:
        with self._lock:
            return self._rate


class Histogram:
    """Bounded reservoir; reports MAX/MEAN/50TH/999TH like the broker's
    request-time Yammer histograms (RawMetricType *_MAX.._999TH)."""

    def __init__(self, capacity: int = 4096):
        self._values: List[float] = []
        self._capacity = capacity
        self._i = 0
        self._lock = threading.Lock()

    def update(self, value: float):
        with self._lock:
            if len(self._values) < self._capacity:
                self._values.append(float(value))
            else:       # ring overwrite keeps the reservoir recent
                self._values[self._i % self._capacity] = float(value)
            self._i += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._values)
        if not vals:
            return {"_MAX": 0.0, "_MEAN": 0.0, "_50TH": 0.0, "_999TH": 0.0}
        n = len(vals)
        return {"_MAX": vals[-1], "_MEAN": sum(vals) / n,
                "_50TH": vals[n // 2],
                "_999TH": vals[min(int(n * 0.999), n - 1)]}


class BrokerMetricsRegistry:
    """The broker-process metric surface the reporter walks each interval —
    the rebuild of ``YammerMetricProcessor.java`` + ``MetricsUtils.java:443``:
    named meters/histograms/gauges registered per raw-metric type (broker
    scope) or per (type, topic[, partition]).

    A broker runtime calls ``meter(...)`` / ``histogram(...)`` on its hot
    paths; :class:`RegistryMetricsSource` converts the registry into the 63
    raw-type records at reporting time.
    """

    def __init__(self, now_fn=time.time):
        self._now = now_fn
        self._meters: Dict[tuple, Meter] = {}
        self._hists: Dict[tuple, Histogram] = {}
        self._gauges: Dict[tuple, Callable[[], float]] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(mtype: str, topic: Optional[str], partition: Optional[int]):
        return (mtype, topic, partition)

    def meter(self, mtype: str, topic: Optional[str] = None,
              partition: Optional[int] = None) -> Meter:
        k = self._key(mtype, topic, partition)
        with self._lock:
            m = self._meters.get(k)
            if m is None:
                m = self._meters[k] = Meter(self._now)
            return m

    def histogram(self, base_type: str, topic: Optional[str] = None,
                  partition: Optional[int] = None) -> Histogram:
        """base_type without the _MAX/_MEAN/_50TH/_999TH suffix."""
        k = self._key(base_type, topic, partition)
        with self._lock:
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = Histogram()
            return h

    def gauge(self, mtype: str, fn: Callable[[], float],
              topic: Optional[str] = None, partition: Optional[int] = None):
        with self._lock:
            self._gauges[self._key(mtype, topic, partition)] = fn

    def collect(self) -> List[tuple]:
        """[(mtype, topic, partition, value)] — the registry walk."""
        out: List[tuple] = []
        with self._lock:
            meters = list(self._meters.items())
            hists = list(self._hists.items())
            gauges = list(self._gauges.items())
        for (mtype, topic, part), m in meters:
            out.append((mtype, topic, part, m.tick()))
        for (base, topic, part), h in hists:
            for suffix, v in h.snapshot().items():
                out.append((base + suffix, topic, part, v))
        for (mtype, topic, part), fn in gauges:
            try:
                out.append((mtype, topic, part, float(fn())))
            except Exception:
                pass
        return out


class RegistryMetricsSource(BrokerMetricsSource):
    """BrokerMetricsSource over a BrokerMetricsRegistry (the default wiring
    a broker runtime uses). Unknown names AND registrations whose key shape
    does not match the metric's scope (e.g. a TOPIC_* meter registered
    without a topic) are dropped, like MetricsUtils' interested-metrics
    filter — a bad registration must never poison the report.

    The registry is walked (meters ticked) once per reporting cycle in
    :meth:`begin_report`; the getters read that collection. Direct callers
    that skip ``begin_report`` get a lazy first walk."""

    @staticmethod
    def _scope_ok(mtype: str, topic, part) -> bool:
        scope = RAW_METRIC_TYPES.get(mtype)
        if scope is None:
            return False
        if scope == MetricScope.BROKER:
            return topic is None and part is None
        if scope == MetricScope.TOPIC:
            return topic is not None and part is None
        return topic is not None and part is not None

    def __init__(self, registry: BrokerMetricsRegistry):
        self.registry = registry
        self._collected: Optional[List[tuple]] = None

    def _walk(self):
        self._collected = [
            (t, topic, part, v) for (t, topic, part, v)
            in self.registry.collect() if self._scope_ok(t, topic, part)]

    def begin_report(self) -> None:
        self._walk()

    def _rows(self) -> List[tuple]:
        if self._collected is None:
            self._walk()
        return self._collected

    def broker_metrics(self) -> Dict[str, float]:
        return {t: v for (t, topic, part, v) in self._rows()
                if topic is None}

    def topic_metrics(self) -> Dict[tuple, float]:
        return {(t, topic): v for (t, topic, part, v) in self._rows()
                if topic is not None and part is None}

    def partition_metrics(self) -> Dict[tuple, float]:
        return {(t, topic, part): v for (t, topic, part, v) in self._rows()
                if part is not None}


class ProcSystemMetricsSource(BrokerMetricsSource):
    """Host-level collection from /proc + the log directories — the part of
    the in-broker agent that measures the machine rather than the broker
    internals: BROKER_CPU_UTIL from /proc/stat deltas (MetricsUtils maps the
    broker's CPU gauge the same way) and PARTITION_SIZE from the on-disk
    size of each ``<topic>-<partition>`` directory under the logdirs.
    """

    def __init__(self, logdirs: Iterable[str] = (), proc_stat: str = "/proc/stat"):
        self._logdirs = list(logdirs)
        self._proc_stat = proc_stat
        self._last_cpu: Optional[tuple] = None

    def _read_cpu(self) -> Optional[tuple]:
        try:
            with open(self._proc_stat) as f:
                line = f.readline()
        except OSError:
            return None
        parts = line.split()
        if not parts or parts[0] != "cpu":
            return None
        vals = [float(x) for x in parts[1:]]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0.0)  # idle+iowait
        return (sum(vals), idle)

    def broker_metrics(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        cur = self._read_cpu()
        if cur is not None:
            if self._last_cpu is not None:
                dt = cur[0] - self._last_cpu[0]
                didle = cur[1] - self._last_cpu[1]
                if dt > 0:
                    # percent, matching BrokerMetricSample.cpu_util units
                    busy_pct = 100.0 * (1.0 - didle / dt)
                    out["BROKER_CPU_UTIL"] = max(0.0, min(100.0, busy_pct))
            self._last_cpu = cur
        return out

    def partition_metrics(self) -> Dict[tuple, float]:
        import os
        import re
        sizes: Dict[tuple, float] = {}
        pat = re.compile(r"^(?P<topic>.+)-(?P<part>\d+)$")
        for root in self._logdirs:
            try:
                entries = os.listdir(root)
            except OSError:
                continue
            for name in entries:
                m = pat.match(name)
                if not m:
                    continue
                d = os.path.join(root, name)
                total = 0.0
                try:
                    for fn in os.listdir(d):
                        try:
                            total += os.path.getsize(os.path.join(d, fn))
                        except OSError:
                            pass
                except OSError:
                    continue
                key = ("PARTITION_SIZE", m.group("topic"), int(m.group("part")))
                sizes[key] = sizes.get(key, 0.0) + total
        return sizes


class CompositeMetricsSource(BrokerMetricsSource):
    """Merge several sources (registry + system) into one report."""

    def __init__(self, *sources: BrokerMetricsSource):
        self.sources = sources

    def begin_report(self) -> None:
        for s in self.sources:
            s.begin_report()

    def _merged(self, attr) -> Dict:
        out: Dict = {}
        for s in self.sources:
            out.update(getattr(s, attr)())
        return out

    def broker_metrics(self) -> Dict[str, float]:
        return self._merged("broker_metrics")

    def topic_metrics(self) -> Dict[tuple, float]:
        return self._merged("topic_metrics")

    def partition_metrics(self) -> Dict[tuple, float]:
        return self._merged("partition_metrics")


class MetricsReporter:
    """The reporting loop (CruiseControlMetricsReporter.run, :172)."""

    def __init__(self, broker_id: int, source: BrokerMetricsSource,
                 transport: MetricsTransport,
                 reporting_interval_ms: int = 60_000,
                 now_fn=lambda: int(time.time() * 1000)):
        self.broker_id = broker_id
        self.source = source
        self.transport = transport
        self.interval_ms = reporting_interval_ms
        self._now = now_fn
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def report_once(self) -> int:
        now = self._now()
        self.source.begin_report()
        records: List[CruiseControlMetric] = []
        for mtype, value in self.source.broker_metrics().items():
            records.append(CruiseControlMetric(mtype, now, self.broker_id,
                                               float(value)))
        for (mtype, topic), value in self.source.topic_metrics().items():
            records.append(CruiseControlMetric(mtype, now, self.broker_id,
                                               float(value), topic=topic))
        for (mtype, topic, part), value in self.source.partition_metrics().items():
            records.append(CruiseControlMetric(mtype, now, self.broker_id,
                                               float(value), topic=topic,
                                               partition=part))
        self.transport.send(records)
        return len(records)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"cc-metrics-reporter-{self.broker_id}")
        self._thread.start()

    def _run(self):
        while not self._shutdown.wait(self.interval_ms / 1000.0):
            try:
                self.report_once()
            except Exception:
                pass

    def close(self):
        self._shutdown.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.transport.close()
