"""Anomaly detectors + the detector service loop.

Rebuild of ``detector/AnomalyDetector.java:46-404`` (anomaly priority queue,
scheduled detector sweeps, handler consulting the notifier and triggering
self-healing) and the individual finders:

- :class:`BrokerFailureDetector` — liveness diff against the metadata source
  with a persisted failed-broker record surviving restarts
  (``BrokerFailureDetector.java:42-202``; file instead of ZK).
- :class:`GoalViolationDetector` — optimizes the detection goals on a fresh
  model and reports violated goals (``GoalViolationDetector.java:48+``).
- :class:`DiskFailureDetector` — logdir-state diff via an adapter callback
  (``DiskFailureDetector.java:35-85``).
- :class:`MetricAnomalyDetector` with the core percentile finder
  (``PercentileMetricAnomalyFinder.java``).
- :class:`SlowBrokerFinder` — log-flush-time vs own history and peers,
  demotion → removal escalation (``SlowBrokerFinder.java:38-77``).
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyAction,
    AnomalyNotifier,
    AnomalyType,
    BrokerFailures,
    DiskFailures,
    GoalViolations,
    MetricAnomaly,
    SelfHealingContext,
    SlowBrokers,
)

_now_ms = lambda: int(time.time() * 1000)


class BrokerFailureDetector:
    """Detects brokers that left the cluster; persists first-seen failure
    times so detection survives restarts (ZK record → JSON file)."""

    def __init__(self, metadata_source, persist_path: Optional[str] = None,
                 report_backoff_ms: int = 0, now_fn=_now_ms,
                 anomaly_class: type = BrokerFailures):
        self._metadata_source = metadata_source
        self._path = persist_path
        self._now = now_fn
        #: broker.failures.class — the payload class this detector emits
        self._anomaly_class = anomaly_class
        #: broker.failure.detection.backoff.ms — an UNCHANGED failure set is
        #: re-reported at most this often; a change reports immediately
        self._backoff_ms = report_backoff_ms
        self._last_report_ms = -10**15
        self._failed_by_time: Dict[int, int] = {}
        if persist_path and os.path.exists(persist_path):
            with open(persist_path) as f:
                self._failed_by_time = {int(k): int(v)
                                        for k, v in json.load(f).items()}

    def detect(self) -> Optional[BrokerFailures]:
        md = self._metadata_source.get_metadata()
        now = self._now()
        alive = {b.broker_id for b in md.brokers if b.alive}
        known = {b.broker_id for b in md.brokers}
        failed = known - alive
        changed = False
        for b in failed:
            if b not in self._failed_by_time:
                self._failed_by_time[b] = now
                changed = True
        for b in list(self._failed_by_time):
            if b in alive:
                del self._failed_by_time[b]
                changed = True
        if changed and self._path:
            with open(self._path, "w") as f:
                json.dump({str(k): v for k, v in self._failed_by_time.items()}, f)
        if self._failed_by_time:
            if not changed and now - self._last_report_ms < self._backoff_ms:
                return None     # persisting failure inside the backoff window
            self._last_report_ms = now
            return self._anomaly_class(
                AnomalyType.BROKER_FAILURE, now,
                failed_brokers_by_time=dict(self._failed_by_time))
        return None


class GoalViolationDetector:
    """Runs the anomaly-detection goal list against a fresh model."""

    def __init__(self, load_monitor, goal_names: Optional[Sequence[str]] = None,
                 allow_capacity_estimation: bool = True, now_fn=_now_ms,
                 anomaly_class: type = GoalViolations,
                 provisioner=None, on_recommendation=None):
        from cruise_control_tpu.analyzer import goals as G
        self._lm = load_monitor
        self._goals = tuple(goal_names or G.ANOMALY_DETECTION_GOALS)
        #: anomaly.detection.allow.capacity.estimation
        self._allow_estimation = allow_capacity_estimation
        self._now = now_fn
        #: goal.violations.class
        self._anomaly_class = anomaly_class
        #: optional cruise_control_tpu.provisioner.Provisioner — violations
        #: no assignment can fix become an under-provisioned anomaly
        #: carrying the recommendation instead of a futile self-heal
        self._provisioner = provisioner
        #: callback(ProvisionRecommendation) — the app records the latest
        #: verdict for /state
        self._on_recommendation = on_recommendation

    def detect(self) -> Optional[GoalViolations]:
        from cruise_control_tpu.analyzer import goals as G
        from cruise_control_tpu.analyzer import objective as OBJ
        from cruise_control_tpu.common.resources import BalancingConstraint
        from cruise_control_tpu.monitor.load_monitor import NotEnoughValidWindowsError
        from cruise_control_tpu.ops.aggregates import (
            compute_aggregates, device_topology)
        import jax.numpy as jnp
        try:
            topo, assign = self._lm.cluster_model(now_ms=self._now())
        except NotEnoughValidWindowsError:
            return None
        if (not self._allow_estimation
                and self._lm.capacity_estimated_brokers):
            return None      # refuse to judge goals on estimated capacities
        dt = device_topology(topo)
        agg = compute_aggregates(dt, assign, topo.num_topics)
        th = G.compute_thresholds(dt, BalancingConstraint(), agg)
        pen = G.full_goal_penalties(dt, assign, th, topo.num_topics,
                                    self._goals,
                                    initial_broker_of=jnp.asarray(assign.broker_of),
                                    agg=agg)
        viol = np.asarray(pen.violations)
        violated = [g for i, g in enumerate(self._goals) if viol[i] > 0]
        if viol[-1] > 0:           # offline/self-healing term
            violated.append("OfflineReplicas")
        if not violated:
            return None
        unfixable: Set[str] = set()
        rec_dict = None
        if self._provisioner is not None:
            try:
                rec, _ = self._provisioner.recommend(topo, assign)
                unfixable = set(rec.unfixable_goals)
                rec_dict = rec.to_dict()
                if self._on_recommendation is not None:
                    self._on_recommendation(rec)
            except Exception:
                # a broken rightsizing pass must not swallow the violation
                # anomaly itself — self-healing still has to run
                logger.exception("provision recommendation failed; "
                                 "reporting all violations as fixable")
        return self._anomaly_class(
            AnomalyType.GOAL_VIOLATION, self._now(),
            fixable_violated_goals=[g for g in violated
                                    if g not in unfixable],
            unfixable_violated_goals=[g for g in violated
                                     if g in unfixable],
            provision_recommendation=rec_dict)


class DiskFailureDetector:
    """Diffs logdir liveness via a callback returning
    {broker_id: {logdir: alive}} (AdminClient describeLogDirs seam)."""

    def __init__(self, logdirs_fn: Callable[[], Dict[int, Dict[str, bool]]],
                 now_fn=_now_ms, anomaly_class: type = DiskFailures):
        self._logdirs_fn = logdirs_fn
        self._now = now_fn
        #: disk.failures.class
        self._anomaly_class = anomaly_class

    def detect(self) -> Optional[DiskFailures]:
        failed: Dict[int, List[str]] = {}
        for broker, dirs in self._logdirs_fn().items():
            dead = [d for d, ok in dirs.items() if not ok]
            if dead:
                failed[broker] = dead
        if failed:
            return self._anomaly_class(AnomalyType.DISK_FAILURE, self._now(),
                                       failed_disks_by_broker=failed)
        return None


def percentile_anomalies(history: np.ndarray, current: float,
                         upper_percentile: float = 95.0,
                         lower_percentile: float = 2.0,
                         upper_margin: float = 0.5,
                         lower_margin: float = 0.2) -> Optional[str]:
    """core PercentileMetricAnomalyFinder.java: current value beyond
    [P_low·(1−margin·…), P_high·(1+margin)] of its own history.

    Thin np wrapper over :func:`cruise_control_tpu.ops.stats.
    percentile_flags` (the jnp/vmappable implementation the provisioner's
    headroom logic shares). An empty or too-short history is NOT an
    anomaly — a zero-length percentile window is undefined, so the guard
    returns None before the kernel runs."""
    import jax.numpy as jnp
    from cruise_control_tpu.ops import stats as STATS
    history = np.asarray(history, dtype=np.float64)
    if history.size < 3:
        return None
    flags = STATS.percentile_flags(
        jnp.asarray(history, jnp.float32), jnp.float32(current),
        jnp.float32(upper_percentile), jnp.float32(lower_percentile),
        jnp.float32(upper_margin), jnp.float32(lower_margin))
    if bool(flags.above):
        return (f"value {current:.3f} above {upper_percentile:.0f}th "
                f"percentile {float(flags.upper):.3f} * "
                f"{1 + upper_margin:.2f}")
    if bool(flags.below):
        return (f"value {current:.3f} below {lower_percentile:.0f}th "
                f"percentile {float(flags.lower):.3f} * {lower_margin:.2f}")
    return None


class MetricAnomalyDetector:
    """Compares each broker's current metric value with its own history
    (MetricAnomalyDetector.java:29-72 + percentile finder)."""

    def __init__(self, broker_history_fn: Callable[[], Dict[int, Dict[str, np.ndarray]]],
                 metrics: Sequence[str] = ("cpu",), now_fn=_now_ms,
                 anomaly_class: type = MetricAnomaly, finder=None,
                 **finder_kw):
        self._history_fn = broker_history_fn
        self._metrics = metrics
        self._now = now_fn
        #: metric.anomaly.class
        self._anomaly_class = anomaly_class
        #: metric.anomaly.finder.class — the finder callable
        #: (history, current, **kw) -> description|None
        self._finder = finder or percentile_anomalies
        self._finder_kw = finder_kw

    def detect(self) -> List[MetricAnomaly]:
        out: List[MetricAnomaly] = []
        for broker, series in self._history_fn().items():
            for metric in self._metrics:
                vals = np.asarray(series.get(metric, ()))
                if vals.size < 4:
                    continue
                desc = self._finder(vals[:-1], float(vals[-1]),
                                    **self._finder_kw)
                if desc:
                    out.append(self._anomaly_class(
                        AnomalyType.METRIC_ANOMALY, self._now(),
                        broker_id=broker, metric=metric, description=desc))
        return out


class SlowBrokerFinder:
    """detector/SlowBrokerFinder.java:38-77: the derived metric
    log-flush-time × (1 / bytes-in) compared against the broker's own
    history and against peers; persistent slowness escalates demote →
    remove. History is supplied by a callback
    {broker: {"flush_time": [...], "bytes_in": [...]}}."""

    def __init__(self, broker_history_fn, self_history_margin: float = 1.5,
                 peer_margin: float = 2.0, score_threshold: int = 3,
                 removal_threshold: int = 6, now_fn=_now_ms):
        self._history_fn = broker_history_fn
        self._self_margin = self_history_margin
        self._peer_margin = peer_margin
        self._score_threshold = score_threshold
        self._removal_threshold = removal_threshold
        self._scores: Dict[int, int] = {}
        self._first_seen: Dict[int, int] = {}
        self._now = now_fn

    @staticmethod
    def _has_tail(series: dict) -> bool:
        ft999 = np.asarray(series.get("flush_time_999", ()), dtype=np.float64)
        return bool(ft999.size and np.nanmax(ft999) > 0)

    @staticmethod
    def _flush_series(series: dict, use_tail: bool) -> np.ndarray:
        """The flush-time series to score: the p99.9 tail gauge
        (``flush_time_999`` — what SlowBrokerFinder.java:38-77 reads) when
        the WHOLE fleet supplies it, else the mean. The choice is
        fleet-wide (``use_tail``): p99.9 runs 10-100x the mean, so mixing
        the two scales in one peer comparison (a rolling reporter upgrade)
        would flag every tail-scored broker against mean-scored peers."""
        if use_tail:
            return np.asarray(series.get("flush_time_999", ()),
                              dtype=np.float64)
        return np.asarray(series.get("flush_time", ()), dtype=np.float64)

    @classmethod
    def _slowness(cls, series: dict, use_tail: bool) -> Optional[float]:
        ft = cls._flush_series(series, use_tail)
        bi = np.asarray(series.get("bytes_in", ()), dtype=np.float64)
        if ft.size == 0 or bi.size == 0:
            return None
        s = float(ft[-1] / max(bi[-1], 1.0))
        # a broker without flush-time samples must not enter the peer pool:
        # one NaN would poison the peer median and mute detection entirely
        return None if np.isnan(s) else s

    def detect(self) -> Optional[SlowBrokers]:
        hist = self._history_fn()
        # tail metric only when EVERY broker reports it (comparable scales)
        use_tail = bool(hist) and all(self._has_tail(s)
                                      for s in hist.values())
        current: Dict[int, float] = {}
        for broker, series in hist.items():
            s = self._slowness(series, use_tail)
            if s is not None:
                current[broker] = s
        if len(current) < 2:
            return None
        values = np.asarray(list(current.values()))
        peer_median = float(np.median(values))
        now = self._now()
        slow_now: Set[int] = set()
        for broker, s in current.items():
            ft = self._flush_series(hist[broker], use_tail)
            bi = np.asarray(hist[broker].get("bytes_in", ()), dtype=np.float64)
            n = min(ft.size, bi.size)
            own_hist = ft[:n - 1] / np.maximum(bi[:n - 1], 1.0) if n > 1 else np.array([])
            own_hist = own_hist[~np.isnan(own_hist)]
            own_slow = (own_hist.size >= 3
                        and s > self._self_margin * float(np.mean(own_hist)))
            peer_slow = s > self._peer_margin * peer_median
            if own_slow and peer_slow:
                slow_now.add(broker)
        for b in slow_now:
            self._scores[b] = self._scores.get(b, 0) + 1
            self._first_seen.setdefault(b, now)
        for b in list(self._scores):
            if b not in slow_now:
                self._scores[b] -= 1
                if self._scores[b] <= 0:
                    del self._scores[b]
                    self._first_seen.pop(b, None)
        demote = {b: self._first_seen[b] for b, sc in self._scores.items()
                  if sc >= self._score_threshold}
        if not demote:
            return None
        remove = all(sc >= self._removal_threshold
                     for b, sc in self._scores.items() if b in demote)
        return SlowBrokers(AnomalyType.METRIC_ANOMALY, now,
                           slow_brokers_by_time=demote,
                           remove_slow_brokers=remove)


# ---------------------------------------------------------------------------
# AnomalyDetector service (detector/AnomalyDetector.java)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(order=True)
class _Queued:
    priority: int
    seq: int
    anomaly: Anomaly = dataclasses.field(compare=False)
    #: earliest handle time; anomalies deferred by an ongoing execution or a
    #: CHECK verdict re-enter the queue with a future ready_at
    #: (AnomalyDetector.java:391-404 re-check with delay)
    ready_at_ms: int = dataclasses.field(compare=False, default=0)


class AnomalyDetectorService:
    """Priority queue + scheduler + handler. Detector sweeps run on timers;
    the handler consults the notifier and triggers ``anomaly.fix(context)``
    for FIX verdicts, skipping while an execution is ongoing
    (AnomalyDetector.java:266-320, 391-404)."""

    def __init__(self, notifier: AnomalyNotifier,
                 context: Optional[SelfHealingContext] = None,
                 has_ongoing_execution: Callable[[], bool] = lambda: False,
                 detectors: Optional[Dict[str, Callable[[], object]]] = None,
                 interval_ms: int = 300_000,
                 intervals_ms: Optional[Dict[str, int]] = None,
                 recheck_delay_ms: Optional[int] = None,
                 num_cached_states: int = 20, now_fn=_now_ms,
                 heartbeat: Optional[Callable[[], None]] = None,
                 decision_sink: Optional[Callable[[dict], None]] = None):
        self.notifier = notifier
        self.context = context
        #: decision audit hook (the app routes this into the flight
        #: recorder): called with one dict per detector decision — fired,
        #: suppressed, deferred, re-check, or self-heal routed — carrying the
        #: triggering anomaly summary. Must not raise; None = no-op.
        self._decision_sink = decision_sink or (lambda payload: None)
        #: watchdog heartbeat: checked into on every sweep so a wedged or
        #: dead detector loop is restartable by the supervisor
        self._heartbeat = heartbeat or (lambda: None)
        self._started = False
        self._has_exec = has_ongoing_execution
        self.detectors = detectors or {}
        self.interval_ms = interval_ms
        #: per-detector schedule overrides (the reference schedules each
        #: finder at its own rate, AnomalyDetector.java:167-180); a detector
        #: without an override runs every ``interval_ms`` sweep.
        self.intervals_ms = {k: v for k, v in (intervals_ms or {}).items()
                             if v is not None}
        self._next_due: Dict[str, int] = {}
        #: how long a deferred anomaly waits before its re-check
        self.recheck_delay_ms = (recheck_delay_ms if recheck_delay_ms is not None
                                 else interval_ms)
        #: num.cached.recent.anomaly.states — history depth in state snapshots
        self.num_cached_states = num_cached_states
        self._queue: List[_Queued] = []
        self._seq = 0
        self._lock = threading.RLock()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._now = now_fn
        self.history: List[dict] = []
        self.metrics = {"anomalies_detected": 0, "fixes_triggered": 0,
                        "fixes_failed": 0, "ignored": 0, "checks": 0,
                        "detector_failures": 0}
        #: per-detector failure tally (one misbehaving detector must be
        #: visible in /state, not just a log line)
        self.detector_failures: Dict[str, int] = {}

    # -- queue --
    @staticmethod
    def _same_target(a: Anomaly, b: Anomaly) -> bool:
        if isinstance(a, MetricAnomaly) and isinstance(b, MetricAnomaly):
            return a.broker_id == b.broker_id and a.metric == b.metric
        return True

    def enqueue(self, anomaly: Anomaly):
        with self._lock:
            # A fresh detection supersedes a queued/deferred anomaly of the
            # same kind — detector payloads carry the full current state
            # (e.g. failed_brokers_by_time), so the newest wins and the queue
            # can't accumulate one entry per sweep for a persistent
            # condition. The superseding entry INHERITS the displaced entry's
            # re-check time, so a CHECK/execution deferral delay is honored
            # even though the condition is re-detected every sweep.
            displaced_ready = 0
            kept = []
            for q in self._queue:
                if (type(q.anomaly) is type(anomaly)
                        and self._same_target(q.anomaly, anomaly)):
                    displaced_ready = max(displaced_ready, q.ready_at_ms)
                else:
                    kept.append(q)
            if len(kept) != len(self._queue):
                self._queue = kept
                heapq.heapify(self._queue)
            heapq.heappush(self._queue, _Queued(
                anomaly.anomaly_type.priority, self._seq, anomaly,
                ready_at_ms=displaced_ready))
            self._seq += 1
            self.metrics["anomalies_detected"] += 1
            from cruise_control_tpu.common.metrics import REGISTRY
            REGISTRY.counter(
                f"anomaly-rate-{anomaly.anomaly_type.value.lower()}")

    def sweep(self) -> int:
        """One detection pass over the detectors that are due. A detector
        runs at its override interval when configured, else every
        ``interval_ms`` (due-tracked, so the loop may tick faster)."""
        n = 0
        self._heartbeat()
        now = self._now()
        for name, det in self.detectors.items():
            interval = self.intervals_ms.get(name, self.interval_ms)
            if now < self._next_due.get(name, -10**15):
                continue
            self._next_due[name] = now + interval
            try:
                found = det()
            except Exception:
                # one raising detector must not stop the sweep: the others
                # still run (AnomalyDetector.java keeps its scheduled tasks
                # independent), and the failure is logged + counted
                logger.warning("anomaly detector %r raised; continuing the "
                               "sweep", name, exc_info=True)
                with self._lock:
                    self.metrics["detector_failures"] += 1
                    self.detector_failures[name] = (
                        self.detector_failures.get(name, 0) + 1)
                from cruise_control_tpu.common.metrics import REGISTRY
                REGISTRY.counter("anomaly-detector-error-rate")
                continue
            if found is None:
                continue
            for a in (found if isinstance(found, list) else [found]):
                self.enqueue(a)
                self._decision_sink({"decision": "fired", "detector": name,
                                     "anomaly": a.summary()})
                n += 1
        return n

    def handle_pending(self) -> int:
        """Drain the ready queue through the notifier (AnomalyHandlerTask).

        Anomalies arriving while an execution is in progress are NOT dropped:
        they re-enter the queue with a delayed ``ready_at_ms`` and are
        re-checked once the delay elapses (AnomalyDetector.java:391-404).
        CHECK verdicts requeue the anomaly with the notifier's delay.
        """
        handled = 0
        now = self._now()
        deferred: List[_Queued] = []
        while True:
            with self._lock:
                if not self._queue:
                    break
                item = heapq.heappop(self._queue)
            a = item.anomaly
            if item.ready_at_ms > now:
                deferred.append(item)     # not due yet — hold for re-push
                continue
            if self._has_exec():
                with self._lock:
                    self.metrics["checks"] += 1
                    self.history.append({"anomaly": a.summary(),
                                         "action": "DELAYED_ONGOING_EXECUTION"})
                deferred.append(dataclasses.replace(
                    item, ready_at_ms=now + self.recheck_delay_ms))
                self._decision_sink({"decision": "deferred",
                                     "reason": "ongoing-execution",
                                     "anomaly": a.summary()})
                continue
            # the notifier callback and the fix itself run OUTSIDE the lock
            # (they hit the adapter); only the tally/history mutations — which
            # /state readers race against — take it
            result = self.notifier.on_anomaly(a)
            record = {"anomaly": a.summary(), "action": result.action.value}
            if result.action == AnomalyAction.FIX and self.context is not None:
                try:
                    fix_result = a.fix(self.context)
                    record["fixResult"] = bool(fix_result)
                    with self._lock:
                        self.metrics["fixes_triggered"] += 1
                    from cruise_control_tpu.common.metrics import REGISTRY
                    REGISTRY.counter("self-healing-fix-rate")
                except Exception as e:   # fix failures must not kill the loop
                    logger.warning("self-healing fix for %s failed",
                                   a.anomaly_type.value, exc_info=True)
                    record["fixError"] = str(e)
                    with self._lock:
                        self.metrics["fixes_failed"] += 1
            elif result.action == AnomalyAction.IGNORE:
                with self._lock:
                    self.metrics["ignored"] += 1
            else:
                with self._lock:
                    self.metrics["checks"] += 1
                if result.delay_ms > 0:   # CHECK with delay → re-check later
                    deferred.append(dataclasses.replace(
                        item, ready_at_ms=now + result.delay_ms))
            with self._lock:
                self.history.append(record)
            # audit the verdict itself, not just the resulting optimization:
            # FIX = self-heal routed, IGNORE = suppressed, CHECK = re-check
            decision = {AnomalyAction.FIX: "self-heal",
                        AnomalyAction.IGNORE: "suppressed"}.get(
                            result.action, "recheck")
            self._decision_sink({"decision": decision, **record})
            handled += 1
        with self._lock:
            for item in deferred:
                heapq.heappush(self._queue, item)
        return handled

    # -- service loop --
    def start(self):
        self._started = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="anomaly-detector")
        self._thread.start()

    def shutdown(self):
        self._started = False
        self._shutdown.set()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def supervised(self) -> bool:
        """True while the service loop is supposed to be running — the
        watchdog only judges (and restarts) the thread in this window."""
        return self._started and not self._shutdown.is_set()

    def restart(self) -> None:
        """Watchdog restart hook: re-spawn the service loop if its thread
        died (an escaped exception) while the service should be running."""
        if not self.supervised:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="anomaly-detector")
        self._thread.start()

    def _run(self):
        # wake at the FASTEST configured cadence so a per-detector interval
        # shorter than anomaly.detection.interval.ms actually takes effect;
        # sweep() gates each detector on its own due time
        tick_ms = min([self.interval_ms] + list(self.intervals_ms.values()))
        while not self._shutdown.wait(tick_ms / 1000.0):
            self.sweep()
            self.handle_pending()

    def state_snapshot(self) -> dict:
        with self._lock:
            return {
                "selfHealingEnabled": {
                    t.value: v for t, v in
                    self.notifier.self_healing_enabled().items()},
                "recentAnomalies": self.history[-self.num_cached_states:],
                "metrics": dict(self.metrics),
                "queuedAnomalies": len(self._queue),
                "detectorFailures": dict(self.detector_failures),
            }


#: ``metric.anomaly.finder.class`` registry (MetricAnomalyFinder SPI):
#: callables (history, current, **kw) -> description | None.
METRIC_ANOMALY_FINDER_REGISTRY = {
    "PercentileMetricAnomalyFinder": percentile_anomalies,
}
