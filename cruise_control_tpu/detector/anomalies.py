"""Anomaly types and the self-healing notifier.

Mirrors ``detector/*.java`` payloads (BrokerFailures, GoalViolations,
DiskFailures, KafkaMetricAnomaly, SlowBrokers — each with a ``fix()`` that
dispatches the corresponding operation) and the ``AnomalyNotifier`` SPI with
``SelfHealingNotifier`` semantics (``detector/notifier/SelfHealingNotifier.java:24-128``):
per-type self-healing enable flags, broker-failure alert and self-healing
thresholds, and IGNORE / CHECK(delay) / FIX verdicts.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Dict, List, Optional, Protocol, Sequence, Set


class AnomalyType(enum.Enum):
    GOAL_VIOLATION = "GOAL_VIOLATION"
    BROKER_FAILURE = "BROKER_FAILURE"
    METRIC_ANOMALY = "METRIC_ANOMALY"
    DISK_FAILURE = "DISK_FAILURE"
    TOPIC_ANOMALY = "TOPIC_ANOMALY"

    @property
    def priority(self) -> int:
        # detector/AnomalyType priority: lower = handled first
        return {"BROKER_FAILURE": 0, "DISK_FAILURE": 1, "METRIC_ANOMALY": 2,
                "GOAL_VIOLATION": 3, "TOPIC_ANOMALY": 4}[self.value]


class AnomalyAction(enum.Enum):
    IGNORE = "IGNORE"
    CHECK = "CHECK"
    FIX = "FIX"


@dataclasses.dataclass
class NotifierResult:
    action: AnomalyAction
    delay_ms: int = 0


class SelfHealingContext(Protocol):
    """What an anomaly fix needs from the service facade: the async
    runnables' surface (rebalance / remove / demote / fix offline)."""

    def rebalance(self, self_healing: bool = True, **kw) -> dict: ...
    def remove_brokers(self, broker_ids: Sequence[int],
                       self_healing: bool = True, **kw) -> dict: ...
    def demote_brokers(self, broker_ids: Sequence[int],
                       self_healing: bool = True, **kw) -> dict: ...
    def fix_offline_replicas(self, self_healing: bool = True, **kw) -> dict: ...


@dataclasses.dataclass
class Anomaly:
    """Base anomaly (core detector/Anomaly.java)."""

    anomaly_type: AnomalyType
    detection_time_ms: int
    anomaly_id: str = ""

    def __post_init__(self):
        if not self.anomaly_id:
            self.anomaly_id = f"{self.anomaly_type.value}-{self.detection_time_ms}"

    def fix(self, context: SelfHealingContext) -> Optional[dict]:
        raise NotImplementedError

    def summary(self) -> dict:
        return {"type": self.anomaly_type.value, "id": self.anomaly_id,
                "detectionMs": self.detection_time_ms}


@dataclasses.dataclass
class BrokerFailures(Anomaly):
    """detector/BrokerFailures.java — fix = remove the failed brokers."""

    failed_brokers_by_time: Dict[int, int] = dataclasses.field(default_factory=dict)

    def fix(self, context):
        return context.remove_brokers(sorted(self.failed_brokers_by_time),
                                      self_healing=True)

    def summary(self):
        return {**super().summary(),
                "failedBrokers": self.failed_brokers_by_time}


@dataclasses.dataclass
class GoalViolations(Anomaly):
    """detector/GoalViolations.java — fix = self-healing rebalance."""

    fixable_violated_goals: List[str] = dataclasses.field(default_factory=list)
    unfixable_violated_goals: List[str] = dataclasses.field(default_factory=list)
    #: provisioner verdict when the detector decided some violations are
    #: unfixable by any assignment (ProvisionRecommendation.to_dict())
    provision_recommendation: Optional[dict] = None

    def fix(self, context):
        if not self.fixable_violated_goals:
            return None
        return context.rebalance(self_healing=True)

    def summary(self):
        s = {**super().summary(),
             "fixableViolatedGoals": self.fixable_violated_goals,
             "unfixableViolatedGoals": self.unfixable_violated_goals}
        if self.provision_recommendation is not None:
            s["provisionRecommendation"] = self.provision_recommendation
        return s


@dataclasses.dataclass
class DiskFailures(Anomaly):
    """detector/DiskFailures.java — fix = move replicas off dead disks."""

    failed_disks_by_broker: Dict[int, List[str]] = dataclasses.field(
        default_factory=dict)

    def fix(self, context):
        return context.fix_offline_replicas(self_healing=True)

    def summary(self):
        return {**super().summary(), "failedDisks": self.failed_disks_by_broker}


@dataclasses.dataclass
class MetricAnomaly(Anomaly):
    """detector/KafkaMetricAnomaly.java — broker metric out of history band."""

    broker_id: int = -1
    metric: str = ""
    description: str = ""

    def fix(self, context):
        return None           # metric anomalies alert; no automatic fix

    def summary(self):
        return {**super().summary(), "broker": self.broker_id,
                "metric": self.metric, "description": self.description}


@dataclasses.dataclass
class SlowBrokers(Anomaly):
    """detector/SlowBrokers.java — demote, or remove when persistent."""

    slow_brokers_by_time: Dict[int, int] = dataclasses.field(default_factory=dict)
    remove_slow_brokers: bool = False

    def fix(self, context):
        ids = sorted(self.slow_brokers_by_time)
        if self.remove_slow_brokers:
            return context.remove_brokers(ids, self_healing=True)
        return context.demote_brokers(ids, self_healing=True)

    def summary(self):
        return {**super().summary(), "slowBrokers": self.slow_brokers_by_time,
                "remove": self.remove_slow_brokers}


#: Pluggable anomaly payload classes (AnomalyDetectorConfig's
#: ``broker.failures.class`` / ``goal.violations.class`` /
#: ``disk.failures.class`` / ``metric.anomaly.class``): register a subclass
#: here and select it by name in the config; detectors construct whatever
@dataclasses.dataclass
class SLOBurnAnomaly(Anomaly):
    """graftwatch SLO burn-rate alert (obs/healthwatch.py) — the service
    itself is degrading (tick SLO, hard violations, fallbacks) faster
    than its error budget allows.  Alert-only: the anomaly detector's
    self-healing already owns the fixes for the underlying causes."""

    rule: str = ""
    signal: str = ""
    burn_fast: float = 0.0
    burn_slow: float = 0.0

    def fix(self, context):
        return None           # burn alerts page; healing stays with fixes

    def summary(self):
        return {**super().summary(), "rule": self.rule,
                "signal": self.signal, "burnFast": self.burn_fast,
                "burnSlow": self.burn_slow}


#: class the config resolved.
ANOMALY_CLASS_REGISTRY: Dict[str, type] = {
    "BrokerFailures": BrokerFailures,
    "GoalViolations": GoalViolations,
    "DiskFailures": DiskFailures,
    "MetricAnomaly": MetricAnomaly,
    "KafkaMetricAnomaly": MetricAnomaly,    # reference default's name
    "SlowBrokers": SlowBrokers,
    "SLOBurnAnomaly": SLOBurnAnomaly,
}


def resolve_anomaly_class(name: str, base: type) -> type:
    """Config class name → registered payload class; must subclass ``base``
    (the built-in payload it replaces) so detector/notifier plumbing holds."""
    cls = ANOMALY_CLASS_REGISTRY.get(name)
    if cls is None:
        raise ValueError(
            f"unknown anomaly class {name!r}; register it in "
            f"ANOMALY_CLASS_REGISTRY (have: {sorted(ANOMALY_CLASS_REGISTRY)})")
    if not issubclass(cls, base):
        raise ValueError(f"{name} must subclass {base.__name__}")
    return cls


# ---------------------------------------------------------------------------
# Notifiers
# ---------------------------------------------------------------------------


class AnomalyNotifier:
    """SPI: decide what to do about an anomaly (AnomalyNotifier.java)."""

    def on_anomaly(self, anomaly: Anomaly) -> NotifierResult:
        raise NotImplementedError

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool):
        pass


class SelfHealingNotifier(AnomalyNotifier):
    """detector/notifier/SelfHealingNotifier.java:50-128.

    Broker failures: alert after ``broker_failure_alert_threshold_ms``,
    self-heal after ``self_healing_threshold_ms`` (CHECK with delay until
    then). Other anomaly types: FIX immediately when enabled, IGNORE
    otherwise.
    """

    def __init__(self, broker_failure_alert_threshold_ms: int = 900_000,
                 self_healing_threshold_ms: int = 1_800_000,
                 enabled: Optional[Dict[AnomalyType, bool]] = None,
                 now_fn=lambda: int(time.time() * 1000)):
        self.alert_threshold_ms = broker_failure_alert_threshold_ms
        self.self_healing_threshold_ms = self_healing_threshold_ms
        self._enabled = {t: False for t in AnomalyType}
        if enabled:
            self._enabled.update(enabled)
        self._now = now_fn
        self.alerts: List[dict] = []

    def self_healing_enabled(self):
        return dict(self._enabled)

    def set_self_healing_for(self, anomaly_type, enabled):
        self._enabled[anomaly_type] = bool(enabled)

    def alert(self, anomaly: Anomaly, auto_fix_triggered: bool):
        self.alerts.append({"anomaly": anomaly.summary(),
                            "autoFixTriggered": auto_fix_triggered,
                            "time": self._now()})

    def on_anomaly(self, anomaly: Anomaly) -> NotifierResult:
        if isinstance(anomaly, BrokerFailures):
            return self._on_broker_failure(anomaly)
        if not self._enabled.get(anomaly.anomaly_type, False):
            return NotifierResult(AnomalyAction.IGNORE)
        self.alert(anomaly, auto_fix_triggered=True)
        return NotifierResult(AnomalyAction.FIX)

    def _on_broker_failure(self, anomaly: BrokerFailures) -> NotifierResult:
        now = self._now()
        if not anomaly.failed_brokers_by_time:
            return NotifierResult(AnomalyAction.IGNORE)
        earliest = min(anomaly.failed_brokers_by_time.values())
        alert_time = earliest + self.alert_threshold_ms
        fix_time = earliest + self.self_healing_threshold_ms
        enabled = self._enabled.get(AnomalyType.BROKER_FAILURE, False)
        if now < alert_time:
            return NotifierResult(AnomalyAction.CHECK, delay_ms=alert_time - now)
        if now < fix_time:
            self.alert(anomaly, auto_fix_triggered=False)
            if enabled:
                return NotifierResult(AnomalyAction.CHECK, delay_ms=fix_time - now)
            return NotifierResult(AnomalyAction.IGNORE)
        if enabled:
            self.alert(anomaly, auto_fix_triggered=True)
            return NotifierResult(AnomalyAction.FIX)
        self.alert(anomaly, auto_fix_triggered=False)
        return NotifierResult(AnomalyAction.IGNORE)


class SlackSelfHealingNotifier(SelfHealingNotifier):
    """notifier/SlackSelfHealingNotifier.java — posts alerts to a webhook.
    The HTTP post is injectable (and a no-op by default in offline envs)."""

    def __init__(self, webhook_url: str = "", channel: str = "",
                 post_fn=None, **kw):
        super().__init__(**kw)
        self.webhook_url = webhook_url
        self.channel = channel
        self._post = post_fn or (lambda url, payload: None)

    def alert(self, anomaly, auto_fix_triggered):
        super().alert(anomaly, auto_fix_triggered)
        if self.webhook_url:
            self._post(self.webhook_url, {
                "channel": self.channel,
                "text": f"[cruise-control-tpu] {anomaly.summary()} "
                        f"autoFix={auto_fix_triggered}"})


#: ``anomaly.notifier.class`` registry (AnomalyNotifier SPI); dotted import
#: paths also resolve via common.config.resolve_pluggable.
NOTIFIER_REGISTRY = {
    "SelfHealingNotifier": SelfHealingNotifier,
    "SlackSelfHealingNotifier": SlackSelfHealingNotifier,
}
