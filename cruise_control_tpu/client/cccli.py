"""``cccli`` — console client for the REST API.

Rebuild of the reference's Python client
(``cruise-control-client/cruisecontrolclient/client/cccli.py:135-225``):
the argparse tree is generated from endpoint + parameter metadata
(mirroring ``client/Endpoint.py:158-454`` and the ``CCParameter`` classes),
requests go through a small Responder layer, async operations poll with the
returned User-Task-ID.

Usage::

    cccli -a host:9090 rebalance --dryrun true
    cccli -a host:9090 remove_broker --brokers 3,4 --dryrun false
    cccli -a host:9090 state
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Parameter:
    """Typed query parameter (client/CCParameter/*.py)."""

    name: str                     # query-parameter name
    flag: str                     # CLI flag
    type: str = "string"          # string | bool | int | csv | csv-int
    help: str = ""

    def validate(self, value: str) -> str:
        if self.type == "bool":
            if value.lower() not in ("true", "false"):
                raise ValueError(f"--{self.flag} must be true|false")
            return value.lower()
        if self.type == "int":
            int(value)
            return value
        if self.type == "csv-int":
            [int(x) for x in value.split(",") if x]
            return value
        return value


_COMMON = [
    Parameter("reason", "reason", "string", "Reason for the request"),
    Parameter("get_response_timeout_ms", "timeout-ms", "int",
              "How long to wait before returning in-progress"),
]
_DRYRUN = Parameter("dryrun", "dryrun", "bool",
                    "true = propose only (default), false = execute")
_BROKERS = Parameter("brokerid", "brokers", "csv-int",
                     "Comma-separated broker ids")
_GOALS = Parameter("goals", "goals", "csv", "Goal list in priority order")

#: GoalBasedOptimizationParameters shared by every optimization request
_GOAL_BASED = (
    Parameter("data_from", "data-from", "string",
              "VALID_WINDOWS | VALID_PARTITIONS"),
    Parameter("use_ready_default_goals", "use-ready-default-goals", "bool"),
    Parameter("exclude_recently_removed_brokers",
              "exclude-recently-removed-brokers", "bool"),
    Parameter("exclude_recently_demoted_brokers",
              "exclude-recently-demoted-brokers", "bool"),
    Parameter("skip_hard_goal_check", "skip-hard-goal-check", "bool"),
    Parameter("allow_capacity_estimation", "allow-capacity-estimation",
              "bool"),
    Parameter("min_valid_partition_ratio", "min-valid-partition-ratio",
              "string", "Per-request completeness ratio override"),
    Parameter("verbose", "verbose", "bool"),
)
#: per-request executor overrides
_EXECUTOR = (
    Parameter("concurrent_leader_movements", "leader-concurrency", "int"),
    Parameter("execution_progress_check_interval_ms",
              "progress-check-interval-ms", "int"),
    Parameter("replication_throttle", "replication-throttle", "int"),
    Parameter("replica_movement_strategies", "movement-strategies", "csv"),
)


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """REST endpoint metadata (client/Endpoint.py)."""

    name: str                     # CLI subcommand and URL path
    method: str                   # GET | POST
    help: str
    parameters: Tuple[Parameter, ...] = ()
    is_async: bool = False


ENDPOINTS: List[Endpoint] = [
    Endpoint("state", "GET", "Cruise Control substates", (
        Parameter("substates", "substates", "csv",
                  "monitor,analyzer,executor,anomaly_detector"),
        Parameter("super_verbose", "super-verbose", "bool",
                  "Include sample-extrapolation flaws and CPU model state"),)),
    Endpoint("kafka_cluster_state", "GET", "Kafka cluster state", (
        Parameter("populate_disk_info", "populate-disk-info", "bool"),)),
    Endpoint("metrics", "GET",
             "Service sensors (timers/meters/gauges snapshot)"),
    Endpoint("explain", "GET",
             "Per-move goal attribution of the cached proposal", (
        Parameter("partition", "partition", "string",
                  "Filter to one topic-partition, e.g. topic3-14"),)),
    Endpoint("flightrecorder", "GET", "Tick flight-recorder export", (
        Parameter("format", "format", "string",
                  "json = wrapped records + ring summary "
                  "(default: canonical JSONL)"),)),
    Endpoint("alerts", "GET",
             "graftwatch burn-rate alerts: active alerts, rule registry, "
             "fire/suppress/resolve counts and decision history", (
                 Parameter("history", "history", "int",
                           "How many recent alert decisions to include "
                           "(default 64)"),)),
    Endpoint("headroom", "GET",
             "graftwatch headroom forecast: device memory in use and "
             "whether the next bucket-ladder step fits", ()),
    Endpoint("load", "GET", "Per-broker load", (
        Parameter("time", "time", "int", "Load as of this epoch ms"),)),
    Endpoint("partition_load", "GET", "Top partition loads", (
        Parameter("resource", "resource", "string", "cpu|disk|network_inbound|network_outbound"),
        Parameter("entries", "entries", "int", "Number of records"),
        Parameter("partition", "partition", "string", "Partition id or range N-M"),
        Parameter("topic", "topic", "string", "Topic regex"),
        Parameter("brokerid", "brokers", "csv-int", "Leader broker filter"),
        Parameter("max_load", "max-load", "bool",
                  "Report max-window load instead of the average"),
        Parameter("avg_load", "avg-load", "bool",
                  "Force the average even when max-load is set"),)),
    Endpoint("proposals", "GET", "Optimization proposals", (
        _GOALS,
        Parameter("ignore_proposal_cache", "ignore-proposal-cache", "bool"),
        Parameter("kafka_assigner", "kafka-assigner", "bool",
                  "Kafka-assigner mode"),
        *_GOAL_BASED), is_async=True),
    Endpoint("user_tasks", "GET", "Active/completed user tasks", (
        Parameter("user_task_ids", "task-ids", "csv"),
        Parameter("client_ids", "client-ids", "csv"),
        Parameter("endpoints", "endpoints", "csv"),
        Parameter("types", "types", "csv", "active,completed"),
        Parameter("fetch_completed_task", "fetch-completed-task", "bool"),)),
    Endpoint("review_board", "GET", "Two-step review board", (
        Parameter("review_ids", "review-ids", "csv-int"),)),
    Endpoint("bootstrap", "GET", "Replay a historical sample range", (
        Parameter("start", "start", "int", "Range start ms"),
        Parameter("end", "end", "int", "Range end ms"),), is_async=True),
    Endpoint("train", "GET", "Train the CPU estimation model", (
        Parameter("start", "start", "int"), Parameter("end", "end", "int"),
        Parameter("clearmetrics", "clearmetrics", "bool",
                  "Clear previous training samples (default true)"),)),
    Endpoint("what_if", "GET", "Score counterfactual scenarios", (
        Parameter("add_brokers", "add-brokers", "csv-int",
                  "Broker counts to add (one scenario per count)"),
        Parameter("add_broker_rack", "add-broker-rack", "string",
                  "Rack for added brokers (default: one new rack each)"),
        Parameter("remove_broker_ids", "remove-brokers", "csv-int",
                  "Broker ids to remove (one combined scenario)"),
        Parameter("fail_racks", "fail-racks", "csv",
                  "Racks to fail (one scenario per rack)"),
        Parameter("scale_capacity", "scale-capacity", "csv",
                  "resource:factor pairs, e.g. disk:0.5,cpu:1.5"),
        Parameter("add_partitions", "add-partitions", "csv",
                  "topic:count pairs"),
        Parameter("deep", "deep", "bool",
                  "Anneal each scenario for a post-rebalance estimate"),
        Parameter("headroom_margin", "headroom-margin", "string",
                  "Capacity headroom fraction (0..1)"),
        Parameter("allow_capacity_estimation",
                  "allow-capacity-estimation", "bool"),
        Parameter("data_from", "data-from", "string"),), is_async=True),
    Endpoint("rebalance", "POST", "Rebalance the cluster", (
        _DRYRUN, _GOALS,
        Parameter("excluded_topics", "excluded-topics", "csv"),
        Parameter("destination_broker_ids", "destination-brokers", "csv-int"),
        Parameter("concurrent_partition_movements_per_broker",
                  "concurrency", "int"),
        Parameter("rebalance_disk", "rebalance-disk", "bool",
                  "Intra-broker (JBOD) disk rebalance"),
        Parameter("kafka_assigner", "kafka-assigner", "bool",
                  "Kafka-assigner mode"),
        *_GOAL_BASED, *_EXECUTOR), is_async=True),
    Endpoint("add_broker", "POST", "Move load onto new brokers",
             (_BROKERS, _DRYRUN,
              Parameter("kafka_assigner", "kafka-assigner", "bool",
                        "Kafka-assigner mode"),
              Parameter("throttle_added_broker", "throttle", "int"),
              *[p for p in _GOAL_BASED if p.name != "skip_hard_goal_check"],
              *_EXECUTOR), is_async=True),
    Endpoint("remove_broker", "POST", "Drain brokers",
             (_BROKERS, _DRYRUN,
              Parameter("kafka_assigner", "kafka-assigner", "bool",
                        "Kafka-assigner mode"),
              Parameter("throttle_removed_broker", "throttle", "int"),
              *[p for p in _GOAL_BASED if p.name != "skip_hard_goal_check"],
              *_EXECUTOR), is_async=True),
    Endpoint("demote_broker", "POST", "Move leadership off brokers",
             (_BROKERS, _DRYRUN,
              Parameter("brokerid_and_logdirs", "broker-logdirs", "csv",
                        "Demote disks: brokerId-logdir pairs"),
              Parameter("skip_urp_demotion", "skip-urp-demotion", "bool"),
              Parameter("exclude_follower_demotion",
                        "exclude-follower-demotion", "bool"),
              Parameter("data_from", "data-from", "string"),
              Parameter("exclude_recently_demoted_brokers",
                        "exclude-recently-demoted-brokers", "bool"),
              Parameter("allow_capacity_estimation",
                        "allow-capacity-estimation", "bool"),
              Parameter("min_valid_partition_ratio",
                        "min-valid-partition-ratio", "string",
                        "Per-request completeness ratio override"),
              Parameter("verbose", "verbose", "bool"),
              *_EXECUTOR), is_async=True),
    Endpoint("fix_offline_replicas", "POST", "Self-heal offline replicas",
             (_DRYRUN,
              *[p for p in _GOAL_BASED if p.name != "skip_hard_goal_check"],
              *_EXECUTOR), is_async=True),
    Endpoint("stop_proposal_execution", "POST", "Stop the ongoing execution", (
        Parameter("force_stop", "force", "bool"),)),
    Endpoint("pause_sampling", "POST", "Pause metric sampling"),
    Endpoint("resume_sampling", "POST", "Resume metric sampling"),
    Endpoint("admin", "POST", "Runtime admin toggles", (
        Parameter("self_healing_for", "enable-self-healing-for", "string",
                  "Anomaly type or ALL"),
        Parameter("disable_self_healing_for", "disable-self-healing-for",
                  "string"),
        Parameter("enable_self_healing", "enable-self-healing", "bool"),
        Parameter("concurrent_partition_movements_per_broker",
                  "concurrency", "int"),
        Parameter("concurrent_leader_movements", "leader-concurrency", "int"),
        Parameter("concurrent_intra_broker_partition_movements",
                  "intra-broker-concurrency", "int"),
        Parameter("execution_progress_check_interval_ms",
                  "progress-check-interval-ms", "int"),
        Parameter("drop_recently_removed_brokers",
                  "drop-recently-removed-brokers", "bool"),
        Parameter("drop_recently_demoted_brokers",
                  "drop-recently-demoted-brokers", "bool"),)),
    Endpoint("review", "POST", "Approve/discard review requests", (
        Parameter("approve", "approve", "csv-int"),
        Parameter("discard", "discard", "csv-int"),)),
    Endpoint("rightsize", "POST", "Rightsizing recommendation", (
        Parameter("headroom_margin", "headroom-margin", "string",
                  "Capacity headroom fraction (0..1)"),
        Parameter("max_added_brokers", "max-added-brokers", "int"),
        Parameter("max_removed_brokers", "max-removed-brokers", "int"),
        Parameter("deep", "deep", "bool",
                  "Anneal each candidate for a post-rebalance estimate"),
        Parameter("verbose", "verbose", "bool",
                  "Include the full what-if grid"),
        Parameter("allow_capacity_estimation",
                  "allow-capacity-estimation", "bool"),
        Parameter("data_from", "data-from", "string"),), is_async=True),
    Endpoint("topic_configuration", "POST", "Change topic replication factor", (
        Parameter("topic", "topic", "string", "Topic regex"),
        Parameter("replication_factor", "replication-factor", "int"),
        Parameter("skip_rack_awareness_check", "skip-rack-awareness-check",
                  "bool", "Allow RF above the alive-rack count"),
        _DRYRUN,), is_async=True),
]


class Responder:
    """HTTP layer (client/Responder.py): issue the request, poll async
    operations with the returned User-Task-ID until done."""

    def __init__(self, address: str, prefix: str = "/kafkacruisecontrol",
                 poll_interval_s: float = 1.0, max_polls: int = 600):
        if "://" not in address:
            address = f"http://{address}"
        self.base = address.rstrip("/") + prefix
        self.poll_interval_s = poll_interval_s
        self.max_polls = max_polls

    def _request(self, method: str, path: str, params: Dict[str, str]
                 ) -> Tuple[int, dict]:
        qs = urllib.parse.urlencode(params)
        url = f"{self.base}/{path}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, method=method,
                                     data=b"" if method == "POST" else None)
        try:
            with urllib.request.urlopen(req) as r:
                raw = r.read()
                try:
                    return r.status, json.loads(raw)
                except ValueError:
                    # text endpoints (/flightrecorder JSONL, prometheus
                    # scrapes) — hand the body through verbatim
                    return r.status, {"text": raw.decode()}
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:
                return e.code, {"errorMessage": str(e)}

    def run(self, endpoint: Endpoint, params: Dict[str, str]) -> Tuple[int, dict]:
        code, body = self._request(endpoint.method, endpoint.name, params)
        polls = 0
        while (endpoint.is_async and code == 202 and "userTaskId" in body
               and polls < self.max_polls):
            time.sleep(self.poll_interval_s)
            polls += 1
            code, body = self._request(
                endpoint.method, endpoint.name,
                {**params, "user_task_id": body["userTaskId"]})
        return code, body


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cccli", description="Cruise Control TPU client")
    parser.add_argument("-a", "--address", required=True,
                        help="host:port of the Cruise Control server")
    parser.add_argument("--prefix", default="/kafkacruisecontrol")
    parser.add_argument("--poll-interval", type=float, default=1.0)
    sub = parser.add_subparsers(dest="endpoint", required=True)
    for ep in ENDPOINTS:
        p = sub.add_parser(ep.name, help=ep.help)
        for param in tuple(ep.parameters) + tuple(_COMMON):
            p.add_argument(f"--{param.flag}", dest=f"param_{param.name}",
                           help=param.help)
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    ep = next(e for e in ENDPOINTS if e.name == args.endpoint)
    by_name = {p.name: p for p in tuple(ep.parameters) + tuple(_COMMON)}
    params: Dict[str, str] = {}
    for key, value in vars(args).items():
        if key.startswith("param_") and value is not None:
            name = key[len("param_"):]
            params[name] = by_name[name].validate(value)
    responder = Responder(args.address, args.prefix, args.poll_interval)
    code, body = responder.run(ep, params)
    if isinstance(body, dict) and set(body) == {"text"}:
        print(body["text"], end="")
    else:
        print(json.dumps(body, indent=2, default=str))
    return 0 if code < 400 else 1


if __name__ == "__main__":
    sys.exit(main())
