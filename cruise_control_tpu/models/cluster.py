"""Array-resident cluster model (struct-of-arrays).

TPU-native mirror of the reference's object graph ``ClusterModel`` →
``Rack``/``Host``/``Broker``/``Disk`` → ``Partition``/``Replica``/``Load``
(``cruise-control/.../model/ClusterModel.java``). Instead of a mutable object
tree, the model is split into:

- :class:`ClusterTopology` — everything immutable during an optimization:
  broker topology (rack/host ids, capacities, liveness), the partition/replica
  index structure, and loads.

- :class:`Assignment` — the decision variables: ``broker_of`` (replica →
  broker) and ``leader_of`` (partition → leader replica index).

Load representation. Every replica carries a *base* (follower-role) load vector
``replica_base_load[R, 4]``; the extra load carried by whichever replica
currently leads is partition-intrinsic: ``leader_extra[P, 4]`` with nonzero
entries only for NW_OUT (the whole outbound rate moves with leadership) and CPU
(the leader-vs-follower CPU delta). This encodes the reference's mutation ops
as pure array updates:

- ``relocateReplica`` (``ClusterModel.java:347``) = one ``broker_of`` scatter;
  the replica's base load (plus leader extra if it leads) travels with it.
- ``relocateLeadership`` (``ClusterModel.java:374``: transfers the whole
  NW_OUT plus a CPU fraction via ``Replica.leaderLoadDelta``,
  ``Replica.java:226-275``) = one ``leader_of`` scatter, because effective
  load is ``base + is_leader * leader_extra``.

For monitor-built models this is exact: follower loads are derived from the
leader's metrics with FOLLOWER_BYTES_OUT = 0 (``MonitorUtils.java:66-76``), so
the leadership delta is partition-intrinsic. (For hand-built models whose
followers carry nonzero NW_OUT, the reference's repeated in-place deltas are
path-dependent; we pin the delta to the initial leader's, which matches the
reference for every first-hop transfer.)

Everything here is jit/vmap-compatible; topology arrays are closed over as
constants, assignments are traced.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common import resources as res


def _pytree_dataclass(cls):
    """Register a dataclass whose fields are all pytree children."""
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_pytree_with_keys(
        cls,
        lambda obj: (
            [(jax.tree_util.GetAttrKey(n), getattr(obj, n)) for n in fields],
            None,
        ),
        lambda aux, children: cls(**dict(zip(fields, children))),
    )
    return cls


@dataclasses.dataclass(frozen=True)
class ClusterTopology:
    """Immutable problem description, all numpy (host) arrays.

    Shapes: B brokers, H hosts, K racks, P partitions, R replicas, T topics.
    Replicas are grouped by partition: ``replicas_of_partition`` is a
    ``(P, max_rf)`` index matrix padded with -1, in Kafka replica-list order
    (slot 0 is the *preferred* leader — what PreferredLeaderElectionGoal
    targets); the *initial* leader's slot is ``initial_leader_slot``.
    """

    # --- broker topology (ClusterModel.createBroker/createRack) ---
    rack_of_broker: np.ndarray        # i32[B]
    host_of_broker: np.ndarray        # i32[B]
    capacity: np.ndarray              # f32[B, 4] broker capacity per resource
    broker_alive: np.ndarray          # bool[B]  (state ALIVE or NEW)
    broker_new: np.ndarray            # bool[B]  (state NEW: destination-only for balancing)
    broker_demoted: np.ndarray        # bool[B]  (state DEMOTED: leadership must leave)
    broker_bad_disks: np.ndarray      # bool[B]  (state BAD_DISKS)
    # --- partition / replica structure ---
    partition_of_replica: np.ndarray  # i32[R]
    topic_of_partition: np.ndarray    # i32[P]
    replicas_of_partition: np.ndarray  # i32[P, max_rf], -1 padded
    rf_of_partition: np.ndarray       # i32[P]
    initial_leader_slot: np.ndarray   # i64[P] slot of the initial leader
    # Replica is offline at the *initial* assignment (on a dead broker or dead
    # disk, ClusterModel.selfHealingEligibleReplicas); must be moved.
    replica_offline: np.ndarray       # bool[R]
    # --- loads (see module docstring) ---
    replica_base_load: np.ndarray     # f32[R, 4] follower-role load
    leader_extra: np.ndarray          # f32[P, 4] extra load carried by the leader
    leader_bytes_in: np.ndarray       # f32[P] model metric LEADER_BYTES_IN
    # --- optional per-window loads (model/Load.java:84-118): the collapsed
    # vectors above are the AVG over valid windows; these carry the full
    # [W]-windowed series so MAX/latest-window semantics stay reproducible.
    replica_base_load_windows: Optional[np.ndarray] = None  # f32[R, W, 4]
    leader_extra_windows: Optional[np.ndarray] = None       # f32[P, W, 4]
    # --- names for decoding back to proposals ---
    topic_names: tuple = ()
    partition_index: Optional[np.ndarray] = None  # i32[P] kafka partition number
    broker_ids: Optional[np.ndarray] = None       # i32[B] external broker ids
    host_names: tuple = ()
    rack_names: tuple = ()
    # --- optional JBOD disk axis (model/Disk.java): D global disks ---
    disk_of_replica: Optional[np.ndarray] = None  # i32[R] (-1 = unknown)
    broker_of_disk: Optional[np.ndarray] = None   # i32[D]
    disk_capacity: Optional[np.ndarray] = None    # f32[D]
    disk_alive: Optional[np.ndarray] = None       # bool[D]
    disk_names: tuple = ()                        # logdir paths, D entries
    # --- shape-bucketing sentinels (pad_topology): None on unpadded models.
    # Padded entries are weight-0 / present=False and must never contribute
    # to a count, total, or goal term (ops.aggregates masks on these).
    replica_weight: Optional[np.ndarray] = None    # i32[R] 1=real
    partition_weight: Optional[np.ndarray] = None  # i32[P] 1=real
    broker_present: Optional[np.ndarray] = None    # bool[B] False=padding

    @property
    def has_disks(self) -> bool:
        return self.disk_of_replica is not None

    @property
    def num_disks(self) -> int:
        return int(self.broker_of_disk.shape[0]) if self.has_disks else 0

    # ---- sizes ----
    @property
    def num_brokers(self) -> int:
        return int(self.capacity.shape[0])

    @property
    def num_hosts(self) -> int:
        return int(self.host_of_broker.max()) + 1 if self.host_of_broker.size else 0

    @property
    def num_racks(self) -> int:
        return int(self.rack_of_broker.max()) + 1 if self.rack_of_broker.size else 0

    @property
    def num_partitions(self) -> int:
        return int(self.topic_of_partition.shape[0])

    @property
    def num_replicas(self) -> int:
        return int(self.partition_of_replica.shape[0])

    @property
    def num_topics(self) -> int:
        if self.topic_names:
            return len(self.topic_names)
        if self.topic_of_partition.shape[0] == 0:
            return 0
        return int(self.topic_of_partition.max()) + 1

    @property
    def max_rf(self) -> int:
        return int(self.replicas_of_partition.shape[1])

    @property
    def topic_of_replica(self) -> np.ndarray:
        return self.topic_of_partition[self.partition_of_replica]

    def host_capacity(self) -> np.ndarray:
        """f32[H, 4] — host capacity sums its *alive* brokers' capacities
        (the reference removes a broker's capacity from its host on DEAD)."""
        hcap = np.zeros((self.num_hosts, res.NUM_RESOURCES), dtype=np.float32)
        np.add.at(hcap, self.host_of_broker,
                  np.where(self.broker_alive[:, None], self.capacity, 0.0))
        return hcap

    def replica_load(self, is_leader: np.ndarray) -> np.ndarray:
        """f32[R, 4] effective load of each replica given leader flags."""
        extra = self.leader_extra[self.partition_of_replica]
        return self.replica_base_load + np.where(is_leader[:, None], extra, 0.0)

    @property
    def num_windows(self) -> int:
        return (self.replica_base_load_windows.shape[1]
                if self.replica_base_load_windows is not None else 0)

    def broker_load_windows(self, broker_of: np.ndarray,
                            is_leader: np.ndarray) -> np.ndarray:
        """f32[W, B, 4] per-window per-broker load (Load.java:84-118 — the
        windowed series behind expectedUtilizationFor)."""
        if self.replica_base_load_windows is None:
            raise ValueError("model built without windowed loads")
        extra = self.leader_extra_windows[self.partition_of_replica]  # [R,W,4]
        eff = (self.replica_base_load_windows
               + np.where(is_leader[:, None, None], extra, 0.0))
        out = np.zeros((eff.shape[1], self.num_brokers, res.NUM_RESOURCES),
                       np.float32)
        for w in range(eff.shape[1]):
            np.add.at(out[w], np.asarray(broker_of), eff[:, w, :])
        return out

    def expected_broker_utilization(self, broker_of: np.ndarray,
                                    is_leader: np.ndarray,
                                    use_max: bool = False) -> np.ndarray:
        """f32[B, 4] — AVG (default) or MAX over windows of per-broker load
        (Load.expectedUtilizationFor with the max-load requirement set)."""
        wl = self.broker_load_windows(broker_of, is_leader)
        return wl.max(axis=0) if use_max else wl.mean(axis=0)


@_pytree_dataclass
@dataclasses.dataclass(frozen=True)
class Assignment:
    """Decision variables: placement + leadership (device arrays inside jit)."""

    broker_of: jax.Array  # i32[R]
    leader_of: jax.Array  # i32[P] — global replica index of the leader

    def is_leader(self, partition_of_replica) -> jax.Array:
        """bool[R] — replica r leads iff leader_of[its partition] == r."""
        r = jnp.arange(self.broker_of.shape[0], dtype=jnp.int32)
        return jnp.asarray(self.leader_of)[partition_of_replica] == r

    def leader_broker(self) -> jax.Array:
        """i32[P] — broker hosting each partition's leader."""
        return jnp.asarray(self.broker_of)[self.leader_of]


def initial_assignment(topo: ClusterTopology, broker_of: np.ndarray,
                       leader_position: Optional[np.ndarray] = None) -> Assignment:
    """Assignment for the topology's initial placement (recorded leader slots)."""
    pos = topo.initial_leader_slot if leader_position is None else leader_position
    leader_of = topo.replicas_of_partition[np.arange(topo.num_partitions), pos]
    return Assignment(
        broker_of=jnp.asarray(broker_of, dtype=jnp.int32),
        leader_of=jnp.asarray(leader_of, dtype=jnp.int32),
    )


# ---------------------------------------------------------------------------
# CPU model (model/ModelParameters.java:21-29)
# ---------------------------------------------------------------------------

CPU_WEIGHT_LEADER_BYTES_IN = 0.7
CPU_WEIGHT_LEADER_BYTES_OUT = 0.15
CPU_WEIGHT_FOLLOWER_BYTES_IN = 0.15


def set_static_cpu_weights(leader_bytes_in: float, leader_bytes_out: float,
                           follower_bytes_in: float) -> None:
    """Override the static attribution weights from config
    ({leader,follower}.network.{inbound,outbound}.weight.for.cpu.util,
    ModelParameters.java:21-29). Process-wide, set once at service init."""
    global CPU_WEIGHT_LEADER_BYTES_IN, CPU_WEIGHT_LEADER_BYTES_OUT, \
        CPU_WEIGHT_FOLLOWER_BYTES_IN
    CPU_WEIGHT_LEADER_BYTES_IN = float(leader_bytes_in)
    CPU_WEIGHT_LEADER_BYTES_OUT = float(leader_bytes_out)
    CPU_WEIGHT_FOLLOWER_BYTES_IN = float(follower_bytes_in)


def follower_cpu_util(leader_bytes_in, leader_bytes_out, leader_cpu):
    """ModelUtils.getFollowerCpuUtilFromLeaderLoad (ModelUtils.java:45-66)."""
    denom = (CPU_WEIGHT_LEADER_BYTES_IN * leader_bytes_in
             + CPU_WEIGHT_LEADER_BYTES_OUT * leader_bytes_out)
    num = CPU_WEIGHT_FOLLOWER_BYTES_IN * leader_bytes_in
    denom = np.asarray(denom, dtype=np.float64)
    safe = np.where(denom > 0, denom, 1.0)
    return np.where(denom > 0, leader_cpu * num / safe, 0.0)


def leadership_extra_from_leader_load(leader_load: np.ndarray) -> np.ndarray:
    """Leadership delta from the leader's as-is load (Replica.java:226-275):
    the whole NW_OUT plus leaderCpu − followerCpu(formula)."""
    leader_load = np.asarray(leader_load, dtype=np.float32)
    extra = np.zeros_like(leader_load)
    extra[..., res.NW_OUT] = leader_load[..., res.NW_OUT]
    extra[..., res.CPU] = leader_load[..., res.CPU] - follower_cpu_util(
        leader_load[..., res.NW_IN], leader_load[..., res.NW_OUT], leader_load[..., res.CPU])
    return extra


def derive_follower_load(leader_load: np.ndarray) -> np.ndarray:
    """Follower load from leader load (MonitorUtils.java:66-76)."""
    return np.asarray(leader_load, dtype=np.float32) - leadership_extra_from_leader_load(leader_load)


@dataclasses.dataclass
class LinearRegressionCpuModel:
    """Trained CPU model (model/LinearRegressionModelParameters.java:81):
    broker CPU utilization as a linear function of the leader bytes-in,
    leader bytes-out, and follower (replication) bytes-in rates, fitted by
    least squares from accumulated broker metric samples. Untrained
    instances fall back to the static ModelParameters weights."""

    #: CPU-per-byte coefficients — zero until trained (the static 0.7/0.15
    #: ModelParameters weights are attribution FRACTIONS in different units
    #: and must never masquerade as regression coefficients)
    coef_leader_bytes_in: float = 0.0
    coef_leader_bytes_out: float = 0.0
    coef_follower_bytes_in: float = 0.0
    trained: bool = False
    num_samples: int = 0

    @classmethod
    def fit(cls, leader_bytes_in, leader_bytes_out, follower_bytes_in,
            cpu_util, cpu_util_bucket_size: Optional[int] = None,
            min_num_buckets: Optional[int] = None,
            samples_per_bucket: Optional[int] = None
            ) -> "LinearRegressionCpuModel":
        """Least-squares fit; returns an untrained fallback when the sample
        set is too small or degenerate (singular design matrix).

        Bucket readiness (LinearRegressionModelParameters.java:40-75,
        ``linear.regression.model.*`` keys): when given, the CPU-utilization
        range must cover ``min_num_buckets`` distinct buckets of width
        ``cpu_util_bucket_size`` percent with ``samples_per_bucket`` samples
        each before the model counts as trained — a fit from a narrow CPU
        band extrapolates badly."""
        x = np.stack([np.asarray(leader_bytes_in, np.float64),
                      np.asarray(leader_bytes_out, np.float64),
                      np.asarray(follower_bytes_in, np.float64)], axis=1)
        y = np.asarray(cpu_util, np.float64)
        n = y.shape[0]
        if n < 3 or np.linalg.matrix_rank(x) < 3:
            return cls()
        if cpu_util_bucket_size and min_num_buckets:
            # cpu_util samples are already PERCENT (BrokerMetricSample),
            # so bucket width divides the raw value
            buckets = np.floor(y / cpu_util_bucket_size).astype(int)
            ids, counts = np.unique(buckets, return_counts=True)
            full = counts >= max(1, samples_per_bucket or 1)
            if int(full.sum()) < min_num_buckets:
                return cls()
        coef, *_ = np.linalg.lstsq(x, y, rcond=None)
        coef = np.maximum(coef, 0.0)   # negative CPU-per-byte is noise
        return cls(coef_leader_bytes_in=float(coef[0]),
                   coef_leader_bytes_out=float(coef[1]),
                   coef_follower_bytes_in=float(coef[2]),
                   trained=True, num_samples=int(n))

    def cpu_util(self, leader_bytes_in, leader_bytes_out,
                 follower_bytes_in=0.0):
        """Predicted CPU utilization for the given rates
        (ModelParameters.getCpuUtil equivalent); trained models only."""
        if not self.trained:
            raise ValueError("CPU model is untrained; run TRAIN first")
        return (self.coef_leader_bytes_in * np.asarray(leader_bytes_in)
                + self.coef_leader_bytes_out * np.asarray(leader_bytes_out)
                + self.coef_follower_bytes_in * np.asarray(follower_bytes_in))

    def to_json(self) -> dict:
        out = {"trained": self.trained, "numSamples": self.num_samples}
        if self.trained:
            out["coefficients"] = {
                "leaderBytesInRate": self.coef_leader_bytes_in,
                "leaderBytesOutRate": self.coef_leader_bytes_out,
                "followerBytesInRate": self.coef_follower_bytes_in}
        return out


# ---------------------------------------------------------------------------
# Builder: friendly mutation-style API used by fixtures and the monitor.
# ---------------------------------------------------------------------------


class ClusterModelBuilder:
    """Incremental builder mirroring ClusterModel's creation API:
    ``createRack``/``createBroker`` (``ClusterModel.java:845,867``),
    ``createReplica`` + ``setReplicaLoad`` (``ClusterModel.java:746,684``) —
    lowering to the array topology at ``build()`` time.
    """

    def __init__(self):
        self._racks: list = []
        self._hosts: dict = {}
        self._brokers: list = []
        self._broker_index: dict = {}
        self._topics: list = []
        self._topic_index: dict = {}
        self._partitions: dict = {}

    # -- topology --
    def create_rack(self, rack: str) -> str:
        if rack not in self._racks:
            self._racks.append(rack)
        return rack

    def create_broker(self, rack: str, host: str, broker_id: int, capacity,
                      alive: bool = True, new: bool = False, demoted: bool = False,
                      bad_disks: bool = False, disks: Optional[dict] = None) -> int:
        """capacity: dict {resource_id: value} or sequence of 4 values.
        ``disks``: optional JBOD map {logdir: disk_capacity} (or
        {logdir: (capacity, alive)}); DISK capacity then sums alive disks."""
        self.create_rack(rack)
        if host not in self._hosts:
            self._hosts[host] = {"rack": rack}
        cap = np.zeros(res.NUM_RESOURCES, dtype=np.float32)
        if isinstance(capacity, dict):
            for k, v in capacity.items():
                cap[k] = v
        else:
            cap[:] = np.asarray(capacity, dtype=np.float32)
        disk_list = None
        if disks is not None:
            disk_list = []
            for logdir, v in disks.items():
                dcap, dalive = v if isinstance(v, tuple) else (v, True)
                disk_list.append(dict(logdir=logdir, capacity=float(dcap),
                                      alive=bool(dalive)))
            cap[res.DISK] = sum(d["capacity"] for d in disk_list if d["alive"])
            bad_disks = bad_disks or any(not d["alive"] for d in disk_list)
        if broker_id in self._broker_index:
            raise ValueError(f"duplicate broker id {broker_id}")
        idx = len(self._brokers)
        self._brokers.append(dict(id=broker_id, rack=rack, host=host, capacity=cap,
                                  alive=alive, new=new, demoted=demoted,
                                  bad_disks=bad_disks, disks=disk_list))
        self._broker_index[broker_id] = idx
        return broker_id

    def set_broker_state(self, broker_id: int, *, alive=None, new=None, demoted=None, bad_disks=None):
        b = self._brokers[self._broker_index[broker_id]]
        for k, v in (("alive", alive), ("new", new), ("demoted", demoted), ("bad_disks", bad_disks)):
            if v is not None:
                b[k] = v

    # -- partitions --
    def create_replica(self, broker_id: int, topic: str, partition: int,
                       index: int, is_leader: bool, offline: bool = False,
                       logdir: Optional[str] = None):
        """Mirror of ClusterModel.createReplica: register a replica at a list
        position; exactly one replica per partition must be the leader.
        ``logdir`` places the replica on a JBOD disk; a dead disk marks it
        offline (ClusterModel.markDiskDead semantics)."""
        if topic not in self._topic_index:
            self._topic_index[topic] = len(self._topics)
            self._topics.append(topic)
        key = (topic, partition)
        part = self._partitions.setdefault(
            key, dict(topic=topic, partition=partition, replicas={}, leader_index=None))
        if index in part["replicas"]:
            raise ValueError(f"duplicate replica index {index} for {key}")
        if logdir is not None:
            b = self._brokers[self._broker_index[broker_id]]
            disk = next((d for d in (b["disks"] or [])
                         if d["logdir"] == logdir), None)
            if disk is None:
                raise ValueError(f"broker {broker_id} has no logdir {logdir}")
            offline = offline or not disk["alive"]
        part["replicas"][index] = dict(broker=broker_id, load=None,
                                       offline=offline, logdir=logdir)
        if is_leader:
            if part["leader_index"] is not None:
                raise ValueError(f"two leaders for {key}")
            part["leader_index"] = index

    def set_replica_load(self, broker_id: int, topic: str, partition: int, load,
                         leader_bytes_in: float = None, load_windows=None):
        """Mirror of ClusterModel.setReplicaLoad; load = 4-vector or dict.
        ``load_windows``: optional [W, 4] per-window loads (Load.java keeps
        the windowed series; the flat vector is its AVG collapse)."""
        part = self._partitions[(topic, partition)]
        vec = np.zeros(res.NUM_RESOURCES, dtype=np.float32)
        if isinstance(load, dict):
            for k, v in load.items():
                vec[k] = v
        else:
            vec[:] = np.asarray(load, dtype=np.float32)
        for rep in part["replicas"].values():
            if rep["broker"] == broker_id:
                rep["load"] = vec
                if load_windows is not None:
                    rep["load_windows"] = np.asarray(load_windows, np.float32)
                if leader_bytes_in is not None:
                    part["leader_bytes_in"] = np.float32(leader_bytes_in)
                return
        raise ValueError(f"no replica of ({topic},{partition}) on broker {broker_id}")

    def create_partition(self, topic: str, partition: int, leader_broker: int,
                         follower_brokers, leader_load, leader_bytes_in: float = 0.0,
                         offline=()):
        """Convenience: leader + followers with reference-derived follower
        loads (MonitorUtils.java:66-76)."""
        ll = np.zeros(res.NUM_RESOURCES, dtype=np.float32)
        if isinstance(leader_load, dict):
            for k, v in leader_load.items():
                ll[k] = v
        else:
            ll[:] = np.asarray(leader_load, dtype=np.float32)
        fl = derive_follower_load(ll)
        self.create_replica(leader_broker, topic, partition, 0, True,
                            offline=leader_broker in offline)
        self.set_replica_load(leader_broker, topic, partition, ll, leader_bytes_in)
        for j, b in enumerate(follower_brokers):
            self.create_replica(b, topic, partition, j + 1, False, offline=b in offline)
            self.set_replica_load(b, topic, partition, fl)

    def build(self) -> tuple:
        """Lower to (ClusterTopology, Assignment)."""
        B = len(self._brokers)
        host_names = sorted(self._hosts)
        host_idx = {h: i for i, h in enumerate(host_names)}
        rack_idx = {r: i for i, r in enumerate(self._racks)}
        rack_of_broker = np.array([rack_idx[b["rack"]] for b in self._brokers], dtype=np.int32)
        host_of_broker = np.array([host_idx[b["host"]] for b in self._brokers], dtype=np.int32)
        capacity = (np.stack([b["capacity"] for b in self._brokers]).astype(np.float32)
                    if B else np.zeros((0, res.NUM_RESOURCES), np.float32))
        broker_ids = np.array([b["id"] for b in self._brokers], dtype=np.int32)

        # JBOD disk axis (only if any broker declares disks)
        has_disks = any(b.get("disks") for b in self._brokers)
        disk_index: dict = {}
        broker_of_disk, disk_capacity, disk_alive, disk_names = [], [], [], []
        if has_disks:
            for bi, b in enumerate(self._brokers):
                for d in (b.get("disks") or []):
                    disk_index[(b["id"], d["logdir"])] = len(disk_names)
                    broker_of_disk.append(bi)
                    disk_capacity.append(d["capacity"])
                    disk_alive.append(d["alive"])
                    disk_names.append(d["logdir"])

        parts = sorted(self._partitions.values(),
                       key=lambda d: (self._topic_index[d["topic"]], d["partition"]))
        P = len(parts)
        max_rf = max((len(p["replicas"]) for p in parts), default=1)
        partition_of_replica, broker_of, replica_offline, base_loads = [], [], [], []
        disk_of_replica = []
        replicas_of_partition = np.full((P, max_rf), -1, dtype=np.int32)
        leader_position = np.zeros(P, dtype=np.int64)
        rf = np.zeros(P, dtype=np.int32)
        topic_of_partition = np.zeros(P, dtype=np.int32)
        partition_index = np.zeros(P, dtype=np.int32)
        leader_extra = np.zeros((P, res.NUM_RESOURCES), dtype=np.float32)
        leader_bytes_in = np.zeros(P, dtype=np.float32)
        # windowed loads: present iff any replica carries them; W from the
        # first windowed replica, others tile their collapsed vector
        n_windows = 0
        for p in parts:
            for rep in p["replicas"].values():
                if rep.get("load_windows") is not None:
                    n_windows = rep["load_windows"].shape[0]
                    break
            if n_windows:
                break
        base_load_windows: list = []
        leader_extra_windows = (np.zeros((P, n_windows, res.NUM_RESOURCES),
                                         np.float32) if n_windows else None)

        r = 0
        for pi, p in enumerate(parts):
            topic_of_partition[pi] = self._topic_index[p["topic"]]
            partition_index[pi] = p["partition"]
            leader_bytes_in[pi] = p.get("leader_bytes_in", 0.0)
            indices = sorted(p["replicas"])
            if p["leader_index"] is None:
                raise ValueError(f"partition ({p['topic']},{p['partition']}) has no leader")
            rf[pi] = len(indices)
            for slot, idx in enumerate(indices):
                rep = p["replicas"][idx]
                load = rep["load"] if rep["load"] is not None else np.zeros(res.NUM_RESOURCES, np.float32)
                lw = rep.get("load_windows")
                if n_windows:
                    if lw is None or lw.shape[0] != n_windows:
                        lw = np.tile(load, (n_windows, 1))
                if idx == p["leader_index"]:
                    leader_position[pi] = slot
                    extra = leadership_extra_from_leader_load(load)
                    leader_extra[pi] = extra
                    base_loads.append(load - extra)
                    if n_windows:
                        extra_w = leadership_extra_from_leader_load(lw)
                        leader_extra_windows[pi] = extra_w
                        base_load_windows.append(lw - extra_w)
                else:
                    base_loads.append(load)
                    if n_windows:
                        base_load_windows.append(lw)
                replicas_of_partition[pi, slot] = r
                partition_of_replica.append(pi)
                bidx = self._broker_index[rep["broker"]]
                broker_of.append(bidx)
                replica_offline.append(rep["offline"] or not self._brokers[bidx]["alive"])
                if has_disks:
                    ld = rep.get("logdir")
                    disk_of_replica.append(
                        disk_index.get((rep["broker"], ld), -1))
                r += 1

        topo = ClusterTopology(
            rack_of_broker=rack_of_broker,
            host_of_broker=host_of_broker,
            capacity=capacity,
            broker_alive=np.array([b["alive"] for b in self._brokers]),
            broker_new=np.array([b["new"] for b in self._brokers]),
            broker_demoted=np.array([b["demoted"] for b in self._brokers]),
            broker_bad_disks=np.array([b["bad_disks"] for b in self._brokers]),
            partition_of_replica=np.asarray(partition_of_replica, dtype=np.int32),
            topic_of_partition=topic_of_partition,
            replicas_of_partition=replicas_of_partition,
            rf_of_partition=rf,
            initial_leader_slot=leader_position,
            replica_offline=np.asarray(replica_offline, dtype=bool),
            replica_base_load=(np.stack(base_loads).astype(np.float32)
                               if base_loads else np.zeros((0, res.NUM_RESOURCES), np.float32)),
            leader_extra=leader_extra,
            leader_bytes_in=leader_bytes_in,
            topic_names=tuple(self._topics),
            partition_index=partition_index,
            broker_ids=broker_ids,
            host_names=tuple(host_names),
            rack_names=tuple(self._racks),
            disk_of_replica=(np.asarray(disk_of_replica, np.int32)
                             if has_disks else None),
            broker_of_disk=(np.asarray(broker_of_disk, np.int32)
                            if has_disks else None),
            disk_capacity=(np.asarray(disk_capacity, np.float32)
                           if has_disks else None),
            disk_alive=(np.asarray(disk_alive, bool) if has_disks else None),
            disk_names=tuple(disk_names),
            replica_base_load_windows=(
                np.stack(base_load_windows).astype(np.float32)
                if n_windows and base_load_windows else None),
            leader_extra_windows=leader_extra_windows,
        )
        assignment = initial_assignment(topo, np.asarray(broker_of, dtype=np.int32))
        return topo, assignment


# ---------------------------------------------------------------------------
# Shape bucketing: pad the broker/host/partition/replica axes to geometric
# bucket sizes so cluster drift within a bucket reuses every compiled program.
# ---------------------------------------------------------------------------

#: geometric bucket growth factor — consecutive buckets differ by ~25%, so a
#: model wastes at most ~25% padded work and drift retraces O(log n) times
BUCKET_GROWTH = 1.25

#: per-axis floors: buckets below these collapse to one size, so tiny models
#: share a single compiled program per axis family
BROKER_BUCKET_FLOOR = 16
HOST_BUCKET_FLOOR = 16
PARTITION_BUCKET_FLOOR = 256
REPLICA_BUCKET_FLOOR = 512


def bucket_size(n: int, floor: int, growth: float = BUCKET_GROWTH) -> int:
    """Smallest bucket ≥ ``n`` on the geometric ladder ``floor·growth^k``.

    Integer-monotone by construction (each rung is ``ceil(prev·growth)``), so
    two clusters whose sizes land in the same bucket get identical padded
    shapes — the property the retrace contract rests on."""
    if n <= floor:
        return floor
    s = floor
    while s < n:
        s = int(np.ceil(s * growth))
    return s


@dataclasses.dataclass(frozen=True)
class PaddingInfo:
    """Real (unpadded) axis sizes of a bucketed model, for decode/slicing."""

    num_brokers: int
    num_hosts: int
    num_partitions: int
    num_replicas: int


def pad_topology(topo: ClusterTopology, assign: Assignment, *,
                 broker_target: "Optional[int]" = None,
                 host_target: "Optional[int]" = None,
                 partition_target: "Optional[int]" = None,
                 replica_target: "Optional[int]" = None,
                 ) -> "tuple[ClusterTopology, Assignment, PaddingInfo]":
    """Pad (topology, assignment) to bucketed axis sizes with neutral
    sentinel entries.

    Sentinel construction (every goal term must see exactly zero from them):

    - padded BROKERS: dead (``broker_alive=False``), zero capacity, parked on
      padded hosts — every alive-masked broker term vanishes and
      ``_DeadBrokerPlacement`` stays zero because padded replica *counts* are
      masked by ``replica_weight`` (ops.aggregates);
    - padded HOSTS: zero capacity and only padded-broker load (zero), so the
      host-scope capacity terms vanish;
    - padded PARTITIONS: rf=1, topic 0, zero loads; their single padded
      replica leads them (slot 0 — PreferredLeaderElection-neutral) from a
      padded broker;
    - padded REPLICAS: zero load, offline=False, ``replica_weight=0``; the
      caller must also pad the DeviceOptions masks (``goals.pad_options``) so
      they are immovable and padded brokers are never destinations.

    At least one padded broker and partition always exist (buckets are
    computed on ``n+1``) so the sentinel host/rack rows are well-defined.
    Returns the padded pair plus a :class:`PaddingInfo` with the real sizes;
    real entries occupy the axis *prefix*, so decode is a plain slice.

    The ``*_target`` keywords override the per-axis bucket choice with an
    explicit padded size (the provisioner pads every scenario of a what-if
    grid to ONE shared bucket so the batch stacks into a single vmapped
    program). A target must leave room for the sentinel rows the padding
    scheme requires — at least one padded broker/host/partition, and one
    padded replica per padded partition; too-small targets raise.
    """
    import jax as _jax

    B, P, R = topo.num_brokers, topo.num_partitions, topo.num_replicas
    H, K = topo.num_hosts, topo.num_racks
    m = topo.max_rf
    B_pad = (bucket_size(B + 1, BROKER_BUCKET_FLOOR)
             if broker_target is None else int(broker_target))
    P_pad = (bucket_size(P + 1, PARTITION_BUCKET_FLOOR)
             if partition_target is None else int(partition_target))
    n_pb = B_pad - B
    n_pp = P_pad - P
    H_pad = (bucket_size(H + 1, HOST_BUCKET_FLOOR)
             if host_target is None else int(host_target))
    R_pad = (bucket_size(R + n_pp, REPLICA_BUCKET_FLOOR)
             if replica_target is None else int(replica_target))
    n_pr = R_pad - R
    if n_pb < 1 or n_pp < 1 or H_pad < H + 1 or n_pr < n_pp:
        raise ValueError(
            f"pad targets too small: B {B}->{B_pad}, H {H}->{H_pad}, "
            f"P {P}->{P_pad}, R {R}->{R_pad} (need >=1 padded "
            "broker/host/partition and a padded replica per padded partition)")

    def _pad(arr, n, fill):
        arr = np.asarray(arr)
        pad_shape = (n,) + arr.shape[1:]
        return np.concatenate(
            [arr, np.full(pad_shape, fill, dtype=arr.dtype)], axis=0)

    # brokers: dead, zero-capacity, one shared padded rack, padded hosts
    # spread over [H, H_pad) (the last padded broker pins host H_pad-1 and
    # rack K so num_hosts/num_racks equal the padded sizes)
    pad_hosts = H + (np.arange(n_pb) % max(1, H_pad - H))
    pad_hosts[-1] = H_pad - 1
    host_of_broker = np.concatenate(
        [np.asarray(topo.host_of_broker),
         pad_hosts.astype(topo.host_of_broker.dtype)])
    rack_of_broker = _pad(topo.rack_of_broker, n_pb, K)

    # partitions: rf=1, topic 0, zero loads, led by their own padded replica
    pp_leader = (R + np.arange(n_pp)).astype(np.int32)
    reps_pad = np.full((n_pp, m), -1, dtype=topo.replicas_of_partition.dtype)
    reps_pad[:, 0] = pp_leader
    replicas_of_partition = np.concatenate(
        [np.asarray(topo.replicas_of_partition), reps_pad], axis=0)

    # replicas: the first n_pp padded replicas are the padded partitions'
    # leaders; any bucket surplus attaches to the first padded partition
    # (deliberately absent from its replica list — every per-partition walk
    # iterates replicas_of_partition rows, never the reverse map)
    pr_part = np.full(n_pr, P, dtype=topo.partition_of_replica.dtype)
    pr_part[:n_pp] = P + np.arange(n_pp)
    partition_of_replica = np.concatenate(
        [np.asarray(topo.partition_of_replica), pr_part])

    topo_pad = dataclasses.replace(
        topo,
        rack_of_broker=rack_of_broker,
        host_of_broker=host_of_broker,
        capacity=_pad(topo.capacity, n_pb, 0.0),
        broker_alive=_pad(topo.broker_alive, n_pb, False),
        broker_new=_pad(topo.broker_new, n_pb, False),
        broker_demoted=_pad(topo.broker_demoted, n_pb, False),
        broker_bad_disks=_pad(topo.broker_bad_disks, n_pb, False),
        partition_of_replica=partition_of_replica,
        topic_of_partition=_pad(topo.topic_of_partition, n_pp, 0),
        replicas_of_partition=replicas_of_partition,
        rf_of_partition=_pad(topo.rf_of_partition, n_pp, 1),
        initial_leader_slot=_pad(topo.initial_leader_slot, n_pp, 0),
        replica_offline=_pad(topo.replica_offline, n_pr, False),
        replica_base_load=_pad(topo.replica_base_load, n_pr, 0.0),
        leader_extra=_pad(topo.leader_extra, n_pp, 0.0),
        leader_bytes_in=_pad(topo.leader_bytes_in, n_pp, 0.0),
        replica_base_load_windows=(
            _pad(topo.replica_base_load_windows, n_pr, 0.0)
            if topo.replica_base_load_windows is not None else None),
        leader_extra_windows=(
            _pad(topo.leader_extra_windows, n_pp, 0.0)
            if topo.leader_extra_windows is not None else None),
        partition_index=(_pad(topo.partition_index, n_pp, -1)
                         if topo.partition_index is not None else None),
        broker_ids=(_pad(topo.broker_ids, n_pb, -1)
                    if topo.broker_ids is not None else None),
        disk_of_replica=(_pad(topo.disk_of_replica, n_pr, -1)
                         if topo.disk_of_replica is not None else None),
        replica_weight=np.concatenate(
            [np.ones(R, np.int32), np.zeros(n_pr, np.int32)]),
        partition_weight=np.concatenate(
            [np.ones(P, np.int32), np.zeros(n_pp, np.int32)]),
        broker_present=np.concatenate(
            [np.ones(B, bool), np.zeros(n_pb, bool)]),
    )
    # all padded replicas sit on the first padded broker
    bo = np.concatenate(
        [np.asarray(_jax.device_get(assign.broker_of), np.int32),
         np.full(n_pr, B, np.int32)])
    lo = np.concatenate(
        [np.asarray(_jax.device_get(assign.leader_of), np.int32), pp_leader])
    assign_pad = Assignment(broker_of=jnp.asarray(bo),
                            leader_of=jnp.asarray(lo))
    return topo_pad, assign_pad, PaddingInfo(
        num_brokers=B, num_hosts=H, num_partitions=P, num_replicas=R)


def unpad_assignment(assign: Assignment, info: PaddingInfo) -> Assignment:
    """Slice a padded assignment back to the real axis prefixes.

    Padded replicas are immovable and padded brokers are never destinations,
    so the real prefix of ``broker_of``/``leader_of`` is the complete real
    assignment.  The slice happens on HOST: a device-side slice would
    trace+compile per distinct real size while the bucket stays fixed
    (exactly the retrace class the bucketing scheme exists to kill)."""
    import jax as _jax
    bo = np.asarray(_jax.device_get(assign.broker_of), np.int32)
    lo = np.asarray(_jax.device_get(assign.leader_of), np.int32)
    return Assignment(broker_of=jnp.asarray(bo[:info.num_replicas]),
                      leader_of=jnp.asarray(lo[:info.num_partitions]))
