"""Test/bench cluster fixtures.

Array-model ports of the reference's fixture generators:
``DeterministicCluster`` (``cruise-control/src/test/java/.../common/
DeterministicCluster.java``) and ``RandomCluster``
(``.../model/RandomCluster.java``), with the same cluster shapes, capacities,
and load values so goal behavior is comparable case-by-case. Fixtures are part
of the framework (used by bench + property tests), mirroring how the reference's
BASELINE configs name these generators.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np

from cruise_control_tpu.common import resources as res
from cruise_control_tpu.common.resources import CPU, DISK, NW_IN, NW_OUT
from cruise_control_tpu.models.cluster import ClusterModelBuilder

# TestConstants.java:39-42
LARGE_BROKER_CAPACITY = 300_000.0
MEDIUM_BROKER_CAPACITY = 200_000.0
TYPICAL_CPU_CAPACITY = 100.0
SMALL_BROKER_CAPACITY = 10.0

# TestConstants.BROKER_CAPACITY (TestConstants.java:75-88)
BROKER_CAPACITY = {
    CPU: TYPICAL_CPU_CAPACITY,
    NW_IN: LARGE_BROKER_CAPACITY,
    NW_OUT: MEDIUM_BROKER_CAPACITY,
    DISK: LARGE_BROKER_CAPACITY,
}

# DeterministicCluster.RACK_BY_BROKER (DeterministicCluster.java:27-33):
# brokers 0,1 on rack 0; broker 2 on rack 1.
RACK_BY_BROKER = {0: 0, 1: 0, 2: 1}


def _homogeneous(rack_by_broker, capacity=None):
    """DeterministicCluster.getHomogeneousCluster: one host per broker."""
    b = ClusterModelBuilder()
    if capacity is None:
        capacity = BROKER_CAPACITY
    for broker_id, rack in sorted(rack_by_broker.items()):
        b.create_broker(f"rack{rack}", f"host{broker_id}", broker_id, capacity)
    return b


def _load(cpu, nw_in, nw_out, disk):
    """getAggregatedMetricValues argument order (cpu, nwIn, nwOut, disk)."""
    vec = np.zeros(res.NUM_RESOURCES, dtype=np.float32)
    vec[CPU], vec[NW_IN], vec[NW_OUT], vec[DISK] = cpu, nw_in, nw_out, disk
    return vec


def small_cluster_model():
    """DeterministicCluster.smallClusterModel (DeterministicCluster.java:300):
    3 brokers / 2 racks, topics T1 (2 partitions) and T2 (3), rf=2."""
    b = _homogeneous(RACK_BY_BROKER)
    reps = [
        # (topic, partition, [(broker, index, is_leader, load)...])
        ("T1", 0, [(0, 0, True, _load(20.0, 100.0, 130.0, 75.0)),
                   (2, 1, False, _load(5.0, 100.0, 0.0, 75.0))]),
        ("T1", 1, [(1, 0, True, _load(15.0, 90.0, 110.0, 55.0)),
                   (0, 1, False, _load(4.5, 90.0, 0.0, 55.0))]),
        ("T2", 0, [(1, 0, True, _load(5.0, 5.0, 6.0, 5.0)),
                   (2, 1, False, _load(4.0, 5.0, 0.0, 5.0))]),
        ("T2", 1, [(0, 0, True, _load(25.0, 25.0, 45.0, 55.0)),
                   (2, 1, False, _load(10.5, 25.0, 0.0, 55.0))]),
        ("T2", 2, [(0, 0, True, _load(20.0, 45.0, 120.0, 95.0)),
                   (1, 1, False, _load(8.0, 45.0, 0.0, 95.0))]),
    ]
    for topic, part, replicas in reps:
        for broker, idx, lead, load in replicas:
            b.create_replica(broker, topic, part, idx, lead)
        for broker, idx, lead, load in replicas:
            b.set_replica_load(broker, topic, part, load)
    return b.build()


def medium_cluster_model():
    """DeterministicCluster.mediumClusterModel (DeterministicCluster.java:421):
    3 brokers / 2 racks, topics A(3 parts), B, C, D, rf=2."""
    b = _homogeneous(RACK_BY_BROKER)
    reps = [
        ("A", 0, [(1, 0, True, _load(5.0, 4.0, 10.0, 10.0)),
                  (0, 1, False, _load(5.0, 5.0, 0.0, 4.0))]),
        ("A", 1, [(0, 0, True, _load(5.0, 3.0, 10.0, 8.0)),
                  (2, 1, False, _load(3.0, 4.0, 0.0, 6.0))]),
        ("A", 2, [(0, 0, True, _load(5.0, 2.0, 10.0, 6.0)),
                  (2, 1, False, _load(4.0, 5.0, 0.0, 3.0))]),
        ("B", 0, [(1, 0, True, _load(5.0, 4.0, 10.0, 7.0)),
                  (2, 1, False, _load(2.0, 2.0, 0.0, 5.0))]),
        ("C", 0, [(2, 0, True, _load(1.0, 8.0, 10.0, 4.0)),
                  (1, 1, False, _load(5.0, 6.0, 0.0, 4.0))]),
        ("D", 0, [(1, 0, True, _load(5.0, 5.0, 10.0, 6.0)),
                  (2, 1, False, _load(2.0, 8.0, 0.0, 7.0))]),
    ]
    for topic, part, replicas in reps:
        for broker, idx, lead, load in replicas:
            b.create_replica(broker, topic, part, idx, lead)
        for broker, idx, lead, load in replicas:
            b.set_replica_load(broker, topic, part, load)
    return b.build()


def unbalanced():
    """DeterministicCluster.unbalanced (DeterministicCluster.java:142): both
    single-replica partitions (T1-0, T2-0) lead on broker 0."""
    b = _homogeneous(RACK_BY_BROKER)
    load = _load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                 MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic in ("T1", "T2"):
        b.create_replica(0, topic, 0, 0, True)
        b.set_replica_load(0, topic, 0, load)
    return b.build()


def unbalanced2():
    """DeterministicCluster.unbalanced2 (:111): unbalanced + four more
    single-replica partitions, three of them on broker 0."""
    b = _homogeneous(RACK_BY_BROKER)
    base = _load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                 MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    extra = _load(LARGE_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                  MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic in ("T1", "T2"):
        b.create_replica(0, topic, 0, 0, True)
        b.set_replica_load(0, topic, 0, base)
    for broker, topic, part in [(1, "T1", 1), (0, "T2", 1), (0, "T1", 2), (0, "T2", 2)]:
        b.create_replica(broker, topic, part, 0, True)
        b.set_replica_load(broker, topic, part, extra)
    return b.build()


def unbalanced3():
    """DeterministicCluster.unbalanced3 (:76): rf=2, leaders at index 1."""
    b = _homogeneous(RACK_BY_BROKER)
    load = _load(TYPICAL_CPU_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2,
                 MEDIUM_BROKER_CAPACITY / 2, LARGE_BROKER_CAPACITY / 2)
    for topic in ("T1", "T2"):
        b.create_replica(1, topic, 0, 0, False)
        b.create_replica(0, topic, 0, 1, True)
        b.set_replica_load(0, topic, 0, load)
        b.set_replica_load(1, topic, 0, load)
    return b.build()


def rack_aware_satisfiable():
    """DeterministicCluster.rackAwareSatisfiable (:171): one rf=2 partition
    with both replicas on rack 0 — fixable by one move to rack 1."""
    b = _homogeneous(RACK_BY_BROKER)
    b.create_replica(0, "T1", 0, 0, True)
    b.create_replica(1, "T1", 0, 1, False)
    b.set_replica_load(0, "T1", 0, _load(40.0, 100.0, 130.0, 75.0))
    b.set_replica_load(1, "T1", 0, _load(5.0, 100.0, 0.0, 75.0))
    return b.build()


def rack_aware_unsatisfiable():
    """DeterministicCluster.rackAwareUnsatisfiable (:199): rf=3 over 2 racks."""
    b = _homogeneous(RACK_BY_BROKER)
    b.create_replica(0, "T1", 0, 0, True)
    b.create_replica(1, "T1", 0, 1, False)
    b.create_replica(2, "T1", 0, 2, False)
    b.set_replica_load(0, "T1", 0, _load(40.0, 100.0, 130.0, 75.0))
    b.set_replica_load(1, "T1", 0, _load(5.0, 100.0, 0.0, 75.0))
    b.set_replica_load(2, "T1", 0, _load(60.0, 100.0, 130.0, 75.0))
    return b.build()


def dead_broker():
    """DeterministicCluster.deadBroker (:350): 5 brokers / 5 racks, 8 rf=2
    partitions, broker 0 dead (its replicas offline)."""
    b = _homogeneous({i: i for i in range(5)})
    reps = [
        ("T1", 0, [(1, 0, True, _load(20.0, 100.0, 200.0, 100.0)),
                   (2, 1, False, _load(15.0, 100.0, 0.0, 100.0))]),
        ("T1", 1, [(1, 0, True, _load(20.0, 90.0, 180.0, 100.0)),
                   (3, 1, False, _load(15.0, 90.0, 0.0, 100.0))]),
        ("T1", 2, [(1, 0, True, _load(15.0, 75.0, 150.0, 100.0)),
                   (4, 1, False, _load(12.0, 75.0, 0.0, 100.0))]),
        ("T1", 3, [(2, 0, True, _load(15.0, 60.0, 120.0, 100.0)),
                   (0, 1, False, _load(12.5, 60.0, 0.0, 100.0))]),
        ("T2", 0, [(1, 0, True, _load(18.0, 100.0, 200.0, 100.0)),
                   (2, 1, False, _load(14.0, 100.0, 0.0, 100.0))]),
        ("T2", 1, [(1, 0, True, _load(18.0, 90.0, 180.0, 100.0)),
                   (3, 1, False, _load(14.0, 90.0, 0.0, 100.0))]),
        ("T2", 2, [(1, 0, True, _load(12.0, 75.0, 150.0, 100.0)),
                   (4, 1, False, _load(10.0, 75.0, 0.0, 100.0))]),
        ("T2", 3, [(3, 0, True, _load(12.0, 60.0, 120.0, 100.0)),
                   (0, 1, False, _load(10.5, 60.0, 0.0, 100.0))]),
    ]
    b.set_broker_state(0, alive=False)
    for topic, part, replicas in reps:
        for broker, idx, lead, load in replicas:
            b.create_replica(broker, topic, part, idx, lead, offline=(broker == 0))
        for broker, idx, lead, load in replicas:
            b.set_replica_load(broker, topic, part, load)
    return b.build()


# ---------------------------------------------------------------------------
# RandomCluster port (model/RandomCluster.java:36-92, ClusterProperty.java:7-19,
# TestConstants.java:17-60).
# ---------------------------------------------------------------------------


def xl_cluster(seed: int = 0):
    """10×-LinkedIn fixture: 26K brokers / 5M replicas — the multi-host
    regime behind ``BENCH_SIZE=xl`` (bench.py) where the [R,4] load tensor
    and the chain pytree are meant to live sharded over a mesh, never
    materialized per-device. Same generator and placement recipe as the
    LinkedIn config, scaled 10× on brokers/replicas (racks 2×: rack count
    grows far sublinearly in real fleets; topics capped at 100K — the
    topic term is beyond the dense limit either way)."""
    return synthetic_cluster(num_brokers=26_000, num_replicas=5_000_000,
                             num_racks=80, num_topics=100_000, seed=seed)


def synthetic_cluster(num_brokers: int = 2_600, num_replicas: int = 500_000,
                      num_racks: int = 40, rf: int = 3, num_topics: int = 30_000,
                      seed: int = 0, mean_nw_in: float = 50.0,
                      mean_nw_out: float = 50.0, mean_disk: float = 100.0,
                      mean_cpu: float = 0.01, capacity=None,
                      rack_aware_placement: bool = True):
    """LinkedIn-scale synthetic model, built as arrays (no per-partition Python
    loop) — the BASELINE.json configs' 2.6K-broker / 500K-replica regime.

    Placement mimics a real Kafka cluster (rack-aware round-robin like
    Kafka's assigner, exponential per-partition load skew), so the
    optimizer's job is *rebalance*, matching the reference benchmark
    scenario. Returns (ClusterTopology, Assignment).
    """
    from cruise_control_tpu.models.cluster import (
        ClusterTopology, initial_assignment, leadership_extra_from_leader_load)

    rng = np.random.default_rng(seed)
    B, K = num_brokers, num_racks
    P = num_replicas // rf
    R = P * rf

    rack_of_broker = (np.arange(B) % K).astype(np.int32)
    host_of_broker = np.arange(B, dtype=np.int32)   # one host per broker
    if capacity is None:
        capacity = np.array([BROKER_CAPACITY[i] for i in range(res.NUM_RESOURCES)],
                            np.float32)
    cap = np.broadcast_to(np.asarray(capacity, np.float32), (B, res.NUM_RESOURCES)).copy()

    # brokers grouped by rack for rack-aware placement
    order = np.argsort(rack_of_broker, kind="stable").astype(np.int32)
    counts = np.bincount(rack_of_broker, minlength=K)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])

    if rack_aware_placement:
        assert rf <= K, "rack-aware placement needs rf <= num_racks"
        # pick rf distinct racks per partition (rotate a random start), then a
        # random broker within each rack
        start_rack = rng.integers(0, K, size=P)
        rack_pick = (start_rack[:, None] + np.arange(rf)[None, :]) % K  # [P, rf]
        within = rng.integers(0, 1 << 30, size=(P, rf))
        broker_of = order[starts[rack_pick] + within % counts[rack_pick]].astype(np.int32)
    else:
        # fully random distinct brokers via iterative resampling
        broker_of = rng.integers(0, B, size=(P, rf)).astype(np.int32)
        for _ in range(8):
            dup = np.zeros((P, rf), bool)
            for j in range(1, rf):
                dup[:, j] = (broker_of[:, :j] == broker_of[:, j:j + 1]).any(axis=1)
            if not dup.any():
                break
            broker_of[dup] = rng.integers(0, B, size=int(dup.sum()))
    broker_of = broker_of.reshape(-1)                                  # [R]

    # topics: exponential popularity over partitions
    popularity = rng.exponential(1.0, size=num_topics)
    topic_of_partition = rng.choice(
        num_topics, size=P, p=popularity / popularity.sum()).astype(np.int32)
    # leader loads: exponential skew around the means
    means = np.zeros(res.NUM_RESOURCES, np.float32)
    means[res.CPU], means[res.DISK] = mean_cpu, mean_disk
    means[res.NW_IN], means[res.NW_OUT] = mean_nw_in, mean_nw_out
    leader_load = (rng.exponential(1.0, size=(P, res.NUM_RESOURCES))
                   .astype(np.float32) * means)
    extra = leadership_extra_from_leader_load(leader_load)             # [P, 4]
    base_leader = leader_load - extra
    # follower base = derived follower load == base_leader (by construction)
    replica_base_load = np.repeat(base_leader, rf, axis=0)             # [R, 4]

    replicas_of_partition = np.arange(R, dtype=np.int32).reshape(P, rf)
    # per-topic running partition numbers
    order_p = np.argsort(topic_of_partition, kind="stable")
    st = topic_of_partition[order_p]
    first = np.concatenate([[True], st[1:] != st[:-1]]) if P else np.zeros(0, bool)
    grp_start = np.maximum.accumulate(np.where(first, np.arange(P), 0))
    partition_index = np.zeros(P, np.int32)
    partition_index[order_p] = (np.arange(P) - grp_start).astype(np.int32)
    topo = ClusterTopology(
        rack_of_broker=rack_of_broker,
        host_of_broker=host_of_broker,
        capacity=cap,
        broker_alive=np.ones(B, bool),
        broker_new=np.zeros(B, bool),
        broker_demoted=np.zeros(B, bool),
        broker_bad_disks=np.zeros(B, bool),
        partition_of_replica=np.repeat(np.arange(P, dtype=np.int32), rf),
        topic_of_partition=topic_of_partition,
        replicas_of_partition=replicas_of_partition,
        rf_of_partition=np.full(P, rf, np.int32),
        initial_leader_slot=np.zeros(P, np.int64),
        replica_offline=np.zeros(R, bool),
        replica_base_load=replica_base_load,
        leader_extra=extra,
        leader_bytes_in=leader_load[:, res.NW_IN].copy(),
        topic_names=tuple(f"topic{i}" for i in range(num_topics)),
        partition_index=partition_index,
        broker_ids=np.arange(B, dtype=np.int32),
        host_names=tuple(f"host{i}" for i in range(B)),
        rack_names=tuple(f"rack{i}" for i in range(K)),
    )
    return topo, initial_assignment(topo, broker_of)


class Distribution(enum.Enum):
    UNIFORM = "uniform"
    LINEAR = "linear"
    EXPONENTIAL = "exponential"


@dataclasses.dataclass
class ClusterProperties:
    """ClusterProperty defaults from TestConstants.BASE_PROPERTIES
    (TestConstants.java:52-66): 10 racks / 40 brokers / 50,001 replicas over
    3,000 topics at rf=3."""

    num_racks: int = 10
    num_brokers: int = 40
    num_dead_brokers: int = 0
    num_brokers_with_bad_disk: int = 0
    num_replicas: int = 50_001
    num_topics: int = 3_000
    min_replication: int = 3
    max_replication: int = 3
    mean_cpu: float = 0.01
    mean_disk: float = 100.0
    mean_nw_in: float = 100.0
    mean_nw_out: float = 100.0


def random_cluster(props: ClusterProperties = None, seed: int = 3140,
                   distribution: Distribution = Distribution.EXPONENTIAL,
                   capacity=None):
    """Property-driven random cluster in the spirit of RandomCluster.java.

    Brokers round-robin over racks (one host per broker); topics get random
    popularity; partition leader loads are drawn per ``distribution`` around
    the configured means (UNIFORM: ±50%, LINEAR: proportional to index,
    EXPONENTIAL: exp-distributed), follower loads reference-derived. Returns
    (topology, assignment).
    """
    props = props or ClusterProperties()
    rng = np.random.default_rng(seed)
    b = ClusterModelBuilder()
    if capacity is None:
        capacity = BROKER_CAPACITY
    for i in range(props.num_brokers):
        b.create_broker(f"rack{i % props.num_racks}", f"host{i}", i, capacity)
    unhealthy = rng.choice(props.num_brokers,
                           size=props.num_dead_brokers + props.num_brokers_with_bad_disk,
                           replace=False)
    dead = set(int(i) for i in unhealthy[:props.num_dead_brokers])
    for i in dead:
        b.set_broker_state(i, alive=False)
    bad_disk = set(int(i) for i in unhealthy[props.num_dead_brokers:])
    for i in bad_disk:
        b.set_broker_state(i, bad_disks=True)

    # split replicas into partitions: rf uniform in [min, max]
    rf = rng.integers(props.min_replication, props.max_replication + 1,
                      size=props.num_replicas)  # upper bound on partitions
    cum = np.cumsum(rf)
    n_parts = int(np.searchsorted(cum, props.num_replicas)) + 1
    rf = rf[:n_parts]
    # topic popularity: partitions distributed over topics (some topics big).
    # Every topic gets at least one partition so the built model's topic
    # count equals n_topics for every seed — keeps shapes (and therefore jit
    # caches) stable across seeds of the same ClusterProperties.
    n_topics = min(props.num_topics, n_parts)
    popularity = rng.exponential(1.0, size=n_topics)
    topic_of_part = rng.choice(n_topics, size=n_parts, p=popularity / popularity.sum())
    topic_of_part[:n_topics] = rng.permutation(n_topics)

    means = np.zeros(res.NUM_RESOURCES)
    means[CPU], means[DISK] = props.mean_cpu, props.mean_disk
    means[NW_IN], means[NW_OUT] = props.mean_nw_in, props.mean_nw_out
    if distribution is Distribution.UNIFORM:
        loads = rng.uniform(0.5, 1.5, size=(n_parts, res.NUM_RESOURCES)) * means
    elif distribution is Distribution.LINEAR:
        ramp = np.linspace(0.1, 1.9, n_parts)[:, None]
        loads = ramp * means
    else:
        loads = rng.exponential(1.0, size=(n_parts, res.NUM_RESOURCES)) * means
    loads = loads.astype(np.float32)

    part_counter: dict = {}
    for pi in range(n_parts):
        topic = f"topic{topic_of_part[pi]}"
        pidx = part_counter.get(topic, 0)
        part_counter[topic] = pidx + 1
        brokers = rng.choice(props.num_brokers, size=int(rf[pi]), replace=False)
        lead_load = loads[pi].copy()
        # Replicas on bad-disk brokers are offline with probability ~1/3,
        # mirroring markDiskDead-style fixtures.
        offline = tuple(int(x) for x in brokers
                        if int(x) in bad_disk and rng.random() < (1 / 3))
        b.create_partition(topic, pidx, int(brokers[0]), [int(x) for x in brokers[1:]],
                           lead_load, leader_bytes_in=float(lead_load[NW_IN]),
                           offline=offline)
    return b.build()


def fixture_digest(topo, assign=None) -> str:
    """Content hash of a fixture: sha256 over every array field (values +
    shape + dtype) of the topology, plus the assignment when given.

    bench.py stamps recorded baselines (e.g. the 2,258.4 s sequential
    LinkedIn walk) with the digest of the fixture they were measured
    against, so a generator change or a different BENCH_SEED can never be
    silently ratioed against a stale number.
    """
    import hashlib

    import jax

    h = hashlib.sha256()

    def feed(name, value):
        arr = np.asarray(jax.device_get(value))
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())

    for f in sorted(dataclasses.fields(topo), key=lambda f: f.name):
        value = getattr(topo, f.name)
        if isinstance(value, (np.ndarray,)) or hasattr(value, "__jax_array__") \
                or type(value).__name__ == "ArrayImpl":
            feed(f.name, value)
    if assign is not None:
        feed("broker_of", assign.broker_of)
        feed("leader_of", assign.leader_of)
    return h.hexdigest()
