"""Async operation machinery: futures, user tasks, sessions, purgatory.

Rebuilds the servlet-side async stack:
- ``OperationFuture`` + typed progress steps
  (``async/OperationFuture.java``, ``async/progress/*.java``)
- ``UserTaskManager`` (``servlet/UserTaskManager.java:62-216``): UUID-keyed
  active/completed task maps with per-endpoint retention, session binding
- ``SessionManager`` (``servlet/SessionManager.java``)
- ``Purgatory`` 2-step verification for POSTs
  (``servlet/purgatory/Purgatory.java:42-166``): submit → PENDING_REVIEW →
  approve/discard → submitted once.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

_now_ms = lambda: int(time.time() * 1000)


class OperationProgress:
    """Typed progress steps (async/progress/OperationProgress.java)."""

    def __init__(self):
        self._steps: List[Tuple[str, float]] = []
        self._lock = threading.Lock()

    def add_step(self, description: str):
        with self._lock:
            self._steps.append((description, time.time()))

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [{"step": s, "time": t} for s, t in self._steps]


import contextvars

#: the progress sink of the operation running on THIS thread — subsystems
#: report steps without threading a handle through every signature
#: (OperationProgress.java is likewise ambient via the runnable)
_current_progress: "contextvars.ContextVar[Optional[OperationProgress]]" = \
    contextvars.ContextVar("operation_progress", default=None)


def report_progress(description: str) -> None:
    """Record a step on the in-flight operation, if any (no-op outside)."""
    p = _current_progress.get()
    if p is not None:
        p.add_step(description)


class OperationFuture:
    """A future with progress + the uuid of its user task."""

    def __init__(self, operation: str):
        self.operation = operation
        self.progress = OperationProgress()
        self._future: Future = Future()

    def set_execution(self, fn: Callable[["OperationFuture"], Any],
                      pool: ThreadPoolExecutor):
        def run():
            token = _current_progress.set(self.progress)
            try:
                self._future.set_result(fn(self))
            except BaseException as e:
                self._future.set_exception(e)
            finally:
                _current_progress.reset(token)
        pool.submit(run)

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None):
        return self._future.result(timeout)

    def exception(self):
        return self._future.exception() if self._future.done() else None

    def describe(self) -> dict:
        out = {"operation": self.operation, "done": self.done(),
               "progress": self.progress.snapshot()}
        if self.done() and self._future.exception() is not None:
            out["error"] = str(self._future.exception())
        return out


class TaskState(enum.Enum):
    ACTIVE = "Active"
    COMPLETED = "Completed"
    COMPLETED_WITH_ERROR = "CompletedWithError"


@dataclasses.dataclass
class UserTaskInfo:
    task_id: str
    endpoint: str
    request_url: str
    client_id: str
    start_ms: int
    future: OperationFuture

    @property
    def state(self) -> TaskState:
        if not self.future.done():
            return TaskState.ACTIVE
        return (TaskState.COMPLETED_WITH_ERROR
                if self.future.exception() is not None else TaskState.COMPLETED)

    def to_json(self) -> dict:
        return {"UserTaskId": self.task_id, "Status": self.state.value,
                "RequestURL": self.request_url, "ClientIdentity": self.client_id,
                "StartMs": self.start_ms, "endpoint": self.endpoint}


class UserTaskManager:
    """UUID-keyed active/completed tasks with retention."""

    def __init__(self, max_active_tasks: int = 25,
                 completed_retention_ms: int = 86_400_000,
                 max_cached_completed: int = 100,
                 retention_ms_by_type: Optional[Dict[str, int]] = None,
                 max_completed_by_type: Optional[Dict[str, int]] = None,
                 endpoint_type_fn: Optional[Callable[[str], str]] = None,
                 num_threads: int = 4, now_fn=_now_ms):
        self._active: Dict[str, UserTaskInfo] = {}
        self._completed: Dict[str, UserTaskInfo] = {}
        self._max_active = max_active_tasks
        self._retention_ms = completed_retention_ms
        self._max_completed = max_cached_completed
        #: per-EndpointType overrides (completed.<type>.user.task.retention
        #: .time.ms / max.cached.completed.<type>.user.tasks)
        self._retention_by_type = {k: v for k, v
                                   in (retention_ms_by_type or {}).items()
                                   if v is not None}
        self._max_by_type = {k: v for k, v
                             in (max_completed_by_type or {}).items()
                             if v is not None}
        self._type_of = endpoint_type_fn or (lambda endpoint: "")
        self._lock = threading.RLock()
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="user-task")
        self._now = now_fn

    def create_task(self, endpoint: str, request_url: str, client_id: str,
                    fn: Callable[[OperationFuture], Any]) -> UserTaskInfo:
        with self._lock:
            self._expire()
            if len(self._active) >= self._max_active:
                raise RuntimeError(
                    f"There are already {len(self._active)} active user "
                    f"tasks, which has reached the limit {self._max_active}")
            tid = str(uuid.uuid4())
            fut = OperationFuture(endpoint)
            info = UserTaskInfo(tid, endpoint, request_url, client_id,
                                self._now(), fut)
            self._active[tid] = info
        fut.set_execution(fn, self._pool)
        return info

    def get(self, task_id: str) -> Optional[UserTaskInfo]:
        with self._lock:
            self._expire()
            return self._active.get(task_id) or self._completed.get(task_id)

    def all_tasks(self) -> List[UserTaskInfo]:
        with self._lock:
            self._expire()
            return list(self._active.values()) + list(self._completed.values())

    def _expire(self):
        now = self._now()
        for tid, info in list(self._active.items()):
            if info.future.done():
                del self._active[tid]
                self._completed[tid] = info
        for tid, info in list(self._completed.items()):
            retention = self._retention_by_type.get(
                self._type_of(info.endpoint), self._retention_ms)
            if now - info.start_ms > retention:
                del self._completed[tid]
        # size caps: per endpoint type where configured, then the global
        # max.cached.completed.user.tasks — oldest evicted first
        if self._max_by_type:
            by_type: Dict[str, List[str]] = {}
            for tid, info in self._completed.items():
                by_type.setdefault(self._type_of(info.endpoint),
                                   []).append(tid)
            for etype, cap in self._max_by_type.items():
                tids = by_type.get(etype, [])
                if len(tids) > cap:
                    tids.sort(key=lambda t: self._completed[t].start_ms)
                    for tid in tids[:len(tids) - cap]:
                        del self._completed[tid]
        if len(self._completed) > self._max_completed:
            for tid, _ in sorted(self._completed.items(),
                                 key=lambda kv: kv[1].start_ms
                                 )[:len(self._completed) - self._max_completed]:
                del self._completed[tid]

    def close(self):
        self._pool.shutdown(wait=False)


class SessionManager:
    """HTTP session key → in-flight task binding with expiry."""

    def __init__(self, max_expiry_ms: int = 60_000, now_fn=_now_ms):
        self._by_session: Dict[str, Tuple[str, int]] = {}
        self._expiry = max_expiry_ms
        self._now = now_fn
        self._lock = threading.Lock()

    def bind(self, session_key: str, task_id: str):
        with self._lock:
            self._by_session[session_key] = (task_id, self._now())

    def unbind(self, session_key: str):
        with self._lock:
            self._by_session.pop(session_key, None)

    def task_for(self, session_key: str) -> Optional[str]:
        with self._lock:
            self._sweep()
            entry = self._by_session.get(session_key)
            return entry[0] if entry else None

    def _sweep(self):
        now = self._now()
        for k, (tid, t0) in list(self._by_session.items()):
            if now - t0 > self._expiry:
                del self._by_session[k]


class ReviewStatus(enum.Enum):
    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


@dataclasses.dataclass
class ReviewRequest:
    review_id: int
    endpoint: str
    request_url: str
    submitter: str
    status: ReviewStatus = ReviewStatus.PENDING_REVIEW
    reason: str = ""
    submitted_task_id: Optional[str] = None
    #: the parameters as reviewed — the resubmission executes THESE, so an
    #: approval cannot be redeemed for a different request
    #: (Purgatory.java submit() executes the stored request's parameters)
    params: Dict[str, str] = dataclasses.field(default_factory=dict)
    submitted_ms: int = 0

    def to_json(self) -> dict:
        return {"Id": self.review_id, "EndPoint": self.endpoint,
                "RequestURL": self.request_url, "Submitter": self.submitter,
                "Status": self.status.value, "Reason": self.reason,
                "SubmittedTaskId": self.submitted_task_id}


class Purgatory:
    """Two-step verification (servlet/purgatory/Purgatory.java:42-166)."""

    def __init__(self, max_requests: int = 25,
                 retention_ms: int = 1_209_600_000, now_fn=_now_ms):
        self._requests: Dict[int, ReviewRequest] = {}
        self._next_id = 0
        self._max_requests = max_requests
        self._retention_ms = retention_ms
        self._now = now_fn
        self._lock = threading.Lock()

    def _evict_locked(self):
        """Drop requests past retention — by submission age REGARDLESS of
        status (Purgatory.java:254 removeOldRequests): stale unreviewed
        submissions must age out too, or ``max_requests`` of them would
        return 429 to every reviewable POST forever."""
        cutoff = self._now() - self._retention_ms
        for rid in [rid for rid, r in self._requests.items()
                    if r.submitted_ms < cutoff]:
            del self._requests[rid]

    def submit(self, endpoint: str, request_url: str, submitter: str,
               params: Optional[Dict[str, str]] = None) -> ReviewRequest:
        with self._lock:
            self._evict_locked()
            pending = sum(1 for r in self._requests.values()
                          if r.status == ReviewStatus.PENDING_REVIEW)
            if pending >= self._max_requests:
                raise ValueError(
                    f"purgatory is full ({pending} pending reviews, "
                    f"max {self._max_requests})")
            r = ReviewRequest(self._next_id, endpoint, request_url, submitter,
                              params=dict(params or {}),
                              submitted_ms=self._now())
            self._requests[self._next_id] = r
            self._next_id += 1
            return r

    def review(self, review_id: int, approve: bool, reason: str = ""
               ) -> ReviewRequest:
        with self._lock:
            r = self._requests.get(review_id)
            if r is None:
                raise KeyError(f"no review request {review_id}")
            if r.status != ReviewStatus.PENDING_REVIEW:
                raise ValueError(f"request {review_id} is {r.status.value}, "
                                 "not PENDING_REVIEW")
            r.status = (ReviewStatus.APPROVED if approve
                        else ReviewStatus.DISCARDED)
            r.reason = reason
            return r

    def take_approved(self, review_id: int,
                      endpoint: Optional[str] = None) -> ReviewRequest:
        """Mark an APPROVED request SUBMITTED (each approval is usable once).

        When ``endpoint`` is given, the approval is only redeemable at the
        endpoint it was reviewed for (Purgatory.submit endpoint check); a
        mismatch raises without consuming the approval.
        """
        with self._lock:
            r = self._requests.get(review_id)
            if r is None:
                raise KeyError(f"no review request {review_id}")
            if endpoint is not None and r.endpoint != endpoint:
                raise ValueError(
                    f"review {review_id} was approved for {r.endpoint}, "
                    f"not {endpoint}")
            if r.status != ReviewStatus.APPROVED:
                raise ValueError(f"request {review_id} is {r.status.value}, "
                                 "not APPROVED")
            r.status = ReviewStatus.SUBMITTED
            return r

    def reopen(self, review_id: int) -> None:
        """Roll a SUBMITTED request back to APPROVED — used when the
        submitted handler fails before doing any work, so a transient error
        does not burn the approval (take/reopen keeps single-use atomic
        under concurrent resubmits)."""
        with self._lock:
            r = self._requests.get(review_id)
            if r is not None and r.status == ReviewStatus.SUBMITTED:
                r.status = ReviewStatus.APPROVED

    def board(self) -> List[dict]:
        with self._lock:
            return [r.to_json() for r in self._requests.values()]
