"""REST API: the 26-endpoint servlet over the service facade.

Rebuild of ``servlet/KafkaCruiseControlServlet.java:95-135`` +
``servlet/CruiseControlEndPoint.java:16-36`` on the stdlib threading HTTP
server: GET/POST dispatch to endpoint handlers, query-parameter parsing
(``servlet/parameters/ParameterUtils.java`` semantics for the parameters
this framework consumes), JSON responses, async endpoints through
UserTaskManager (poll with the returned User-Task-ID), optional 2-step
verification through the Purgatory.
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import json
import logging
import os
import threading
import urllib.parse

logger = logging.getLogger(__name__)

#: cookie session identity of the in-flight request (see RestApi.dispatch)
_SESSION_ID: "contextvars.ContextVar" = contextvars.ContextVar(
    "cc_session_id", default=None)
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.app import CruiseControlApp
from cruise_control_tpu.server.async_ops import (
    Purgatory,
    SessionManager,
    UserTaskManager,
)

#: The ONE endpoint registry: (name, HTTP method, EndpointType). Every
#: derived structure below — the method-specific lists the dispatcher
#: validates against, ALL_ENDPOINTS in 404 payloads, and the EndpointType
#: classification (CruiseControlEndPoint.java:17-36) driving per-type
#: completed-task retention — comes from this table, so a new endpoint
#: cannot be half-registered (in ENDPOINT_TYPES but missing from the
#: method list, or vice versa).
_ENDPOINT_TABLE = (
    # -- GET --------------------------------------------------------------
    ("BOOTSTRAP", "GET", "CRUISE_CONTROL_ADMIN"),
    ("TRAIN", "GET", "CRUISE_CONTROL_ADMIN"),
    ("LOAD", "GET", "KAFKA_MONITOR"),
    ("PARTITION_LOAD", "GET", "KAFKA_MONITOR"),
    ("PROPOSALS", "GET", "KAFKA_MONITOR"),
    ("STATE", "GET", "CRUISE_CONTROL_MONITOR"),
    ("KAFKA_CLUSTER_STATE", "GET", "KAFKA_MONITOR"),
    ("USER_TASKS", "GET", "CRUISE_CONTROL_MONITOR"),
    ("REVIEW_BOARD", "GET", "CRUISE_CONTROL_MONITOR"),
    ("METRICS", "GET", "CRUISE_CONTROL_MONITOR"),
    ("OBSERVATORY", "GET", "CRUISE_CONTROL_MONITOR"),
    ("EXPLAIN", "GET", "KAFKA_MONITOR"),
    ("FLIGHTRECORDER", "GET", "CRUISE_CONTROL_MONITOR"),
    ("ALERTS", "GET", "CRUISE_CONTROL_MONITOR"),
    ("HEADROOM", "GET", "CRUISE_CONTROL_MONITOR"),
    ("WHAT_IF", "GET", "KAFKA_MONITOR"),
    # -- POST -------------------------------------------------------------
    ("ADD_BROKER", "POST", "KAFKA_ADMIN"),
    ("REMOVE_BROKER", "POST", "KAFKA_ADMIN"),
    ("FIX_OFFLINE_REPLICAS", "POST", "KAFKA_ADMIN"),
    ("REBALANCE", "POST", "KAFKA_ADMIN"),
    ("STOP_PROPOSAL_EXECUTION", "POST", "KAFKA_ADMIN"),
    ("PAUSE_SAMPLING", "POST", "CRUISE_CONTROL_ADMIN"),
    ("RESUME_SAMPLING", "POST", "CRUISE_CONTROL_ADMIN"),
    ("DEMOTE_BROKER", "POST", "KAFKA_ADMIN"),
    ("ADMIN", "POST", "CRUISE_CONTROL_ADMIN"),
    ("REVIEW", "POST", "CRUISE_CONTROL_ADMIN"),
    ("TOPIC_CONFIGURATION", "POST", "KAFKA_ADMIN"),
    ("RIGHTSIZE", "POST", "KAFKA_ADMIN"),
)

GET_ENDPOINTS = [n for n, m, _ in _ENDPOINT_TABLE if m == "GET"]
POST_ENDPOINTS = [n for n, m, _ in _ENDPOINT_TABLE if m == "POST"]
ALL_ENDPOINTS = [n for n, _, _ in _ENDPOINT_TABLE]
ENDPOINT_TYPES = {n: t for n, _, t in _ENDPOINT_TABLE}

#: POST endpoints subject to 2-step verification when enabled
REVIEWABLE = {"ADD_BROKER", "REMOVE_BROKER", "FIX_OFFLINE_REPLICAS",
              "REBALANCE", "DEMOTE_BROKER", "TOPIC_CONFIGURATION"}


def _parse_bool(params: dict, name: str, default: bool) -> bool:
    v = params.get(name)
    if v is None:
        return default
    return str(v).strip().lower() == "true"


def _parse_csv_ints(params: dict, name: str) -> List[int]:
    v = params.get(name)
    if not v:
        return []
    return [int(x) for x in str(v).split(",") if x.strip()]


def _parse_csv(params: dict, name: str) -> List[str]:
    v = params.get(name)
    if not v:
        return []
    return [x.strip() for x in str(v).split(",") if x.strip()]


def _goal_based_params(params: Dict[str, str]) -> dict:
    """Shared GoalBasedOptimizationParameters surface
    (servlet/parameters/GoalBasedOptimizationParameters.java): data_from,
    use_ready_default_goals, exclude_recently_removed/demoted_brokers."""
    return dict(
        data_from=params.get("data_from"),
        use_ready_default_goals=_parse_bool(
            params, "use_ready_default_goals", False),
        exclude_recently_removed_brokers=_parse_bool(
            params, "exclude_recently_removed_brokers", False),
        exclude_recently_demoted_brokers=_parse_bool(
            params, "exclude_recently_demoted_brokers", False),
        skip_hard_goal_check=_parse_bool(params, "skip_hard_goal_check",
                                         False),
        allow_capacity_estimation=_parse_bool(
            params, "allow_capacity_estimation", True),
        min_valid_partition_ratio=(
            float(params["min_valid_partition_ratio"])
            if params.get("min_valid_partition_ratio") else None),
    )


def _executor_params(params: Dict[str, str]) -> dict:
    """Per-request executor overrides (ParameterUtils):
    concurrent_leader_movements, execution_progress_check_interval_ms,
    replication_throttle, replica_movement_strategies."""
    kw: dict = {}
    if params.get("concurrent_leader_movements"):
        kw["leader_concurrency"] = int(params["concurrent_leader_movements"])
    if params.get("execution_progress_check_interval_ms"):
        kw["progress_check_interval_ms"] = int(
            params["execution_progress_check_interval_ms"])
    if params.get("replication_throttle"):
        kw["replication_throttle"] = int(params["replication_throttle"])
    strategies = _parse_csv(params, "replica_movement_strategies")
    if strategies:
        kw["strategy_names"] = strategies
    return kw


class RestApi:
    """Endpoint handlers; transport-independent (the HTTP layer and tests
    call ``dispatch`` directly)."""

    def __init__(self, app: CruiseControlApp):
        self.app = app
        cfg = app.config
        _types = (("cruise.control.admin", "CRUISE_CONTROL_ADMIN"),
                  ("cruise.control.monitor", "CRUISE_CONTROL_MONITOR"),
                  ("kafka.admin", "KAFKA_ADMIN"),
                  ("kafka.monitor", "KAFKA_MONITOR"))
        self.user_tasks = UserTaskManager(
            max_active_tasks=cfg.get("max.active.user.tasks"),
            completed_retention_ms=cfg.get(
                "completed.user.task.retention.time.ms"),
            max_cached_completed=cfg.get("max.cached.completed.user.tasks"),
            retention_ms_by_type={
                label: cfg.get(f"completed.{key}.user.task.retention.time.ms")
                for key, label in _types},
            max_completed_by_type={
                label: cfg.get(f"max.cached.completed.{key}.user.tasks")
                for key, label in _types},
            endpoint_type_fn=lambda e: ENDPOINT_TYPES.get(e.upper(), ""))
        self.sessions = SessionManager(
            max_expiry_ms=cfg.get("webserver.session.maxExpiryPeriodMs"))
        self.purgatory = Purgatory(
            max_requests=cfg.get("two.step.purgatory.max.requests"),
            retention_ms=cfg.get("two.step.purgatory.retention.time.ms"),
        ) if cfg.get("two.step.verification.enabled") else None
        self.prefix = cfg.get("webserver.api.urlprefix").rstrip("/")
        self.reason_required = bool(cfg.get("request.reason.required"))
        self._accesslog_lock = threading.Lock()
        self._accesslog_file = None
        self._accesslog_date = None   # date the open file was started

    def close(self):
        if self._accesslog_file:
            try:
                self._accesslog_file.close()
            except OSError:
                pass
        self.user_tasks.close()

    def _open_accesslog(self, path: str):
        """Open the access log, rotating a previous day's file to
        ``path.YYYY-MM-DD`` and deleting rotated logs older than
        ``webserver.accesslog.retention.days``."""
        import datetime
        import glob
        import time as _time
        retention_days = int(
            self.app.config.get("webserver.accesslog.retention.days") or 14)
        try:
            st = os.stat(path)
            mdate = datetime.date.fromtimestamp(st.st_mtime)
            if mdate != datetime.date.today():
                os.replace(path, f"{path}.{mdate.isoformat()}")
        except OSError:
            pass
        cutoff = _time.time() - retention_days * 86_400
        for rotated in glob.glob(path + ".*"):
            try:
                if os.path.getmtime(rotated) < cutoff:
                    os.remove(rotated)
            except OSError:
                continue
        return open(path, "a", buffering=1)

    # ------------------------------------------------------------- dispatch

    def dispatch(self, method: str, endpoint: str, params: Dict[str, str],
                 client_id: str = "local", request_url: str = "",
                 session_id: Optional[str] = None) -> Tuple[int, dict]:
        """``client_id`` stays the request origin (peer address — the
        identity USER_TASKS client_ids filtering and review submitters
        record); ``session_id`` is the cookie identity the session→task
        binding keys on (defaults to client_id for cookie-less callers).
        It rides a contextvar so the ~20 per-endpoint handlers keep their
        (params, client_id, request_url) signature."""
        token = _SESSION_ID.set(session_id or client_id)
        try:
            return self._dispatch(method, endpoint, params, client_id,
                                  request_url)
        finally:
            _SESSION_ID.reset(token)

    def _dispatch(self, method: str, endpoint: str, params: Dict[str, str],
                  client_id: str = "local", request_url: str = ""
                  ) -> Tuple[int, dict]:
        endpoint = endpoint.upper()
        if endpoint not in ALL_ENDPOINTS:
            return 404, {"errorMessage": f"Unknown endpoint {endpoint}",
                         "validEndpoints": ALL_ENDPOINTS}
        if method == "GET" and endpoint not in GET_ENDPOINTS:
            return 405, {"errorMessage": f"{endpoint} requires POST",
                         "validEndpoints": GET_ENDPOINTS}
        if method == "POST" and endpoint not in POST_ENDPOINTS:
            return 405, {"errorMessage": f"{endpoint} requires GET",
                         "validEndpoints": POST_ENDPOINTS}
        # restart reconciliation in flight: the executor is still resolving
        # journaled pre-crash tasks, so mutating requests must wait — 503
        # (retryable, unlike a 500) while reads (/state etc.) stay served
        if method == "POST" and getattr(self.app, "is_reconciling", False):
            return 503, {"errorMessage":
                         "restart reconciliation in progress; retry shortly",
                         "reconciling": True}
        # two-step verification (Purgatory.java:116-166)
        consumed_review: Optional[int] = None
        if (method == "POST" and self.purgatory is not None
                and endpoint in REVIEWABLE):
            review_id = params.get("review_id")
            if review_id is None:
                try:
                    r = self.purgatory.submit(endpoint, request_url, client_id,
                                              params=params)
                except ValueError as e:    # purgatory full
                    return 429, {"errorMessage": str(e)}
                return 202, {"reviewResult": r.to_json(),
                             "message": "Submitted for review; approve via "
                                        "REVIEW then resubmit with review_id."}
            try:
                r = self.purgatory.take_approved(int(review_id),
                                                 endpoint=endpoint)
            except (KeyError, ValueError) as e:
                return 400, {"errorMessage": str(e)}
            consumed_review = int(review_id)
            # execute the request exactly as reviewed: an approval cannot be
            # redeemed with different parameters (e.g. flipping dryrun=false).
            # Client plumbing (poll timeout / task id) is not part of the
            # reviewed action and carries over from the resubmission.
            reviewed = dict(r.params)
            for k in ("get_response_timeout_ms", "user_task_id"):
                if k in params:
                    reviewed[k] = params[k]
            params = reviewed
            request_url = r.request_url

        # request.reason.required (ParameterUtils.java reason handling):
        # every POST operation must say why it was issued. Checked AFTER the
        # purgatory swap so an approved resubmission is judged on the params
        # as reviewed (which carried the reason).
        if (method == "POST" and self.reason_required
                and endpoint != "REVIEW" and not params.get("reason")):
            return 400, {"errorMessage":
                         f"{endpoint} requires a reason parameter "
                         "(request.reason.required=true)"}

        try:
            handler = getattr(self, f"_{endpoint.lower()}")
            code, payload = handler(params, client_id, request_url)
        except Exception as e:     # surface as the reference's error JSON
            # the client gets the error payload; the server log keeps the
            # traceback (the payload's one-liner is not enough to debug)
            logger.warning("%s request failed", endpoint, exc_info=True)
            code, payload = 500, {"errorMessage": f"{type(e).__name__}: {e}"}
        if consumed_review is not None and code >= 500:
            # the reviewed action never ran: re-open the approval so a
            # transient failure doesn't force a full re-review cycle
            self.purgatory.reopen(consumed_review)
        return code, payload

    # -------------------------------------------------- async plumbing

    def _async_op(self, endpoint: str, params: dict, client_id: str,
                  request_url: str, fn: Callable[[], dict]) -> Tuple[int, dict]:
        """Run an operation on the task pool; block up to
        ``get_response_timeout`` then return in-progress + User-Task-ID
        (AbstractAsyncRequest.handle semantics)."""
        existing = params.get("user_task_id")
        if existing:
            info = self.user_tasks.get(existing)
            if info is None:
                return 404, {"errorMessage": f"unknown user task {existing}"}
        else:
            # session → task binding (UserTaskManager.getOrCreateUserTask):
            # the SAME session repeating the SAME request (endpoint + its
            # parameters, minus the volatile polling ones) gets its
            # original task — in flight or successfully completed — instead
            # of spawning a duplicate operation; repetition is the
            # documented polling pattern, and a completed task's result
            # must stay deliverable to the poller. Replay staleness is
            # bounded by the session expiry
            # (webserver.session.maxExpiryPeriodMs). A task that FAILED
            # unbinds: a retry after a transient error must re-execute,
            # not replay the cached exception for the rest of the session.
            essence = sorted((k, v) for k, v in params.items()
                             if k not in ("user_task_id", "json",
                                          "get_response_timeout_ms"))
            sid = _SESSION_ID.get() or client_id
            session_key = f"{sid} {endpoint} {essence}"
            bound = self.sessions.task_for(session_key)
            info = self.user_tasks.get(bound) if bound else None
            if info is not None and info.future.exception() is not None:
                # deliver the stored failure ONCE (the result path below
                # re-raises it as the 500 payload), but unbind so the NEXT
                # repeat re-executes instead of replaying the error — and
                # so a persistently-failing mutating op is retried at the
                # client's pace, never in a silent loop
                self.sessions.unbind(session_key)
            elif info is None:
                info = self.user_tasks.create_task(
                    endpoint, request_url, client_id, lambda fut: fn())
                self.sessions.bind(session_key, info.task_id)
        timeout = float(params.get("get_response_timeout_ms", 1_000)) / 1000.0
        try:
            result = info.future.result(timeout=timeout)
            return 200, {"userTaskId": info.task_id, **result}
        except (TimeoutError, concurrent.futures.TimeoutError):
            # concurrent.futures.TimeoutError is NOT the builtin on
            # Python < 3.11; catching only the builtin turned every
            # still-in-flight wait into a 500 (and unbound the session,
            # breaking the repeat-request → same-task polling contract)
            return 202, {"userTaskId": info.task_id,
                         "progress": info.future.describe()}
        except Exception as e:
            # a failure observed LIVE (inside the wait) also unbinds, so
            # the error is delivered exactly once and the next repeat
            # re-executes (mirrors the pre-wait failed-binding check)
            if not existing:
                self.sessions.unbind(session_key)
            return 500, {"userTaskId": info.task_id,
                         "errorMessage": f"{type(e).__name__}: {e}"}

    # ------------------------------------------------------------ GET

    def _state(self, params, client_id, request_url):
        """CruiseControlState. AnalyzerState carries the mesh-policy
        surface (meshDevices: device count, 0 when unmeshed; shardedPath:
        whether optimize/warm-up run the sharded kernels) alongside the
        proposal/tick fields. SimulatorState (present after a scenario
        run — docs/simulation.md) carries the latest scorecard and is
        addressable via ``substates=simulator``. ReplicationState (role:
        leader/follower/standalone, lease holder + leaseExpiryMs,
        followerLagRecords — docs/operations.md "Replication and
        failover") is addressable via ``substates=replication``."""
        state = self.app.state(
            super_verbose=_parse_bool(params, "super_verbose", False))
        substates = _parse_csv(params, "substates")
        if substates:
            want = {s.lower() for s in substates}
            state = {k: v for k, v in state.items()
                     if k.lower().replace("state", "") in want
                     or k.lower() in want}
        return 200, state

    def _kafka_cluster_state(self, params, client_id, request_url):
        return 200, self.app.kafka_cluster_state(
            populate_disk_info=_parse_bool(params, "populate_disk_info",
                                           False))

    def _metrics(self, params, client_id, request_url):
        """Metrics registry scrape. Default is the JSON snapshot;
        ``format=prometheus`` returns the text exposition format (the
        HTTP layer serves a str payload as
        ``text/plain; version=0.0.4`` verbatim)."""
        from cruise_control_tpu.common.metrics import REGISTRY
        if str(params.get("format", "")).strip().lower() == "prometheus":
            return 200, REGISTRY.prometheus()
        return 200, REGISTRY.snapshot()

    def _observatory(self, params, client_id, request_url):
        """Compile/retrace observatory: per-function jit trace / XLA
        compile counts, compile wall-time, steady-state retraces,
        device dispatches, transfer-guard violations — plus the span
        tracer summary (docs/observability.md)."""
        return 200, self.app.observability_state()

    def _explain(self, params, client_id, request_url):
        """Per-move goal attribution of the cached proposal (decision
        provenance, docs/observability.md): per-goal penalty deltas for
        every move, most beneficial first. ``partition=Topic-3`` filters to
        one topic-partition. Requires ``obs.provenance.enable``."""
        partition = params.get("partition")
        return 200, self.app.explain(
            partition=str(partition) if partition else None)

    def _flightrecorder(self, params, client_id, request_url):
        """Tick flight recorder export. Default is the canonical JSONL log
        (a str payload — served text/plain verbatim, the same bytes
        replay_tick.py consumes); ``format=json`` — or the common
        ``json=true`` — wraps the records + ring summary in a JSON body."""
        if (str(params.get("format", "")).strip().lower() == "json"
                or _parse_bool(params, "json", False)):
            return 200, {"summary": self.app.flightrec.summary(),
                         "records": self.app.flightrec.records()}
        return 200, self.app.flightrecorder_jsonl()

    def _alerts(self, params, client_id, request_url):
        """graftwatch burn-rate alerts (obs/healthwatch.py): active
        alerts, the rule registry, fire/suppress/resolve counts and —
        with ``history=N`` — the last N alert decisions. Requires
        ``healthwatch.enable``."""
        history = params.get("history")
        try:
            n = max(0, int(history)) if history is not None else 64
        except (TypeError, ValueError):
            return 400, {"errorMessage": f"bad history: {history!r}"}
        return 200, self.app.alerts_state(history=n)

    def _headroom(self, params, client_id, request_url):
        """graftwatch headroom forecast (obs/costmodel.py): device memory
        in use, the live-buffer census, and whether the next bucket-ladder
        step (×1.25 growth) fits the remaining device memory. Requires
        ``obs.costmodel.enable``."""
        return 200, self.app.headroom_state()

    def _proposals(self, params, client_id, request_url):
        if _parse_bool(params, "kafka_assigner", False):
            # ProposalsParameters accepts KAFKA_ASSIGNER_MODE_PARAM: the
            # proposals come from the deterministic assigner goals
            return self._async_op(
                "PROPOSALS", params, client_id, request_url,
                lambda: self.app.rebalance_kafka_assigner(dryrun=True))
        goals = _parse_csv(params, "goals") or None
        ignore_cache = _parse_bool(params, "ignore_proposal_cache", False)
        verbose = _parse_bool(params, "verbose", False)
        kw = _goal_based_params(params)
        return self._async_op(
            "PROPOSALS", params, client_id, request_url,
            lambda: self.app.proposals(
                goal_names=goals,
                ignore_proposal_cache=ignore_cache,
                **kw).to_json(verbose=verbose))

    def _load(self, params, client_id, request_url):
        # time: build the load view as of this epoch-ms (windows completed
        # by then; LoadRunnable TIME_PARAM)
        t = int(params["time"]) if params.get("time") else None
        topo, assign = self.app._model(now_ms=t)
        from cruise_control_tpu.ops.aggregates import (
            compute_aggregates, device_topology)
        import numpy as np
        dt = device_topology(topo)
        agg = compute_aggregates(dt, assign, topo.num_topics)
        hosts = {}
        brokers = []
        load = np.asarray(agg.broker_load)
        cnt = np.asarray(agg.replica_count)
        leaders = np.asarray(agg.leader_count)
        pot = np.asarray(agg.potential_nw_out)
        from cruise_control_tpu.common import resources as res
        for i, bid in enumerate(topo.broker_ids):
            brokers.append({
                "Broker": int(bid),
                "Host": topo.host_names[topo.host_of_broker[i]]
                if topo.host_names else str(topo.host_of_broker[i]),
                "Rack": topo.rack_names[topo.rack_of_broker[i]]
                if topo.rack_names else str(topo.rack_of_broker[i]),
                "BrokerState": "ALIVE" if topo.broker_alive[i] else "DEAD",
                "Replicas": int(cnt[i]),
                "Leaders": int(leaders[i]),
                "CpuPct": float(load[i, res.CPU]),
                "DiskMB": float(load[i, res.DISK]),
                "NwInRate": float(load[i, res.NW_IN]),
                "NwOutRate": float(load[i, res.NW_OUT]),
                "PnwOutRate": float(pot[i]),
            })
        return 200, {"brokers": brokers, "hosts": list(hosts.values()),
                     "version": 1}

    def _partition_load(self, params, client_id, request_url):
        topo, assign = self.app._model()
        import numpy as np
        from cruise_control_tpu.common import resources as res
        sort_res = {"cpu": res.CPU, "disk": res.DISK,
                    "network_inbound": res.NW_IN,
                    "network_outbound": res.NW_OUT}.get(
            str(params.get("resource", "disk")).lower(), res.DISK)
        n = int(params.get("entries", 50))
        lo = np.asarray(assign.leader_of)
        # max_load=true reports the MAX over metric windows instead of the
        # collapsed average (PartitionLoadParameters max_load/avg_load
        # booleans; model/Load.java:84-118 expectedUtilizationFor);
        # avg_load=true explicitly forces the average even with max_load set
        use_max = (_parse_bool(params, "max_load", False)
                   and not _parse_bool(params, "avg_load", False))
        windowed = use_max and topo.replica_base_load_windows is not None
        if windowed:
            win = (topo.replica_base_load_windows[lo]
                   + topo.leader_extra_windows)           # [P,W,4]
            leader_load = win.max(axis=1)
        else:
            leader_load = (topo.replica_base_load[lo]
                           + topo.leader_extra)           # [P,4]
        keep = np.ones(leader_load.shape[0], bool)
        # partition range "N" or "N-M" (PartitionLoadParameters)
        prange = params.get("partition")
        if prange:
            lohi = str(prange).split("-")
            p0 = int(lohi[0]); p1 = int(lohi[-1])
            keep &= ((topo.partition_index >= p0)
                     & (topo.partition_index <= p1))
        tpat = params.get("topic")
        if tpat:
            import re
            rx = re.compile(tpat)
            tmask = np.array([bool(rx.fullmatch(t)) for t in topo.topic_names])
            keep &= tmask[topo.topic_of_partition]
        want = _parse_csv_ints(params, "brokerid")
        if want:
            bo_l = np.asarray(assign.broker_of)[lo]
            keep &= np.isin(np.asarray(topo.broker_ids)[bo_l], want)
        masked = np.where(keep, leader_load[:, sort_res], -np.inf)
        order = np.argsort(-masked)[:min(n, int(keep.sum()))]
        bo = np.asarray(assign.broker_of)
        records = []
        for p in order:
            slots = topo.replicas_of_partition[p]
            slots = slots[slots >= 0]
            records.append({
                "topic": topo.topic_names[topo.topic_of_partition[p]],
                "partition": int(topo.partition_index[p]),
                "leader": int(topo.broker_ids[bo[lo[p]]]),
                "followers": [int(topo.broker_ids[bo[s]]) for s in slots
                              if s != lo[p]],
                "cpu": float(leader_load[p, res.CPU]),
                "disk": float(leader_load[p, res.DISK]),
                "networkInbound": float(leader_load[p, res.NW_IN]),
                "networkOutbound": float(leader_load[p, res.NW_OUT]),
            })
        # maxWindowLoad says whether max_load semantics were actually honored
        # (false = the model carries no windowed series, values are averages)
        return 200, {"records": records, "maxWindowLoad": windowed,
                     "version": 1}

    def _user_tasks(self, params, client_id, request_url):
        """UserTasksParameters: user_task_ids, client_ids, endpoints, types
        (Active/Completed), fetch_completed_task (include the result)."""
        tasks = self.user_tasks.all_tasks()
        ids = set(_parse_csv(params, "user_task_ids"))
        if ids:
            tasks = [t for t in tasks if t.task_id in ids]
        clients = set(_parse_csv(params, "client_ids"))
        if clients:
            tasks = [t for t in tasks if t.client_id in clients]
        endpoints = {e.upper() for e in _parse_csv(params, "endpoints")}
        if endpoints:
            tasks = [t for t in tasks if t.endpoint.upper() in endpoints]
        types = {t.lower() for t in _parse_csv(params, "types")}
        if types:
            tasks = [t for t in tasks
                     if ("completed" if t.future.done() else "active")
                     in types]
        fetch = _parse_bool(params, "fetch_completed_task", False)
        out = []
        for t in tasks:
            d = t.to_json()
            if fetch and t.future.done():
                try:
                    d["result"] = t.future.result(timeout=0)
                except Exception as e:
                    d["result"] = {"errorMessage": str(e)}
            out.append(d)
        return 200, {"userTasks": out, "version": 1}

    def _review_board(self, params, client_id, request_url):
        if self.purgatory is None:
            return 400, {"errorMessage": "two-step verification disabled"}
        board = self.purgatory.board()
        rids = set(_parse_csv_ints(params, "review_ids"))
        if rids:
            board = [r for r in board if r["Id"] in rids]
        return 200, {"requestInfo": board, "version": 1}

    def _bootstrap(self, params, client_id, request_url):
        start = int(params.get("start", 0))
        end = int(params.get("end", 0))
        return self._async_op(
            "BOOTSTRAP", params, client_id, request_url,
            lambda: (self.app.load_monitor.bootstrap(start, end)
                     or {"bootstrap": "done", "startMs": start, "endMs": end}))

    def _train(self, params, client_id, request_url):
        """Fit the linear-regression CPU model over a historical range
        (TrainRunnable → LoadMonitor.train; LinearRegressionModelParameters).
        The range is mandatory and bounded (the reference's TrainParameters
        rejects a missing start/end with 400)."""
        if "start" not in params or "end" not in params:
            return 400, {"errorMessage": "start and end parameters required"}
        try:
            start, end = int(params["start"]), int(params["end"])
        except ValueError:
            return 400, {"errorMessage": "start/end must be epoch ms"}
        if not (0 <= start < end):
            return 400, {"errorMessage": "need 0 <= start < end"}
        max_span = 10_000 * self.app.load_monitor.sampling_interval_ms
        if end - start > max_span:
            return 400, {"errorMessage":
                         f"training range too large (max {max_span} ms)"}
        clear = _parse_bool(params, "clearmetrics", True)
        return self._async_op(
            "TRAIN", params, client_id, request_url,
            lambda: {"train": self.app.load_monitor.train(
                         start, end, clear_metrics=clear),
                     "startMs": start, "endMs": end})

    def _what_if(self, params, client_id, request_url):
        """WHAT_IF: dry-run a counterfactual-scenario grid.

        ``add_brokers=2,4`` (one scenario per count, optional
        ``add_broker_rack``), ``remove_broker_ids=3,7`` (one scenario
        removing all listed), ``fail_racks=r1,r2`` (one per rack),
        ``scale_capacity=disk:0.5,cpu:1.5`` (one per resource:factor),
        ``add_partitions=topic:count``, ``deep=true`` for the anneal-based
        post-rebalance estimate."""
        kw = dict(
            add_broker_counts=_parse_csv_ints(params, "add_brokers"),
            add_broker_rack=params.get("add_broker_rack"),
            remove_broker_ids=_parse_csv_ints(params, "remove_broker_ids"),
            fail_racks=_parse_csv(params, "fail_racks"),
            scale_capacity=_parse_csv(params, "scale_capacity"),
            add_partitions=_parse_csv(params, "add_partitions"),
            deep=_parse_bool(params, "deep", False),
            headroom_margin=(float(params["headroom_margin"])
                             if params.get("headroom_margin") else None),
            allow_capacity_estimation=_parse_bool(
                params, "allow_capacity_estimation", True),
            data_from=params.get("data_from"),
            min_valid_partition_ratio=(
                float(params["min_valid_partition_ratio"])
                if params.get("min_valid_partition_ratio") else None),
        )
        return self._async_op("WHAT_IF", params, client_id, request_url,
                              lambda: self.app.what_if(**kw))

    # ------------------------------------------------------------ POST

    def _rightsize(self, params, client_id, request_url):
        """RIGHTSIZE: classify the cluster UNDER/OVER/RIGHT_SIZED and
        surface the recommendation (also recorded in /state)."""
        kw = dict(
            headroom_margin=(float(params["headroom_margin"])
                             if params.get("headroom_margin") else None),
            max_added_brokers=(int(params["max_added_brokers"])
                               if params.get("max_added_brokers") else None),
            max_removed_brokers=(
                int(params["max_removed_brokers"])
                if params.get("max_removed_brokers") else None),
            deep=_parse_bool(params, "deep", False),
            verbose=_parse_bool(params, "verbose", False),
            allow_capacity_estimation=_parse_bool(
                params, "allow_capacity_estimation", True),
            data_from=params.get("data_from"),
        )
        return self._async_op("RIGHTSIZE", params, client_id, request_url,
                              lambda: self.app.rightsize(**kw))

    def _rebalance(self, params, client_id, request_url):
        if _parse_bool(params, "rebalance_disk", False):
            dry = _parse_bool(params, "dryrun", True)
            return self._async_op(
                "REBALANCE", params, client_id, request_url,
                lambda: self.app.rebalance_disk(dryrun=dry))
        if _parse_bool(params, "kafka_assigner", False):
            dry = _parse_bool(params, "dryrun", True)
            return self._async_op(
                "REBALANCE", params, client_id, request_url,
                lambda: self.app.rebalance_kafka_assigner(dryrun=dry))
        kw = dict(
            goal_names=_parse_csv(params, "goals") or None,
            dryrun=_parse_bool(params, "dryrun", True),
            excluded_topics=_parse_csv(params, "excluded_topics"),
            destination_broker_ids=_parse_csv_ints(
                params, "destination_broker_ids"),
            verbose=_parse_bool(params, "verbose", False),
            **_goal_based_params(params),
        )
        if params.get("concurrent_partition_movements_per_broker"):
            kw["concurrency"] = int(
                params["concurrent_partition_movements_per_broker"])
        ek = _executor_params(params)
        if ek:
            kw["executor_kw"] = ek
        return self._async_op("REBALANCE", params, client_id, request_url,
                              lambda: self.app.rebalance(**kw))

    def _add_broker(self, params, client_id, request_url):
        ids = _parse_csv_ints(params, "brokerid")
        if not ids:
            return 400, {"errorMessage": "brokerid parameter required"}
        dry = _parse_bool(params, "dryrun", True)
        if _parse_bool(params, "kafka_assigner", False):
            # AddedOrRemovedBrokerParameters accepts kafka_assigner: the
            # even placement spreads onto the new brokers deterministically
            return self._async_op(
                "ADD_BROKER", params, client_id, request_url,
                lambda: self.app.rebalance_kafka_assigner(dryrun=dry))
        verbose = _parse_bool(params, "verbose", False)
        df = params.get("data_from")
        gb = _goal_based_params(params)
        gb.pop("skip_hard_goal_check", None)   # no custom goal list here
        gb.pop("data_from", None)              # passed explicitly
        tab = (int(params["throttle_added_broker"])
               if params.get("throttle_added_broker") else None)
        ek = _executor_params(params)
        return self._async_op("ADD_BROKER", params, client_id, request_url,
                              lambda: self.app.add_brokers(
                                  ids, dryrun=dry, verbose=verbose,
                                  data_from=df, **gb,
                                  throttle_added_broker=tab,
                                  executor_kw=ek))

    def _remove_broker(self, params, client_id, request_url):
        ids = _parse_csv_ints(params, "brokerid")
        if not ids:
            return 400, {"errorMessage": "brokerid parameter required"}
        dry = _parse_bool(params, "dryrun", True)
        if _parse_bool(params, "kafka_assigner", False):
            # kafka-assigner decommission: removed brokers become dead for
            # the deterministic placement, so every replica drains off them
            return self._async_op(
                "REMOVE_BROKER", params, client_id, request_url,
                lambda: self.app.rebalance_kafka_assigner(
                    dryrun=dry, removed_brokers=ids))
        verbose = _parse_bool(params, "verbose", False)
        df = params.get("data_from")
        gb = _goal_based_params(params)
        gb.pop("skip_hard_goal_check", None)
        gb.pop("data_from", None)
        trb = (int(params["throttle_removed_broker"])
               if params.get("throttle_removed_broker") else None)
        ek = _executor_params(params)
        return self._async_op("REMOVE_BROKER", params, client_id, request_url,
                              lambda: self.app.remove_brokers(
                                  ids, dryrun=dry, verbose=verbose,
                                  data_from=df, **gb,
                                  throttle_removed_broker=trb,
                                  executor_kw=ek))

    def _demote_broker(self, params, client_id, request_url):
        ids = _parse_csv_ints(params, "brokerid")
        dry = _parse_bool(params, "dryrun", True)
        verbose = _parse_bool(params, "verbose", False)
        df = params.get("data_from")
        # brokerid_and_logdirs=b1-logdir1,b2-logdir2 (disk demotion;
        # broker id before the FIRST dash, logdir may itself contain dashes)
        bld = {}
        if params.get("brokerid_and_logdirs"):
            for ent in str(params["brokerid_and_logdirs"]).split(","):
                ent = ent.strip()
                if not ent:
                    continue
                b, _, ld = ent.partition("-")
                if not ld or not b.isdigit():
                    return 400, {"errorMessage":
                                 f"bad brokerid_and_logdirs entry {ent!r}; "
                                 "expected brokerId-logdir"}
                bld.setdefault(int(b), []).append(ld)
        if not ids and not bld:
            return 400, {"errorMessage": "brokerid or brokerid_and_logdirs "
                                         "parameter required"}
        if bld and set(ids) & set(bld):
            return 400, {"errorMessage":
                         "Attempt to demote the broker and its disk in the "
                         "same request is not allowed."}
        skip_urp = _parse_bool(params, "skip_urp_demotion", False)
        excl_follower = _parse_bool(params, "exclude_follower_demotion",
                                    False)
        ace = _parse_bool(params, "allow_capacity_estimation", True)
        erd = _parse_bool(params, "exclude_recently_demoted_brokers", False)
        mvpr = (float(params["min_valid_partition_ratio"])
                if params.get("min_valid_partition_ratio") else None)
        ek = _executor_params(params)
        return self._async_op("DEMOTE_BROKER", params, client_id, request_url,
                              lambda: self.app.demote_brokers(
                                  ids, dryrun=dry, verbose=verbose,
                                  data_from=df,
                                  min_valid_partition_ratio=mvpr,
                                  skip_urp_demotion=skip_urp,
                                  exclude_follower_demotion=excl_follower,
                                  allow_capacity_estimation=ace,
                                  exclude_recently_demoted_brokers=erd,
                                  broker_id_and_logdirs=bld or None,
                                  executor_kw=ek))

    def _fix_offline_replicas(self, params, client_id, request_url):
        dry = _parse_bool(params, "dryrun", True)
        verbose = _parse_bool(params, "verbose", False)
        df = params.get("data_from")
        ek = _executor_params(params)
        gb = _goal_based_params(params)
        gb.pop("skip_hard_goal_check", None)   # fixed default-goal list
        gb.pop("data_from", None)
        return self._async_op(
            "FIX_OFFLINE_REPLICAS", params, client_id, request_url,
            lambda: self.app.fix_offline_replicas(
                dryrun=dry, verbose=verbose, data_from=df, **gb,
                executor_kw=ek))

    def _stop_proposal_execution(self, params, client_id, request_url):
        return 200, self.app.stop_execution(
            forced=_parse_bool(params, "force_stop", False))

    def _pause_sampling(self, params, client_id, request_url):
        return 200, self.app.pause_sampling(
            params.get("reason", "Paused by user"))

    def _resume_sampling(self, params, client_id, request_url):
        return 200, self.app.resume_sampling(
            params.get("reason", "Resumed by user"))

    def _admin(self, params, client_id, request_url):
        out = {}
        if "self_healing_for" in params or "enable_self_healing_for" in params:
            t = params.get("self_healing_for") or params.get(
                "enable_self_healing_for")
            enabled = _parse_bool(params, "enable_self_healing", True)
            out.update(self.app.set_self_healing(
                t.upper() if t and t.upper() != "ALL" else None, enabled))
        if "disable_self_healing_for" in params:
            t = params["disable_self_healing_for"]
            out.update(self.app.set_self_healing(
                t.upper() if t and t.upper() != "ALL" else None, False))
        if "concurrent_partition_movements_per_broker" in params:
            n = int(params["concurrent_partition_movements_per_broker"])
            self.app.executor.config.num_concurrent_partition_movements_per_broker = n
            out["concurrentPartitionMovementsPerBroker"] = n
        if "concurrent_leader_movements" in params:
            n = int(params["concurrent_leader_movements"])
            self.app.executor.config.num_concurrent_leader_movements = n
            out["concurrentLeaderMovements"] = n
        if "concurrent_intra_broker_partition_movements" in params:
            n = int(params["concurrent_intra_broker_partition_movements"])
            self.app.executor.config\
                .num_concurrent_intra_broker_partition_movements = n
            out["concurrentIntraBrokerPartitionMovements"] = n
        if "execution_progress_check_interval_ms" in params:
            n = int(params["execution_progress_check_interval_ms"])
            self.app.executor.config.execution_progress_check_interval_ms = n
            out["executionProgressCheckIntervalMs"] = n
        if _parse_bool(params, "drop_recently_removed_brokers", False):
            dropped = sorted(self.app.executor.recently_removed_brokers)
            self.app.executor.drop_history(removed=True)
            out["droppedRecentlyRemovedBrokers"] = dropped
        if _parse_bool(params, "drop_recently_demoted_brokers", False):
            dropped = sorted(self.app.executor.recently_demoted_brokers)
            self.app.executor.drop_history(demoted=True)
            out["droppedRecentlyDemotedBrokers"] = dropped
        if not out:
            return 400, {"errorMessage": "no admin action specified"}
        return 200, out

    def _review(self, params, client_id, request_url):
        if self.purgatory is None:
            return 400, {"errorMessage": "two-step verification disabled"}
        approve = _parse_csv_ints(params, "approve")
        discard = _parse_csv_ints(params, "discard")
        reason = params.get("reason", "")
        results = []
        for rid in approve:
            results.append(self.purgatory.review(rid, True, reason).to_json())
        for rid in discard:
            results.append(self.purgatory.review(rid, False, reason).to_json())
        return 200, {"requestInfo": results, "version": 1}

    def _topic_configuration(self, params, client_id, request_url):
        topic = params.get("topic")
        rf = params.get("replication_factor")
        if not topic or not rf:
            return 400, {"errorMessage":
                         "topic and replication_factor parameters required"}
        dry = _parse_bool(params, "dryrun", True)
        skip_rack = _parse_bool(params, "skip_rack_awareness_check", False)
        return self._async_op(
            "TOPIC_CONFIGURATION", params, client_id, request_url,
            lambda: self.app.update_topic_replication_factor(
                topic_pattern=topic, replication_factor=int(rf), dryrun=dry,
                skip_rack_awareness_check=skip_rack))


def _to_plaintext(payload, indent: int = 0) -> str:
    """Flat key/value text rendering for json=false responses."""
    pad = " " * indent
    if isinstance(payload, dict):
        lines = []
        for k, v in payload.items():
            if isinstance(v, (dict, list)):
                lines.append(f"{pad}{k}:")
                lines.append(_to_plaintext(v, indent + 2))
            else:
                lines.append(f"{pad}{k}: {v}")
        return "\n".join(lines)
    if isinstance(payload, list):
        return "\n".join(_to_plaintext(v, indent) for v in payload)
    return f"{pad}{payload}"


class _Handler(BaseHTTPRequestHandler):
    api: RestApi = None     # injected by serve()

    def _serve_ui(self, path: str) -> bool:
        """Static UI assets (webserver.ui.diskpath under
        webserver.ui.urlprefix; WebServerConfig's UI serving). Returns True
        when this request was a UI request (served or 404)."""
        cfg = self.api.app.config
        ui_dir = cfg.get("webserver.ui.diskpath")
        if not ui_dir:
            return False
        ui_prefix = (cfg.get("webserver.ui.urlprefix") or "/*").rstrip("*")
        ui_prefix = "/" + ui_prefix.strip("/")
        rel = None
        if ui_prefix == "/":
            rel = path.lstrip("/")
        elif path == ui_prefix or path.startswith(ui_prefix + "/"):
            rel = path[len(ui_prefix):].lstrip("/")
        if rel is None:
            return False
        full = os.path.realpath(os.path.join(ui_dir, rel or "index.html"))
        root = os.path.realpath(ui_dir)
        if not (full == root or full.startswith(root + os.sep)) \
                or not os.path.isfile(full):
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return True
        import mimetypes
        ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
        with open(full, "rb") as f:
            data = f.read()
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)
        return True

    def _session_id(self):
        """JSESSIONID from the request cookie, or a fresh one to set
        (None, new_id). The cookie binds async tasks to the caller's
        session (SessionManager); its path comes from
        ``webserver.session.path``."""
        cookie = self.headers.get("Cookie", "") or ""
        for part in cookie.split(";"):
            k, _, v = part.strip().partition("=")
            if k == "JSESSIONID" and v:
                return v, None
        import uuid
        return None, uuid.uuid4().hex

    def _do(self, method: str):
        parsed = urllib.parse.urlparse(self.path)
        params = {k: v[-1] for k, v in
                  urllib.parse.parse_qs(parsed.query).items()}
        if method == "POST":
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                body = self.rfile.read(length).decode()
                params.update({k: v[-1] for k, v in
                               urllib.parse.parse_qs(body).items()})
        path = parsed.path.rstrip("/")
        prefix = self.api.prefix
        if method == "GET" and not path.startswith(prefix) \
                and self._serve_ui(parsed.path):
            return
        endpoint = path[len(prefix):].strip("/") if path.startswith(prefix) \
            else path.strip("/")
        sid, new_sid = self._session_id()
        # client_id: always the peer address (USER_TASKS client_ids filters
        # and review submitters are request origins). The cookie identity
        # keys the session→task binding; a session's FIRST request binds
        # under the id the Set-Cookie below establishes, so the follow-up
        # carrying the cookie finds it instead of spawning a duplicate.
        # Cookie-less clients get a fresh session per request — exactly the
        # reference's Jetty behavior — and poll via User-Task-ID (cccli
        # does; the response carries the id on 200 AND 202).
        code, payload = self.api.dispatch(
            method, endpoint or "STATE", params,
            client_id=self.client_address[0],
            request_url=self.path,
            session_id=sid or new_sid)
        # json=false → text/plain rendering (the reference's default wire
        # format; ParameterUtils JSON_PARAM)
        as_json = str(params.get("json", "true")).strip().lower() != "false"
        if isinstance(payload, str):
            # pre-rendered text payload (/metrics?format=prometheus)
            data = payload.encode()
            ctype = "text/plain; version=0.0.4"
        elif as_json:
            data = json.dumps(payload, indent=2, default=str).encode()
            ctype = "application/json"
        else:
            data = _to_plaintext(payload).encode()
            ctype = "text/plain"
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        if new_sid is not None:
            self.send_header(
                "Set-Cookie",
                f"JSESSIONID={new_sid}; "
                f"Path={self.api.app.config.get('webserver.session.path')}")
        self._cors_headers()
        self.end_headers()
        self.wfile.write(data)

    def _cors_headers(self):
        cfg = self.api.app.config
        if cfg.get("webserver.http.cors.enabled"):
            self.send_header("Access-Control-Allow-Origin",
                             cfg.get("webserver.http.cors.origin"))
            self.send_header("Access-Control-Allow-Methods",
                             cfg.get("webserver.http.cors.allowmethods"))
            self.send_header("Access-Control-Expose-Headers",
                             cfg.get("webserver.http.cors.exposeheaders"))

    def do_GET(self):
        self._do("GET")

    def do_POST(self):
        self._do("POST")

    def do_OPTIONS(self):    # CORS preflight
        self.send_response(200)
        self._cors_headers()
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, fmt, *args):   # NCSA-style access log
        cfg = self.api.app.config
        if not cfg.get("webserver.accesslog.enabled"):
            return
        line = f"{self.client_address[0]} - {args[0] if args else ''}"
        path = cfg.get("webserver.accesslog.path")
        if path:
            # one handle for the server lifetime, opened lazily under a lock
            # (ThreadingHTTPServer logs concurrently); open failures are NOT
            # cached, so file logging resumes once the path is writable
            import datetime
            with self.api._accesslog_lock:
                f = self.api._accesslog_file
                today = datetime.date.today()
                if f is not None and self.api._accesslog_date != today:
                    # day rolled over mid-run: close and rotate
                    try:
                        f.close()
                    except OSError:
                        pass
                    f = self.api._accesslog_file = None
                if f is None:
                    try:
                        f = self.api._accesslog_file = \
                            self.api._open_accesslog(path)
                        self.api._accesslog_date = today
                    except OSError:
                        f = None
                if f is not None:
                    try:
                        f.write(line + "\n")
                        return
                    except OSError:
                        pass
        import sys
        print(line, file=sys.stderr)


def serve(app: CruiseControlApp, port: Optional[int] = None,
          address: Optional[str] = None) -> ThreadingHTTPServer:
    """Start the REST server (KafkaCruiseControlMain.java:79-115)."""
    api = RestApi(app)
    handler = type("Handler", (_Handler,), {"api": api})
    server = ThreadingHTTPServer(
        (address or app.config.get("webserver.http.address"),
         port if port is not None else app.config.get("webserver.http.port")),
        handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="cc-rest")
    thread.start()
    server.api = api          # for tests
    return server
