"""Device-resident health ring + vmapped multi-window burn-rate kernels.

The graftwatch layer (``obs/healthwatch.py``) keeps the last N per-tick
health vectors in a fixed-shape ``[N, F]`` float32 ring that lives on
device for the process lifetime.  Each tick is one ``push`` dispatch
(pure ``.at[idx].set`` on the carried ring) and one ``burn_rates``
dispatch that evaluates *every* alert rule's SRE-style fast/slow burn
windows in a single compiled program (``vmap`` over the rule axis) —
zero retraces after warmup because every shape is pinned at ring
construction and rule tables are baked device arrays.

Burn-rate semantics follow the multiwindow multi-burn-rate alerting
recipe (Google SRE workbook ch. 5): with an error budget ``b`` (allowed
bad-tick fraction) and a window of ``w`` ticks, the burn rate is
``bad_fraction(w) / b``; a rule fires only when *both* its fast and slow
windows exceed their burn thresholds, which keeps detection fast without
paging on blips.  All window math is ring-age arithmetic on the modular
write cursor, so a partially-filled ring never reads stale slots.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "HEALTH_FIELDS", "FIELD_INDEX", "new_ring", "push", "burn_rates",
]

#: column layout of one health vector (order is the wire format — the
#: ring, the rule ``signal`` lookup and the timeline export all index it)
HEALTH_FIELDS = (
    "ok",              # 1.0 when the tick produced/kept a usable proposal
    "latencyMs",       # tick wall time on the injected clock
    "latencyBreach",   # latencyMs > tick SLO
    "notReady",        # monitor starved (NotEnoughValidWindows)
    "failed",          # precompute raised
    "fallback",        # engine fallback engaged this tick
    "engineAnneal",    # 1.0 while the primary anneal engine is serving
    "healWallMs",      # last self-heal wall time
    "cacheHitRatio",   # proposal cache hits / (hits + misses)
    "watchdogRestarts",  # cumulative watchdog restart count
    "replicationLag",  # journal-shipping follower lag (records)
    "hardViolations",  # hard-goal violations on the served proposal
    "softViolations",  # soft-goal violations on the served proposal
    "degraded",        # max(latencyBreach, notReady, failed, fallback)
)

FIELD_INDEX = {name: i for i, name in enumerate(HEALTH_FIELDS)}


def new_ring(capacity: int):
    """Fresh ``([N, F] zeros ring, 0 count)`` pair, both device-resident."""
    ring = jnp.zeros((int(capacity), len(HEALTH_FIELDS)), jnp.float32)
    count = jnp.zeros((), jnp.int32)
    return ring, count


@jax.jit
def push(ring, count, vec):
    """Append one health vector; returns the updated ``(ring, count)``.

    The write cursor is ``count mod N`` so the ring wraps in place; the
    count itself grows without bound (age arithmetic in the burn kernel
    uses it to mask slots that were never written).
    """
    n = ring.shape[0]
    idx = jnp.mod(count, n)
    return ring.at[idx].set(vec.astype(ring.dtype)), count + 1


def _one_rule(ring, count, col, threshold, budget,
              fast_w, slow_w, fast_burn, slow_burn):
    """Burn-rate evaluation of a single rule (vmapped over rules)."""
    n = ring.shape[0]
    slots = jnp.arange(n, dtype=jnp.int32)
    # age 0 = the most recently written slot; never-written slots get an
    # age >= min(count, n) and fall out of every window mask below
    age = jnp.mod(count - 1 - slots, n)
    written = jnp.minimum(count, n)
    signal = jnp.take(ring, col, axis=1)              # [N]
    bad = (signal > threshold).astype(jnp.float32)

    def bad_fraction(window):
        span = jnp.minimum(written, window)
        mask = (age < span).astype(jnp.float32)
        return jnp.sum(bad * mask) / jnp.maximum(span, 1).astype(jnp.float32)

    safe_budget = jnp.maximum(budget, 1e-9)
    frac_fast = bad_fraction(fast_w)
    frac_slow = bad_fraction(slow_w)
    burn_fast = frac_fast / safe_budget
    burn_slow = frac_slow / safe_budget
    # a rule is not evaluable before its fast window has filled once —
    # firing off two warmup ticks would page on every cold start
    ready = count >= fast_w
    firing = ready & (burn_fast >= fast_burn) & (burn_slow >= slow_burn)
    return burn_fast, burn_slow, frac_fast, frac_slow, firing


@jax.jit
def burn_rates(ring, count, cols, thresholds, budgets,
               fast_windows, slow_windows, fast_burns, slow_burns):
    """Evaluate every rule's fast/slow burn in one compiled program.

    All rule tables are ``[K]`` device arrays baked once at registry
    build; the only per-tick inputs are the carried ``(ring, count)``.
    Returns ``(burn_fast[K], burn_slow[K], frac_fast[K], frac_slow[K],
    firing[K])``.
    """
    return jax.vmap(partial(_one_rule, ring, count))(
        cols, thresholds, budgets,
        fast_windows, slow_windows, fast_burns, slow_burns)


def rule_tables(rules):
    """Bake an iterable of rule tuples into the device arrays that
    :func:`burn_rates` consumes.  Each rule is ``(col, threshold, budget,
    fast_w, slow_w, fast_burn, slow_burn)``."""
    rows = list(rules)
    cols = jnp.asarray(np.array([r[0] for r in rows], np.int32))
    thr = jnp.asarray(np.array([r[1] for r in rows], np.float32))
    bud = jnp.asarray(np.array([r[2] for r in rows], np.float32))
    fw = jnp.asarray(np.array([r[3] for r in rows], np.int32))
    sw = jnp.asarray(np.array([r[4] for r in rows], np.int32))
    fb = jnp.asarray(np.array([r[5] for r in rows], np.float32))
    sb = jnp.asarray(np.array([r[6] for r in rows], np.float32))
    return cols, thr, bud, fw, sw, fb, sb
