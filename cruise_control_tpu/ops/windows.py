"""Device-resident ring-buffered sample windows.

The kernels behind :class:`~cruise_control_tpu.monitor.aggregator.
MetricSampleAggregator`'s storage: per-entity cyclic window buffers
``[capacity, num_windows + 1, num_metrics]`` live on device, and the three
aggregator hot paths become batched array programs instead of per-sample
Python:

- **ingest** — ``fold_pending`` collapses a whole batch of samples into one
  update row per touched ``(entity, window-slot)`` cell on the host (the
  sequential-equivalence proof is in its docstring), then ``scatter_batch``
  applies every cell in a single scatter;
- **roll** — ``roll_slots`` zeroes the slots that cycle out with one masked
  store over the full buffer instead of a Python loop per slot;
- **aggregate** — ``collapse_windows`` gathers the queried window slots and
  applies each metric's strategy (AVG / MAX / LATEST) plus the AVG_ADJACENT
  blend in one fused program, and ``changed_rows`` diffs the collapse
  against the previous tick's to produce the per-entity **dirty mask** the
  incremental model build and goal rescore key off.

Shape discipline (zero retraces in steady state): the entity axis is the
buffer *capacity* (doubled geometrically, so growth retraces O(log E)
times), update batches are padded to power-of-two buckets with
out-of-range sentinel rows (``mode="drop"``), and the window axes are
fixed by configuration. Only the warmup phase — where the number of
completed windows is still growing — traces new collapse shapes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class WindowBuffers(NamedTuple):
    """Device mirror of one aggregator's cyclic sample windows.

    ``W1 = num_windows + 1`` (the N stable windows plus the current,
    still-filling one). The host keeps twin int mirrors of ``count`` and the
    per-cell latest-sample timestamp: completeness / extrapolation logic is
    integer bookkeeping that never needs the device round-trip, and ms
    timestamps need int64, which device arrays don't carry without x64.
    """

    sums: jax.Array     # f32[cap, W1, M] NaN-masked running sums
    maxs: jax.Array     # f32[cap, W1, M] running maxima (-inf = empty)
    latest: jax.Array   # f32[cap, W1, M] value of the newest sample per cell
    count: jax.Array    # i32[cap, W1] samples per cell


def make_buffers(capacity: int, w1: int, num_metrics: int) -> WindowBuffers:
    return WindowBuffers(
        sums=jnp.zeros((capacity, w1, num_metrics), jnp.float32),
        maxs=jnp.full((capacity, w1, num_metrics), -jnp.inf, jnp.float32),
        latest=jnp.zeros((capacity, w1, num_metrics), jnp.float32),
        count=jnp.zeros((capacity, w1), jnp.int32),
    )


def grow_buffers(wb: WindowBuffers, new_capacity: int) -> WindowBuffers:
    """Double-style capacity growth (host-driven, rare — O(log E) total)."""
    pad = new_capacity - wb.sums.shape[0]
    if pad <= 0:
        return wb
    tail3 = (pad,) + wb.sums.shape[1:]
    return WindowBuffers(
        sums=jnp.concatenate([wb.sums, jnp.zeros(tail3, jnp.float32)]),
        maxs=jnp.concatenate(
            [wb.maxs, jnp.full(tail3, -jnp.inf, jnp.float32)]),
        latest=jnp.concatenate([wb.latest, jnp.zeros(tail3, jnp.float32)]),
        count=jnp.concatenate(
            [wb.count, jnp.zeros((pad, wb.count.shape[1]), jnp.int32)]),
    )


def bucket_len(n: int, floor: int = 64) -> int:
    """Power-of-two batch bucket so ingest batch sizes reuse compiled
    scatters instead of retracing per tick."""
    b = floor
    while b < n:
        b *= 2
    return b


# --------------------------------------------------------------- kernels

def _scatter_batch(wb: WindowBuffers, rows: jax.Array, slots: jax.Array,
                   sum_add: jax.Array, cnt_add: jax.Array,
                   max_cand: jax.Array, lat_vals: jax.Array) -> WindowBuffers:
    """Apply one folded update row per unique (entity row, window slot).

    Padding rows carry ``rows == capacity`` (out of range, NEVER -1:
    negative indices wrap) and are dropped by the scatter mode. ``lat_vals``
    is NaN where the batch made no accepted write for that metric — the
    current device value is kept.
    """
    sums = wb.sums.at[rows, slots].add(sum_add, mode="drop")
    maxs = wb.maxs.at[rows, slots].max(max_cand, mode="drop")
    count = wb.count.at[rows, slots].add(cnt_add, mode="drop")
    cur = wb.latest.at[rows, slots].get(mode="fill", fill_value=0.0)
    latest = wb.latest.at[rows, slots].set(
        jnp.where(jnp.isnan(lat_vals), cur, lat_vals), mode="drop")
    return WindowBuffers(sums=sums, maxs=maxs, latest=latest, count=count)


def _roll_slots(wb: WindowBuffers, slot_mask: jax.Array) -> WindowBuffers:
    """Reset every cell of the masked slots (bool[W1]) to the empty state."""
    m3 = slot_mask[None, :, None]
    return WindowBuffers(
        sums=jnp.where(m3, 0.0, wb.sums),
        maxs=jnp.where(m3, -jnp.inf, wb.maxs),
        latest=jnp.where(m3, 0.0, wb.latest),
        count=jnp.where(slot_mask[None, :], 0, wb.count),
    )


# Donation keeps the (cap × W1 × M) buffers from being double-allocated on
# every ingest/roll; the CPU runtime can't honor it and would warn per call.
if jax.default_backend() == "cpu":
    scatter_batch = jax.jit(_scatter_batch)
    roll_slots = jax.jit(_roll_slots)
else:
    scatter_batch = jax.jit(_scatter_batch, donate_argnums=(0,))
    roll_slots = jax.jit(_roll_slots, donate_argnums=(0,))


@jax.jit
def collapse_windows(wb: WindowBuffers, slots: jax.Array, real: jax.Array,
                     min_samples: jax.Array, avg_mask: jax.Array,
                     max_mask: jax.Array) -> jax.Array:
    """f32[cap, Wv, M] per-window values for the queried window slots.

    ``slots`` (i32[Wv]) are the cyclic slots of the queried windows oldest
    first; ``real`` (bool[Wv]) masks queried windows that actually live in
    the buffer (an aliasing slot after a sampling gap must read as empty).
    Strategy selection per metric: ``avg_mask`` → sum/count, ``max_mask`` →
    running max (empty → 0), otherwise LATEST. Empty windows whose two
    neighbors both have ≥ ``min_samples`` samples get the AVG_ADJACENT
    blend, exactly mirroring the host extrapolation codes.
    """
    cnt = jnp.where(real[None, :], wb.count[:, slots], 0)          # [cap, Wv]
    ssum = jnp.where(real[None, :, None], wb.sums[:, slots], 0.0)
    smax = jnp.where(real[None, :, None], wb.maxs[:, slots], -jnp.inf)
    slat = jnp.where(real[None, :, None], wb.latest[:, slots], 0.0)
    safe = jnp.maximum(cnt, 1)[:, :, None].astype(jnp.float32)
    vals = jnp.where(
        avg_mask[None, None, :], ssum / safe,
        jnp.where(max_mask[None, None, :],
                  jnp.where(jnp.isfinite(smax), smax, 0.0), slat))
    full = cnt >= min_samples
    some = cnt > 0
    wv = cnt.shape[1]
    edge = jnp.arange(wv)
    left = jnp.roll(full, 1, axis=1) & (edge > 0)[None, :]
    right = jnp.roll(full, -1, axis=1) & (edge < wv - 1)[None, :]
    adj = (~some) & left & right
    blend = 0.5 * (jnp.roll(vals, 1, axis=1) + jnp.roll(vals, -1, axis=1))
    return jnp.where(adj[:, :, None], blend, vals)


@jax.jit
def changed_rows(vals: jax.Array, prev: jax.Array) -> jax.Array:
    """bool[cap] dirty mask: any per-window value differs from last tick.

    NaN-padded ``prev`` rows (fresh capacity growth) compare unequal, so new
    entities always read dirty.
    """
    return jnp.any(vals != prev, axis=(1, 2))


# ---------------------------------------------------------- host-side fold

def fold_pending(rows: np.ndarray, slots: np.ndarray, times: np.ndarray,
                 vals: np.ndarray, w1: int, latest_t: np.ndarray
                 ) -> Tuple[np.ndarray, ...]:
    """Collapse a pending sample batch into one update per (row, slot) cell.

    Sequential-equivalence: replaying the batch sample-by-sample through the
    scalar ingest rule must give the same buffer state. Sum/max/count are
    order-free. The LATEST rule accepts sample *i* iff
    ``t_i >= latest_t`` *at that moment*; since rejected samples never raise
    the running ``latest_t``, that is exactly
    ``t_i >= max(buffer_latest_t, max(t_j for j < i in the same cell))`` —
    the buffer value combined with an exclusive per-cell prefix max over the
    batch (a rejected earlier time is strictly below the running max, so
    including it in the prefix never changes it). The final per-metric
    LATEST value is the last accepted sample in insertion order where that
    metric was present (NaN = absent), and the new ``latest_t`` is the max
    accepted time (an all-NaN accepted sample still bumps it, writing no
    values — matching the scalar rule).

    Returns ``(cell_rows, cell_slots, sum_add f64[K, M], cnt_add i64[K],
    max_cand f64[K, M], lat_vals f64[K, M] (NaN = keep), new_latest_t
    i64[K])`` with cells in ascending ``row * w1 + slot`` order.
    """
    n = rows.shape[0]
    m = vals.shape[1]
    key = rows.astype(np.int64) * w1 + slots
    order = np.argsort(key, kind="stable")     # stable: keeps insertion order
    key_s = key[order]
    t_s = times[order]
    v_s = vals[order]
    first = np.empty(n, bool)
    first[0] = True
    first[1:] = key_s[1:] != key_s[:-1]
    starts = np.flatnonzero(first)
    grp = np.cumsum(first) - 1                                 # [n] cell id
    cell_rows = (key_s[starts] // w1).astype(np.int64)
    cell_slots = (key_s[starts] % w1).astype(np.int64)
    cnt_add = np.diff(np.append(starts, n)).astype(np.int64)

    present = ~np.isnan(v_s)
    sum_add = np.add.reduceat(np.where(present, v_s, 0.0), starts, axis=0)
    max_cand = np.maximum.reduceat(
        np.where(present, v_s, -np.inf), starts, axis=0)

    # exclusive per-cell prefix max of sample times via the offset trick:
    # shift each cell's times into a disjoint band, one global cummax, then
    # de-offset — no Python loop over cells
    t_min = int(t_s.min())
    band = int(t_s.max()) - t_min + 1
    shifted = (t_s - t_min) + grp * band
    cm = np.maximum.accumulate(shifted) - grp * band + t_min   # inclusive
    low = np.iinfo(np.int64).min
    prev_cm = np.empty_like(cm)
    prev_cm[1:] = cm[:-1]
    prev_cm[first] = low                                       # exclusive
    buf_lt = latest_t[cell_rows, cell_slots]                   # i64[K]
    accepted = t_s >= np.maximum(buf_lt[grp], prev_cm)

    lat_vals = np.full((starts.size, m), np.nan)
    for k in range(m):
        sel = np.flatnonzero(accepted & present[:, k])
        if sel.size:
            g = grp[sel]
            last = np.append(g[1:] != g[:-1], True)  # last write per cell
            lat_vals[g[last], k] = v_s[sel[last], k]
    acc_t = np.where(accepted, t_s, low)
    grp_max_t = np.maximum.reduceat(acc_t, starts)
    new_latest_t = np.maximum(buf_lt, grp_max_t)
    return (cell_rows, cell_slots, sum_add, cnt_add, max_cand, lat_vals,
            new_latest_t)


def pad_update(cell_rows: np.ndarray, cell_slots: np.ndarray,
               sum_add: np.ndarray, cnt_add: np.ndarray,
               max_cand: np.ndarray, lat_vals: np.ndarray,
               capacity: int) -> Tuple[np.ndarray, ...]:
    """Pad a folded update to its power-of-two bucket with dropped sentinel
    rows (``row == capacity``, out of range — never -1, which would wrap)."""
    k = cell_rows.shape[0]
    kb = bucket_len(k)
    pad = kb - k
    m = sum_add.shape[1]
    rows32 = np.concatenate(
        [cell_rows, np.full(pad, capacity)]).astype(np.int32)
    slots32 = np.concatenate([cell_slots, np.zeros(pad)]).astype(np.int32)
    sum32 = np.concatenate(
        [sum_add, np.zeros((pad, m))]).astype(np.float32)
    cnt32 = np.concatenate([cnt_add, np.zeros(pad)]).astype(np.int32)
    max32 = np.concatenate(
        [max_cand, np.full((pad, m), -np.inf)]).astype(np.float32)
    lat32 = np.concatenate(
        [lat_vals, np.full((pad, m), np.nan)]).astype(np.float32)
    return rows32, slots32, sum32, cnt32, max32, lat32
