"""ClusterModelStats parity kernel.

Reproduces ``model/ClusterModelStats.java:74-460`` as one jittable function:
AVG/MAX/MIN/ST_DEV of utilization per resource over alive brokers, potential
NW_OUT stats, replica / leader-replica / topic-replica count stats, balanced
broker counts, and scalar counters. Used by goal stats-comparators, the
REGRESSION check of the optimization verifier, and response builders.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cruise_control_tpu.common import resources as res
from cruise_control_tpu.common.resources import BalancingConstraint
from cruise_control_tpu.models.cluster import Assignment
from cruise_control_tpu.ops.aggregates import (
    BrokerAggregates,
    DeviceTopology,
    broker_resource_utilization,
    broker_scope_capacity,
    compute_aggregates,
    replica_count_weights,
)

_BIG = jnp.float32(3.4e38)


class ClusterStats(NamedTuple):
    """Array mirror of ClusterModelStats (ClusterModelStats.java:26-46)."""

    # per-resource [4]: AVG is total-load/numAliveBrokers
    # (ClusterModelStats.java:304); MAX/MIN are hottest/coldest alive-broker
    # *absolute* utilization at host scope for host resources (:291-300).
    resource_avg: jax.Array
    resource_max: jax.Array
    resource_min: jax.Array
    resource_std: jax.Array
    num_balanced_brokers: jax.Array       # i32[4]
    # potential nw-out over alive brokers (ClusterModelStats.java:320-348)
    potential_nw_out_avg: jax.Array
    potential_nw_out_max: jax.Array
    potential_nw_out_min: jax.Array
    potential_nw_out_std: jax.Array
    num_brokers_under_potential_nw_out: jax.Array
    # replica count stats (ClusterModelStats.java:353-414): MAX/MIN over all
    # brokers, AVG/ST_DEV over alive brokers.
    replica_avg: jax.Array
    replica_max: jax.Array
    replica_min: jax.Array
    replica_std: jax.Array
    leader_avg: jax.Array
    leader_max: jax.Array
    leader_min: jax.Array
    leader_std: jax.Array
    # topic replica stats (ClusterModelStats.java:417-460): AVG and ST_DEV are
    # means over topics; MAX/MIN extrema over (topic, broker).
    topic_replica_avg: jax.Array
    topic_replica_max: jax.Array
    topic_replica_min: jax.Array
    topic_replica_std: jax.Array
    # scalars
    num_partitions_with_offline_replicas: jax.Array


from functools import partial


@partial(jax.jit, static_argnames=("constraint", "num_topics",
                                   "sparse_topic"))
def compute_cluster_stats(dt: DeviceTopology, assign: Assignment,
                          constraint: BalancingConstraint, num_topics: int,
                          agg: BrokerAggregates | None = None,
                          sparse_topic: bool = False) -> ClusterStats:
    """``sparse_topic``: compute the topic-replica stats from sorted
    (broker, topic) cell runs instead of the dense [B, T] histogram — at
    LinkedIn scale the histogram is hundreds of MB per call."""
    if agg is None:
        agg = compute_aggregates(dt, assign,
                                 1 if sparse_topic else num_topics)
    alive = dt.broker_alive
    n_alive = jnp.maximum(jnp.sum(alive.astype(jnp.float32)), 1.0)

    util = broker_resource_utilization(dt, agg)          # [B,4] scoped utilization
    cap = broker_scope_capacity(dt)                      # [B,4]
    total_load = jnp.sum(agg.broker_load, axis=0)        # [4]
    total_capacity = jnp.sum(jnp.where(alive[:, None], dt.capacity, 0.0), axis=0)
    avg_pct = total_load / total_capacity                # avgUtilizationPercentage

    bal = jnp.asarray(constraint.balance_percentage_array())
    upper = avg_pct * bal
    lower = avg_pct * jnp.maximum(0.0, 2.0 - bal)
    pct = util / cap
    balanced = (pct >= lower[None, :]) & (pct <= upper[None, :]) & alive[:, None]
    num_balanced = jnp.sum(balanced.astype(jnp.int32), axis=0)

    res_max = jnp.max(jnp.where(alive[:, None], util, 0.0), axis=0)
    res_min = jnp.min(jnp.where(alive[:, None], util, _BIG), axis=0)
    var = jnp.sum(jnp.where(alive[:, None], (util - avg_pct[None, :] * cap) ** 2, 0.0), axis=0)
    res_std = jnp.sqrt(var / n_alive)
    res_avg = total_load / n_alive

    # potential NW_OUT (ClusterModelStats.java:320-348)
    pot = agg.potential_nw_out
    pot_total = jnp.sum(jnp.where(alive, pot, 0.0))
    nw_out_cap = total_capacity[res.NW_OUT]
    pot_avg_pct = pot_total / nw_out_cap
    cap_thresh = float(constraint.capacity_threshold[res.NW_OUT])
    b_nw_cap = dt.capacity[:, res.NW_OUT]
    under = (pot / b_nw_cap <= cap_thresh) & alive
    pot_var = jnp.sum(jnp.where(alive, (pot - pot_avg_pct * b_nw_cap) ** 2, 0.0))

    def _count_stats(count):
        cnt = count.astype(jnp.float32)
        avg = jnp.sum(cnt) / n_alive
        if dt.broker_present is not None:
            # MAX/MIN run over *real* brokers only (dead included, matching
            # the reference); padded sentinel rows carry count 0 and would
            # otherwise pin MIN to zero.
            mx = jnp.max(jnp.where(dt.broker_present, cnt, 0.0))
            mn = jnp.min(jnp.where(dt.broker_present, cnt, _BIG))
        else:
            mx = jnp.max(cnt)
            mn = jnp.min(cnt)
        sd = jnp.sqrt(jnp.sum(jnp.where(alive, (cnt - avg) ** 2, 0.0)) / n_alive)
        return avg, mx, mn, sd

    rep_avg, rep_max, rep_min, rep_std = _count_stats(agg.replica_count)
    led_avg, led_max, led_min, led_std = _count_stats(agg.leader_count)

    # topic replica stats: per-topic avg & stdev over alive brokers, then
    # averaged over topics; max/min over all (topic, broker) pairs.
    if sparse_topic:
        T = num_topics
        R = dt.num_replicas
        t_of_r = dt.topic_of_partition[dt.partition_of_replica]
        w_r = replica_count_weights(dt).astype(jnp.float32)
        per_topic_total = jax.ops.segment_sum(w_r, t_of_r, num_segments=T)
        per_topic_avg = per_topic_total / n_alive
        # non-empty (broker, topic) cell counts via sorted key runs. ALL
        # brokers' cells are counted (the dense path's max/min run over every
        # broker row, dead included); the variance term below masks to alive
        # cells just as the dense path does.
        alive_r = alive[assign.broker_of]
        if dt.broker_present is not None:
            # bucketed model: the (broker, topic) matrix is the *real*
            # broker rows only; padded sentinel replicas park at an
            # out-of-range key so their cell never enters the extrema
            n_real_b = jnp.sum(dt.broker_present.astype(jnp.int32))
            BT = n_real_b * T
            key = jnp.where(w_r > 0, assign.broker_of * T + t_of_r,
                            dt.num_brokers * T)
        else:
            BT = dt.num_brokers * T
            key = assign.broker_of * T + t_of_r
        sk = jnp.sort(key)
        first = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
        cell_id = jnp.cumsum(first.astype(jnp.int32)) - 1
        counts = jax.ops.segment_sum(jnp.ones((R,), jnp.float32), cell_id,
                                     num_segments=R)
        cell_key = jax.ops.segment_max(sk, cell_id, num_segments=R)
        n_cells = cell_id[-1] + 1
        valid_c = ((jnp.arange(R) < n_cells)
                   & (cell_key >= 0) & (cell_key < BT))
        t_cell = jnp.where(valid_c, cell_key % T, 0)
        alive_c = valid_c & alive[jnp.where(valid_c, cell_key // T, 0)]
        avg_c = per_topic_avg[t_cell]
        sq = jnp.where(alive_c, (counts - avg_c) ** 2, 0.0)
        sq_t = jax.ops.segment_sum(sq, t_cell, num_segments=T)
        nnz_alive_t = jax.ops.segment_sum(alive_c.astype(jnp.float32),
                                          t_cell, num_segments=T)
        # empty alive cells contribute avg_t^2 each
        t_var = (sq_t + jnp.maximum(n_alive - nnz_alive_t, 0.0)
                 * per_topic_avg ** 2) / n_alive
        topic_avg = jnp.mean(per_topic_avg)
        topic_std = jnp.mean(jnp.sqrt(t_var))
        topic_max = jnp.max(jnp.where(valid_c, counts, 0.0))
        # min over the full (broker, topic) matrix: 0 unless every cell of
        # every broker (dead included, dense-path parity) is non-empty
        n_valid = jnp.sum(valid_c.astype(jnp.int32))
        topic_min = jnp.where(n_valid >= BT,
                              jnp.min(jnp.where(valid_c, counts, _BIG)), 0.0)
    else:
        tc = agg.topic_count.astype(jnp.float32)             # [B, T]
        per_topic_total = jnp.sum(tc, axis=0)                # [T]
        per_topic_avg = per_topic_total / n_alive
        t_var = jnp.sum(jnp.where(alive[:, None], (tc - per_topic_avg[None, :]) ** 2, 0.0), axis=0) / n_alive
        topic_avg = jnp.mean(per_topic_avg)
        topic_std = jnp.mean(jnp.sqrt(t_var))
        if dt.broker_present is not None:
            topic_max = jnp.max(jnp.where(dt.broker_present[:, None], tc, 0.0))
            topic_min = jnp.min(jnp.where(dt.broker_present[:, None], tc, _BIG))
        else:
            topic_max = jnp.max(tc)
            topic_min = jnp.min(tc)

    # partitions with offline replicas
    p_off = jax.ops.segment_max(
        dt.replica_offline.astype(jnp.int32), dt.partition_of_replica,
        num_segments=dt.num_partitions)
    n_off = jnp.sum(p_off)

    return ClusterStats(
        resource_avg=res_avg, resource_max=res_max, resource_min=res_min,
        resource_std=res_std, num_balanced_brokers=num_balanced,
        potential_nw_out_avg=pot_total / n_alive,
        potential_nw_out_max=jnp.max(jnp.where(alive, pot, 0.0)),
        potential_nw_out_min=jnp.min(jnp.where(alive, pot, _BIG)),
        potential_nw_out_std=jnp.sqrt(pot_var / n_alive),
        num_brokers_under_potential_nw_out=jnp.sum(under.astype(jnp.int32)),
        replica_avg=rep_avg, replica_max=rep_max, replica_min=rep_min, replica_std=rep_std,
        leader_avg=led_avg, leader_max=led_max, leader_min=led_min, leader_std=led_std,
        topic_replica_avg=topic_avg, topic_replica_max=topic_max,
        topic_replica_min=topic_min, topic_replica_std=topic_std,
        num_partitions_with_offline_replicas=n_off,
    )


def sanity_check(dt: DeviceTopology, assign: Assignment, num_topics: int) -> dict:
    """Invariant cross-validation, the analogue of ClusterModel.sanityCheck
    (ClusterModel.java:1081-1231): load sums agree between replica-level and
    broker/host/cluster-level aggregation, exactly one leader per partition and
    it is one of the partition's replicas, every replica's broker is in range.

    Returns a dict of boolean/float diagnostics (host-side friendly).
    """
    agg = compute_aggregates(dt, assign, num_topics)
    p = dt.partition_of_replica
    eff = dt.replica_base_load + jnp.where(
        assign.is_leader(p)[:, None], dt.leader_extra[p], 0.0)
    total_from_replicas = jnp.sum(eff, axis=0)
    total_from_brokers = jnp.sum(agg.broker_load, axis=0)
    total_from_hosts = jnp.sum(agg.host_load, axis=0)
    eps = jnp.maximum(jnp.asarray(res.RESOURCE_EPSILON, jnp.float32),
                      res.EPSILON_PERCENT * (total_from_replicas + total_from_brokers))
    leader_part = p[assign.leader_of]
    leader_valid = jnp.all(leader_part == jnp.arange(dt.num_partitions))
    brokers_in_range = jnp.all((assign.broker_of >= 0) & (assign.broker_of < dt.num_brokers))
    # weighted counts on bucketed models sum to the *real* entity counts
    expected_r = (jnp.sum(dt.replica_weight) if dt.replica_weight is not None
                  else dt.num_replicas)
    expected_p = (jnp.sum(dt.partition_weight)
                  if dt.partition_weight is not None else dt.num_partitions)
    count_ok = jnp.sum(agg.replica_count) == expected_r
    leader_count_ok = jnp.sum(agg.leader_count) == expected_p
    return {
        "load_broker_consistent": bool(jnp.all(jnp.abs(total_from_replicas - total_from_brokers) <= eps)),
        "load_host_consistent": bool(jnp.all(jnp.abs(total_from_replicas - total_from_hosts) <= eps)),
        "one_leader_per_partition": bool(leader_valid),
        "brokers_in_range": bool(brokers_in_range),
        "replica_count_consistent": bool(count_ok),
        "leader_count_consistent": bool(leader_count_ok),
    }


# ---------------------------------------------------------------------------
# Robust-stats percentile band (PercentileMetricAnomalyFinder.java core).
# Shared by the MetricAnomalyDetector (thin np wrapper in
# detector/detectors.py keeps its message format) and the provisioner's
# adaptive headroom margin. jnp + vmappable: flags instead of Optional[str].
# ---------------------------------------------------------------------------


class PercentileFlags(NamedTuple):
    """Outcome of one percentile-band check (all 0-d arrays; ``above`` /
    ``below`` are bool, the rest f32)."""

    above: jax.Array
    below: jax.Array
    upper: jax.Array   # the raw upper-percentile value of the history
    lower: jax.Array   # the raw lower-percentile value of the history


@partial(jax.jit, static_argnames=())
def percentile_flags(history: jax.Array, current: jax.Array,
                     upper_percentile: jax.Array,
                     lower_percentile: jax.Array,
                     upper_margin: jax.Array,
                     lower_margin: jax.Array) -> PercentileFlags:
    """``current`` beyond [P_low·lower_margin, P_high·(1+upper_margin)] of
    its own ``history``. Pure jnp so a [N, W] history batch vmaps to [N]
    verdicts in one program; callers guard the degenerate empty-history
    case (a zero-length percentile window is undefined, not an anomaly)."""
    hi = jnp.percentile(history, upper_percentile)
    lo = jnp.percentile(history, lower_percentile)
    current = jnp.asarray(current, hi.dtype)
    return PercentileFlags(
        above=current > hi * (1.0 + upper_margin),
        below=current < lo * lower_margin,
        upper=hi,
        lower=lo,
    )
