"""Jittable per-broker aggregate kernels over the array cluster model.

These reductions replace the reference's incremental object-graph bookkeeping
(``Broker``/``Host``/``Rack`` load sums updated on every mutation,
``ClusterModel.java:347-420``) with one-shot XLA segment reductions, and are the
foundation for both :mod:`cruise_control_tpu.ops.stats` (ClusterModelStats
parity) and the goal penalty terms.

Everything takes a :class:`DeviceTopology` (device-resident constants) plus an
:class:`~cruise_control_tpu.models.cluster.Assignment` and is safe under
``jit``/``vmap`` — shapes are static per problem.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common import resources as res
from cruise_control_tpu.models.cluster import Assignment, ClusterTopology


class DeviceTopology(NamedTuple):
    """Device-array mirror of the ClusterTopology fields the kernels need."""

    rack_of_broker: jax.Array      # i32[B]
    host_of_broker: jax.Array      # i32[B]
    capacity: jax.Array            # f32[B, 4]
    host_capacity: jax.Array       # f32[H, 4]
    broker_alive: jax.Array        # bool[B]
    broker_new: jax.Array          # bool[B]
    broker_demoted: jax.Array      # bool[B]
    partition_of_replica: jax.Array   # i32[R]
    topic_of_partition: jax.Array     # i32[P]
    replicas_of_partition: jax.Array  # i32[P, max_rf] (-1 padded)
    rf_of_partition: jax.Array        # i32[P]
    replica_offline: jax.Array        # bool[R]
    replica_base_load: jax.Array      # f32[R, 4] follower-role load
    leader_extra: jax.Array           # f32[P, 4] extra load carried by the leader
    leader_bytes_in: jax.Array        # f32[P]
    # --- shape-bucketing sentinels (models.cluster.pad_topology) ---
    # None on unpadded models: every kernel then traces exactly the historical
    # program. When present, padded entries carry weight 0 / present=False and
    # must contribute nothing to any count, total, or goal term.
    replica_weight: Optional[jax.Array] = None    # i32[R] 1=real, 0=padding
    partition_weight: Optional[jax.Array] = None  # i32[P] 1=real, 0=padding
    broker_present: Optional[jax.Array] = None    # bool[B] False=padding

    @property
    def num_brokers(self) -> int:
        return self.capacity.shape[0]

    @property
    def num_hosts(self) -> int:
        return self.host_capacity.shape[0]

    @property
    def num_partitions(self) -> int:
        return self.topic_of_partition.shape[0]

    @property
    def num_replicas(self) -> int:
        return self.partition_of_replica.shape[0]

    @property
    def max_rf(self) -> int:
        return self.replicas_of_partition.shape[1]


def device_topology(topo: ClusterTopology) -> DeviceTopology:
    return DeviceTopology(
        rack_of_broker=jnp.asarray(topo.rack_of_broker, jnp.int32),
        host_of_broker=jnp.asarray(topo.host_of_broker, jnp.int32),
        capacity=jnp.asarray(topo.capacity, jnp.float32),
        host_capacity=jnp.asarray(topo.host_capacity(), jnp.float32),
        broker_alive=jnp.asarray(topo.broker_alive),
        broker_new=jnp.asarray(topo.broker_new),
        broker_demoted=jnp.asarray(topo.broker_demoted),
        partition_of_replica=jnp.asarray(topo.partition_of_replica, jnp.int32),
        topic_of_partition=jnp.asarray(topo.topic_of_partition, jnp.int32),
        replicas_of_partition=jnp.asarray(topo.replicas_of_partition, jnp.int32),
        rf_of_partition=jnp.asarray(topo.rf_of_partition, jnp.int32),
        replica_offline=jnp.asarray(topo.replica_offline),
        replica_base_load=jnp.asarray(topo.replica_base_load, jnp.float32),
        leader_extra=jnp.asarray(topo.leader_extra, jnp.float32),
        leader_bytes_in=jnp.asarray(topo.leader_bytes_in, jnp.float32),
        replica_weight=(jnp.asarray(topo.replica_weight, jnp.int32)
                        if getattr(topo, "replica_weight", None) is not None
                        else None),
        partition_weight=(jnp.asarray(topo.partition_weight, jnp.int32)
                          if getattr(topo, "partition_weight", None) is not None
                          else None),
        broker_present=(jnp.asarray(topo.broker_present)
                        if getattr(topo, "broker_present", None) is not None
                        else None),
    )


def replica_count_weights(dt: DeviceTopology) -> jax.Array:
    """i32[R] per-replica count weight: 1s, or the padding mask when bucketed.

    Every replica-count segment sum (aggregates, chain rescore, sharded
    aggregates, stats) must use this instead of raw ones so padded sentinel
    replicas never count — a padded replica sits on a dead padded broker and
    an unweighted count would fire _DeadBrokerPlacement."""
    if dt.replica_weight is not None:
        return dt.replica_weight
    return jnp.ones_like(dt.partition_of_replica)


def leader_count_weights(dt: DeviceTopology) -> jax.Array:
    """i32[P] per-partition leader-count weight (1s, or the padding mask)."""
    if dt.partition_weight is not None:
        return dt.partition_weight
    return jnp.ones_like(dt.topic_of_partition)


class BrokerAggregates(NamedTuple):
    """Per-broker aggregates — the array analogue of Broker/Host load state."""

    broker_load: jax.Array       # f32[B, 4] effective utilization per resource
    host_load: jax.Array         # f32[H, 4]
    replica_count: jax.Array     # i32[B]
    leader_count: jax.Array      # i32[B]
    potential_nw_out: jax.Array  # f32[B] all-leaders NW_OUT (ClusterModel.java:205)
    leader_bytes_in: jax.Array   # f32[B] sum of led partitions' LEADER_BYTES_IN
    topic_count: jax.Array       # i32[B, T] replicas per (broker, topic)
    offline_count: jax.Array     # i32[B] offline replicas currently on broker


def replica_effective_load(dt: DeviceTopology, assign: Assignment) -> jax.Array:
    """f32[R, 4] — base (follower-role) load plus leader extra for leaders."""
    p = dt.partition_of_replica
    is_leader = assign.is_leader(p)
    return dt.replica_base_load + jnp.where(is_leader[:, None], dt.leader_extra[p], 0.0)


from functools import partial


@partial(jax.jit, static_argnames=("num_topics",))
def compute_aggregates(dt: DeviceTopology, assign: Assignment, num_topics: int) -> BrokerAggregates:
    B = dt.num_brokers
    p = dt.partition_of_replica
    eff = replica_effective_load(dt, assign)

    broker_load = jax.ops.segment_sum(eff, assign.broker_of, num_segments=B)
    host_load = jax.ops.segment_sum(broker_load, dt.host_of_broker, num_segments=dt.num_hosts)
    ones = replica_count_weights(dt)
    replica_count = jax.ops.segment_sum(ones, assign.broker_of, num_segments=B)
    leader_broker = assign.leader_broker()
    leader_count = jax.ops.segment_sum(
        leader_count_weights(dt), leader_broker, num_segments=B)
    # Potential leadership NW_OUT: every replica contributes its partition's
    # *current leader's* NW_OUT to the broker it lives on
    # (ClusterModel.java:205,361 — potentialLeadershipLoadByBrokerId).
    part_leader_nw_out = (dt.leader_extra[:, res.NW_OUT]
                          + dt.replica_base_load[assign.leader_of, res.NW_OUT])
    potential_nw_out = jax.ops.segment_sum(
        part_leader_nw_out[p], assign.broker_of, num_segments=B)
    leader_bytes_in = jax.ops.segment_sum(
        dt.leader_bytes_in, leader_broker, num_segments=B)
    # (broker, topic) replica counts via combined segment ids.
    topic_ids = dt.topic_of_partition[p]
    combined = assign.broker_of * num_topics + topic_ids
    topic_count = jax.ops.segment_sum(
        ones, combined, num_segments=B * num_topics).reshape(B, num_topics)
    offline_count = jax.ops.segment_sum(
        dt.replica_offline.astype(jnp.int32), assign.broker_of, num_segments=B)
    return BrokerAggregates(
        broker_load=broker_load,
        host_load=host_load,
        replica_count=replica_count,
        leader_count=leader_count,
        potential_nw_out=potential_nw_out,
        leader_bytes_in=leader_bytes_in,
        topic_count=topic_count,
        offline_count=offline_count,
    )


@partial(jax.jit, static_argnames=("num_topics",))
def topic_totals(dt: DeviceTopology, num_topics: int) -> jax.Array:
    """f32[T] — total replicas per topic. Assignment-invariant (a replica's
    topic never changes), so goal thresholds can use this without ever
    materializing the [B, T] histogram."""
    t_of_r = dt.topic_of_partition[dt.partition_of_replica]
    return jax.ops.segment_sum(
        replica_count_weights(dt).astype(jnp.float32), t_of_r,
        num_segments=num_topics)


def partition_rack_excess(dt: DeviceTopology, broker_of: jax.Array) -> jax.Array:
    """f32[P] — per partition, number of replicas beyond one in any rack.

    The RackAwareGoal violation measure (``goals/RackAwareGoal.java:161-259``):
    a partition with rf replicas spread over d distinct racks has ``rf - d``
    excess replicas. Computed by pairwise comparison over the (small) padded
    replica axis — no P×K count matrix needed.
    """
    reps = dt.replicas_of_partition            # i32[P, m]
    valid = reps >= 0
    racks = dt.rack_of_broker[broker_of[jnp.clip(reps, 0)]]  # i32[P, m]
    m = reps.shape[1]
    # replica j is a "duplicate" if some k < j (valid) shares its rack
    same = (racks[:, None, :] == racks[:, :, None])           # [P, j, k]
    earlier = (jnp.arange(m)[None, :, None] > jnp.arange(m)[None, None, :])
    dup = jnp.any(same & earlier & valid[:, None, :], axis=-1) & valid
    return jnp.sum(dup, axis=-1).astype(jnp.float32)


def broker_resource_utilization(dt: DeviceTopology, agg: BrokerAggregates) -> jax.Array:
    """f32[B, 4] utilization per broker per resource at goal scope.

    Host-level resources (CPU, NW_IN, NW_OUT) read the broker's *host* load,
    broker-level read the broker load (ClusterModelStats.java:291-294;
    CapacityGoal host/broker scoping per Resource.java:13-16). Note CPU is both:
    capacity goals treat CPU at host scope for utilization checks but the
    distribution goal uses broker scope — callers pick columns accordingly.
    """
    host_of = dt.host_of_broker
    return jnp.where(
        jnp.asarray(res.IS_HOST_RESOURCE)[None, :],
        agg.host_load[host_of],
        agg.broker_load,
    )


def broker_scope_capacity(dt: DeviceTopology) -> jax.Array:
    """f32[B, 4] capacity at the same scope as broker_resource_utilization."""
    return jnp.where(
        jnp.asarray(res.IS_HOST_RESOURCE)[None, :],
        dt.host_capacity[dt.host_of_broker],
        dt.capacity,
    )


# --- delta variants (incremental tick path) ---------------------------------
#
# Most control-loop ticks change the load of a handful of partitions and
# nothing structural. Instead of shipping a whole new DeviceTopology to the
# device (R×4 + P×4 + P floats at LinkedIn scale), the monitor hands the
# analyzer only the dirty rows and these kernels scatter them into the
# resident arrays. Index buffers are padded to power-of-two buckets with the
# axis length as the sentinel (out-of-range ⇒ mode="drop"/"fill" no-ops), so
# steady-state ticks reuse one compiled program regardless of how many
# partitions went dirty.


@jax.jit
def splice_replica_loads(dt: DeviceTopology,
                         replica_idx: jax.Array, base_rows: jax.Array,
                         partition_idx: jax.Array, extra_rows: jax.Array,
                         lbi_rows: jax.Array) -> DeviceTopology:
    """Scatter dirty load rows into a resident DeviceTopology.

    ``replica_idx`` i32[Rd] / ``base_rows`` f32[Rd, 4] update
    ``replica_base_load``; ``partition_idx`` i32[Pd] with ``extra_rows``
    f32[Pd, 4] and ``lbi_rows`` f32[Pd] update ``leader_extra`` /
    ``leader_bytes_in``. Sentinel indices (== axis length) are dropped.
    Bit-identical to rebuilding the topology with the spliced host arrays:
    scatter-set of the exact rows the host path would have written."""
    return dt._replace(
        replica_base_load=dt.replica_base_load.at[replica_idx].set(
            base_rows, mode="drop"),
        leader_extra=dt.leader_extra.at[partition_idx].set(
            extra_rows, mode="drop"),
        leader_bytes_in=dt.leader_bytes_in.at[partition_idx].set(
            lbi_rows, mode="drop"),
    )


@jax.jit
def load_delta_mass(dt_old: DeviceTopology,
                    replica_idx: jax.Array, base_rows: jax.Array,
                    partition_idx: jax.Array,
                    extra_rows: jax.Array) -> tuple:
    """(delta_mass, total_mass) — L1 size of a pending load splice vs the
    resident arrays. Sentinel-padded indices gather 0 and contribute nothing.
    The analyzer compares ``delta_mass / max(total_mass, ε)`` against the
    proposal-cache dirty-mass threshold to decide whether a cached proposal
    is still worth revalidating instead of re-annealing."""
    old_base = dt_old.replica_base_load.at[replica_idx].get(
        mode="fill", fill_value=0.0)
    old_extra = dt_old.leader_extra.at[partition_idx].get(
        mode="fill", fill_value=0.0)
    pad_r = (replica_idx < dt_old.replica_base_load.shape[0])[:, None]
    pad_p = (partition_idx < dt_old.leader_extra.shape[0])[:, None]
    delta = (jnp.sum(jnp.abs(jnp.where(pad_r, base_rows - old_base, 0.0)))
             + jnp.sum(jnp.abs(jnp.where(pad_p, extra_rows - old_extra, 0.0))))
    total = (jnp.sum(jnp.abs(dt_old.replica_base_load))
             + jnp.sum(jnp.abs(dt_old.leader_extra)))
    return delta, total
