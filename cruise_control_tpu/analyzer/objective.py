"""Lexicographic two-channel objective over the goal penalty terms.

Bridges :mod:`cruise_control_tpu.analyzer.goals` (per-goal penalties) and the
search engines (greedy descent, annealer). The reference's sequential
goal-priority semantics (``GoalOptimizer.java:429`` +
``AbstractGoal.actionAcceptance``: an action may never sacrifice a
higher-priority goal for lower ones) are carried by TWO channels:

    O(state) = VIOL_SCALE · Σ_g v_g · violations_g(state)  +  Σ_g w_g · cost_g

- **Violation channel** (primary): per-goal violation *counts* weighted by a
  power-of-two priority ladder (``goals.goal_viol_weights``). Counts are
  small integers and ladder weights are powers of two, so products and the
  all-important "unaffected goal ⇒ exactly zero delta" property are exact in
  f32 — a move is never accepted on float noise from a higher tier.
- **Cost channel** (tiebreak): the continuous out-of-spec distance with the
  soft geometric weights, providing descent direction inside a violation
  level set.

The two channels are kept separate through every delta computation and
**differenced separately** (``f1 - f0`` per channel), then combined with
:func:`combine` only at the end — this is what makes the lexicographic
ordering numerically sound.

Everything decomposes as

    O = Σ_b f_broker(b) + Σ_h f_host(h) + rack + topic + healing

which is what both engines exploit: greedy evaluates f on batched hypothetical
loads; the annealer maintains running aggregates and evaluates f only on
touched brokers/hosts.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.models.cluster import Assignment
from cruise_control_tpu.ops.aggregates import (
    BrokerAggregates,
    DeviceTopology,
    compute_aggregates,
)


#: separates the violation channel from the cost channel in the combined
#: scalar; any single violation-count change dominates any cost change
VIOL_SCALE = 2.0 ** 20


class ObjectiveWeights(NamedTuple):
    """Per-term weights in the decomposed layout, both channels."""

    broker_terms: jax.Array   # f32[NUM_BROKER_TERMS] cost weights
    host_terms: jax.Array     # f32[3] (CpuCapacity, NwInCapacity, NwOutCapacity)
    rack: jax.Array           # f32 scalar
    topic: jax.Array          # f32 scalar
    healing: jax.Array        # f32 scalar (offline replicas must relocate)
    preferred_leader: jax.Array  # f32 scalar
    per_goal: jax.Array       # f32[G+1] — cost weights for full evals
    # --- violation-channel (lexicographic priority ladder) mirrors ---
    broker_terms_viol: jax.Array  # f32[NUM_BROKER_TERMS]
    host_terms_viol: jax.Array    # f32[3]
    rack_viol: jax.Array
    topic_viol: jax.Array
    healing_viol: jax.Array
    preferred_leader_viol: jax.Array
    per_goal_viol: jax.Array      # f32[G+1]


def combine(two: jax.Array) -> jax.Array:
    """Collapse a [..., 2] (viol, cost) pair into the lexicographic scalar.
    Call this only on *differenced* channels (or totals used for ranking)."""
    return two[..., 0] * VIOL_SCALE + two[..., 1]


def build_weights(goal_names: Sequence[str],
                  hard_weight: float = 2.0 ** 13,
                  soft_base: float = 2.0,
                  active_prefix: Optional[int] = None,
                  hard_only: bool = False) -> ObjectiveWeights:
    """Map a priority-ordered goal list to decomposed two-channel weights.

    ``hard_weight`` (cost channel) stays well below ``VIOL_SCALE``: the
    maximum per-action cost delta must never outweigh a single violation
    count on the primary channel, or cost could buy soft-goal regressions.

    ``active_prefix``: zero both channels for goals at index >= the prefix —
    the staged sequential descent (GoalOptimizer.java:429 phase structure)
    reuses one compiled loop across stages because only weight *values*
    change, never shapes. Internal hard terms and self-healing stay active
    in every stage.

    ``hard_only``: zero both channels for every SOFT goal, by value — the
    hard-violation backstop descends on hard goals alone while keeping the
    full goal list's array SHAPES, so the jitted repair kernels it re-
    engages are the already-compiled ones.
    """
    w = G.goal_weights(goal_names, hard_weight, soft_base)       # [G+1]
    wv = G.goal_viol_weights(goal_names)                         # [G+1]
    if active_prefix is not None:
        mask = np.arange(len(w), dtype=np.float32) < active_prefix
        mask[-1] = True                       # appended self-healing term
        w = w * mask
        wv = wv * mask
    if hard_only:
        mask = np.array([G.is_hard(g) for g in goal_names] + [True],
                        np.float32)
        w = w * mask
        wv = wv * mask
    by_goal = {g: float(w[i]) for i, g in enumerate(goal_names)}
    by_goal_v = {g: float(wv[i]) for i, g in enumerate(goal_names)}
    bt = np.zeros(G.NUM_BROKER_TERMS, np.float32)
    btv = np.zeros(G.NUM_BROKER_TERMS, np.float32)
    for g, i in ((g, G.BROKER_TERM_GOALS.index(g)) for g in goal_names
                 if g in G.BROKER_TERM_GOALS):
        bt[i] = by_goal[g]
        btv[i] = by_goal_v[g]
    for internal in ("_DeadBrokerPlacement", "_DemotedLeadership"):
        bt[G.BROKER_TERM_GOALS.index(internal)] = hard_weight
        btv[G.BROKER_TERM_GOALS.index(internal)] = G.HARD_VIOL_WEIGHT
    ht = np.array([by_goal.get(g, 0.0) for g in G.HOST_TERM_GOALS], np.float32)
    htv = np.array([by_goal_v.get(g, 0.0) for g in G.HOST_TERM_GOALS],
                   np.float32)
    return ObjectiveWeights(
        broker_terms=jnp.asarray(bt),
        host_terms=jnp.asarray(ht),
        rack=jnp.float32(by_goal.get("RackAwareGoal", 0.0)),
        topic=jnp.float32(by_goal.get("TopicReplicaDistributionGoal", 0.0)),
        healing=jnp.float32(hard_weight),
        preferred_leader=jnp.float32(by_goal.get("PreferredLeaderElectionGoal", 0.0)),
        per_goal=jnp.asarray(w),
        broker_terms_viol=jnp.asarray(btv),
        host_terms_viol=jnp.asarray(htv),
        rack_viol=jnp.float32(by_goal_v.get("RackAwareGoal", 0.0)),
        topic_viol=jnp.float32(by_goal_v.get("TopicReplicaDistributionGoal", 0.0)),
        healing_viol=jnp.float32(G.HARD_VIOL_WEIGHT),
        preferred_leader_viol=jnp.float32(
            by_goal_v.get("PreferredLeaderElectionGoal", 0.0)),
        per_goal_viol=jnp.asarray(wv),
    )


def broker_cost(th: G.GoalThresholds, weights: ObjectiveWeights,
                broker_load: jax.Array, replica_count: jax.Array,
                leader_count: jax.Array, potential_nw_out: jax.Array,
                leader_bytes_in: jax.Array) -> jax.Array:
    """Two-channel per-broker objective, shape [..., 2] = (viol, cost);
    broadcasts over any leading batch dims.

    All per-broker inputs must be *gathered for the same broker index* so the
    alive/capacity threshold rows line up: callers evaluating hypothetical
    loads for broker b pass ``th`` rows for b via :func:`gather_thresholds`.
    """
    bt = G.broker_terms(th, broker_load, replica_count, leader_count,
                        potential_nw_out, leader_bytes_in)
    return jnp.stack([
        jnp.sum(bt.violations * weights.broker_terms_viol, axis=-1),
        jnp.sum(bt.cost * weights.broker_terms, axis=-1)], axis=-1)


def gather_thresholds(th: G.GoalThresholds, idx: jax.Array) -> G.GoalThresholds:
    """Threshold rows for specific brokers (for batched hypothetical evals)."""
    return th._replace(
        alive=th.alive[idx],
        demoted=th.demoted[idx],
        broker_capacity=th.broker_capacity[idx],
        cap_limit_broker=th.cap_limit_broker[idx],
        pot_nw_out_limit=th.pot_nw_out_limit[idx],
    )


def host_cost(th: G.GoalThresholds, weights: ObjectiveWeights,
              host_load: jax.Array) -> jax.Array:
    """Two-channel per-host objective [..., 2]; broadcasts over leading batch
    dims (rows of ``host_load`` must correspond to ``th.cap_limit_host``)."""
    viol, cost = G.host_terms(th, host_load)
    return jnp.stack([jnp.sum(viol * weights.host_terms_viol, axis=-1),
                      jnp.sum(cost * weights.host_terms, axis=-1)], axis=-1)


def gather_host_thresholds(th: G.GoalThresholds, hidx: jax.Array) -> G.GoalThresholds:
    return th._replace(cap_limit_host=th.cap_limit_host[hidx])


class ObjectiveState(NamedTuple):
    """Everything needed to score a full state in one pass."""

    #: f32[2] — (weighted violation total, weighted cost total). Kept as two
    #: channels: the combined f32 scalar would absorb every cost digit under
    #: any violation (see module docstring). Rank states with
    #: :func:`combine_f64` on host.
    value: jax.Array
    penalties: G.GoalPenalties


def combine_f64(value: "np.ndarray | jax.Array") -> float:
    """Host-side lexicographic scalar from a (viol, cost) value pair —
    float64 keeps both channels' digits."""
    v = np.asarray(jax.device_get(value), np.float64)
    return float(v[..., 0] * VIOL_SCALE + v[..., 1])


def evaluate_objective(dt: DeviceTopology, assign: Assignment,
                       th: G.GoalThresholds, weights: ObjectiveWeights,
                       goal_names: Sequence[str], num_topics: int,
                       initial_broker_of: Optional[jax.Array] = None,
                       agg: Optional[BrokerAggregates] = None,
                       sparse_topic: bool = False) -> ObjectiveState:
    """Exact full-state objective (used for scoring/ranking final states and
    for periodic drift correction of the annealer's running aggregates)."""
    pen = G.full_goal_penalties(dt, assign, th, num_topics, goal_names,
                                initial_broker_of=initial_broker_of, agg=agg,
                                sparse_topic=sparse_topic)
    value = _weighted_value(pen, weights)
    return ObjectiveState(value=value, penalties=pen)


@jax.jit
def _weighted_value(pen, weights):
    """One program for the per-goal weighting (was 5 eager tiny programs)."""
    return jnp.stack([jnp.sum(pen.violations * weights.per_goal_viol),
                      jnp.sum(pen.cost * weights.per_goal)])
