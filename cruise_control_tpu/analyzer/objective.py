"""Weighted scalar objective over the goal penalty terms.

Bridges :mod:`cruise_control_tpu.analyzer.goals` (per-goal penalties) and the
two search engines (greedy descent, annealer). The objective is

    O(state) = Σ_goals w_g · cost_g(state)

with hierarchical weights approximating the reference's sequential
goal-priority semantics (``GoalOptimizer.java:429``: earlier goals veto later
actions; hard goals always win). It decomposes as

    O = Σ_b f_broker(b) + Σ_h f_host(h) + w_rack·excess + topic term + healing

which is what both engines exploit: greedy evaluates f on batched hypothetical
loads; the annealer maintains running aggregates and evaluates f only on
touched brokers/hosts.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.models.cluster import Assignment
from cruise_control_tpu.ops.aggregates import (
    BrokerAggregates,
    DeviceTopology,
    compute_aggregates,
)


class ObjectiveWeights(NamedTuple):
    """Per-term weights in the decomposed layout."""

    broker_terms: jax.Array   # f32[NUM_BROKER_TERMS] (0 where goal not selected)
    host_terms: jax.Array     # f32[3] (CpuCapacity, NwInCapacity, NwOutCapacity)
    rack: jax.Array           # f32 scalar
    topic: jax.Array          # f32 scalar
    healing: jax.Array        # f32 scalar (offline replicas must relocate)
    preferred_leader: jax.Array  # f32 scalar
    per_goal: jax.Array       # f32[G+1] — goal_weights vector for full evals


def build_weights(goal_names: Sequence[str],
                  hard_weight: float = 1e7,
                  soft_base: float = 2.0) -> ObjectiveWeights:
    """Map a priority-ordered goal list to decomposed term weights."""
    w = G.goal_weights(goal_names, hard_weight, soft_base)  # [G+1]
    by_goal = {g: float(w[i]) for i, g in enumerate(goal_names)}
    bt = np.zeros(G.NUM_BROKER_TERMS, np.float32)
    for g, i in ((g, G.BROKER_TERM_GOALS.index(g)) for g in goal_names
                 if g in G.BROKER_TERM_GOALS):
        bt[i] = by_goal[g]
    bt[G.BROKER_TERM_GOALS.index("_DeadBrokerPlacement")] = hard_weight
    bt[G.BROKER_TERM_GOALS.index("_DemotedLeadership")] = hard_weight
    ht = np.array([by_goal.get(g, 0.0) for g in G.HOST_TERM_GOALS], np.float32)
    return ObjectiveWeights(
        broker_terms=jnp.asarray(bt),
        host_terms=jnp.asarray(ht),
        rack=jnp.float32(by_goal.get("RackAwareGoal", 0.0)),
        topic=jnp.float32(by_goal.get("TopicReplicaDistributionGoal", 0.0)),
        healing=jnp.float32(hard_weight),
        preferred_leader=jnp.float32(by_goal.get("PreferredLeaderElectionGoal", 0.0)),
        per_goal=jnp.asarray(w),
    )


def broker_cost(th: G.GoalThresholds, weights: ObjectiveWeights,
                broker_load: jax.Array, replica_count: jax.Array,
                leader_count: jax.Array, potential_nw_out: jax.Array,
                leader_bytes_in: jax.Array) -> jax.Array:
    """Weighted per-broker cost; broadcasts over any leading batch dims.

    All per-broker inputs must be *gathered for the same broker index* so the
    alive/capacity threshold rows line up: callers evaluating hypothetical
    loads for broker b pass ``th`` rows for b via :func:`gather_thresholds`.
    """
    bt = G.broker_terms(th, broker_load, replica_count, leader_count,
                        potential_nw_out, leader_bytes_in)
    return jnp.sum(bt.cost * weights.broker_terms, axis=-1)


def gather_thresholds(th: G.GoalThresholds, idx: jax.Array) -> G.GoalThresholds:
    """Threshold rows for specific brokers (for batched hypothetical evals)."""
    return th._replace(
        alive=th.alive[idx],
        demoted=th.demoted[idx],
        broker_capacity=th.broker_capacity[idx],
        cap_limit_broker=th.cap_limit_broker[idx],
        pot_nw_out_limit=th.pot_nw_out_limit[idx],
    )


def host_cost(th: G.GoalThresholds, weights: ObjectiveWeights,
              host_load: jax.Array) -> jax.Array:
    """Weighted per-host cost; broadcasts over leading batch dims (rows of
    ``host_load`` must correspond to rows of ``th.cap_limit_host``)."""
    _, cost = G.host_terms(th, host_load)
    return jnp.sum(cost * weights.host_terms, axis=-1)


def gather_host_thresholds(th: G.GoalThresholds, hidx: jax.Array) -> G.GoalThresholds:
    return th._replace(cap_limit_host=th.cap_limit_host[hidx])


class ObjectiveState(NamedTuple):
    """Everything needed to score a full state in one pass."""

    value: jax.Array          # f32 scalar — the weighted objective
    penalties: G.GoalPenalties


def evaluate_objective(dt: DeviceTopology, assign: Assignment,
                       th: G.GoalThresholds, weights: ObjectiveWeights,
                       goal_names: Sequence[str], num_topics: int,
                       initial_broker_of: Optional[jax.Array] = None,
                       agg: Optional[BrokerAggregates] = None) -> ObjectiveState:
    """Exact full-state objective (used for scoring/ranking final states and
    for periodic drift correction of the annealer's running aggregates)."""
    pen = G.full_goal_penalties(dt, assign, th, num_topics, goal_names,
                                initial_broker_of=initial_broker_of, agg=agg)
    return ObjectiveState(value=jnp.sum(pen.cost * weights.per_goal), penalties=pen)
