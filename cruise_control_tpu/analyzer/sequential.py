"""Single-threaded sequential goal optimizer — the measured reference baseline.

This module is a faithful NumPy/Python port of the reference's sequential
``GoalOptimizer`` inner loop (``analyzer/GoalOptimizer.java:429-453``): goals
run in priority order, each goal walks brokers one at a time
(``AbstractGoal.java:68-109``), and every candidate action passes the
legality → selfSatisfied → prior-goal-veto chain of
``AbstractGoal.maybeApplyBalancingAction`` (``AbstractGoal.java:181-238``)
before mutating the shared model one replica at a time.

Purpose (round-5 north-star accounting): the BASELINE.json target is
"≥20× vs single-threaded GoalOptimizer at equal-or-better violation score".
There is no JVM in this environment, so this port IS the single-threaded
baseline: same fixtures, same ``ClusterTopology`` arrays, same thresholds
family, measured wall-clock against ``optimizer.optimize``. It also supplies
the per-goal ``ClusterModelStatsComparator`` semantics (``goals/Goal.java:128``
implementations) as the parity oracle SURVEY §4 tier 3 demands.

Deliberately NOT vectorized over the walk: the per-replica candidate loop with
per-accept model mutation is the algorithm being measured (the reference's
O(goals × brokers × replicas × candidates) hot nest). Incremental aggregate
bookkeeping mirrors what the reference's ``ClusterModel`` mutation ops
(``ClusterModel.java:347,374``) keep hot — using dicts/sets per broker the way
the Java model keeps per-broker replica TreeSets.

No JAX imports here: this is the host-only oracle.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from cruise_control_tpu.common import resources as res

# ---------------------------------------------------------------------------
# Action / acceptance taxonomy (analyzer/ActionType.java:26-33,
# ActionAcceptance.java:85)
# ---------------------------------------------------------------------------

MOVE = "INTER_BROKER_REPLICA_MOVEMENT"
LEAD = "LEADERSHIP_MOVEMENT"
SWAP = "INTER_BROKER_REPLICA_SWAP"

ACCEPT = 0
REPLICA_REJECT = 1
BROKER_REJECT = 2

#: AnalyzerUtils.EPSILON (AnalyzerUtils.java:42) — count-stat comparators
EPSILON = 1e-5

#: ResourceDistributionGoal.BALANCE_MARGIN / ReplicaDistributionAbstractGoal /
#: TopicReplicaDistributionGoal all use 0.9 (churn guard on the thresholds)
BALANCE_MARGIN = 0.9

#: ResourceDistributionGoal.PER_BROKER_SWAP_TIMEOUT_MS = 1000
PER_BROKER_SWAP_TIMEOUT_S = 1.0


class SeqOptimizationFailure(Exception):
    """OptimizationFailureException analogue (hard goal unsatisfiable or a
    goal's post-optimization stats regressed its own comparator)."""


def _compare(d1: float, d2: float, eps: float) -> int:
    """AnalyzerUtils.compare (AnalyzerUtils.java:158): 1 if d1 > d2 beyond
    eps, -1 if d1 < d2 beyond eps, else 0."""
    if d2 - d1 > eps:
        return -1
    if d1 - d2 > eps:
        return 1
    return 0


def _resource_compare(d1: float, d2: float, r: int) -> int:
    """AnalyzerUtils.compare with the per-resource epsilon policy
    (Resource.java:87-89)."""
    return _compare(d1, d2, float(res.epsilon(r, d1, d2)))


# ---------------------------------------------------------------------------
# Options (OptimizationOptions.java:14-21, host-side form)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SeqOptions:
    excluded_topics: frozenset = frozenset()          # topic ids (int)
    excluded_brokers_for_leadership: frozenset = frozenset()
    excluded_brokers_for_replica_move: frozenset = frozenset()
    requested_destination_broker_ids: frozenset = frozenset()
    only_move_immigrant_replicas: bool = False
    is_triggered_by_goal_violation: bool = False


# ---------------------------------------------------------------------------
# Mutable single-threaded cluster model mirror
# ---------------------------------------------------------------------------


class SeqModel:
    """Mutable mirror of the reference ``ClusterModel`` over the repo's
    ``ClusterTopology`` arrays.

    State parallels model/ClusterModel.java: per-broker replica sets, broker /
    host load vectors, leadership load, potential leadership NW_OUT
    (``ClusterModel.java:205``), replica/leader/topic counts — all maintained
    incrementally by ``relocate_replica`` / ``relocate_leadership``
    (``ClusterModel.java:347,374``).
    """

    def __init__(self, topo, broker_of: np.ndarray, leader_of: np.ndarray):
        self.topo = topo
        B = topo.num_brokers
        self.B = B
        self.R = topo.num_replicas
        self.P = topo.num_partitions
        self.T = topo.num_topics
        self.part_of = np.asarray(topo.partition_of_replica, np.int64)
        self.topic_of_p = np.asarray(topo.topic_of_partition, np.int64)
        self.reps_of_p = np.asarray(topo.replicas_of_partition, np.int64)
        self.rack_of_b = np.asarray(topo.rack_of_broker, np.int64)
        self.host_of_b = np.asarray(topo.host_of_broker, np.int64)
        self.H = topo.num_hosts
        self.brokers_of_host: List[List[int]] = [[] for _ in range(self.H)]
        for b in range(B):
            self.brokers_of_host[int(self.host_of_b[b])].append(b)
        self.cap = np.asarray(topo.capacity, np.float64)          # [B,4]
        self.host_cap = np.asarray(topo.host_capacity(), np.float64)
        self.alive = np.asarray(topo.broker_alive, bool).copy()
        self.new = np.asarray(topo.broker_new, bool)
        self.has_new = bool(self.new.any())
        self.base = np.asarray(topo.replica_base_load, np.float64)  # [R,4]
        self.extra = np.asarray(topo.leader_extra, np.float64)      # [P,4]

        # decision state
        self.broker_of = np.asarray(broker_of, np.int64).copy()
        self.leader_of = np.asarray(leader_of, np.int64).copy()    # [P]→r
        self.orig_broker = self.broker_of.copy()
        r_idx = np.arange(self.R)
        self.is_leader = np.zeros(self.R, bool)
        self.is_leader[self.leader_of] = True
        # currently-offline flag (Replica.isCurrentOffline): offline at the
        # ORIGINAL placement and not yet relocated to an alive broker
        self.offline = np.asarray(topo.replica_offline, bool).copy()

        # per-broker replica sets + (broker, partition) → replica lookup
        self.replicas_on: List[Set[int]] = [set() for _ in range(B)]
        self.rep_at: Dict[Tuple[int, int], int] = {}
        for r in r_idx:
            b = int(self.broker_of[r])
            self.replicas_on[b].add(int(r))
            self.rep_at[(b, int(self.part_of[r]))] = int(r)

        # incremental aggregates (f64, like the Java doubles)
        eff = self.base + np.where(self.is_leader[:, None],
                                   self.extra[self.part_of], 0.0)
        self.broker_load = np.zeros((B, 4))
        np.add.at(self.broker_load, self.broker_of, eff)
        self.host_load = np.zeros((self.H, 4))
        np.add.at(self.host_load, self.host_of_b[self.broker_of], eff)
        # leadershipLoadForNwResources (Broker.java): leader replicas' load
        self.lead_load = np.zeros((B, 4))
        np.add.at(self.lead_load, self.broker_of[self.leader_of],
                  eff[self.leader_of])
        # potential leadership NW_OUT (ClusterModel.java:205): every replica
        # contributes its partition LEADER's NW_OUT
        leader_nw_out = eff[self.leader_of, res.NW_OUT]           # [P]
        self.leader_nw_out = leader_nw_out.copy()
        self.pot_nw_out = np.zeros(B)
        np.add.at(self.pot_nw_out, self.broker_of, leader_nw_out[self.part_of])

        self.replica_count = np.bincount(self.broker_of, minlength=B)
        self.leader_count = np.bincount(self.broker_of[self.leader_of],
                                        minlength=B)
        # per-broker per-topic replica counts (Broker.numReplicasOfTopicInBroker)
        self.topic_count: List[Dict[int, int]] = [dict() for _ in range(B)]
        t_of_r = self.topic_of_p[self.part_of]
        for r in r_idx:
            tc = self.topic_count[int(self.broker_of[r])]
            t = int(t_of_r[r])
            tc[t] = tc.get(t, 0) + 1
        # per-topic cluster totals (move-invariant)
        self.topic_total = np.bincount(t_of_r, minlength=self.T)

        self.num_moves = 0
        self.num_leads = 0

    # ---- queries ---------------------------------------------------------

    def eff_load(self, r: int) -> np.ndarray:
        if self.is_leader[r]:
            return self.base[r] + self.extra[self.part_of[r]]
        return self.base[r]

    def eff_util(self, r: int, resource: int) -> float:
        v = self.base[r, resource]
        if self.is_leader[r]:
            v += self.extra[self.part_of[r], resource]
        return float(v)

    def util_pct(self, b: int, resource: int) -> float:
        """GoalUtils.utilizationPercentage (GoalUtils.java:307-310)."""
        cap = self.cap[b, resource]
        return self.broker_load[b, resource] / cap if cap > 0 else -1.0

    def alive_brokers(self) -> List[int]:
        return [b for b in range(self.B) if self.alive[b]]

    def current_offline_on(self, b: int) -> List[int]:
        return [r for r in self.replicas_on[b] if self.offline[r]]

    def has_offline(self) -> bool:
        return bool(self.offline.any())

    def partition_brokers(self, p: int) -> List[int]:
        return [int(self.broker_of[r]) for r in self.reps_of_p[p]
                if r >= 0]

    def is_immigrant(self, r: int) -> bool:
        return self.broker_of[r] != self.orig_broker[r]

    # ---- mutations (ClusterModel.java:347,374) ---------------------------

    def relocate_replica(self, r: int, dst: int) -> None:
        src = int(self.broker_of[r])
        p = int(self.part_of[r])
        t = int(self.topic_of_p[p])
        eff = self.eff_load(r)
        self.replicas_on[src].discard(r)
        self.replicas_on[dst].add(r)
        del self.rep_at[(src, p)]
        self.rep_at[(dst, p)] = r
        self.broker_of[r] = dst
        self.broker_load[src] -= eff
        self.broker_load[dst] += eff
        self.host_load[self.host_of_b[src]] -= eff
        self.host_load[self.host_of_b[dst]] += eff
        if self.is_leader[r]:
            self.lead_load[src] -= eff
            self.lead_load[dst] += eff
            self.leader_count[src] -= 1
            self.leader_count[dst] += 1
        lno = self.leader_nw_out[p]
        self.pot_nw_out[src] -= lno
        self.pot_nw_out[dst] += lno
        self.replica_count[src] -= 1
        self.replica_count[dst] += 1
        tc = self.topic_count[src]
        tc[t] -= 1
        if not tc[t]:
            del tc[t]
        tc = self.topic_count[dst]
        tc[t] = tc.get(t, 0) + 1
        if self.offline[r] and self.alive[dst]:
            self.offline[r] = False
        self.num_moves += 1

    def relocate_leadership(self, p: int, r_new: int) -> None:
        r_old = int(self.leader_of[p])
        if r_old == r_new:
            return
        b_old = int(self.broker_of[r_old])
        b_new = int(self.broker_of[r_new])
        ex = self.extra[p]
        eff_old = self.base[r_old] + ex        # old leader's leader-role load
        # broker/host loads move by the leader extra only
        self.broker_load[b_old] -= ex
        self.broker_load[b_new] += ex
        self.host_load[self.host_of_b[b_old]] -= ex
        self.host_load[self.host_of_b[b_new]] += ex
        self.lead_load[b_old] -= eff_old
        self.lead_load[b_new] += self.base[r_new] + ex
        self.leader_count[b_old] -= 1
        self.leader_count[b_new] += 1
        self.is_leader[r_old] = False
        self.is_leader[r_new] = True
        self.leader_of[p] = r_new
        # potential NW_OUT: every holder of p now contributes the NEW
        # leader's NW_OUT
        new_lno = self.base[r_new, res.NW_OUT] + ex[res.NW_OUT]
        d = new_lno - self.leader_nw_out[p]
        if d:
            for rr in self.reps_of_p[p]:
                if rr >= 0:
                    self.pot_nw_out[self.broker_of[rr]] += d
            self.leader_nw_out[p] = new_lno
        self.num_leads += 1

    # ---- legality (GoalUtils.java:153-167) -------------------------------

    def legit_move(self, r: int, dst: int, action: str) -> bool:
        p = int(self.part_of[r])
        if action == MOVE:
            return (dst, p) not in self.rep_at
        if action == LEAD:
            return bool(self.is_leader[r]) and (dst, p) in self.rep_at
        return False


# ---------------------------------------------------------------------------
# ClusterModelStats port (model/ClusterModelStats.java:26-46,275-460)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SeqStats:
    """The comparator-relevant subset of ClusterModelStats."""

    avg_util: np.ndarray        # [4] total utilization / num alive brokers
    max_util: np.ndarray        # [4] hottest alive broker (absolute)
    stdev_util: np.ndarray      # [4] sqrt(sum((u - avgPct*cap)^2)/nAlive)
    num_balanced_by_resource: np.ndarray   # i64[4]
    num_brokers_under_pot_nw_out: int
    replica_stdev: float
    leader_stdev: float
    topic_stdev: float          # mean over topics of per-topic stdev


def compute_seq_stats(m: SeqModel, constraint) -> SeqStats:
    """ClusterModelStats.populate (ClusterModelStats.java:74-90) over the
    mutable model — alive-broker populations throughout."""
    alive = np.flatnonzero(m.alive)
    n_alive = max(len(alive), 1)
    bal = np.asarray(constraint.resource_balance_percentage, np.float64)
    cap_thresh = np.asarray(constraint.capacity_threshold, np.float64)

    avg_util = np.zeros(4)
    max_util = np.zeros(4)
    stdev = np.zeros(4)
    n_balanced = np.zeros(4, np.int64)
    for r in range(4):
        host_scope = bool(res.IS_HOST_RESOURCE[r])
        if host_scope:
            util = m.host_load[m.host_of_b[alive], r]
            cap = m.host_cap[m.host_of_b[alive], r]
        else:
            util = m.broker_load[alive, r]
            cap = m.cap[alive, r]
        total = m.broker_load[alive, r].sum()
        total_cap = m.cap[alive, r].sum()
        avg_pct = total / total_cap if total_cap > 0 else 0.0
        upper = avg_pct * bal[r]
        lower = avg_pct * max(0.0, 2.0 - bal[r])
        with np.errstate(divide="ignore", invalid="ignore"):
            pct = np.where(cap > 0, util / cap, 0.0)
        n_balanced[r] = int(((pct >= lower) & (pct <= upper)).sum())
        max_util[r] = util.max(initial=0.0)
        stdev[r] = float(np.sqrt(
            np.square(util - avg_pct * cap).sum() / n_alive))
        avg_util[r] = total / n_alive

    pot = m.pot_nw_out[alive]
    pot_cap = m.cap[alive, res.NW_OUT]
    with np.errstate(divide="ignore", invalid="ignore"):
        pot_pct = np.where(pot_cap > 0, pot / pot_cap, np.inf)
    n_under = int((pot_pct <= cap_thresh[res.NW_OUT]).sum())

    def _count_stdev(counts: np.ndarray) -> float:
        avg = counts.sum() / n_alive
        return float(np.sqrt(np.square(counts[alive] - avg).sum() / n_alive))

    rep_stdev = _count_stdev(m.replica_count.astype(np.float64))
    lead_stdev = _count_stdev(m.leader_count.astype(np.float64))

    # per-topic stdev over alive brokers, averaged over topics
    # (ClusterModelStats.java:417-455). Sparse accumulation: sum_b (c-avg)^2
    # = sum_b c^2 - 2*avg*sum_b c + n_alive*avg^2, walking only the nonzero
    # per-broker topic counts (a dense [B, T] matrix is 600+ MB at the
    # LinkedIn 2,600 x 30,000 shape).
    avg_t = m.topic_total / n_alive                     # [T]
    sum_c = np.zeros(m.T)
    sum_c2 = np.zeros(m.T)
    alive_mask = m.alive
    for b in range(m.B):
        if not alive_mask[b]:
            continue
        for t, c in m.topic_count[b].items():
            sum_c[t] += c
            sum_c2[t] += c * c
    var_t = np.maximum(
        (sum_c2 - 2.0 * avg_t * sum_c + n_alive * avg_t * avg_t) / n_alive,
        0.0)
    topic_stdev = float(np.sqrt(var_t).sum() / max(m.T, 1))

    return SeqStats(avg_util=avg_util, max_util=max_util, stdev_util=stdev,
                    num_balanced_by_resource=n_balanced,
                    num_brokers_under_pot_nw_out=n_under,
                    replica_stdev=rep_stdev, leader_stdev=lead_stdev,
                    topic_stdev=topic_stdev)


def compare_stats(goal_name: str, s1: SeqStats, s2: SeqStats,
                  constraint) -> int:
    """Per-goal ClusterModelStatsComparator.compare(stats1=after,
    stats2=before) — the exact semantics of each reference comparator:

    - Capacity / RackAware / ReplicaCapacity: always 0 (``CapacityGoal.java:489``,
      ``RackAwareGoal.java:338``, ``ReplicaCapacityGoal.java:318``)
    - ReplicaDistribution / LeaderReplicaDistribution / TopicReplicaDistribution:
      st-dev of the respective count must not increase
      (``ReplicaDistributionGoal.java:288``, ``LeaderReplicaDistributionGoal.java:338``,
      ``TopicReplicaDistributionGoal.java:568``)
    - ResourceDistribution: fewer balanced brokers is only OK if the
      utilization st-dev improved (``ResourceDistributionGoal.java:960-988``)
    - LeaderBytesInDistribution: NW_IN max under avg·balance% → better; else
      st-dev compare with the NW_IN epsilon (``LeaderBytesInDistributionGoal.java:258``)
    - PotentialNwOut: brokers under potential NW_OUT must not decrease
      (``PotentialNwOutGoal.java:351``)
    """
    if goal_name in ("RackAwareGoal", "ReplicaCapacityGoal",
                     "DiskCapacityGoal", "NetworkInboundCapacityGoal",
                     "NetworkOutboundCapacityGoal", "CpuCapacityGoal",
                     "PreferredLeaderElectionGoal"):
        return 0
    if goal_name == "ReplicaDistributionGoal":
        return _compare(s2.replica_stdev, s1.replica_stdev, EPSILON)
    if goal_name == "LeaderReplicaDistributionGoal":
        return _compare(s2.leader_stdev, s1.leader_stdev, EPSILON)
    if goal_name == "TopicReplicaDistributionGoal":
        return _compare(s2.topic_stdev, s1.topic_stdev, EPSILON)
    if goal_name == "LeaderBytesInDistributionGoal":
        bal = constraint.resource_balance_percentage[res.NW_IN]
        threshold = s1.avg_util[res.NW_IN] * bal
        if s1.max_util[res.NW_IN] <= threshold:
            return 1
        # NOTE the reference's own quirk, reproduced deliberately: it reads
        # ST_DEV (already a standard deviation,
        # ClusterModelStats.java:305-309) into locals named "variance" and
        # takes Math.sqrt AGAIN before comparing
        # (LeaderBytesInDistributionGoal.java:270-273) — so this comparator
        # runs in sqrt(stdev) space, unlike ResourceDistributionGoal's raw
        # ST_DEV compare. Faithful parity means keeping the double sqrt.
        return _resource_compare(np.sqrt(s2.stdev_util[res.NW_IN]),
                                 np.sqrt(s1.stdev_util[res.NW_IN]),
                                 res.NW_IN)
    if goal_name == "PotentialNwOutGoal":
        a, b = s1.num_brokers_under_pot_nw_out, s2.num_brokers_under_pot_nw_out
        return (a > b) - (a < b)
    # ResourceDistributionGoal family
    r = _DISTRIBUTION_RESOURCE.get(goal_name)
    if r is not None:
        if (s2.num_balanced_by_resource[r] > s1.num_balanced_by_resource[r]
                and s2.stdev_util[r] < s1.stdev_util[r]):
            return -1
        return 1
    raise ValueError(f"unknown goal {goal_name!r}")


_DISTRIBUTION_RESOURCE = {
    "DiskUsageDistributionGoal": res.DISK,
    "NetworkInboundUsageDistributionGoal": res.NW_IN,
    "NetworkOutboundUsageDistributionGoal": res.NW_OUT,
    "CpuUsageDistributionGoal": res.CPU,
}
_CAPACITY_RESOURCE = {
    "DiskCapacityGoal": res.DISK,
    "NetworkInboundCapacityGoal": res.NW_IN,
    "NetworkOutboundCapacityGoal": res.NW_OUT,
    "CpuCapacityGoal": res.CPU,
}


# ---------------------------------------------------------------------------
# Goal base — the AbstractGoal walk (AbstractGoal.java:68-238)
# ---------------------------------------------------------------------------


class SeqGoal:
    name = "SeqGoal"
    hard = False

    def __init__(self, constraint, options: SeqOptions):
        self.constraint = constraint
        self.options = options
        self.finished = False
        self.succeeded = True

    # -- SPI hooks (subclasses override) -----------------------------------
    def init_goal_state(self, m: SeqModel) -> None:
        pass

    def brokers_to_balance(self, m: SeqModel) -> List[int]:
        return list(range(m.B))

    def rebalance_for_broker(self, m: SeqModel, b: int,
                             optimized: List["SeqGoal"]) -> None:
        raise NotImplementedError

    def update_goal_state(self, m: SeqModel) -> None:
        self.finished = True

    def self_satisfied(self, m: SeqModel, action) -> bool:
        return True

    def action_acceptance(self, m: SeqModel, action) -> int:
        return ACCEPT

    # -- the optimize loop (AbstractGoal.java:68-109) ----------------------
    def optimize(self, m: SeqModel, optimized: List["SeqGoal"],
                 stats_before: Optional[SeqStats] = None
                 ) -> Tuple[bool, SeqStats, SeqStats]:
        """Run the goal; returns (succeeded, stats_before, stats_after) so
        the driver never recomputes the stats passes this loop already paid
        for (each pass walks every broker's topic-count dict — real money
        at the 2,600 x 30,000 LinkedIn shape this module gets timed at)."""
        self.succeeded = True
        self.finished = False
        if stats_before is None:
            stats_before = compute_seq_stats(m, self.constraint)
        broken_before = bool((~m.alive).any()) or m.has_offline()
        self.init_goal_state(m)
        while not self.finished:
            for b in self.brokers_to_balance(m):
                self.rebalance_for_broker(m, b, optimized)
            self.update_goal_state(m)
        stats_after = compute_seq_stats(m, self.constraint)
        if not broken_before:
            if compare_stats(self.name, stats_after, stats_before,
                             self.constraint) < 0:
                raise SeqOptimizationFailure(
                    f"{self.name}: optimized result worse than before")
        return self.succeeded, stats_before, stats_after

    # -- eligible brokers (GoalUtils.java:121-140) -------------------------
    def _eligible_brokers(self, m: SeqModel, r: int, candidates,
                          action: str) -> List[int]:
        opts = self.options
        if opts.requested_destination_broker_ids and action != LEAD:
            # requested destinations REPLACE the exclusion filters for
            # non-leadership actions (GoalUtils.java:100-104): the caller
            # explicitly picked the destinations, so the excluded-broker
            # sets don't apply; the early return also skips the new-broker
            # invariant (GoalUtils.java:130-132)
            return [b for b in candidates
                    if b in opts.requested_destination_broker_ids]
        out = []
        is_lead_action = (action == LEAD
                          or (action == MOVE and m.is_leader[r]))
        # NO offline-replica carve-out here: the reference exempts offline
        # replicas from the exclusion filters only in
        # eligibleReplicasForSwap (GoalUtils.java:207-212), not in the
        # per-action eligible-brokers path
        for b in candidates:
            if is_lead_action and b in opts.excluded_brokers_for_leadership:
                continue
            if action == MOVE and b in opts.excluded_brokers_for_replica_move:
                continue
            out.append(b)
        if opts.requested_destination_broker_ids:
            # LEAD with requested destinations: filters applied above, and
            # the early return still skips the new-broker invariant
            return out
        if m.has_new:
            out = [b for b in out
                   if m.new[b] or b == int(m.orig_broker[r])]
        return out

    # -- maybeApplyBalancingAction (AbstractGoal.java:181-223) -------------
    def maybe_apply(self, m: SeqModel, r: int, candidates, action: str,
                    optimized: List["SeqGoal"]) -> Optional[int]:
        for b in self._eligible_brokers(m, r, candidates, action):
            if not m.legit_move(r, b, action):
                continue
            act = (int(m.part_of[r]), int(m.broker_of[r]), b, action, None)
            if not self.self_satisfied(m, act):
                continue
            if any(g.action_acceptance(m, act) != ACCEPT for g in optimized):
                continue
            if action == LEAD:
                m.relocate_leadership(act[0], m.rep_at[(b, act[0])])
            else:
                m.relocate_replica(r, b)
            return b
        return None

    # -- maybeApplySwapAction (AbstractGoal.java:238-289) ------------------
    def maybe_apply_swap(self, m: SeqModel, r_src: int,
                         candidate_replicas: Sequence[int],
                         optimized: List["SeqGoal"]) -> Optional[int]:
        if not len(candidate_replicas):
            return None
        dst_broker = int(m.broker_of[candidate_replicas[0]])
        opts = self.options
        # eligibleReplicasForSwap invariants (GoalUtils.java:200-230)
        if (dst_broker in opts.excluded_brokers_for_leadership
                and m.is_leader[r_src] and not m.offline[r_src]):
            return None
        if (dst_broker in opts.excluded_brokers_for_replica_move
                and not m.offline[r_src]):
            return None
        src_broker = int(m.broker_of[r_src])
        for r_dst in candidate_replicas:
            if not m.legit_move(r_src, dst_broker, MOVE):
                return None
            if not m.legit_move(r_dst, src_broker, MOVE):
                continue
            act = (int(m.part_of[r_src]), src_broker, dst_broker, SWAP,
                   int(m.part_of[r_dst]))
            if not self.self_satisfied(m, act):
                return None
            acc = ACCEPT
            for g in optimized:
                acc = g.action_acceptance(m, act)
                if acc != ACCEPT:
                    break
            if acc == ACCEPT:
                m.relocate_replica(r_src, dst_broker)
                m.relocate_replica(r_dst, src_broker)
                return r_dst
            if acc == BROKER_REJECT:
                return None
        return None

    # -- shared selection/sort helpers -------------------------------------
    def _movable(self, m: SeqModel, r: int) -> bool:
        """Excluded-topic / immigrant-only selection shared by the sort
        helpers (ReplicaSortFunctionFactory selection funcs)."""
        t = int(m.topic_of_p[m.part_of[r]])
        if t in self.options.excluded_topics and not m.offline[r]:
            return False
        if (self.options.only_move_immigrant_replicas
                and not m.is_immigrant(r) and not m.offline[r]):
            return False
        return True


# ---------------------------------------------------------------------------
# RackAwareGoal (goals/RackAwareGoal.java:43,161-316)
# ---------------------------------------------------------------------------


class SeqRackAwareGoal(SeqGoal):
    name = "RackAwareGoal"
    hard = True

    def action_acceptance(self, m: SeqModel, action) -> int:
        p, src, dst, kind, p2 = action
        if kind == LEAD:
            return ACCEPT
        if self._move_violates(m, p, src, dst):
            return BROKER_REJECT
        if kind == SWAP and self._move_violates(m, p2, dst, src):
            return REPLICA_REJECT
        return ACCEPT

    def _move_violates(self, m: SeqModel, p: int, src: int, dst: int) -> bool:
        dst_rack = m.rack_of_b[dst]
        for b in m.partition_brokers(p):
            if b != src and m.rack_of_b[b] == dst_rack:
                return True
        return False

    def init_goal_state(self, m: SeqModel) -> None:
        num_racks_alive = len({int(m.rack_of_b[b]) for b in m.alive_brokers()})
        max_rf = int(np.max(np.asarray(m.topo.rf_of_partition)))
        if max_rf > num_racks_alive:
            raise SeqOptimizationFailure(
                f"RackAwareGoal: {num_racks_alive} racks < max RF {max_rf}")

    def _satisfied(self, m: SeqModel, r: int) -> bool:
        p = int(m.part_of[r])
        my_rack = m.rack_of_b[m.broker_of[r]]
        my_broker = int(m.broker_of[r])
        for b in m.partition_brokers(p):
            if b != my_broker and m.rack_of_b[b] == my_rack:
                return False
        return True

    def rebalance_for_broker(self, m, b, optimized):
        for r in sorted(m.replicas_on[b]):
            if not self._movable(m, r):
                continue
            if (m.alive[b] and not m.offline[r]
                    and self._satisfied(m, r)):
                continue
            # move to a broker in a rack with no other replica of p
            p = int(m.part_of[r])
            taken = {int(m.rack_of_b[pb]) for pb in m.partition_brokers(p)
                     if pb != int(m.broker_of[r])}
            eligible = [bb for bb in m.alive_brokers()
                        if int(m.rack_of_b[bb]) not in taken]
            if self.maybe_apply(m, r, eligible, MOVE, optimized) is None:
                raise SeqOptimizationFailure(
                    f"RackAwareGoal: violated for broker {b}")


# ---------------------------------------------------------------------------
# ReplicaCapacityGoal (goals/ReplicaCapacityGoal.java:41-318)
# ---------------------------------------------------------------------------


class SeqReplicaCapacityGoal(SeqGoal):
    name = "ReplicaCapacityGoal"
    hard = True

    def __init__(self, constraint, options):
        super().__init__(constraint, options)
        self.self_healing_mode = False

    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        if kind == MOVE:
            return (ACCEPT if m.replica_count[dst]
                    < self.constraint.max_replicas_per_broker
                    else REPLICA_REJECT)
        return ACCEPT

    def self_satisfied(self, m, action) -> bool:
        return (m.replica_count[action[2]]
                < self.constraint.max_replicas_per_broker)

    def init_goal_state(self, m) -> None:
        self.self_healing_mode = bool((~m.alive).any()) or m.has_offline()
        limit = self.constraint.max_replicas_per_broker
        n_alive = len(m.alive_brokers())
        if int(m.replica_count.sum()) > limit * n_alive:
            raise SeqOptimizationFailure(
                "ReplicaCapacityGoal: total replicas exceed cluster limit")

    def update_goal_state(self, m) -> None:
        if not self.self_healing_mode:
            limit = self.constraint.max_replicas_per_broker
            for b in range(m.B):
                if m.replica_count[b] > limit:
                    raise SeqOptimizationFailure(
                        f"ReplicaCapacityGoal: broker {b} over limit")
            self.finished = True
        else:
            self.self_healing_mode = False

    def rebalance_for_broker(self, m, b, optimized):
        limit = self.constraint.max_replicas_per_broker
        # offline replicas first (the reference's replica comparator)
        reps = sorted(m.replicas_on[b],
                      key=lambda r: (not m.offline[r], r))
        for r in reps:
            if not self._movable(m, r):
                continue
            if m.replica_count[b] <= limit and not m.offline[r]:
                break
            eligible = sorted(
                (bb for bb in m.alive_brokers()
                 if bb != b and (self.self_healing_mode
                                 or m.replica_count[bb] < limit)),
                key=lambda bb: (m.replica_count[bb], bb))
            dst = self.maybe_apply(m, r, eligible, MOVE, optimized)
            if dst is None and (not m.alive[b] or m.offline[r]):
                raise SeqOptimizationFailure(
                    f"ReplicaCapacityGoal: cannot move replica {r} off "
                    f"broker {b}")


# ---------------------------------------------------------------------------
# CapacityGoal family (goals/CapacityGoal.java:38-502)
# ---------------------------------------------------------------------------


class SeqCapacityGoal(SeqGoal):
    hard = True

    def __init__(self, name, constraint, options):
        super().__init__(constraint, options)
        self.name = name
        self.r = _CAPACITY_RESOURCE[name]

    # capacity check after adding load (CapacityGoal.java:436-466)
    def _under_limit_after_add(self, m: SeqModel, dst: int,
                               util: float) -> bool:
        r = self.r
        thresh = self.constraint.capacity_threshold[r]
        if res.IS_HOST_RESOURCE[r]:
            h = m.host_of_b[dst]
            if m.host_load[h, r] + util >= m.host_cap[h, r] * thresh:
                return False
        if res.IS_BROKER_RESOURCE[r]:
            return (m.broker_load[dst, r] + util
                    < m.cap[dst, r] * thresh)
        return True

    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if kind == SWAP:
            r_dst = m.rep_at[(dst, p2)]
            d = m.eff_util(r_dst, self.r) - m.eff_util(r_src, self.r)
            ok = (self._under_limit_after_add(m, src, d) if d > 0
                  else self._under_limit_after_add(m, dst, -d))
            return ACCEPT if ok else REPLICA_REJECT
        # NOTE (CapacityGoal.java:74-81): leadership CPU moves are treated
        # as carrying the FULL leader utilization — intentional reference
        # behavior we reproduce
        util = m.eff_util(r_src, self.r)
        return (ACCEPT if self._under_limit_after_add(m, dst, util)
                else REPLICA_REJECT)

    def self_satisfied(self, m, action) -> bool:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        return self._under_limit_after_add(m, dst,
                                           m.eff_util(r_src, self.r))

    def init_goal_state(self, m) -> None:
        r = self.r
        alive = np.flatnonzero(m.alive)
        existing = m.broker_load[alive, r].sum()
        allowed = (m.cap[alive, r].sum()
                   * self.constraint.capacity_threshold[r])
        if allowed < existing:
            raise SeqOptimizationFailure(
                f"{self.name}: insufficient healthy capacity")

    def _over_limit(self, m: SeqModel, b: int) -> bool:
        r = self.r
        thresh = self.constraint.capacity_threshold[r]
        if res.IS_HOST_RESOURCE[r]:
            h = m.host_of_b[b]
            host_has_reps = any(m.replicas_on[bb]
                                for bb in m.brokers_of_host[h])
            if (host_has_reps
                    and m.host_load[h, r] > m.host_cap[h, r] * thresh):
                return True
        if res.IS_BROKER_RESOURCE[r]:
            return (bool(m.replicas_on[b])
                    and m.broker_load[b, r] > m.cap[b, r] * thresh)
        return False

    def update_goal_state(self, m) -> None:
        for b in range(m.B):
            if self._over_limit(m, b):
                raise SeqOptimizationFailure(
                    f"{self.name}: broker {b} above capacity after balance")
        if m.has_offline():
            raise SeqOptimizationFailure(
                f"{self.name}: offline replicas remain")
        self.finished = True

    def rebalance_for_broker(self, m, b, optimized):
        r = self.r
        if not self._over_limit(m, b) and not m.current_offline_on(b):
            return
        # (1) leadership moves for NW_OUT / CPU (CapacityGoal.java:305-330)
        if r in (res.NW_OUT, res.CPU):
            leaders = sorted(
                (rr for rr in m.replicas_on[b]
                 if m.is_leader[rr] and self._movable(m, rr)),
                key=lambda rr: -m.eff_util(rr, r))
            for leader in leaders:
                p = int(m.part_of[leader])
                followers = [rr for rr in m.reps_of_p[p]
                             if rr >= 0 and rr != leader
                             and not m.offline[rr]]
                eligible = sorted(
                    (int(m.broker_of[rr]) for rr in followers),
                    key=lambda bb: m.util_pct(bb, r))
                self.maybe_apply(m, leader, eligible, LEAD, optimized)
                if not self._over_limit(m, b):
                    break
        # (2) replica moves (CapacityGoal.java:332-356)
        if self._over_limit(m, b) or m.current_offline_on(b):
            thresh = self.constraint.capacity_threshold[r]
            under = self._sorted_alive_under_threshold(m, thresh)
            reps = sorted(
                (rr for rr in m.replicas_on[b] if self._movable(m, rr)),
                key=lambda rr: (not m.offline[rr],
                                not m.is_immigrant(rr),
                                -m.eff_util(rr, r)))
            for rr in reps:
                self.maybe_apply(m, rr, under, MOVE, optimized)
                if (not self._over_limit(m, b)
                        and not m.current_offline_on(b)):
                    break
        if self._over_limit(m, b):
            raise SeqOptimizationFailure(
                f"{self.name}: violated capacity for broker {b}")
        if m.current_offline_on(b):
            raise SeqOptimizationFailure(
                f"{self.name}: offline replicas remain on broker {b}")

    def _sorted_alive_under_threshold(self, m: SeqModel,
                                      thresh: float) -> List[int]:
        """ClusterModel.sortedAliveBrokersUnderThreshold
        (ClusterModel.java:984-1031)."""
        r = self.r
        out = []
        for b in m.alive_brokers():
            if (res.IS_BROKER_RESOURCE[r]
                    and m.broker_load[b, r] >= m.cap[b, r] * thresh):
                continue
            if res.IS_HOST_RESOURCE[r]:
                h = m.host_of_b[b]
                if m.host_load[h, r] >= m.host_cap[h, r] * thresh:
                    continue
            out.append(b)
        if res.IS_HOST_RESOURCE[r]:
            out.sort(key=lambda b: (m.host_load[m.host_of_b[b], r],
                                    m.broker_load[b, r]))
        else:
            out.sort(key=lambda b: m.broker_load[b, r])
        return out


# ---------------------------------------------------------------------------
# ResourceDistributionGoal family (goals/ResourceDistributionGoal.java:50-999)
# ---------------------------------------------------------------------------


class SeqResourceDistributionGoal(SeqGoal):
    hard = False

    def __init__(self, name, constraint, options):
        super().__init__(constraint, options)
        self.name = name
        self.r = _DISTRIBUTION_RESOURCE[name]
        self.fix_offline_only = False
        self.upper = 0.0   # balance thresholds in utilization PERCENTAGE
        self.lower = 0.0

    # -- thresholds (ResourceDistributionGoal.java:926-957) ----------------
    def _balance_pct_with_margin(self) -> float:
        bal = self.constraint.resource_balance_percentage[self.r]
        if self.options.is_triggered_by_goal_violation:
            bal *= self.constraint.goal_violation_distribution_threshold_multiplier
        return (bal - 1.0) * BALANCE_MARGIN

    def init_goal_state(self, m) -> None:
        self.fix_offline_only = False
        r = self.r
        alive = np.flatnonzero(m.alive)
        avg_pct = (m.broker_load[alive, r].sum()
                   / max(m.cap[alive, r].sum(), 1e-30))
        margin = self._balance_pct_with_margin()
        self.upper = avg_pct * (1.0 + margin)
        self.lower = avg_pct * max(0.0, 1.0 - margin)

    # -- band checks (ResourceDistributionGoal.java:757-815) ---------------
    def _above_lower_after(self, m, b: int, delta: float, add: bool) -> bool:
        r = self.r
        d = delta if add else -delta
        broker_ok = (m.broker_load[b, r] + d
                     >= m.cap[b, r] * self.lower)
        if res.IS_HOST_RESOURCE[r]:
            h = m.host_of_b[b]
            host_ok = (m.host_load[h, r] + d
                       >= m.host_cap[h, r] * self.lower)
            return host_ok or broker_ok
        return broker_ok

    def _under_upper_after(self, m, b: int, delta: float, add: bool) -> bool:
        r = self.r
        d = delta if add else -delta
        broker_ok = (m.broker_load[b, r] + d
                     <= m.cap[b, r] * self.upper)
        if res.IS_HOST_RESOURCE[r]:
            h = m.host_of_b[b]
            host_ok = (m.host_load[h, r] + d
                       <= m.host_cap[h, r] * self.upper)
            return host_ok or broker_ok
        return broker_ok

    def _above_lower(self, m, b: int) -> bool:
        return self._above_lower_after(m, b, 0.0, True)

    def _under_upper(self, m, b: int) -> bool:
        return self._under_upper_after(m, b, 0.0, False)

    # -- swap limit checks (ResourceDistributionGoal.java:867-925) ---------
    def _swap_violates_limit(self, m, r_src: int, r_dst: int) -> bool:
        d = (m.eff_util(r_dst, self.r) - m.eff_util(r_src, self.r))
        b_src = int(m.broker_of[r_src])
        b_dst = int(m.broker_of[r_dst])

        def container_violates(load_src, cap_src, load_dst, cap_dst):
            if d > 0:
                if load_src + d > cap_src * self.upper:
                    return True
            else:
                if load_dst - d > cap_dst * self.upper:
                    return True
            if d < 0:
                return load_src + d < cap_src * self.lower
            return load_dst - d < cap_dst * self.lower

        r = self.r
        broker_bad = container_violates(
            m.broker_load[b_src, r], m.cap[b_src, r],
            m.broker_load[b_dst, r], m.cap[b_dst, r])
        if not broker_bad or not res.IS_HOST_RESOURCE[r]:
            return broker_bad
        h_src, h_dst = m.host_of_b[b_src], m.host_of_b[b_dst]
        return container_violates(
            m.host_load[h_src, r], m.host_cap[h_src, r],
            m.host_load[h_dst, r], m.host_cap[h_dst, r])

    def _more_balanced(self, m, b_src: int, b_dst: int, d: float) -> bool:
        """isGettingMoreBalanced (ResourceDistributionGoal.java:853-865):
        d is the utilization delta REMOVED from dst and ADDED to src."""
        r = self.r
        prev = m.broker_load[b_src, r] - m.broker_load[b_dst, r]
        nxt = prev + 2 * d
        return abs(nxt) < abs(prev)

    # -- acceptance / selfSatisfied (ResourceDistributionGoal.java:95-215) -
    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if kind == SWAP:
            r_dst = m.rep_at[(dst, p2)]
            d = m.eff_util(r_dst, self.r) - m.eff_util(r_src, self.r)
            if d == 0:
                return ACCEPT
            both_within = ((self._above_lower(m, dst)
                            and self._under_upper(m, src)) if d > 0
                           else (self._above_lower(m, src)
                                 and self._under_upper(m, dst)))
            if both_within:
                return (REPLICA_REJECT
                        if self._swap_violates_limit(m, r_src, r_dst)
                        else ACCEPT)
            return (ACCEPT if self._more_balanced(m, src, dst, d)
                    else REPLICA_REJECT)
        # MOVE / LEAD
        util = m.eff_util(r_src, self.r)
        if self._above_lower(m, src) and self._under_upper(m, dst):
            ok = (self._under_upper_after(m, dst, util, True)
                  and self._above_lower_after(m, src, util, False))
            return ACCEPT if ok else REPLICA_REJECT
        return (ACCEPT if self._more_balanced(m, src, dst, -util)
                else REPLICA_REJECT)

    def self_satisfied(self, m, action) -> bool:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if self.fix_offline_only and m.offline[r_src]:
            return kind == MOVE
        if kind == SWAP:
            r_dst = m.rep_at[(dst, p2)]
            d = m.eff_util(r_dst, self.r) - m.eff_util(r_src, self.r)
            return d != 0 and not self._swap_violates_limit(m, r_src, r_dst)
        util = m.eff_util(r_src, self.r)
        return (self._under_upper_after(m, dst, util, True)
                and self._above_lower_after(m, src, util, False))

    def brokers_to_balance(self, m) -> List[int]:
        if m.has_new:
            return [b for b in range(m.B) if m.new[b]]
        return list(range(m.B))

    def update_goal_state(self, m) -> None:
        for b in m.alive_brokers():
            if not self._under_upper(m, b) or not self._above_lower(m, b):
                self.succeeded = False
                break
        if m.has_offline():
            if self.fix_offline_only:
                raise SeqOptimizationFailure(
                    f"{self.name}: offline replicas remain")
            self.fix_offline_only = True
            return
        self.finished = True

    # -- the move/swap ladder (ResourceDistributionGoal.java:308-686) ------
    def rebalance_for_broker(self, m, b, optimized):
        n_offline = len(m.current_offline_on(b))
        require_less = n_offline > 0 or not self._under_upper(m, b)
        require_more = not self._above_lower(m, b)
        move_immigrants_only = False
        if n_offline == 0:
            if not require_less and not require_more:
                return
            move_immigrants_only = (m.has_offline()
                                    or self.options.only_move_immigrant_replicas)
            if (move_immigrants_only and require_less
                    and not any(m.is_immigrant(r) for r in m.replicas_on[b])):
                return

        if self.r in (res.NW_OUT, res.CPU) and not (
                self.fix_offline_only and n_offline):
            if require_less and not self._move_load_out(m, b, LEAD,
                                                        optimized):
                require_less = False
            if require_more and not self._move_load_in(m, b, LEAD, optimized,
                                                       False):
                require_more = False

        unbalanced = False
        if require_less:
            if self._move_load_out(m, b, MOVE, optimized):
                unbalanced = self._swap_load_out(m, b, optimized,
                                                 move_immigrants_only)
        if require_more:
            if self._move_load_in(m, b, MOVE, optimized,
                                  move_immigrants_only):
                unbalanced = unbalanced or self._swap_load_in(
                    m, b, optimized, move_immigrants_only)
        if unbalanced:
            self.succeeded = False

    def _sorted_replicas(self, m, b, leaders_only=False, followers_only=False,
                         immigrants_only=False, ascending=False,
                         load_limit=None):
        """sortedCandidateReplicas (ResourceDistributionGoal.java:449-472):
        offline first, then by resource load."""
        r = self.r
        out = []
        for rr in m.replicas_on[b]:
            if not self._movable(m, rr):
                continue
            if leaders_only and not m.is_leader[rr]:
                continue
            if followers_only and m.is_leader[rr]:
                continue
            if immigrants_only and not m.is_immigrant(rr):
                continue
            u = m.eff_util(rr, r)
            if load_limit is not None:
                if ascending and u >= load_limit:
                    continue
                if not ascending and u <= load_limit:
                    continue
            out.append(rr)
        out.sort(key=(lambda rr: (not m.offline[rr], m.eff_util(rr, r)))
                 if ascending else
                 (lambda rr: (not m.offline[rr], -m.eff_util(rr, r))))
        return out

    def _move_load_out(self, m, b, action, optimized) -> bool:
        """rebalanceByMovingLoadOut (ResourceDistributionGoal.java:686-756).
        Returns True when still over the upper limit."""
        r = self.r
        if self.fix_offline_only:
            candidates = sorted(m.alive_brokers(),
                                key=lambda bb: (m.util_pct(bb, r), bb))
        else:
            candidates = sorted(
                (bb for bb in m.alive_brokers()
                 if m.util_pct(bb, r) < self.upper),
                key=lambda bb: (m.util_pct(bb, r), bb))
        healing = m.has_offline()
        reps = []
        for rr in m.replicas_on[b]:
            if not self._movable(m, rr):
                continue
            if action == LEAD and not m.is_leader[rr]:
                continue
            if (healing and m.alive[b] and not m.is_immigrant(rr)
                    and not m.offline[rr]):
                continue
            reps.append(rr)
        reps.sort(key=lambda rr: (not m.offline[rr],
                                  not m.is_immigrant(rr),
                                  -m.eff_util(rr, r)))
        for rr in reps:
            if m.eff_util(rr, r) == 0.0 and not m.offline[rr]:
                break
            if action == LEAD:
                p = int(m.part_of[rr])
                cand_set = set(candidates)
                eligible = sorted(
                    (int(m.broker_of[f]) for f in m.reps_of_p[p]
                     if f >= 0 and f != rr and not m.offline[f]
                     and int(m.broker_of[f]) in cand_set),
                    key=lambda bb: (m.util_pct(bb, r), bb))
            else:
                eligible = candidates
            dst = self.maybe_apply(m, rr, eligible, action, optimized)
            if dst is not None:
                if self._under_upper(m, b) and not (
                        self.fix_offline_only and m.current_offline_on(b)):
                    return False
                if action == MOVE:
                    candidates = [c for c in candidates if c != dst]
                    if m.util_pct(dst, r) < self.upper:
                        candidates.append(dst)
                        candidates.sort(
                            key=lambda bb: (m.util_pct(bb, r), bb))
        return bool(m.replicas_on[b])

    def _move_load_in(self, m, b, action, optimized,
                      move_immigrants_only) -> bool:
        """rebalanceByMovingLoadIn (ResourceDistributionGoal.java:364-432).
        Returns True when still under the lower limit."""
        r = self.r
        if m.has_new and not m.new[b]:
            return True
        follower_only = (b in self.options.excluded_brokers_for_leadership)
        alive = np.flatnonzero(m.alive)
        cluster_pct = (m.broker_load[alive, r].sum()
                       / max(m.cap[alive, r].sum(), 1e-30))
        pq = sorted((bb for bb in m.alive_brokers()
                     if m.util_pct(bb, r) > cluster_pct),
                    key=lambda bb: (-m.util_pct(bb, r), bb))
        srcs = {bb: self._sorted_replicas(
                    m, bb, leaders_only=(r == res.NW_OUT),
                    followers_only=follower_only,
                    immigrants_only=move_immigrants_only)
                for bb in pq}
        while pq and (action == MOVE
                      or m.leader_count[b] != m.replica_count[b]):
            cb = pq.pop(0)
            for rr in list(srcs[cb]):
                dst = self.maybe_apply(m, rr, [b], action, optimized)
                if dst is not None:
                    if self._above_lower(m, b):
                        return False
                    if action == MOVE:
                        srcs[cb].remove(rr)
                    if pq and m.util_pct(cb, r) < m.util_pct(pq[0], r):
                        pq.append(cb)
                        pq.sort(key=lambda bb: (-m.util_pct(bb, r), bb))
                        break
        return True

    def _swap_load_out(self, m, b, optimized, move_immigrants_only) -> bool:
        """rebalanceBySwappingLoadOut (ResourceDistributionGoal.java:502-590).
        Returns True when still over the limit after swaps."""
        t0 = time.time()
        r = self.r
        if (not m.alive[b]
                or b in self.options.excluded_brokers_for_replica_move):
            return True
        src_reps = self._sorted_replicas(
            m, b, leaders_only=(r == res.NW_OUT),
            immigrants_only=move_immigrants_only, ascending=False,
            load_limit=0.0)
        if not src_reps:
            return True
        max_src_load = max((m.eff_util(rr, r) for rr in src_reps
                            if not m.offline[rr]),
                           default=m.eff_util(src_reps[0], r))
        follower_only = (b in self.options.excluded_brokers_for_leadership)
        pq = sorted((bb for bb in m.alive_brokers()
                     if bb != b and m.replicas_on[bb]
                     and m.util_pct(bb, r) < self.upper),
                    key=lambda bb: (m.util_pct(bb, r), bb))
        while pq:
            if time.time() - t0 > PER_BROKER_SWAP_TIMEOUT_S:
                break
            cb = pq.pop(0)
            cand = self._sorted_replicas(
                m, cb, followers_only=follower_only,
                immigrants_only=move_immigrants_only, ascending=True,
                load_limit=max_src_load)
            swapped = None
            for r_src in list(src_reps):
                swapped = self.maybe_apply_swap(m, r_src, cand, optimized)
                if swapped is not None:
                    if self._under_upper(m, b):
                        return False
                    break
                if time.time() - t0 > PER_BROKER_SWAP_TIMEOUT_S:
                    return True
            if swapped is not None:
                src_reps = self._sorted_replicas(
                    m, b, leaders_only=(r == res.NW_OUT),
                    immigrants_only=move_immigrants_only, ascending=False,
                    load_limit=0.0)
                pq.append(cb)
                pq.sort(key=lambda bb: (m.util_pct(bb, r), bb))
        return True

    def _swap_load_in(self, m, b, optimized, move_immigrants_only) -> bool:
        """rebalanceBySwappingLoadIn (ResourceDistributionGoal.java:599-686)."""
        t0 = time.time()
        r = self.r
        if (not m.alive[b]
                or b in self.options.excluded_brokers_for_replica_move):
            return True
        src_reps = self._sorted_replicas(
            m, b, immigrants_only=move_immigrants_only, ascending=True)
        if not src_reps:
            return True
        min_src_load = min((m.eff_util(rr, r) for rr in src_reps
                            if not m.offline[rr]),
                           default=m.eff_util(src_reps[0], r))
        follower_only = (b in self.options.excluded_brokers_for_leadership)
        pq = sorted((bb for bb in m.alive_brokers()
                     if bb != b and m.util_pct(bb, r) > self.lower),
                    key=lambda bb: (-m.util_pct(bb, r), bb))
        while pq:
            if time.time() - t0 > PER_BROKER_SWAP_TIMEOUT_S:
                break
            cb = pq.pop(0)
            cand = self._sorted_replicas(
                m, cb, leaders_only=(r == res.NW_OUT),
                followers_only=follower_only,
                immigrants_only=move_immigrants_only, ascending=False,
                load_limit=min_src_load)
            swapped = None
            for r_src in list(src_reps):
                swapped = self.maybe_apply_swap(m, r_src, cand, optimized)
                if swapped is not None:
                    if self._above_lower(m, b):
                        return False
                    break
                if time.time() - t0 > PER_BROKER_SWAP_TIMEOUT_S:
                    return True
            if swapped is not None:
                src_reps = self._sorted_replicas(
                    m, b, immigrants_only=move_immigrants_only,
                    ascending=True)
                pq.append(cb)
                pq.sort(key=lambda bb: (-m.util_pct(bb, r), bb))
        return True


# ---------------------------------------------------------------------------
# Replica / LeaderReplica count distribution
# (goals/ReplicaDistributionAbstractGoal.java:23-240,
#  ReplicaDistributionGoal.java:39-290, LeaderReplicaDistributionGoal.java:38-340)
# ---------------------------------------------------------------------------


class _SeqCountDistributionBase(SeqGoal):
    hard = False

    def __init__(self, constraint, options):
        super().__init__(constraint, options)
        self.fix_offline_only = False
        self.upper = 0
        self.lower = 0
        self._failed_above: Set[int] = set()
        self._failed_below: Set[int] = set()

    def _balance_percentage(self) -> float:
        raise NotImplementedError

    def _num_interested(self, m) -> int:
        raise NotImplementedError

    def init_goal_state(self, m) -> None:
        self.fix_offline_only = False
        avg = self._num_interested(m) / max(len(m.alive_brokers()), 1)
        bal = self._balance_percentage()
        if self.options.is_triggered_by_goal_violation:
            bal *= self.constraint.goal_violation_distribution_threshold_multiplier
        margin = (bal - 1.0) * BALANCE_MARGIN
        self.upper = int(np.ceil(avg * (1.0 + margin)))
        self.lower = int(np.floor(avg * max(0.0, 1.0 - margin)))

    def _count_ok_after(self, m, b: int, count: int, add: bool,
                        check_upper: bool) -> bool:
        limit_u = self.upper if m.alive[b] else 0
        limit_l = self.lower if m.alive[b] else 0
        c = count + (1 if add else -1)
        return c <= limit_u if check_upper else c >= limit_l

    def update_goal_state(self, m) -> None:
        if self._failed_above or self._failed_below:
            self.succeeded = False
            self._failed_above.clear()
            self._failed_below.clear()
        if m.has_offline():
            if self.fix_offline_only:
                raise SeqOptimizationFailure(
                    f"{self.name}: offline replicas remain")
            self.fix_offline_only = True
            return
        self.finished = True


class SeqReplicaDistributionGoal(_SeqCountDistributionBase):
    name = "ReplicaDistributionGoal"

    def _balance_percentage(self) -> float:
        return self.constraint.replica_balance_percentage

    def _num_interested(self, m) -> int:
        return m.R

    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        if kind != MOVE:
            return ACCEPT
        ok = (self._count_ok_after(m, dst, int(m.replica_count[dst]),
                                   True, True)
              and self._count_ok_after(m, src, int(m.replica_count[src]),
                                       False, False))
        return ACCEPT if ok else REPLICA_REJECT

    def self_satisfied(self, m, action) -> bool:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if self.fix_offline_only and m.offline[r_src]:
            return True
        return self.action_acceptance(m, action) == ACCEPT

    def _sorted_reps(self, m, b) -> List[int]:
        healing = m.has_offline()
        out = []
        for rr in m.replicas_on[b]:
            if not self._movable(m, rr):
                continue
            if (healing and m.alive[b] and not m.is_immigrant(rr)
                    and not m.offline[rr]):
                continue
            out.append(rr)
        # offline first, then immigrants, then ascending disk load
        out.sort(key=lambda rr: (not m.offline[rr], not m.is_immigrant(rr),
                                 m.eff_util(rr, res.DISK)))
        return out

    def rebalance_for_broker(self, m, b, optimized):
        n = int(m.replica_count[b])
        n_off = len(m.current_offline_on(b))
        require_less = n_off > 0 or n > self.upper
        require_more = m.alive[b] and n - n_off < self.lower
        if m.alive[b] and not require_more and not require_less:
            return
        if m.has_new and not m.new[b] and not require_less:
            return
        if (((m.has_offline() and not n_off)
             or self.options.only_move_immigrant_replicas)
                and require_less
                and not any(m.is_immigrant(r) for r in m.replicas_on[b])):
            return
        if require_less and self._move_out(m, b, optimized):
            self._failed_above.add(b)
        if require_more and self._move_in(m, b, optimized):
            self._failed_below.add(b)

    def _move_out(self, m, b, optimized) -> bool:
        if self.fix_offline_only:
            candidates = sorted(m.alive_brokers(),
                                key=lambda bb: (m.replica_count[bb], bb))
        else:
            candidates = sorted(
                (bb for bb in m.alive_brokers()
                 if m.replica_count[bb] < self.upper),
                key=lambda bb: (m.replica_count[bb], bb))
        stuck_offline = False
        for rr in self._sorted_reps(m, b):
            if (stuck_offline and not m.offline[rr]
                    and m.replica_count[b] <= self.upper):
                return False
            dst = self.maybe_apply(m, rr, candidates, MOVE, optimized)
            if dst is not None:
                limit = self.upper if not m.current_offline_on(b) else 0
                if m.replica_count[b] <= limit:
                    return False
                candidates = [c for c in candidates if c != dst]
                if (m.replica_count[dst] < self.upper
                        or self.fix_offline_only):
                    candidates.append(dst)
                    candidates.sort(key=lambda bb: (m.replica_count[bb], bb))
            elif m.offline[rr]:
                stuck_offline = True
        return bool(m.replicas_on[b])

    def _move_in(self, m, b, optimized) -> bool:
        if self.fix_offline_only:
            pq = [bb for bb in range(m.B) if bb != b]
        else:
            pq = [bb for bb in range(m.B)
                  if m.replica_count[bb] > self.lower
                  or m.current_offline_on(bb)]
        pq.sort(key=lambda bb: (-len(m.current_offline_on(bb)),
                                -m.replica_count[bb], bb))
        while pq:
            src = pq.pop(0)
            for rr in self._sorted_reps(m, src):
                dst = self.maybe_apply(m, rr, [b], MOVE, optimized)
                if dst is not None:
                    if m.replica_count[b] >= self.lower:
                        return False
                    if pq:
                        s_off = len(m.current_offline_on(src))
                        n_off = len(m.current_offline_on(pq[0]))
                        if (s_off < n_off
                                or (s_off == n_off
                                    and m.replica_count[src]
                                    < m.replica_count[pq[0]])):
                            pq.append(src)
                            pq.sort(key=lambda bb: (
                                -len(m.current_offline_on(bb)),
                                -m.replica_count[bb], bb))
                            break
        return True


class SeqLeaderReplicaDistributionGoal(_SeqCountDistributionBase):
    name = "LeaderReplicaDistributionGoal"

    def _balance_percentage(self) -> float:
        return self.constraint.leader_replica_balance_percentage

    def _num_interested(self, m) -> int:
        return m.P

    def _lead_move_ok(self, m, src: int, dst: int) -> int:
        ok = (self._count_ok_after(m, dst, int(m.leader_count[dst]),
                                   True, True)
              and self._count_ok_after(m, src, int(m.leader_count[src]),
                                       False, False))
        return ACCEPT if ok else REPLICA_REJECT

    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if kind == SWAP:
            r_dst = m.rep_at[(dst, p2)]
            if m.is_leader[r_src] and not m.is_leader[r_dst]:
                return self._lead_move_ok(m, src, dst)
            if not m.is_leader[r_src] and m.is_leader[r_dst]:
                return self._lead_move_ok(m, dst, src)
            return ACCEPT
        if kind == MOVE:
            if m.is_leader[r_src]:
                return self._lead_move_ok(m, src, dst)
            return ACCEPT
        return self._lead_move_ok(m, src, dst)

    def self_satisfied(self, m, action) -> bool:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if self.fix_offline_only and m.offline[r_src]:
            return True
        return self.action_acceptance(m, action) == ACCEPT

    def rebalance_for_broker(self, m, b, optimized):
        n_lead = int(m.leader_count[b])
        require_less_lead = m.alive[b] and n_lead > self.upper
        require_more_lead = m.alive[b] and n_lead < self.lower
        require_less_reps = (self.fix_offline_only
                             and len(m.current_offline_on(b)) > 0)
        if ((require_less_lead
             and self._move_leadership_out(m, b, optimized))
                or require_less_reps):
            if self._move_replicas_out(m, b, optimized):
                if not require_less_reps:
                    self._failed_above.add(b)
        elif (require_more_lead
              and self._move_leadership_in(m, b, optimized)
              and self._move_leader_replicas_in(m, b, optimized)):
            self._failed_below.add(b)

    def _move_leadership_out(self, m, b, optimized) -> bool:
        if (~m.alive).any():
            return True
        n = int(m.leader_count[b])
        for rr in sorted(r for r in m.replicas_on[b] if m.is_leader[r]):
            p = int(m.part_of[rr])
            candidates = [int(m.broker_of[f]) for f in m.reps_of_p[p]
                          if f >= 0 and f != rr and not m.offline[f]]
            if self.maybe_apply(m, rr, candidates, LEAD,
                                optimized) is not None:
                n -= 1
                if n <= self.upper:
                    return False
        return True

    def _move_leadership_in(self, m, b, optimized) -> bool:
        if ((~m.alive).any()
                or b in self.options.excluded_brokers_for_leadership):
            return True
        n = int(m.leader_count[b])
        for rr in sorted(m.replicas_on[b]):
            if m.is_leader[rr] or m.offline[rr]:
                continue
            leader = int(m.leader_of[m.part_of[rr]])
            if self.maybe_apply(m, leader, [b], LEAD,
                                optimized) is not None:
                n += 1
                if n >= self.lower:
                    return False
        return True

    def _move_replicas_out(self, m, b, optimized) -> bool:
        if self.fix_offline_only:
            candidates = sorted(m.alive_brokers(),
                                key=lambda bb: (m.replica_count[bb], bb))
        else:
            candidates = sorted(
                (bb for bb in m.alive_brokers()
                 if m.leader_count[bb] < self.upper),
                key=lambda bb: (m.leader_count[bb], bb))
        limit = 0 if self.fix_offline_only else self.upper
        healing = m.has_offline()
        reps = []
        for rr in m.replicas_on[b]:
            if not self._movable(m, rr):
                continue
            if self.fix_offline_only:
                if not m.offline[rr]:
                    continue
            else:
                if not m.is_leader[rr]:
                    continue
                if (healing and not m.is_immigrant(rr)
                        and not m.offline[rr]):
                    continue
            reps.append(rr)
        n = len(reps)
        for rr in sorted(reps):
            dst = self.maybe_apply(m, rr, candidates, MOVE, optimized)
            if dst is not None:
                n -= 1
                if n <= limit:
                    return False
                candidates = [c for c in candidates if c != dst]
                if (m.leader_count[dst] < self.upper
                        or self.fix_offline_only):
                    candidates.append(dst)
                    candidates.sort(key=lambda bb: (m.leader_count[bb], bb))
        return True

    def _move_leader_replicas_in(self, m, b, optimized) -> bool:
        if b in self.options.excluded_brokers_for_leadership:
            return True
        pq = sorted((bb for bb in m.alive_brokers()
                     if m.leader_count[bb] > self.lower),
                    key=lambda bb: (-m.leader_count[bb], bb))
        n = int(m.leader_count[b])
        broken = bool((~m.alive).any()) or m.has_offline()
        while pq:
            src = pq.pop(0)
            reps = sorted(rr for rr in m.replicas_on[src]
                          if m.is_leader[rr] and self._movable(m, rr)
                          and (not broken or m.is_immigrant(rr)))
            for rr in reps:
                dst = self.maybe_apply(m, rr, [b], MOVE, optimized)
                if dst is not None:
                    n += 1
                    if n >= self.lower:
                        return False
                    if pq and m.leader_count[src] < m.leader_count[pq[0]]:
                        pq.append(src)
                        pq.sort(key=lambda bb: (-m.leader_count[bb], bb))
                        break
        return True


# ---------------------------------------------------------------------------
# TopicReplicaDistributionGoal (goals/TopicReplicaDistributionGoal.java:45-590)
# ---------------------------------------------------------------------------


class SeqTopicReplicaDistributionGoal(SeqGoal):
    name = "TopicReplicaDistributionGoal"
    hard = False

    def __init__(self, constraint, options):
        super().__init__(constraint, options)
        self.fix_offline_only = False
        self.upper_by_topic: Dict[int, int] = {}
        self.lower_by_topic: Dict[int, int] = {}
        self.rebalance_topics: Set[int] = set()
        self._failed = False

    def _margin(self) -> float:
        bal = self.constraint.topic_replica_balance_percentage
        if self.options.is_triggered_by_goal_violation:
            bal *= self.constraint.goal_violation_distribution_threshold_multiplier
        return (bal - 1.0) * BALANCE_MARGIN

    def init_goal_state(self, m) -> None:
        self.fix_offline_only = False
        n_alive = max(len(m.alive_brokers()), 1)
        margin = self._margin()
        for t in range(m.T):
            avg = m.topic_total[t] / n_alive
            self.upper_by_topic[t] = int(np.ceil(avg * (1.0 + margin)))
            self.lower_by_topic[t] = int(np.floor(avg * max(0.0, 1.0 - margin)))
        if m.has_offline():
            self.rebalance_topics = {
                int(m.topic_of_p[m.part_of[r]])
                for r in np.flatnonzero(m.offline)}
        else:
            self.rebalance_topics = (set(range(m.T))
                                     - set(self.options.excluded_topics))

    def _count_ok_after(self, m, t: int, b: int, add: bool,
                        check_upper: bool) -> bool:
        n = m.topic_count[b].get(t, 0) + (1 if add else -1)
        limit_u = self.upper_by_topic[t] if m.alive[b] else 0
        limit_l = self.lower_by_topic[t] if m.alive[b] else 0
        return n <= limit_u if check_upper else n >= limit_l

    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        t = int(m.topic_of_p[p])
        if kind == LEAD:
            return ACCEPT
        if kind == SWAP:
            t2 = int(m.topic_of_p[p2])
            if t == t2:
                return ACCEPT
            ok = (self._count_ok_after(m, t, dst, True, True)
                  and self._count_ok_after(m, t, src, False, False)
                  and self._count_ok_after(m, t2, src, True, True)
                  and self._count_ok_after(m, t2, dst, False, False))
            return ACCEPT if ok else REPLICA_REJECT
        ok = (self._count_ok_after(m, t, dst, True, True)
              and self._count_ok_after(m, t, src, False, False))
        return ACCEPT if ok else REPLICA_REJECT

    def self_satisfied(self, m, action) -> bool:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if self.fix_offline_only and m.offline[r_src]:
            return kind == MOVE
        t = int(m.topic_of_p[p])
        return (self._count_ok_after(m, t, dst, True, True)
                and self._count_ok_after(m, t, src, False, False))

    def update_goal_state(self, m) -> None:
        if self._failed:
            self.succeeded = False
            self._failed = False
        if m.has_offline():
            if self.fix_offline_only:
                raise SeqOptimizationFailure(
                    f"{self.name}: offline replicas remain")
            self.fix_offline_only = True
            return
        self.finished = True

    def rebalance_for_broker(self, m, b, optimized):
        for t in sorted(m.topic_count[b].keys()):
            if t not in self.rebalance_topics:
                continue
            reps = [r for r in m.replicas_on[b]
                    if int(m.topic_of_p[m.part_of[r]]) == t]
            n = len(reps)
            n_off = sum(1 for r in reps if m.offline[r])
            require_less = n_off > 0 or n > self.upper_by_topic[t]
            require_more = (m.alive[b]
                            and n - n_off < self.lower_by_topic[t])
            has_imm = any(m.is_immigrant(r) for r in reps)
            # skipBrokerRebalance (TopicReplicaDistributionGoal.java:341-368)
            if m.alive[b] and not require_more and not require_less:
                continue
            if m.has_new and not m.new[b] and not require_less:
                continue
            if (m.has_offline() and require_less and n_off == 0
                    and not has_imm):
                continue
            if (self.options.only_move_immigrant_replicas and require_less
                    and not has_imm):
                continue
            if require_less and self._move_out(m, b, t, optimized):
                self._failed = True
            if require_more and self._move_in(m, b, t, optimized):
                self._failed = True

    def _topic_reps(self, m, b, t) -> List[int]:
        healing = m.has_offline()
        out = []
        for rr in m.replicas_on[b]:
            if int(m.topic_of_p[m.part_of[rr]]) != t:
                continue
            if not self._movable(m, rr):
                continue
            if (healing and m.alive[b] and not m.is_immigrant(rr)
                    and not m.offline[rr]):
                continue
            out.append(rr)
        out.sort(key=lambda rr: (not m.offline[rr], rr))
        return out

    def _move_out(self, m, b, t, optimized) -> bool:
        if self.fix_offline_only:
            candidates = sorted(
                m.alive_brokers(),
                key=lambda bb: (m.topic_count[bb].get(t, 0), bb))
        else:
            candidates = sorted(
                (bb for bb in m.alive_brokers()
                 if m.topic_count[bb].get(t, 0) < self.upper_by_topic[t]),
                key=lambda bb: (m.topic_count[bb].get(t, 0), bb))
        n = m.topic_count[b].get(t, 0)
        n_off = sum(1 for r in m.replicas_on[b]
                    if m.offline[r] and int(m.topic_of_p[m.part_of[r]]) == t)
        stuck_offline = False
        for rr in self._topic_reps(m, b, t):
            if (stuck_offline and not m.offline[rr]
                    and n <= self.upper_by_topic[t]):
                return False
            was_off = bool(m.offline[rr])
            dst = self.maybe_apply(m, rr, candidates, MOVE, optimized)
            if dst is not None:
                if was_off:
                    n_off -= 1
                n -= 1
                if n <= (self.upper_by_topic[t] if n_off == 0 else 0):
                    return False
                candidates = [c for c in candidates if c != dst]
                if (m.topic_count[dst].get(t, 0) < self.upper_by_topic[t]
                        or self.fix_offline_only):
                    candidates.append(dst)
                    candidates.sort(
                        key=lambda bb: (m.topic_count[bb].get(t, 0), bb))
            elif m.offline[rr]:
                stuck_offline = True
        return n > 0

    def _move_in(self, m, b, t, optimized) -> bool:
        pq = sorted((bb for bb in range(m.B)
                     if m.topic_count[bb].get(t, 0) > self.lower_by_topic[t]
                     or any(m.offline[r] for r in m.replicas_on[bb]
                            if int(m.topic_of_p[m.part_of[r]]) == t)),
                    key=lambda bb: (-m.topic_count[bb].get(t, 0), bb))
        n = m.topic_count[b].get(t, 0)
        while pq:
            src = pq.pop(0)
            for rr in self._topic_reps(m, src, t):
                dst = self.maybe_apply(m, rr, [b], MOVE, optimized)
                if dst is not None:
                    n += 1
                    if n >= self.lower_by_topic[t]:
                        return False
                    if (pq and m.topic_count[src].get(t, 0)
                            < m.topic_count[pq[0]].get(t, 0)):
                        pq.append(src)
                        pq.sort(key=lambda bb: (
                            -m.topic_count[bb].get(t, 0), bb))
                        break
        return True


# ---------------------------------------------------------------------------
# PotentialNwOutGoal (goals/PotentialNwOutGoal.java:37-400)
# ---------------------------------------------------------------------------


class SeqPotentialNwOutGoal(SeqGoal):
    name = "PotentialNwOutGoal"
    hard = False

    def __init__(self, constraint, options):
        super().__init__(constraint, options)
        self.fix_offline_only = False

    def _limit(self, m, b: int) -> float:
        return (m.cap[b, res.NW_OUT]
                * self.constraint.capacity_threshold[res.NW_OUT])

    def self_satisfied(self, m, action) -> bool:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if self.fix_offline_only and m.offline[r_src]:
            return kind == MOVE
        dst_util = m.pot_nw_out[dst]
        dst_cap = self._limit(m, dst)
        src_rep_util = m.leader_nw_out[p]
        if kind != SWAP:
            return dst_cap >= dst_util + src_rep_util
        dst_rep_util = m.leader_nw_out[p2]
        if dst_cap < dst_util + src_rep_util - dst_rep_util:
            return False
        src_util = m.pot_nw_out[src]
        src_cap = self._limit(m, src)
        return src_cap >= src_util + dst_rep_util - src_rep_util

    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        if kind == LEAD:
            return ACCEPT
        if self.self_satisfied(m, action):
            return ACCEPT
        dst_util = m.pot_nw_out[dst]
        src_util = m.pot_nw_out[src]
        src_rep_util = m.leader_nw_out[p]
        max_util = max(dst_util, src_util)
        if kind == SWAP:
            dst_rep_util = m.leader_nw_out[p2]
            if src_util + dst_rep_util - src_rep_util > max_util:
                return REPLICA_REJECT
            return (ACCEPT if dst_util + src_rep_util - dst_rep_util
                    <= max_util else REPLICA_REJECT)
        return (ACCEPT if dst_util + src_rep_util <= max_util
                else REPLICA_REJECT)

    def brokers_to_balance(self, m) -> List[int]:
        broken = [b for b in range(m.B)
                  if not m.alive[b] or m.current_offline_on(b)]
        return broken if broken else list(range(m.B))

    def update_goal_state(self, m) -> None:
        if m.has_offline():
            if self.fix_offline_only:
                raise SeqOptimizationFailure(
                    f"{self.name}: offline replicas remain")
            self.fix_offline_only = True
            return
        self.finished = True

    def rebalance_for_broker(self, m, b, optimized):
        limit = self._limit(m, b)
        over = bool(m.replicas_on[b]) and m.pot_nw_out[b] > limit
        if not over and not (self.fix_offline_only
                             and m.current_offline_on(b)):
            return
        if self.fix_offline_only:
            candidates = set(m.alive_brokers())
        else:
            candidates = {bb for bb in m.alive_brokers()
                          if m.pot_nw_out[bb] < self._limit(m, bb)}
        reps = sorted(rr for rr in m.replicas_on[b] if self._movable(m, rr))
        for rr in reps:
            p = int(m.part_of[rr])
            part_brokers = set(m.partition_brokers(p))
            eligible = sorted(
                (bb for bb in candidates if bb not in part_brokers),
                key=lambda bb: (-m.lead_load[bb, res.NW_OUT], bb))
            dst = self.maybe_apply(m, rr, eligible, MOVE, optimized)
            if dst is not None:
                over = (bool(m.replicas_on[b])
                        and m.pot_nw_out[b] > limit)
                if not over and not (self.fix_offline_only
                                     and m.current_offline_on(b)):
                    break
                if (not self.fix_offline_only
                        and m.pot_nw_out[dst] > self._limit(m, dst)):
                    candidates.discard(dst)
        if over:
            self.succeeded = False


# ---------------------------------------------------------------------------
# LeaderBytesInDistributionGoal
# (goals/LeaderBytesInDistributionGoal.java:39-290)
# ---------------------------------------------------------------------------


class SeqLeaderBytesInDistributionGoal(SeqGoal):
    name = "LeaderBytesInDistributionGoal"
    hard = False

    def __init__(self, constraint, options):
        super().__init__(constraint, options)
        self.mean_lbi = 0.0
        self.over_limit: Set[int] = set()

    def _mean(self, m) -> float:
        if self.mean_lbi == 0.0:
            alive = m.alive_brokers()
            self.mean_lbi = (sum(m.lead_load[b, res.NW_IN] for b in alive)
                             / max(len(alive), 1))
        return self.mean_lbi

    def _threshold(self, m, b: int) -> float:
        low = (self.constraint.low_utilization_threshold[res.NW_IN]
               * m.cap[b, res.NW_IN])
        return max(self._mean(m)
                   * self.constraint.resource_balance_percentage[res.NW_IN],
                   low)

    def action_acceptance(self, m, action) -> int:
        p, src, dst, kind, p2 = action
        r_src = m.rep_at[(src, p)]
        if not m.is_leader[r_src]:
            if kind == SWAP:
                r_dst = m.rep_at[(dst, p2)]
                if not m.is_leader[r_dst]:
                    return ACCEPT
            elif kind == MOVE:
                return ACCEPT
        src_util = m.eff_util(r_src, res.NW_IN)
        if kind == SWAP:
            r_dst = m.rep_at[(dst, p2)]
            dst_util = m.eff_util(r_dst, res.NW_IN)
            new_dst = (m.lead_load[dst, res.NW_IN] + src_util - dst_util)
            new_src = (m.lead_load[src, res.NW_IN] + dst_util - src_util)
            if new_src > self._threshold(m, src):
                return REPLICA_REJECT
        else:
            new_dst = m.lead_load[dst, res.NW_IN] + src_util
        return (ACCEPT if new_dst <= self._threshold(m, dst)
                else REPLICA_REJECT)

    def self_satisfied(self, m, action) -> bool:
        return self.action_acceptance(m, action) == ACCEPT

    def brokers_to_balance(self, m) -> List[int]:
        return [b for b in range(m.B)
                if m.lead_load[b, res.NW_IN] > self._threshold(m, b)]

    def init_goal_state(self, m) -> None:
        self.mean_lbi = 0.0
        self.over_limit = set()

    def update_goal_state(self, m) -> None:
        if self.over_limit:
            self.succeeded = False
        self.finished = True

    def rebalance_for_broker(self, m, b, optimized):
        threshold = self._threshold(m, b)
        if m.lead_load[b, res.NW_IN] < threshold:
            return
        leaders = sorted(
            (rr for rr in m.replicas_on[b]
             if m.is_leader[rr] and self._movable(m, rr)),
            key=lambda rr: -m.eff_util(rr, res.NW_IN))
        over = True
        for rr in leaders:
            if not over:
                break
            p = int(m.part_of[rr])
            followers = [f for f in m.reps_of_p[p]
                         if f >= 0 and f != rr and not m.offline[f]]
            eligible = sorted(
                (int(m.broker_of[f]) for f in followers),
                key=lambda bb: m.lead_load[bb, res.NW_IN])
            self.maybe_apply(m, rr, eligible, LEAD, optimized)
            over = m.lead_load[b, res.NW_IN] > threshold
        if over:
            self.over_limit.add(b)


# ---------------------------------------------------------------------------
# Driver: the GoalOptimizer sequential loop (GoalOptimizer.java:429-453)
# ---------------------------------------------------------------------------


def _make_goal(name: str, constraint, options: SeqOptions) -> SeqGoal:
    if name == "RackAwareGoal":
        return SeqRackAwareGoal(constraint, options)
    if name == "ReplicaCapacityGoal":
        return SeqReplicaCapacityGoal(constraint, options)
    if name in _CAPACITY_RESOURCE:
        return SeqCapacityGoal(name, constraint, options)
    if name in _DISTRIBUTION_RESOURCE:
        return SeqResourceDistributionGoal(name, constraint, options)
    if name == "ReplicaDistributionGoal":
        return SeqReplicaDistributionGoal(constraint, options)
    if name == "LeaderReplicaDistributionGoal":
        return SeqLeaderReplicaDistributionGoal(constraint, options)
    if name == "TopicReplicaDistributionGoal":
        return SeqTopicReplicaDistributionGoal(constraint, options)
    if name == "PotentialNwOutGoal":
        return SeqPotentialNwOutGoal(constraint, options)
    if name == "LeaderBytesInDistributionGoal":
        return SeqLeaderBytesInDistributionGoal(constraint, options)
    raise ValueError(f"sequential engine does not implement {name!r}")


@dataclasses.dataclass
class SeqGoalReport:
    name: str
    succeeded: bool
    comparator_vs_before: int
    wall_s: float


@dataclasses.dataclass
class SeqResult:
    """Outcome of the single-threaded sequential optimization."""

    broker_of: np.ndarray            # i64[R] final placement
    leader_of: np.ndarray            # i64[P] final leader replica index
    goal_reports: List[SeqGoalReport]
    num_replica_movements: int
    num_leadership_movements: int
    wall_time_s: float
    stats_before: SeqStats
    stats_after: SeqStats

    @property
    def violated_goals_after(self) -> List[str]:
        return [g.name for g in self.goal_reports if not g.succeeded]


def optimize_sequential(topo, broker_of: np.ndarray, leader_of: np.ndarray,
                        goal_names: Optional[Sequence[str]] = None,
                        constraint=None,
                        options: Optional[SeqOptions] = None) -> SeqResult:
    """Run the reference's sequential per-goal walk end to end.

    Mirrors ``GoalOptimizer.optimizations`` (``GoalOptimizer.java:408-467``):
    instantiate goals by priority, run each ``goal.optimize(model,
    optimizedGoals, options)`` over the SHARED mutable model, collecting the
    optimized set so later goals' candidate actions are vetoed by earlier
    goals' ``actionAcceptance`` (``AbstractGoal.java:211``).

    ``leader_of`` is the per-partition GLOBAL replica index of the leader
    (the repo's ``Assignment.leader_of`` convention).
    """
    from cruise_control_tpu.common.resources import (
        DEFAULT_BALANCING_CONSTRAINT)
    from cruise_control_tpu.analyzer import goals as G
    constraint = constraint or DEFAULT_BALANCING_CONSTRAINT
    options = options or SeqOptions()
    goal_names = tuple(goal_names or G.DEFAULT_GOALS)

    t0 = time.time()
    m = SeqModel(topo, np.asarray(broker_of), np.asarray(leader_of))
    stats_before = compute_seq_stats(m, constraint)
    optimized: List[SeqGoal] = []
    reports: List[SeqGoalReport] = []
    prev_stats = stats_before
    for name in goal_names:
        goal = _make_goal(name, constraint, options)
        g0 = time.time()
        succeeded, sb, sa = goal.optimize(m, optimized,
                                          stats_before=prev_stats)
        reports.append(SeqGoalReport(
            name=name, succeeded=succeeded,
            comparator_vs_before=compare_stats(name, sa, sb, constraint),
            wall_s=time.time() - g0))
        optimized.append(goal)
        prev_stats = sa
    stats_after = prev_stats
    return SeqResult(
        broker_of=m.broker_of.copy(),
        leader_of=m.leader_of.copy(),
        goal_reports=reports,
        num_replica_movements=m.num_moves,
        num_leadership_movements=m.num_leads,
        wall_time_s=time.time() - t0,
        stats_before=stats_before,
        stats_after=stats_after,
    )
