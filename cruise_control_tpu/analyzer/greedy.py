"""Deterministic batched greedy descent over the weighted goal objective.

The TPU-idiomatic replacement for the reference's per-goal sequential
rebalance loops (``AbstractGoal.java:68-109`` × ``rebalanceForBroker`` ×
``maybeApplyBalancingAction``, the O(goals·brokers·replicas·candidates) hot
nest at ``GoalOptimizer.java:429``): instead of walking replicas one goal at a
time with veto checks, every round scores **all** candidate actions at once —
the full (replica × destination-broker) move matrix and the (partition ×
replica-slot) leadership matrix — against the *combined* hierarchical
objective, applies the single best action, and repeats until no action
improves. Priority semantics are carried by the objective weights
(hard ≫ soft, earlier-priority ≫ later, :func:`objective.build_weights`);
legality (``GoalUtils.legitMove``: alive destination, no duplicate replica of
the same partition on a broker, excluded topics/brokers) is enforced by masks.

Exactness: the chosen action's effect on the running aggregates is applied
with the same arithmetic used to propose it, and the final state is re-scored
with the exact full evaluation, so greedy never reports a stale objective.

Scale note: the move matrix materializes O(R·B) intermediates — intended for
clusters up to ~tens of thousands of replicas (the reference's unit/property
test sizes). The annealer handles the 100K+ regime.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import objective as OBJ
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.models.cluster import Assignment
from cruise_control_tpu.ops.aggregates import (
    DeviceTopology,
    compute_aggregates,
)

_INF = jnp.float32(3.0e38)


class GreedyState(NamedTuple):
    broker_of: jax.Array        # i32[R]
    leader_of: jax.Array        # i32[P]
    broker_load: jax.Array      # f32[B,4]
    host_load: jax.Array        # f32[H,4]
    replica_count: jax.Array    # f32[B]
    leader_count: jax.Array     # f32[B]
    potential_nw_out: jax.Array  # f32[B]
    leader_bytes_in: jax.Array  # f32[B]
    topic_count: jax.Array      # f32[B,T]
    moves: jax.Array            # i32 scalar — replica moves applied
    leadership_moves: jax.Array  # i32 scalar
    done: jax.Array             # bool scalar


def _init_state(dt: DeviceTopology, assign: Assignment, num_topics: int) -> GreedyState:
    agg = compute_aggregates(dt, assign, num_topics)
    return GreedyState(
        broker_of=jnp.asarray(assign.broker_of, jnp.int32),
        leader_of=jnp.asarray(assign.leader_of, jnp.int32),
        broker_load=agg.broker_load,
        host_load=agg.host_load,
        replica_count=agg.replica_count.astype(jnp.float32),
        leader_count=agg.leader_count.astype(jnp.float32),
        potential_nw_out=agg.potential_nw_out,
        leader_bytes_in=agg.leader_bytes_in,
        topic_count=agg.topic_count.astype(jnp.float32),
        moves=jnp.int32(0),
        leadership_moves=jnp.int32(0),
        done=jnp.asarray(False),
    )


_band_cost = G.band_cost


def _replica_move_deltas(dt: DeviceTopology, th: G.GoalThresholds,
                         w: OBJ.ObjectiveWeights, opts: G.DeviceOptions,
                         st: GreedyState, initial_broker_of: jax.Array):
    """f32[R, B] objective delta of moving replica r to broker b (+inf invalid)."""
    R, B = dt.num_replicas, dt.num_brokers
    p = dt.partition_of_replica
    a = st.broker_of                                       # i32[R] source broker
    is_leader = st.leader_of[p] == jnp.arange(R, dtype=jnp.int32)
    eff = dt.replica_base_load + jnp.where(is_leader[:, None],
                                           dt.leader_extra[p], 0.0)  # [R,4]
    # partition's potential-leadership NW_OUT rides with every replica
    pl = (dt.leader_extra[:, res.NW_OUT]
          + dt.replica_base_load[st.leader_of, res.NW_OUT])          # [P]
    pl_r = pl[p]                                                     # [R]
    lbi_r = jnp.where(is_leader, dt.leader_bytes_in[p], 0.0)         # [R]
    lead_f = is_leader.astype(jnp.float32)

    # ---- current per-broker / per-host costs (two channels each)
    f0 = OBJ.broker_cost(th, w, st.broker_load, st.replica_count,
                         st.leader_count, st.potential_nw_out, st.leader_bytes_in)  # [B, 2]
    h0 = OBJ.host_cost(th, w, st.host_load)                                         # [H, 2]

    # ---- source side: broker a without replica r  → [R, 2]
    th_a = OBJ.gather_thresholds(th, a)
    f_minus = OBJ.broker_cost(
        th_a, w,
        st.broker_load[a] - eff,
        st.replica_count[a] - 1.0,
        st.leader_count[a] - lead_f,
        st.potential_nw_out[a] - pl_r,
        st.leader_bytes_in[a] - lbi_r,
    )
    d_src = f_minus - f0[a]                                          # [R, 2]

    # ---- destination side: broker b with replica r → [R, B, 2]
    f_plus = OBJ.broker_cost(
        th, w,
        st.broker_load[None, :, :] + eff[:, None, :],
        st.replica_count[None, :] + 1.0,
        st.leader_count[None, :] + lead_f[:, None],
        st.potential_nw_out[None, :] + pl_r[:, None],
        st.leader_bytes_in[None, :] + lbi_r[:, None],
    )
    d_dst = f_plus - f0[None, :]                                     # [R, B, 2]

    # ---- host terms (zero when the move stays on one host)
    ha = dt.host_of_broker[a]                                        # [R]
    hb = dt.host_of_broker                                           # [B]
    h_minus = OBJ.host_cost(OBJ.gather_host_thresholds(th, ha), w,
                            st.host_load[ha] - eff)                  # [R, 2]
    h_plus = OBJ.host_cost(OBJ.gather_host_thresholds(th, hb), w,
                           st.host_load[None, :, :][:, hb] + eff[:, None, :])  # [R,B,2]
    cross_host = (ha[:, None] != hb[None, :]).astype(jnp.float32)[..., None]
    d_host = ((h_minus - h0[ha])[:, None, :]
              + (h_plus - h0[hb][None, :, :])) * cross_host

    # ---- rack-awareness delta: occ[r, k] = some *other* replica of r's
    # partition lives in rack k (under the current assignment). Rack ids are
    # < B (each broker sits in exactly one rack), which keeps this jittable.
    K = B
    reps = dt.replicas_of_partition[p]                               # [R, m]
    valid_sib = (reps >= 0) & (reps != jnp.arange(R)[:, None])
    sib_broker = st.broker_of[jnp.clip(reps, 0)]                     # [R, m]
    sib_rack = dt.rack_of_broker[sib_broker]                         # [R, m]
    occ = jnp.zeros((R, K), jnp.bool_).at[
        jnp.arange(R)[:, None], sib_rack].max(valid_sib)             # [R, K]
    occ_a = occ[jnp.arange(R), dt.rack_of_broker[a]]                 # [R]
    occ_b = occ[:, dt.rack_of_broker]                                # [R, B]
    d_rack_n = (occ_b.astype(jnp.float32)
                - occ_a.astype(jnp.float32)[:, None])                # [R, B]
    w_rack2 = jnp.stack([w.rack_viol, w.rack])
    d_rack = d_rack_n[..., None] * w_rack2                           # [R, B, 2]

    # ---- topic distribution delta (cost + violation-count channels)
    t = dt.topic_of_partition[p]                                     # [R]
    n_a = st.topic_count[a, t]                                       # [R]
    n_b = st.topic_count[:, t].T                                     # [R, B]
    u_t, l_t = th.topic_upper[t], th.topic_lower[t]                  # [R]
    dc_topic = (
        (_band_cost(n_a - 1.0, u_t, l_t) - _band_cost(n_a, u_t, l_t))[:, None]
        + _band_cost(n_b + 1.0, u_t[:, None], l_t[:, None])
        - _band_cost(n_b, u_t[:, None], l_t[:, None]))
    vi = lambda n, uu, ll: (_band_cost(n, uu, ll) > 0).astype(jnp.float32)
    dv_topic = (
        (vi(n_a - 1.0, u_t, l_t) - vi(n_a, u_t, l_t))[:, None]
        + vi(n_b + 1.0, u_t[:, None], l_t[:, None])
        - vi(n_b, u_t[:, None], l_t[:, None]))
    d_topic = jnp.stack([w.topic_viol * dv_topic, w.topic * dc_topic],
                        axis=-1)                                     # [R, B, 2]

    # ---- self-healing: offline replicas must leave their original broker
    on_init = st.broker_of == initial_broker_of
    heal_gain = (dt.replica_offline & on_init & dt.broker_alive[a]).astype(jnp.float32)
    heal_back = (dt.replica_offline & ~on_init)
    back_to_init = heal_back[:, None] & (initial_broker_of[:, None] == jnp.arange(B)[None, :])
    d_heal_n = back_to_init.astype(jnp.float32) - heal_gain[:, None]
    d_heal = d_heal_n[..., None] * jnp.stack([w.healing_viol, w.healing])

    delta = OBJ.combine(d_src[:, None, :] + d_dst + d_host + d_rack
                        + d_topic + d_heal)                          # [R, B]

    # ---- legality (GoalUtils.legitMove): destination alive+allowed, not the
    # source, and not already hosting a replica of the partition.
    sib_on_b = jnp.zeros((R, B), jnp.bool_).at[
        jnp.arange(R)[:, None], sib_broker].max(valid_sib)           # [R, B]
    ok = (opts.replica_movable[:, None]
          & opts.move_dest_ok[None, :]
          & (a[:, None] != jnp.arange(B)[None, :])
          & ~sib_on_b)
    return jnp.where(ok, delta, _INF)


def _leadership_deltas(dt: DeviceTopology, th: G.GoalThresholds,
                       w: OBJ.ObjectiveWeights, opts: G.DeviceOptions,
                       st: GreedyState):
    """f32[P, m] objective delta of moving partition p's leadership to slot s."""
    P, m = dt.num_partitions, dt.max_rf
    R = dt.num_replicas
    reps = dt.replicas_of_partition                                  # [P, m]
    valid = reps >= 0
    rep_broker = st.broker_of[jnp.clip(reps, 0)]                     # [P, m]
    cur_leader = st.leader_of                                        # [P]
    a = st.broker_of[cur_leader]                                     # [P] current leader broker
    extra = dt.leader_extra                                          # [P, 4]
    lbi = dt.leader_bytes_in                                         # [P]
    # potential-NW_OUT per member changes by the leader's base NW_OUT diff
    base_nwout = dt.replica_base_load[:, res.NW_OUT]                 # [R]
    d_pl = base_nwout[jnp.clip(reps, 0)] - base_nwout[cur_leader][:, None]  # [P, m]

    f0 = OBJ.broker_cost(th, w, st.broker_load, st.replica_count,
                         st.leader_count, st.potential_nw_out,
                         st.leader_bytes_in)                         # [B, 2]
    h0 = OBJ.host_cost(th, w, st.host_load)                          # [H, 2]

    # Evaluate every member broker under candidate s: loads move extra from a
    # to b_s; potential shifts by d_pl on every member broker (each member
    # hosts one replica of p).
    b_s = rep_broker                                                 # [P, m] candidate dest
    mem_b = rep_broker                                               # members' brokers
    is_a = (mem_b[:, None, :] == a[:, None, None])                   # [P, 1, m] broadcastable
    is_b = (mem_b[:, None, :] == b_s[:, :, None])                    # [P, m(cand), m(mem)]
    sgn = is_b.astype(jnp.float32) - is_a.astype(jnp.float32)        # net extra movement
    load_new = (st.broker_load[mem_b][:, None, :, :]
                + sgn[..., None] * extra[:, None, None, :])          # [P, mc, mm, 4]
    lc_new = (st.leader_count[mem_b][:, None, :]
              + sgn * 1.0)
    pot_new = (st.potential_nw_out[mem_b][:, None, :]
               + d_pl[:, :, None])                                   # all members shift
    lbi_new = (st.leader_bytes_in[mem_b][:, None, :]
               + sgn * lbi[:, None, None])
    th_mem = OBJ.gather_thresholds(th, mem_b)
    th_mem = th_mem._replace(
        alive=th_mem.alive[:, None, :],
        demoted=th_mem.demoted[:, None, :],
        broker_capacity=th_mem.broker_capacity[:, None, :, :],
        cap_limit_broker=th_mem.cap_limit_broker[:, None, :, :],
        pot_nw_out_limit=th_mem.pot_nw_out_limit[:, None, :],
    )
    f_new = OBJ.broker_cost(th_mem, w, load_new,
                            st.replica_count[mem_b][:, None, :],
                            lc_new, pot_new, lbi_new)                # [P, mc, mm, 2]
    # mask duplicate-broker double counting: each member counted once; padded
    # slots contribute 0.
    mem_valid = valid[:, None, :, None]
    d_brokers = jnp.sum(jnp.where(mem_valid,
                                  f_new - f0[mem_b][:, None, :, :], 0.0),
                        axis=-2)                                     # [P, mc, 2]

    # host terms: extra moves host(a) → host(b_s)
    ha = dt.host_of_broker[a]                                        # [P]
    hb = dt.host_of_broker[jnp.clip(b_s, 0)]                         # [P, m]
    h_minus = OBJ.host_cost(OBJ.gather_host_thresholds(th, ha), w,
                            st.host_load[ha] - extra)                # [P, 2]
    h_plus = OBJ.host_cost(OBJ.gather_host_thresholds(th, hb), w,
                           st.host_load[hb] + extra[:, None, :])     # [P, m, 2]
    cross = (ha[:, None] != hb).astype(jnp.float32)[..., None]
    d_host = ((h_minus - h0[ha])[:, None, :] + (h_plus - h0[hb])) * cross

    # preferred-leader term: moving to slot 0 earns, off slot 0 pays
    first = reps[:, 0]
    cur_is_first = (cur_leader == first).astype(jnp.float32)
    cand_is_first = (reps == first[:, None]).astype(jnp.float32)
    d_ple_n = cur_is_first[:, None] - cand_is_first                  # [P, m]
    d_ple = d_ple_n[..., None] * jnp.stack([w.preferred_leader_viol,
                                            w.preferred_leader])

    delta = OBJ.combine(d_brokers + d_host + d_ple)                  # [P, m]

    cand_replica = jnp.clip(reps, 0)
    ok = (valid
          & (reps != cur_leader[:, None])
          & opts.leader_dest_ok[jnp.clip(b_s, 0)]
          & opts.leadership_movable[cand_replica]
          & ~dt.replica_offline[cand_replica]
          & dt.broker_alive[jnp.clip(b_s, 0)])
    return jnp.where(ok, delta, _INF)


def _apply_replica_move(dt: DeviceTopology, st: GreedyState, r: jax.Array,
                        b: jax.Array) -> GreedyState:
    R = dt.num_replicas
    p = dt.partition_of_replica[r]
    a = st.broker_of[r]
    is_leader = st.leader_of[p] == r
    eff = dt.replica_base_load[r] + jnp.where(is_leader, dt.leader_extra[p],
                                              jnp.zeros(res.NUM_RESOURCES))
    pl = (dt.leader_extra[p, res.NW_OUT]
          + dt.replica_base_load[st.leader_of[p], res.NW_OUT])
    lbi = jnp.where(is_leader, dt.leader_bytes_in[p], 0.0)
    lead_f = is_leader.astype(jnp.float32)
    ha, hb = dt.host_of_broker[a], dt.host_of_broker[b]
    t = dt.topic_of_partition[p]
    return st._replace(
        broker_of=st.broker_of.at[r].set(b),
        broker_load=st.broker_load.at[a].add(-eff).at[b].add(eff),
        host_load=st.host_load.at[ha].add(-eff).at[hb].add(eff),
        replica_count=st.replica_count.at[a].add(-1.0).at[b].add(1.0),
        leader_count=st.leader_count.at[a].add(-lead_f).at[b].add(lead_f),
        potential_nw_out=st.potential_nw_out.at[a].add(-pl).at[b].add(pl),
        leader_bytes_in=st.leader_bytes_in.at[a].add(-lbi).at[b].add(lbi),
        topic_count=st.topic_count.at[a, t].add(-1.0).at[b, t].add(1.0),
        moves=st.moves + 1,
    )


def _apply_leadership_move(dt: DeviceTopology, st: GreedyState, pa: jax.Array,
                           slot: jax.Array) -> GreedyState:
    new_leader = dt.replicas_of_partition[pa, slot]
    old_leader = st.leader_of[pa]
    a = st.broker_of[old_leader]
    b = st.broker_of[new_leader]
    extra = dt.leader_extra[pa]
    lbi = dt.leader_bytes_in[pa]
    ha, hb = dt.host_of_broker[a], dt.host_of_broker[b]
    d_pl = (dt.replica_base_load[new_leader, res.NW_OUT]
            - dt.replica_base_load[old_leader, res.NW_OUT])
    reps = dt.replicas_of_partition[pa]
    valid = reps >= 0
    mem_b = st.broker_of[jnp.clip(reps, 0)]
    pot = st.potential_nw_out.at[mem_b].add(jnp.where(valid, d_pl, 0.0))
    return st._replace(
        leader_of=st.leader_of.at[pa].set(new_leader),
        broker_load=st.broker_load.at[a].add(-extra).at[b].add(extra),
        host_load=st.host_load.at[ha].add(-extra).at[hb].add(extra),
        leader_count=st.leader_count.at[a].add(-1.0).at[b].add(1.0),
        potential_nw_out=pot,
        leader_bytes_in=st.leader_bytes_in.at[a].add(-lbi).at[b].add(lbi),
        moves=st.moves,
        leadership_moves=st.leadership_moves + 1,
    )


class GreedyResult(NamedTuple):
    assignment: Assignment
    moves: int
    leadership_moves: int
    rounds: int


from functools import partial


#: descent rounds per device dispatch. One dispatch of the unbounded loop
#: can run for minutes at 300-broker shapes (~50K sequential [R, B] argmin
#: rounds), which a remote-TPU tunnel's RPC deadline treats as a hung
#: worker and kills. Chunking bounds a dispatch's wall-clock; the loop
#: state round-trips nothing between chunks (donated carry), so the only
#: host cost is one tiny (done, rounds) fetch per chunk.
GREEDY_CHUNK_ROUNDS = 4096


@partial(jax.jit, static_argnames=("num_topics",))
def _greedy_init(dt: DeviceTopology, broker_of, leader_of, num_topics: int):
    return _init_state(dt, Assignment(broker_of=broker_of,
                                      leader_of=leader_of), num_topics)


@partial(jax.jit, static_argnames=("num_topics", "min_improvement"),
         donate_argnums=(1,))
def _greedy_loop(dt: DeviceTopology, st, rounds, limit,
                 th: G.GoalThresholds, weights: OBJ.ObjectiveWeights,
                 opts: G.DeviceOptions, num_topics: int,
                 min_improvement: float, initial_broker_of):
    """One bounded chunk of the jitted descent loop; module-level so
    repeated optimize calls on same-shaped models hit the jit cache instead
    of retracing the while_loop (fresh closures defeat lax's own cache)."""
    B, m = dt.num_brokers, dt.max_rf

    def cond(carry):
        st, rounds = carry
        return (~st.done) & (rounds < limit)

    def body(carry):
        st, rounds = carry
        mv = _replica_move_deltas(dt, th, weights, opts, st, initial_broker_of)
        ld = _leadership_deltas(dt, th, weights, opts, st)
        mv_flat_idx = jnp.argmin(mv)
        ld_flat_idx = jnp.argmin(ld)
        mv_best = mv.reshape(-1)[mv_flat_idx]
        ld_best = ld.reshape(-1)[ld_flat_idx]
        best = jnp.minimum(mv_best, ld_best)
        take_move = mv_best <= ld_best

        def do_move(s):
            r = (mv_flat_idx // B).astype(jnp.int32)
            b = (mv_flat_idx % B).astype(jnp.int32)
            return _apply_replica_move(dt, s, r, b)

        def do_lead(s):
            pa = (ld_flat_idx // m).astype(jnp.int32)
            slot = (ld_flat_idx % m).astype(jnp.int32)
            return _apply_leadership_move(dt, s, pa, slot)

        improved = best < -min_improvement
        st2 = jax.lax.cond(
            improved,
            lambda s: jax.lax.cond(take_move, do_move, do_lead, s),
            lambda s: s._replace(done=jnp.asarray(True)),
            st)
        return st2, rounds + 1

    return jax.lax.while_loop(cond, body, (st, rounds))


def optimize_greedy(dt: DeviceTopology, assign: Assignment,
                    th: G.GoalThresholds, weights: OBJ.ObjectiveWeights,
                    opts: G.DeviceOptions, num_topics: int,
                    max_actions: Optional[int] = None,
                    min_improvement: float = 1e-6,
                    initial_broker_of=None) -> GreedyResult:
    """Greedy descent until no candidate action improves the objective.

    Mirrors the convergence contract of the reference's optimize loop
    (``AbstractGoal.optimize`` runs until ``_finished``/no action applies):
    deterministic given the model, terminates, and never accepts an action
    that worsens the weighted objective. ``initial_broker_of``: the true
    original placement for self-healing accounting (defaults to ``assign``;
    staged/sequential callers must pass the pre-optimization original).
    """
    if max_actions is None:
        max_actions = 4 * dt.num_replicas + 2 * dt.num_partitions
    if initial_broker_of is None:
        initial_broker_of = jnp.asarray(assign.broker_of, jnp.int32)
    st = _greedy_init(dt, jnp.asarray(assign.broker_of, jnp.int32),
                      jnp.asarray(assign.leader_of, jnp.int32), num_topics)
    rounds = jnp.int32(0)
    done_rounds = 0
    while done_rounds < max_actions:
        limit = jnp.int32(min(done_rounds + GREEDY_CHUNK_ROUNDS,
                              int(max_actions)))
        st, rounds = _greedy_loop(dt, st, rounds, limit, th, weights, opts,
                                  num_topics, float(min_improvement),
                                  initial_broker_of)
        done_rounds = int(jax.device_get(rounds))
        if bool(jax.device_get(st.done)):
            break
    return GreedyResult(
        assignment=Assignment(broker_of=st.broker_of, leader_of=st.leader_of),
        moves=int(st.moves),
        leadership_moves=int(st.leadership_moves),
        rounds=int(rounds),
    )


def optimize_greedy_staged(dt: DeviceTopology, assign: Assignment,
                           th: G.GoalThresholds, goal_names: Sequence[str],
                           opts: G.DeviceOptions, num_topics: int,
                           max_actions: Optional[int] = None) -> GreedyResult:
    """Sequential-priority descent: the reference's per-goal phase structure
    (GoalOptimizer.java:429 — optimize goal 1, then goal 2 subject to goal 1,
    ...). Stage k descends on the weight set with goals > k zeroed, starting
    from stage k−1's assignment; the violation-ladder channel guarantees no
    stage trades a higher-priority goal's violations for lower-priority
    gains. All stages share one compiled loop (weights are traced values).
    """
    goal_names = tuple(goal_names)
    init_bo = jnp.asarray(assign.broker_of, jnp.int32)
    # stage ends: the leading hard block as one stage, then one stage per
    # soft goal, always finishing with the full list
    hard_prefix = 0
    for g in goal_names:
        if not G.is_hard(g):
            break
        hard_prefix += 1
    ends = sorted({hard_prefix, len(goal_names),
                   *(i + 1 for i, g in enumerate(goal_names)
                     if not G.is_hard(g))} - {0})
    cur = assign
    total_moves = total_leads = total_rounds = 0
    for k in ends:
        w_k = OBJ.build_weights(goal_names, active_prefix=k)
        res = optimize_greedy(dt, cur, th, w_k, opts, num_topics,
                              max_actions=max_actions,
                              initial_broker_of=init_bo)
        cur = res.assignment
        total_moves += res.moves
        total_leads += res.leadership_moves
        total_rounds += res.rounds
    return GreedyResult(assignment=cur, moves=total_moves,
                        leadership_moves=total_leads, rounds=total_rounds)
