"""Parallel-tempering annealer: the TPU-scale optimizer engine.

Replaces the reference's single-threaded heuristic sweep
(``GoalOptimizer.java:429`` × ``AbstractGoal.java:81-86``) with thousands of
Metropolis chains exploring batched replica-move / leadership-move actions
(mirroring ``ActionType``: INTER_BROKER_REPLICA_MOVEMENT,
LEADERSHIP_MOVEMENT) over the weighted goal objective — the BASELINE.json
north-star design.

Architecture (all shapes static, everything inside one jit):

- Each chain carries the assignment plus *running aggregates* (per-broker
  load/counts, per-host load, optional dense per-(broker,topic) counts) so a
  proposed action's objective delta is O(max_rf) — independent of R and B.
  Total load/counts are move-invariant, so goal thresholds are constants
  (:mod:`goals`) and per-broker costs decompose exactly.
- Multi-try Metropolis: each step draws ``tries_move`` candidate replica
  moves and ``tries_lead`` leadership moves, takes the best delta, and
  accepts it at the chain's temperature. Rejected/no-op steps apply a
  degenerate scatter (src == dst) so control flow stays vmappable.
- Parallel tempering: chains sit on a geometric temperature ladder; every
  ``swap_interval`` steps adjacent chains exchange *temperatures* with the
  usual PT acceptance, letting hot explorers hand good states down to cold
  exploiters.
- The final answer is the best chain re-scored with the exact full
  evaluation (:func:`objective.evaluate_objective`), so incremental float
  drift can never corrupt the reported result.

Sharding: chains are embarrassingly parallel — `optimize_anneal` accepts a
``jax.sharding.Mesh`` and shards the chain axis with pjit; see
``parallel/sharding.py``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import goals as G
from cruise_control_tpu.analyzer import objective as OBJ
from cruise_control_tpu.common import resources as res
from cruise_control_tpu.common import sentinels as SENT
from cruise_control_tpu.obs import costmodel as CM
from cruise_control_tpu.models.cluster import (Assignment,
                                               BROKER_BUCKET_FLOOR,
                                               REPLICA_BUCKET_FLOOR,
                                               bucket_size)
from cruise_control_tpu.ops.aggregates import (DeviceTopology,
                                               compute_aggregates,
                                               leader_count_weights,
                                               replica_count_weights)

_INF = jnp.float32(3.0e38)


@dataclasses.dataclass(frozen=True)
class AnnealConfig:
    num_chains: int = 64
    steps: int = 2048
    swap_interval: int = 64
    tries_move: int = 32
    tries_lead: int = 8
    tries_swap: int = 16
    t_min: float = 1e-3
    t_max: float = 64.0
    #: include the dense [B,T] topic-count aggregate (memory B·T per chain)
    topic_term_limit: int = 2_000_000
    #: explicit topic-term mode override: "dense" | "sparse" | "off".
    #: None = dense when B·T fits, otherwise off (the optimizer's targeted
    #: repair pass handles the topic goal at scale — in-step sparse CSR
    #: counts are exact but cost ~2.5x wall-clock while random candidate
    #: sampling rarely lands on the few violating cells)
    topic_mode: Optional[str] = None
    #: greedy-at-T≈0 fraction of chains (pure descent)
    cold_fraction: float = 0.25


class WarmStart(NamedTuple):
    """Previous accepted assignment used to seed a fraction of the chains.

    ``optimize_anneal`` initializes ``round(C * fraction)`` chains — the
    COLDEST temperature-ladder slots, where exploitation lives — from this
    assignment instead of the current one; the remaining chains keep the
    status-quo init (the current assignment) so exploration is never
    forfeited to a stale optimum. ``dirty_partitions`` (the PR 6 dirty-mask
    delta: partition indices whose loads/placement moved since this
    assignment was accepted) perturbs the warm state back toward reality:
    dirty partitions take the CURRENT assignment's rows. The mix is
    whole-partition — every partition's replica set comes wholly from one
    individually-legal assignment, so the mixed state carries no
    duplicate-sibling placements.

    Contracts:

    - ``fraction <= 0`` (or ``warm_start=None``) takes EXACTLY the
      status-quo code path — the warm base state is never built, so the
      result is bit-identical to a run without warm start.
    - RNG is untouched: per-step chain keys still split from the final
      chain count, so warm start changes only chain INITIAL STATES, never
      proposal draws.
    - The caller owns structural continuity: ``broker_of``/``leader_of``
      must index the CURRENT model's replica/partition axes and the
      per-partition replica membership must be unchanged since the warm
      assignment was accepted (the app gates on the monitor's structural
      digest). Broker-axis growth (add_broker) is fine — old placements
      stay legal.
    - ``fraction`` lives here and NOT on :class:`AnnealConfig` on purpose:
      the config is a static key of the compiled PT scan, so a
      fraction-knob there would retrace the whole scan every time the knob
      moved; here it only selects between tiny init programs.
    """

    broker_of: jax.Array                    # i32[R] previous accepted
    leader_of: jax.Array                    # i32[P]
    dirty_partitions: Optional[np.ndarray] = None   # i32[K] moved partitions
    fraction: float = 0.5


class ChainState(NamedTuple):
    broker_of: jax.Array         # i32[R]
    leader_of: jax.Array         # i32[P]
    broker_load: jax.Array       # f32[B,4]
    host_load: jax.Array         # f32[H,4]
    replica_count: jax.Array     # f32[B]
    leader_count: jax.Array      # f32[B]
    potential_nw_out: jax.Array  # f32[B]
    leader_bytes_in: jax.Array   # f32[B]
    topic_count: jax.Array       # f32[B,T] or f32[1,1] when disabled
    #: f32[2] — incremental (violation, cost) channel totals. Kept as two
    #: channels because the combined scalar exceeds f32 precision (a single
    #: hard violation at 2^40·2^20 absorbs every cost digit); deltas combine
    #: fine, totals must not.
    energy: jax.Array


class AnnealResult(NamedTuple):
    assignment: Assignment
    energy: jax.Array
    chain_energies: jax.Array
    #: JSON-able ladder telemetry (None unless requested): per-ladder-slot
    #: proposal acceptance rates by family, PT exchange rates, and the
    #: per-round best-energy descent curve — the autotuner's signals
    telemetry: Optional[dict] = None


_band_cost = G.band_cost


def _chain_energy(dt: DeviceTopology, th: G.GoalThresholds,
                  w: OBJ.ObjectiveWeights, st: ChainState,
                  initial_broker_of: jax.Array, topic_mode: str,
                  num_topics: int = 1) -> jax.Array:
    """Decomposed two-channel objective from the running aggregates
    (init/rescore); returns f32[2] = (violation, cost) channel totals.

    ``topic_mode``: "dense" scores the maintained [B, T] histogram;
    "sparse" recomputes the exact topic penalty from ``broker_of`` without
    the histogram (LinkedIn scale); "off" skips the term (goal unselected).
    """
    f = OBJ.broker_cost(th, w, st.broker_load, st.replica_count,
                        st.leader_count, st.potential_nw_out,
                        st.leader_bytes_in)                     # [B, 2]
    h = OBJ.host_cost(th, w, st.host_load)                      # [H, 2]
    e2 = jnp.sum(f, axis=0) + jnp.sum(h, axis=0)                # [2]
    from cruise_control_tpu.ops.aggregates import partition_rack_excess
    rack_n = jnp.sum(partition_rack_excess(dt, st.broker_of))
    e2 = e2 + jnp.stack([w.rack_viol, w.rack]) * rack_n
    if topic_mode == "dense":
        alive_f = th.alive.astype(jnp.float32)[:, None]
        out = (_band_cost(st.topic_count, th.topic_upper[None, :],
                          th.topic_lower[None, :]) * alive_f)
        e2 = e2 + jnp.stack([w.topic_viol * jnp.sum((out > 0).astype(jnp.float32)),
                             w.topic * jnp.sum(out)])
    elif topic_mode == "sparse":
        tv, tc = G.sparse_topic_penalty(dt, st.broker_of, th, num_topics)
        e2 = e2 + jnp.stack([w.topic_viol * tv, w.topic * tc])
    unhealed = jnp.sum((dt.replica_offline
                        & (st.broker_of == initial_broker_of)
                        & dt.broker_alive[st.broker_of]).astype(jnp.float32))
    return e2 + jnp.stack([w.healing_viol, w.healing]) * unhealed


def _move_delta(dt: DeviceTopology, th: G.GoalThresholds, w: OBJ.ObjectiveWeights,
                opts: G.DeviceOptions, st: ChainState,
                initial_broker_of: jax.Array, topic_mode: str,
                topic_reps: jax.Array, r: jax.Array, b: jax.Array) -> jax.Array:
    """Two-channel objective delta of moving replica r to broker b.
    O(max_rf) (+ O(topic size) for the sparse topic count)."""
    p = dt.partition_of_replica[r]
    a = st.broker_of[r]
    is_leader = st.leader_of[p] == r
    eff = dt.replica_base_load[r] + jnp.where(is_leader, dt.leader_extra[p],
                                              jnp.zeros(res.NUM_RESOURCES))
    pl = (dt.leader_extra[p, res.NW_OUT]
          + dt.replica_base_load[st.leader_of[p], res.NW_OUT])
    lbi = jnp.where(is_leader, dt.leader_bytes_in[p], 0.0)
    lead_f = is_leader.astype(jnp.float32)

    ab = jnp.stack([a, b])
    th_ab = OBJ.gather_thresholds(th, ab)
    f0 = OBJ.broker_cost(th_ab, w, st.broker_load[ab], st.replica_count[ab],
                         st.leader_count[ab], st.potential_nw_out[ab],
                         st.leader_bytes_in[ab])                # [2, 2ch]
    sgn = jnp.array([-1.0, 1.0])
    f1 = OBJ.broker_cost(
        th_ab, w,
        st.broker_load[ab] + sgn[:, None] * eff[None, :],
        st.replica_count[ab] + sgn,
        st.leader_count[ab] + sgn * lead_f,
        st.potential_nw_out[ab] + sgn * pl,
        st.leader_bytes_in[ab] + sgn * lbi,
    )
    d2 = jnp.sum(f1 - f0, axis=0)                               # [2]

    ha, hb = dt.host_of_broker[a], dt.host_of_broker[b]
    hab = jnp.stack([ha, hb])
    th_h = OBJ.gather_host_thresholds(th, hab)
    h0 = OBJ.host_cost(th_h, w, st.host_load[hab])
    h1 = OBJ.host_cost(th_h, w, st.host_load[hab] + sgn[:, None] * eff[None, :])
    d2 = d2 + jnp.where(ha != hb, jnp.sum(h1 - h0, axis=0), 0.0)

    # rack: Δexcess = occ(dest rack) − occ(src rack) over the *other* replicas
    reps = dt.replicas_of_partition[p]                      # [m]
    valid_sib = (reps >= 0) & (reps != r)
    sib_rack = dt.rack_of_broker[st.broker_of[jnp.clip(reps, 0)]]
    occ_a = jnp.any(valid_sib & (sib_rack == dt.rack_of_broker[a]))
    occ_b = jnp.any(valid_sib & (sib_rack == dt.rack_of_broker[b]))
    d_rack = occ_b.astype(jnp.float32) - occ_a.astype(jnp.float32)
    d2 = d2 + jnp.stack([w.rack_viol, w.rack]) * d_rack

    if topic_mode != "off":
        t = dt.topic_of_partition[p]
        if topic_mode == "dense":
            n_a, n_b = st.topic_count[a, t], st.topic_count[b, t]
        else:   # sparse: count topic-t replicas on a / b via the topic CSR
            ids = topic_reps[t]                                  # [M]
            vm = ids >= 0
            bro = st.broker_of[jnp.clip(ids, 0)]
            n_a = jnp.sum(((bro == a) & vm).astype(jnp.float32))
            n_b = jnp.sum(((bro == b) & vm).astype(jnp.float32))
        u, l = th.topic_upper[t], th.topic_lower[t]
        dc_t = (_band_cost(n_a - 1.0, u, l) - _band_cost(n_a, u, l)
                + _band_cost(n_b + 1.0, u, l) - _band_cost(n_b, u, l))
        vi = lambda n, uu, ll: (_band_cost(n, uu, ll) > 0).astype(jnp.float32)
        dv_t = (vi(n_a - 1.0, u, l) - vi(n_a, u, l)
                + vi(n_b + 1.0, u, l) - vi(n_b, u, l))
        d2 = d2 + jnp.stack([w.topic_viol * dv_t, w.topic * dc_t])

    on_init = a == initial_broker_of[r]
    heals = dt.replica_offline[r] & on_init & dt.broker_alive[a]
    back = dt.replica_offline[r] & (b == initial_broker_of[r])
    d_heal = back.astype(jnp.float32) - heals.astype(jnp.float32)
    d2 = d2 + jnp.stack([w.healing_viol, w.healing]) * d_heal

    # legality: no duplicate replica of p on b; eligible dest; movable replica
    sib_on_b = jnp.any(valid_sib & (st.broker_of[jnp.clip(reps, 0)] == b))
    ok = (opts.replica_movable[r] & opts.move_dest_ok[b] & (b != a) & ~sib_on_b)
    return jnp.where(ok, d2, _INF)


def _lead_delta(dt: DeviceTopology, th: G.GoalThresholds, w: OBJ.ObjectiveWeights,
                opts: G.DeviceOptions, st: ChainState,
                p: jax.Array, slot: jax.Array) -> jax.Array:
    """Objective delta of moving partition p's leadership to slot. O(max_rf)."""
    reps = dt.replicas_of_partition[p]                      # [m]
    valid = reps >= 0
    cand = reps[slot]
    cur = st.leader_of[p]
    a = st.broker_of[cur]
    b = st.broker_of[jnp.clip(cand, 0)]
    extra = dt.leader_extra[p]
    lbi = dt.leader_bytes_in[p]
    d_pl = (dt.replica_base_load[jnp.clip(cand, 0), res.NW_OUT]
            - dt.replica_base_load[cur, res.NW_OUT])

    mem_b = st.broker_of[jnp.clip(reps, 0)]                 # [m]
    th_m = OBJ.gather_thresholds(th, mem_b)
    sgn = ((mem_b == b).astype(jnp.float32) - (mem_b == a).astype(jnp.float32))
    f0 = OBJ.broker_cost(th_m, w, st.broker_load[mem_b], st.replica_count[mem_b],
                         st.leader_count[mem_b], st.potential_nw_out[mem_b],
                         st.leader_bytes_in[mem_b])             # [m, 2]
    f1 = OBJ.broker_cost(
        th_m, w,
        st.broker_load[mem_b] + sgn[:, None] * extra[None, :],
        st.replica_count[mem_b],
        st.leader_count[mem_b] + sgn,
        st.potential_nw_out[mem_b] + d_pl,
        st.leader_bytes_in[mem_b] + sgn * lbi,
    )
    d2 = jnp.sum(jnp.where(valid[:, None], f1 - f0, 0.0), axis=0)   # [2]

    ha, hb = dt.host_of_broker[a], dt.host_of_broker[b]
    hab = jnp.stack([ha, hb])
    th_h = OBJ.gather_host_thresholds(th, hab)
    sgn_h = jnp.array([-1.0, 1.0])
    h0 = OBJ.host_cost(th_h, w, st.host_load[hab])
    h1 = OBJ.host_cost(th_h, w, st.host_load[hab] + sgn_h[:, None] * extra[None, :])
    d2 = d2 + jnp.where(ha != hb, jnp.sum(h1 - h0, axis=0), 0.0)

    first = reps[0]
    d_ple = ((cur == first).astype(jnp.float32)
             - (cand == first).astype(jnp.float32))
    d2 = d2 + jnp.stack([w.preferred_leader_viol, w.preferred_leader]) * d_ple

    ok = (valid[slot] & (cand != cur)
          & opts.leader_dest_ok[b] & opts.leadership_movable[jnp.clip(cand, 0)]
          & ~dt.replica_offline[jnp.clip(cand, 0)] & dt.broker_alive[b])
    return jnp.where(ok, d2, _INF)


def _swap_delta(dt: DeviceTopology, th: G.GoalThresholds, w: OBJ.ObjectiveWeights,
                opts: G.DeviceOptions, st: ChainState,
                initial_broker_of: jax.Array, topic_mode: str,
                topic_reps: jax.Array, r1: jax.Array, r2: jax.Array) -> jax.Array:
    """Two-channel objective delta of exchanging replicas r1 ↔ r2 between
    their brokers (ActionType.INTER_BROKER_REPLICA_SWAP). O(max_rf)."""
    p1 = dt.partition_of_replica[r1]
    p2 = dt.partition_of_replica[r2]
    a = st.broker_of[r1]
    b = st.broker_of[r2]

    def rep_stats(rr, pp):
        is_l = st.leader_of[pp] == rr
        eff = dt.replica_base_load[rr] + jnp.where(
            is_l, dt.leader_extra[pp], jnp.zeros(res.NUM_RESOURCES))
        pl = (dt.leader_extra[pp, res.NW_OUT]
              + dt.replica_base_load[st.leader_of[pp], res.NW_OUT])
        lbi = jnp.where(is_l, dt.leader_bytes_in[pp], 0.0)
        return eff, pl, lbi, is_l.astype(jnp.float32)

    e1, pl1, lbi1, l1 = rep_stats(r1, p1)
    e2, pl2, lbi2, l2 = rep_stats(r2, p2)
    de = e2 - e1      # net load change on a (b gets -de)
    dpl = pl2 - pl1
    dlbi = lbi2 - lbi1
    dl = l2 - l1

    ab = jnp.stack([a, b])
    sgn = jnp.array([1.0, -1.0])
    th_ab = OBJ.gather_thresholds(th, ab)
    f0 = OBJ.broker_cost(th_ab, w, st.broker_load[ab], st.replica_count[ab],
                         st.leader_count[ab], st.potential_nw_out[ab],
                         st.leader_bytes_in[ab])                # [2, 2ch]
    f1 = OBJ.broker_cost(
        th_ab, w,
        st.broker_load[ab] + sgn[:, None] * de[None, :],
        st.replica_count[ab],
        st.leader_count[ab] + sgn * dl,
        st.potential_nw_out[ab] + sgn * dpl,
        st.leader_bytes_in[ab] + sgn * dlbi,
    )
    d2 = jnp.sum(f1 - f0, axis=0)                               # [2]

    ha, hb = dt.host_of_broker[a], dt.host_of_broker[b]
    hab = jnp.stack([ha, hb])
    th_h = OBJ.gather_host_thresholds(th, hab)
    h0 = OBJ.host_cost(th_h, w, st.host_load[hab])
    h1 = OBJ.host_cost(th_h, w, st.host_load[hab] + sgn[:, None] * de[None, :])
    d2 = d2 + jnp.where(ha != hb, jnp.sum(h1 - h0, axis=0), 0.0)

    # rack deltas, one per partition
    def rack_delta(rr, pp, src_b, dst_b):
        reps = dt.replicas_of_partition[pp]
        valid_sib = (reps >= 0) & (reps != rr)
        sib_rack = dt.rack_of_broker[st.broker_of[jnp.clip(reps, 0)]]
        occ_s = jnp.any(valid_sib & (sib_rack == dt.rack_of_broker[src_b]))
        occ_d = jnp.any(valid_sib & (sib_rack == dt.rack_of_broker[dst_b]))
        return occ_d.astype(jnp.float32) - occ_s.astype(jnp.float32)

    d_rack = rack_delta(r1, p1, a, b) + rack_delta(r2, p2, b, a)
    d2 = d2 + jnp.stack([w.rack_viol, w.rack]) * d_rack

    if topic_mode != "off":
        t1 = dt.topic_of_partition[p1]
        t2 = dt.topic_of_partition[p2]

        def count(t, broker):
            if topic_mode == "dense":
                return st.topic_count[broker, t]
            ids = topic_reps[t]
            vm = ids >= 0
            bro = st.broker_of[jnp.clip(ids, 0)]
            return jnp.sum(((bro == broker) & vm).astype(jnp.float32))

        def topic_delta(t, frm, to):
            n_f, n_t = count(t, frm), count(t, to)
            u, l = th.topic_upper[t], th.topic_lower[t]
            vi = lambda n: (_band_cost(n, u, l) > 0).astype(jnp.float32)
            dc = (_band_cost(n_f - 1.0, u, l) - _band_cost(n_f, u, l)
                  + _band_cost(n_t + 1.0, u, l) - _band_cost(n_t, u, l))
            dv = (vi(n_f - 1.0) - vi(n_f) + vi(n_t + 1.0) - vi(n_t))
            return jnp.stack([dv, dc])

        same_topic = t1 == t2
        d2 = d2 + jnp.where(
            same_topic, 0.0,
            jnp.stack([w.topic_viol, w.topic])
            * (topic_delta(t1, a, b) + topic_delta(t2, b, a)))

    def heal_delta(rr, src_b, dst_b):
        on_init = src_b == initial_broker_of[rr]
        heals = dt.replica_offline[rr] & on_init & dt.broker_alive[src_b]
        back = dt.replica_offline[rr] & (dst_b == initial_broker_of[rr])
        return back.astype(jnp.float32) - heals.astype(jnp.float32)

    d2 = d2 + (jnp.stack([w.healing_viol, w.healing])
               * (heal_delta(r1, a, b) + heal_delta(r2, b, a)))

    def sib_on(rr, pp, broker):
        reps = dt.replicas_of_partition[pp]
        valid_sib = (reps >= 0) & (reps != rr)
        return jnp.any(valid_sib & (st.broker_of[jnp.clip(reps, 0)] == broker))

    ok = (opts.replica_movable[r1] & opts.replica_movable[r2]
          & opts.move_dest_ok[a] & opts.move_dest_ok[b]
          & (a != b) & (p1 != p2)
          & ~sib_on(r1, p1, b) & ~sib_on(r2, p2, a))
    return jnp.where(ok, d2, _INF)


def _apply_moves(dt: DeviceTopology, st: ChainState, r_vec, b_vec,
                 use_topic) -> ChainState:
    """Apply a batch of replica moves in one scatter pass.

    ``b_vec[k] == current broker`` encodes a no-op (its ± contributions
    cancel); the conflict-free selection guarantees accepted moves touch
    disjoint brokers/hosts/partitions, so scatter-adds commute exactly.
    """
    p = dt.partition_of_replica[r_vec]
    a = st.broker_of[r_vec]
    is_leader = st.leader_of[p] == r_vec
    eff = dt.replica_base_load[r_vec] + jnp.where(
        is_leader[:, None], dt.leader_extra[p], 0.0)          # [K,4]
    pl = (dt.leader_extra[p, res.NW_OUT]
          + dt.replica_base_load[st.leader_of[p], res.NW_OUT])
    lbi = jnp.where(is_leader, dt.leader_bytes_in[p], 0.0)
    lead_f = is_leader.astype(jnp.float32)
    one = jnp.ones_like(lead_f)
    ha, hb = dt.host_of_broker[a], dt.host_of_broker[b_vec]
    tc = st.topic_count
    if use_topic:
        t = dt.topic_of_partition[p]
        tc = tc.at[a, t].add(-1.0).at[b_vec, t].add(1.0)
    return st._replace(
        # delta-add instead of set: no-ops contribute 0, so a duplicate
        # sampled replica (one accepted, one no-op) still lands exactly once
        broker_of=st.broker_of.at[r_vec].add(b_vec - a),
        broker_load=st.broker_load.at[a].add(-eff).at[b_vec].add(eff),
        host_load=st.host_load.at[ha].add(-eff).at[hb].add(eff),
        replica_count=st.replica_count.at[a].add(-one).at[b_vec].add(one),
        leader_count=st.leader_count.at[a].add(-lead_f).at[b_vec].add(lead_f),
        potential_nw_out=st.potential_nw_out.at[a].add(-pl).at[b_vec].add(pl),
        leader_bytes_in=st.leader_bytes_in.at[a].add(-lbi).at[b_vec].add(lbi),
        topic_count=tc,
    )


def _apply_leads(dt: DeviceTopology, st: ChainState, p_vec, new_leader_vec
                 ) -> ChainState:
    """Apply a batch of leadership moves (``new_leader == current`` = no-op)."""
    cur = st.leader_of[p_vec]
    new_leader = new_leader_vec
    changed = new_leader != cur
    a = st.broker_of[cur]
    b = st.broker_of[new_leader]
    extra = jnp.where(changed[:, None], dt.leader_extra[p_vec], 0.0)  # [K,4]
    lbi = jnp.where(changed, dt.leader_bytes_in[p_vec], 0.0)
    d_pl = jnp.where(changed,
                     dt.replica_base_load[new_leader, res.NW_OUT]
                     - dt.replica_base_load[cur, res.NW_OUT], 0.0)
    ha, hb = dt.host_of_broker[a], dt.host_of_broker[b]
    reps = dt.replicas_of_partition[p_vec]                    # [K, m]
    valid = reps >= 0
    mem_b = st.broker_of[jnp.clip(reps, 0)]
    pot = st.potential_nw_out.at[mem_b.reshape(-1)].add(
        jnp.where(valid, d_pl[:, None], 0.0).reshape(-1))
    one = changed.astype(jnp.float32)
    return st._replace(
        leader_of=st.leader_of.at[p_vec].add(new_leader - cur),
        broker_load=st.broker_load.at[a].add(-extra).at[b].add(extra),
        host_load=st.host_load.at[ha].add(-extra).at[hb].add(extra),
        leader_count=st.leader_count.at[a].add(-one).at[b].add(one),
        potential_nw_out=pot,
        leader_bytes_in=st.leader_bytes_in.at[a].add(-lbi).at[b].add(lbi),
    )


def make_step_fn(dt: DeviceTopology, th, weights, opts, cfg: AnnealConfig,
                 movable_idx, dest_idx, initial_broker_of, topic_mode: str,
                 topic_reps=None, n_movable=None, n_dest=None,
                 telemetry: bool = False):
    """Build the per-chain annealer step (module-level for profiling/tests).

    ``n_movable`` / ``n_dest``: traced scalar sampling bounds over the
    real prefix of bucket-padded candidate pools. None (the unpadded path)
    keeps the historical static ``.size`` bounds; a bucketed run passes the
    real pool sizes so pool drift within a bucket changes only these scalar
    *values* — no retrace — while ``jax.random.randint`` draws stay
    identical to an unpadded run's (equal bound values ⇒ equal draws, the
    padded == unpadded proposal contract).

    ``telemetry`` makes the step ALSO return i32[3] accepted-proposal
    counts (move, lead, swap) folded from the already-computed ``accept``
    mask — no extra RNG draws, no change to the accept decision itself,
    so the walked state sequence is identical either way."""
    R, P, B = dt.num_replicas, dt.num_partitions, dt.num_brokers
    Km, Kl, Ks = cfg.tries_move, cfg.tries_lead, cfg.tries_swap
    m = dt.max_rf
    if n_movable is None:
        n_movable = movable_idx.size
    if n_dest is None:
        n_dest = dest_idx.size
    # --- propose-mask: destination-restricted sampling (add_broker, drain-
    # this-rack, move-this-topic). The pool handed in is mask-INDEPENDENT
    # (optimize_anneal builds it from th.alive when a mask is present), and
    # the restriction happens here in-trace: stable-partition the pool so
    # allowed destinations form the prefix, then shrink the sampling bound
    # to the allowed count. Executed once at trace time — hoisted out of the
    # scanned step — so WHICH brokers are requested changes only array
    # values, never the compiled program (the zero-retrace heal contract).
    # An all-true mask partitions to the identity permutation with an equal
    # bound value, so draws are bit-identical to the unmasked path (equal
    # randint bounds ⇒ equal draws, same contract as padded == unpadded).
    mask = getattr(opts, "propose_dest_mask", None)
    if mask is not None:
        in_pool = jnp.arange(dest_idx.shape[0]) < n_dest
        valid = in_pool & mask[dest_idx]
        order = jnp.argsort(jnp.where(valid, 0, 1).astype(jnp.int32),
                            stable=True)
        dest_idx = dest_idx[order]
        # empty mask clamps to 1: the single drawn destination is illegal
        # under move_dest_ok, so every such proposal prices at +inf
        n_dest = jnp.maximum(jnp.sum(valid.astype(jnp.int32)), jnp.int32(1))
    # real partition count: padded partitions must never be sampled (their
    # sentinel replicas are immovable anyway, but the RNG stream has to
    # match the unpadded run draw for draw)
    if dt.partition_weight is not None:
        n_parts = jnp.sum(dt.partition_weight)
    else:
        n_parts = P
    if topic_reps is None:
        topic_reps = jax.device_put(np.full((1, 1), -1, np.int32))
    use_topic = topic_mode == "dense"   # maintained-histogram updates

    def _pressure(st, brokers):
        """Max resource-utilization fraction — power-of-two-choices key."""
        load = st.broker_load[brokers]
        cap = jnp.maximum(th.broker_capacity[brokers], 1e-30)
        return jnp.max(load / cap, axis=-1)

    def step(st: ChainState, temp, key):
        ks = jax.random.split(key, 11)
        # --- candidate replica moves: two-choice biased source (hotter
        # broker) and destination (colder broker)
        r1 = movable_idx[jax.random.randint(ks[0], (Km,), 0, n_movable)]
        r2 = movable_idx[jax.random.randint(ks[1], (Km,), 0, n_movable)]
        hot = _pressure(st, st.broker_of[r1]) >= _pressure(st, st.broker_of[r2])
        r_c = jnp.where(hot, r1, r2)
        b1 = dest_idx[jax.random.randint(ks[2], (Km,), 0, n_dest)]
        b2 = dest_idx[jax.random.randint(ks[3], (Km,), 0, n_dest)]
        cold = _pressure(st, b1) <= _pressure(st, b2)
        b_c = jnp.where(cold, b1, b2)
        d_move = jax.vmap(
            lambda r, b: _move_delta(dt, th, weights, opts, st,
                                     initial_broker_of, topic_mode,
                                     topic_reps, r, b)
        )(r_c, b_c)
        # --- candidate leadership moves
        p_c = jax.random.randint(ks[4], (Kl,), 0, n_parts)
        s_c = jax.random.randint(ks[5], (Kl,), 0, m)
        d_lead = jax.vmap(
            lambda p, s: _lead_delta(dt, th, weights, opts, st, p, s)
        )(p_c, s_c)

        # --- candidate swaps: hot-biased r1, cold-biased r2
        w1 = movable_idx[jax.random.randint(ks[7], (Ks,), 0, n_movable)]
        w2 = movable_idx[jax.random.randint(ks[8], (Ks,), 0, n_movable)]
        hot_w = _pressure(st, st.broker_of[w1]) >= _pressure(st, st.broker_of[w2])
        s_r1 = jnp.where(hot_w, w1, w2)
        w3 = movable_idx[jax.random.randint(ks[9], (Ks,), 0, n_movable)]
        w4 = movable_idx[jax.random.randint(ks[10], (Ks,), 0, n_movable)]
        cold_w = _pressure(st, st.broker_of[w3]) <= _pressure(st, st.broker_of[w4])
        s_r2 = jnp.where(cold_w, w3, w4)
        d_swap = jax.vmap(
            lambda x, y: _swap_delta(dt, th, weights, opts, st,
                                     initial_broker_of, topic_mode,
                                     topic_reps, x, y)
        )(s_r1, s_r2)

        # --- conflict-free selection: proposals touching disjoint brokers /
        # hosts / partitions (and topics, when the topic term is on) have
        # exactly additive deltas. Conservative rule: in delta-sorted order a
        # proposal survives only if it conflicts with NO earlier candidate.
        K = Km + Kl + Ks
        deltas2 = jnp.concatenate([d_move, d_lead, d_swap])       # [K, 2]
        deltas = OBJ.combine(deltas2)   # ordering/acceptance scalar
        mm = max(m, 2)

        def padset(x, width=mm):   # pad id-set rows to a common width with -1
            return jnp.pad(x, ((0, 0), (0, width - x.shape[1])),
                           constant_values=-1)

        mv_brokers = padset(jnp.stack([st.broker_of[r_c], b_c], axis=1))
        ld_reps = dt.replicas_of_partition[p_c]                        # [Kl,m]
        ld_brokers = padset(jnp.where(ld_reps >= 0,
                                      st.broker_of[jnp.clip(ld_reps, 0)], -1))
        sw_brokers = padset(jnp.stack([st.broker_of[s_r1],
                                       st.broker_of[s_r2]], axis=1))
        touched_b = jnp.concatenate([mv_brokers, ld_brokers, sw_brokers])
        touched_h = jnp.where(touched_b >= 0,
                              dt.host_of_broker[jnp.clip(touched_b, 0)], -1)
        p_of_r = dt.partition_of_replica
        neg1 = jnp.full((Km,), -1, jnp.int32)
        negl = jnp.full((Kl,), -1, jnp.int32)
        part = jnp.concatenate([
            jnp.stack([p_of_r[r_c], neg1], axis=1),
            jnp.stack([p_c, negl], axis=1),
            jnp.stack([p_of_r[s_r1], p_of_r[s_r2]], axis=1)])          # [K,2]
        if topic_mode != "off":
            t_of_p = dt.topic_of_partition
            topic = jnp.concatenate([
                jnp.stack([t_of_p[p_of_r[r_c]], neg1], axis=1),
                jnp.stack([negl, negl], axis=1),
                jnp.stack([t_of_p[p_of_r[s_r1]], t_of_p[p_of_r[s_r2]]], axis=1)])
        else:
            topic = jnp.full((K, 2), -1, jnp.int32)

        def overlap(x):   # [K,w] padded-id sets → bool[K,K] any shared id
            eq = (x[:, None, :, None] == x[None, :, None, :])
            eq &= (x[:, None, :, None] >= 0)
            return jnp.any(eq, axis=(2, 3))

        conflict = (overlap(touched_b) | overlap(touched_h)
                    | overlap(part) | overlap(topic))

        # "j precedes i" in delta order, computed pairwise (no sort — TPU
        # sorts are many bitonic passes and dominated the step cost)
        idx = jnp.arange(K)
        earlier = ((deltas[None, :] < deltas[:, None])
                   | ((deltas[None, :] == deltas[:, None])
                      & (idx[None, :] < idx[:, None])))
        blocked = jnp.any(conflict & earlier, axis=1)
        selected = ~blocked

        u = jax.random.uniform(ks[6], (K,))
        mh = (deltas < 0) | (u < jnp.exp(-jnp.minimum(deltas, 80.0 * temp)
                                         / jnp.maximum(temp, 1e-9)))
        accept = selected & mh & (deltas < _INF)

        acc_mv = accept[:Km]
        acc_ld = accept[Km:Km + Kl]
        acc_sw = accept[Km + Kl:]
        # swap = two moves appended to the move batch
        all_r = jnp.concatenate([r_c, s_r1, s_r2])
        all_b = jnp.concatenate([
            jnp.where(acc_mv, b_c, st.broker_of[r_c]),
            jnp.where(acc_sw, st.broker_of[s_r2], st.broker_of[s_r1]),
            jnp.where(acc_sw, st.broker_of[s_r1], st.broker_of[s_r2])])
        cand = dt.replicas_of_partition[p_c, s_c]
        cur = st.leader_of[p_c]
        new_leader = jnp.where(acc_ld & (cand >= 0), cand, cur)

        st = _apply_moves(dt, st, all_r, all_b, use_topic)
        st = _apply_leads(dt, st, p_c, new_leader)
        st = st._replace(energy=st.energy + jnp.sum(
            jnp.where(accept[:, None], deltas2, 0.0), axis=0))
        if telemetry:
            counts = jnp.stack([jnp.sum(acc_mv), jnp.sum(acc_ld),
                                jnp.sum(acc_sw)]).astype(jnp.int32)
            return st, counts
        return st

    return step


@partial(jax.jit, static_argnames=("use_topic",))
def _make_base_state(agg, broker_of, leader_of, use_topic: bool):
    """Single compiled program for the eager glue that builds the seed
    chain state (the astype/zeros chain was ~8 separate tiny programs —
    each a remote-compile + persistent-cache load on the TPU tunnel).

    The ``+ 0`` is load-bearing: a pass-through jit output ALIASES its
    input array, and repair's donating fused applies would then delete the
    caller's assignment buffers. The add forces a real output buffer."""
    return ChainState(
        broker_of=jnp.asarray(broker_of, jnp.int32) + 0,
        leader_of=jnp.asarray(leader_of, jnp.int32) + 0,
        broker_load=agg.broker_load,
        host_load=agg.host_load,
        replica_count=agg.replica_count.astype(jnp.float32),
        leader_count=agg.leader_count.astype(jnp.float32),
        potential_nw_out=agg.potential_nw_out,
        leader_bytes_in=agg.leader_bytes_in,
        topic_count=(agg.topic_count.astype(jnp.float32) if use_topic
                     else jnp.zeros((1, 1), jnp.float32)),
        energy=jnp.zeros((2,), jnp.float32),
    )


@partial(jax.jit, static_argnames=("num_chains",))
def _broadcast_chains(base, num_chains: int):
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (num_chains,) + x.shape), base)


@jax.jit
def _mix_dirty(partition_of_replica, cur_bo, cur_lo, warm_bo, warm_lo,
               dirty_mask):
    """Perturb the warm assignment along the dirty-mask delta: dirty
    partitions take the CURRENT assignment's rows (their placement/load
    moved since the warm state was accepted), clean partitions keep the
    previous accepted placement. Whole-partition granularity keeps each
    partition's replica set from ONE legal assignment — no mixed state can
    introduce a duplicate-sibling placement."""
    rep_dirty = dirty_mask[partition_of_replica]
    return (jnp.where(rep_dirty, cur_bo, warm_bo),
            jnp.where(dirty_mask, cur_lo, warm_lo))


@partial(jax.jit, static_argnames=("num_chains", "n_warm"))
def _broadcast_chains_warm(base_cur, base_warm, num_chains: int, n_warm: int):
    """Seed the first ``n_warm`` chains (the coldest temperature-ladder
    slots) from the warm base state and the rest from the current one.
    Like ``_broadcast_chains``, the output is a fresh buffer the PT run may
    donate."""
    def pick(c, w):
        return jnp.concatenate([
            jnp.broadcast_to(w, (n_warm,) + w.shape),
            jnp.broadcast_to(c, (num_chains - n_warm,) + c.shape)], axis=0)

    return jax.tree.map(pick, base_cur, base_warm)


@partial(jax.jit, static_argnames=("out_s",))
def _take_chain(chains, best, out_s=None):
    """One program for the winning chain's (broker_of, leader_of) rows.

    ``out_s`` (a replicated NamedSharding when the chains are mesh-sharded)
    pins the winner REPLICATED: left to GSPMD the slice may come out
    device-sharded, and every downstream consumer (repair's aggregates,
    the after-eval) would then reorder its f32 reductions — breaking the
    sharded == unsharded bitwise contract in a state-dependent way."""
    bo, lo = chains.broker_of[best], chains.leader_of[best]
    if out_s is not None:
        bo = jax.lax.with_sharding_constraint(bo, out_s)
        lo = jax.lax.with_sharding_constraint(lo, out_s)
    return bo, lo


def optimize_anneal(dt: DeviceTopology, assign: Assignment,
                    th: G.GoalThresholds, weights: OBJ.ObjectiveWeights,
                    opts: G.DeviceOptions, num_topics: int,
                    config: Optional[AnnealConfig] = None, seed: int = 0,
                    goal_names: Sequence[str] = G.DEFAULT_GOALS,
                    initial_broker_of: Optional[jax.Array] = None,
                    mesh: Optional[jax.sharding.Mesh] = None,
                    warm_start: Optional[WarmStart] = None,
                    telemetry: bool = False) -> AnnealResult:
    """Parallel-tempering anneal; with ``mesh`` the chain axis shards over
    it (the production multi-device path).

    ``warm_start`` seeds ``round(C * warm_start.fraction)`` chains — the
    coldest ladder slots — from a previous accepted assignment perturbed
    along the dirty-mask delta (see :class:`WarmStart` for the legality and
    bit-identity contracts). ``None`` (or fraction <= 0) is the status-quo
    cold init, bit for bit.

    Chain round-up + RNG contract: the chain count rounds UP to the next
    multiple of the mesh size so the chain axis tiles the mesh evenly —
    the extra chains are real extra search (live temperature-ladder slots
    with their own proposal streams), not dead padding. Per-step chain
    keys come from ``split(fold_in(step_key, 1), C)``, so the streams
    depend on the FINAL chain count: a rounded-up run is a legitimately
    different (larger) search than the unrounded request. A 1-device mesh
    collapses to ``mesh=None`` right here (and at the optimizer entry, and
    in parallel/mesh.build_mesh) — same program, therefore BIT-EXACT —
    pinned by tests/test_parallel.py::test_single_device_mesh_bit_parity.
    Multi-device meshes run structurally different programs (sharded
    rescore, distributed psum, different per-chain fusion order), so the
    end-to-end contract there is quality parity, not bitwise (see
    docs/performance.md Stage 6).
    """
    cfg = config or AnnealConfig()
    C = cfg.num_chains
    if mesh is not None and int(np.prod(mesh.devices.shape)) <= 1:
        # 1-device mesh == no mesh (optimizer._collapse_trivial_mesh):
        # sharding over one device would only swap in structurally
        # different programs; collapsing keeps the bit-parity contract
        mesh = None
    if mesh is not None:   # chain axis must tile the mesh evenly
        n_dev = int(np.prod(mesh.devices.shape))
        C = -(-C // n_dev) * n_dev
    R, P, B = dt.num_replicas, dt.num_partitions, dt.num_brokers
    # topic term: dense maintained histogram when it fits; beyond the dense
    # limit the default hands TopicReplicaDistributionGoal to the optimizer's
    # targeted repair pass (analyzer/repair.py); cfg.topic_mode = "sparse"
    # forces exact in-step CSR counts at any scale instead. Mode routing
    # uses the REAL broker count on bucketed models so a padded and an
    # unpadded run of the same cluster pick the same mode near the limit.
    B_eff = (int(np.asarray(jax.device_get(dt.broker_present)).sum())
             if dt.broker_present is not None else B)
    topic_on = "TopicReplicaDistributionGoal" in tuple(goal_names)
    if cfg.topic_mode not in (None, "dense", "sparse", "off"):
        raise ValueError(f"invalid topic_mode {cfg.topic_mode!r}: "
                         "use dense | sparse | off")
    if not topic_on:
        topic_mode = "off"
    elif cfg.topic_mode is not None:
        topic_mode = cfg.topic_mode
    elif B_eff * num_topics <= cfg.topic_term_limit:
        topic_mode = "dense"
    else:
        topic_mode = "off"
    use_topic = topic_mode == "dense"
    if initial_broker_of is None:
        initial_broker_of = jnp.asarray(assign.broker_of, jnp.int32)

    topic_reps = jax.device_put(np.full((1, 1), -1, np.int32))
    if topic_mode == "sparse":
        # topic CSR: [T, M] replica ids per topic, -1 padded (assignment-
        # invariant, built once on host)
        t_of_r = np.asarray(jax.device_get(
            dt.topic_of_partition[dt.partition_of_replica]))
        counts = np.bincount(t_of_r, minlength=num_topics)
        M = max(int(counts.max()), 1)
        order = np.argsort(t_of_r, kind="stable")
        starts = np.zeros(num_topics + 1, np.int64)
        starts[1:] = np.cumsum(counts)
        cols = np.arange(R, dtype=np.int64) - starts[t_of_r[order]]
        csr = np.full((num_topics, M), -1, np.int32)
        csr[t_of_r[order], cols] = order
        topic_reps = jax.device_put(csr)

    # Empty candidate pools degrade to a single always-illegal index (the
    # legality masks turn those proposals into +inf deltas) so leadership-only
    # optimization still runs.
    movable_np = np.flatnonzero(np.asarray(jax.device_get(opts.replica_movable)))
    if opts.propose_dest_mask is not None:
        # propose-mask path: the host-side pool must not depend on WHICH
        # destinations are requested (a different request would change the
        # pool contents/size and retrace the PT scan). Build it from the
        # mask-independent alive set; make_step_fn partitions it in-trace
        # by the mask. On a mask-free model move_dest_ok == alive, so an
        # all-true mask reproduces the legacy pool exactly (bit-parity).
        dest_np = np.flatnonzero(np.asarray(jax.device_get(th.alive)))
    else:
        dest_np = np.flatnonzero(np.asarray(jax.device_get(opts.move_dest_ok)))
    movable_src = movable_np if movable_np.size else np.array([0], np.int64)
    dest_src = dest_np if dest_np.size else np.array([0], np.int64)
    n_mov_dev = n_dst_dev = None
    if dt.replica_weight is not None:
        # bucketed model: bucket the candidate pools too (a pool-size drift
        # would otherwise retrace the whole PT scan) and sample over the
        # real prefix with traced bounds. The zero fill is never drawn.
        def _padpool(a, floor):
            out = np.zeros(bucket_size(a.size, floor), a.dtype)
            out[:a.size] = a
            return out
        movable_src = _padpool(movable_src, REPLICA_BUCKET_FLOOR)
        dest_src = _padpool(dest_src, BROKER_BUCKET_FLOOR)
        # bounds are device scalars (put *before* the transfer guard)
        n_mov_dev = jax.device_put(np.int32(max(movable_np.size, 1)))
        n_dst_dev = jax.device_put(np.int32(max(dest_np.size, 1)))
    movable_idx = jax.device_put(np.asarray(movable_src, np.int32))
    dest_idx = jax.device_put(np.asarray(dest_src, np.int32))

    # when the topic term is off, skip building the (potentially huge) dense
    # [B, T] histogram — pass a 1-topic axis instead
    agg = compute_aggregates(dt, assign, num_topics if use_topic else 1)
    base = _make_base_state(agg, assign.broker_of, assign.leader_of,
                            use_topic)
    e0 = _chain_energy_jit(dt, th, weights, base, initial_broker_of,
                           topic_mode, num_topics)
    n_warm = 0
    if warm_start is not None:
        n_warm = int(np.clip(round(C * float(warm_start.fraction)), 0, C))
    if n_warm > 0:
        wbo = jnp.asarray(warm_start.broker_of, jnp.int32)
        wlo = jnp.asarray(warm_start.leader_of, jnp.int32)
        if wbo.shape[0] != R or wlo.shape[0] != P:
            raise ValueError(
                f"warm_start shapes {wbo.shape[0]}/{wlo.shape[0]} do not "
                f"match the model's replica/partition axes {R}/{P} — the "
                "caller must gate warm starts on structural continuity")
        dirty = warm_start.dirty_partitions
        if dirty is not None and len(dirty) > 0:
            dirty_mask = np.zeros(P, bool)
            dirty_mask[np.asarray(dirty, np.int64)] = True
            wbo, wlo = _mix_dirty(dt.partition_of_replica, base.broker_of,
                                  base.leader_of, wbo, wlo,
                                  jax.device_put(dirty_mask))
        agg_w = compute_aggregates(dt, Assignment(broker_of=wbo,
                                                  leader_of=wlo),
                                   num_topics if use_topic else 1)
        base_w = _make_base_state(agg_w, wbo, wlo, use_topic)
        e0_w = _chain_energy_jit(dt, th, weights, base_w, initial_broker_of,
                                 topic_mode, num_topics)
        chains = _broadcast_chains_warm(base._replace(energy=e0),
                                        base_w._replace(energy=e0_w),
                                        C, n_warm)
    else:
        # fraction <= 0 / no warm start: EXACTLY the historical init path
        # (the warm base state is never even built) — bit-identical output
        chains = _broadcast_chains(base._replace(energy=e0), C)

    # temperature ladder: a cold block at ~0 (pure descent) + geometric ladder
    n_cold = max(1, int(C * cfg.cold_fraction))
    ladder = np.concatenate([
        np.full(n_cold, cfg.t_min, np.float32),
        np.geomspace(cfg.t_min, cfg.t_max, max(C - n_cold, 1)).astype(np.float32)[:C - n_cold],
    ])[:C]
    temps0 = jax.device_put(ladder)

    n_rounds = max(1, cfg.steps // cfg.swap_interval)
    keys = jax.random.split(jax.random.PRNGKey(seed), n_rounds)

    if mesh is not None:
        # chains are embarrassingly parallel: shard the chain axis across the
        # mesh (parallel/sharding.py); XLA inserts the (cheap) collectives
        # for the PT temperature swap and the final argmin.
        from cruise_control_tpu.parallel.sharding import replicate, shard_chains
        chains = shard_chains(chains, mesh)
        temps0 = shard_chains(temps0, mesh)
        # every OTHER operand must be placed on the mesh EXPLICITLY too:
        # the guarded dispatch below runs under transfer_guard("disallow"),
        # and a device-0-committed array (the round keys, the model
        # constants) would otherwise be replicated by an IMPLICIT
        # device-to-device transfer at dispatch — which the guard rejects,
        # silently degrading the engine chain to greedy
        (keys, dt, th, weights, opts, movable_idx, dest_idx,
         initial_broker_of, topic_reps, n_mov_dev, n_dst_dev) = replicate(
            (keys, dt, th, weights, opts, movable_idx, dest_idx,
             initial_broker_of, topic_reps, n_mov_dev, n_dst_dev), mesh)

    # steady-state dispatch: every argument is a device array (or hashed
    # static), so any implicit transfer inside this call is a hazard the
    # sentinel should catch, not tolerate (see common/sentinels.py)
    # CPU XLA rejects donation per-buffer (with a warning each); everywhere
    # else the broadcast seed state is donated — it is a fresh buffer no
    # caller reuses, and donating halves the chain-state HBM footprint.
    run_pt = _run_pt if jax.default_backend() == "cpu" else _run_pt_donated
    tel_dev = None
    with SENT.no_implicit_transfers():
        out = run_pt(chains, temps0, keys, dt, th, weights, opts,
                     movable_idx, dest_idx, initial_broker_of,
                     topic_reps, cfg, topic_mode, n_rounds,
                     n_movable=n_mov_dev, n_dest=n_dst_dev,
                     telemetry=telemetry)
        if telemetry:
            chains, temps, tel_dev = out
        else:
            chains, temps = out
    # graftwatch cost ledger (obs/costmodel.py): one flag check when
    # disabled; outside the transfer guard because deep pricing lowers
    CM.capture_program(
        "anneal-pt", run_pt,
        (chains, temps, keys, dt, th, weights, opts, movable_idx,
         dest_idx, initial_broker_of, topic_reps, cfg, topic_mode,
         n_rounds),
        out, {"n_movable": n_mov_dev, "n_dest": n_dst_dev,
              "telemetry": telemetry})
    chain_rows = None
    if mesh is not None and topic_mode in ("dense", "off"):
        # replica-sharded exact rescore (parallel/sharding.py): the per-chain
        # O(R) gathers and segment-sums run on replica shards with one psum,
        # so no device materializes C× all-R intermediates. Parity with
        # _rescore_chains is locked by test_parallel.py.
        from cruise_control_tpu.parallel.sharding import sharded_chain_energies
        energies = sharded_chain_energies(
            mesh, dt, th, weights, chains.broker_of, chains.leader_of,
            initial_broker_of, use_topic=use_topic,
            topic_count=chains.topic_count if use_topic else None)
    else:
        # the donating variant frees the post-run chain state (loads,
        # counts, histogram) and passes only the assignment rows through;
        # the mesh path keeps the undonated program (parity contract).
        rescore = (_rescore_chains_donated
                   if mesh is None and jax.default_backend() != "cpu"
                   else _rescore_chains)
        CM.capture_program(
            "anneal-rescore", rescore,
            (chains, dt, th, weights, initial_broker_of,
             topic_mode, num_topics))
        energies, bo_all, lo_all = rescore(
            chains, dt, th, weights, initial_broker_of,
            topic_mode, num_topics)                              # f32[C, 2]
        chain_rows = (bo_all, lo_all)
    # lexicographic best chain, combined in f64 on host — the f32 combined
    # scalar would absorb the cost channel under any hard violation.
    # Telemetry rides the same fetch (the "one extra fetch" contract is
    # actually zero extra round-trips: one device_get either way).
    tel_host = None
    if tel_dev is not None:
        e2_raw, tel_host = jax.device_get((energies, tel_dev))
    else:
        e2_raw = jax.device_get(energies)
    e2 = np.asarray(e2_raw, np.float64)
    comb = e2[:, 0] * OBJ.VIOL_SCALE + e2[:, 1]
    best = int(np.argmin(comb))
    out_s = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        out_s = NamedSharding(mesh, PartitionSpec())
    if chain_rows is None:
        best_bo, best_lo = _take_chain(chains, best, out_s=out_s)
    else:
        best_bo, best_lo = _take_chain_rows(chain_rows[0], chain_rows[1],
                                            best, out_s=out_s)
    telemetry_out = None
    if tel_host is not None:
        slot_acc, exch_att, exch_acc, best_curve = (
            np.asarray(t) for t in tel_host)
        # attempts per family per slot are static: every step proposes the
        # full candidate batch at every ladder slot
        steps_total = n_rounds * cfg.swap_interval
        tries = np.array([cfg.tries_move, cfg.tries_lead, cfg.tries_swap],
                         np.float64) * steps_total

        def rates(col):
            return [round(float(v), 6)
                    for v in slot_acc[:, col] / max(tries[col], 1.0)]
        telemetry_out = {
            "rounds": int(n_rounds),
            "stepsPerRound": int(cfg.swap_interval),
            "numChains": int(C),
            "ladderTemps": [round(float(t), 6) for t in ladder.tolist()],
            "acceptRates": {"move": rates(0), "lead": rates(1),
                            "swap": rates(2)},
            "exchangeAttempts": [int(v) for v in exch_att.tolist()],
            "exchangeAcceptRates": [
                round(float(a) / max(float(t), 1.0), 6)
                for a, t in zip(exch_acc.tolist(), exch_att.tolist())],
            "bestEnergyCurve": [round(float(v), 3)
                                for v in best_curve.tolist()],
        }
    return AnnealResult(
        assignment=Assignment(broker_of=best_bo, leader_of=best_lo),
        energy=jnp.float32(comb[best]),
        chain_energies=energies,
        telemetry=telemetry_out,
    )


from functools import partial as _partial

_chain_energy_jit = jax.jit(_chain_energy,
                            static_argnames=("topic_mode", "num_topics"))


def _run_pt_impl(chains, temps, keys, dt, th, weights, opts, movable_idx,
                 dest_idx, initial_broker_of, topic_reps, cfg: AnnealConfig,
                 topic_mode: str, n_rounds: int,
                 n_movable=None, n_dest=None, telemetry: bool = False):
    """The whole parallel-tempering run as ONE module-level jit.

    Module-level matters: a jit wrapper created inside ``optimize_anneal``
    would be a fresh function object per call, so every service/bench
    invocation would re-trace and re-lower the full scan (tens of seconds at
    LinkedIn scale — this was the dominant cost of the whole proposal path,
    ~50× the actual device time of the annealing steps). Keyed here by the
    (hashable, frozen) AnnealConfig + shapes, repeat calls are pure cache
    hits and pay device time only.

    Jitted twice below: ``_run_pt`` (no donation — CPU, where XLA rejects
    donation with a warning per buffer) and ``_run_pt_donated`` (chain
    state donated, argnum 0) so warm ticks don't hold two copies of the
    500k-replica chain state in HBM. The input ``chains`` is always a
    fresh ``_broadcast_chains`` output, never reused by the caller.
    """
    C = temps.shape[0]
    step = make_step_fn(dt, th, weights, opts, cfg, movable_idx, dest_idx,
                        initial_broker_of, topic_mode, topic_reps,
                        n_movable=n_movable, n_dest=n_dest,
                        telemetry=telemetry)

    def chain_round(st: ChainState, temp, key):
        ks = jax.random.split(key, cfg.swap_interval)

        if telemetry:
            # ys are the per-step accept counts; summed here so the round
            # hands one i32[3] per chain up to the PT carry
            st, counts = jax.lax.scan(
                lambda s, k: step(s, temp, k), st, ks)
            return st, jnp.sum(counts, axis=0)

        def body(s, k):
            return step(s, temp, k), None

        st, _ = jax.lax.scan(body, st, ks)
        return st

    def pt_round(carry, inp):
        if telemetry:
            chains, temps, slot_acc, exch_att, exch_acc = carry
        else:
            chains, temps = carry
        rnd, key = inp
        kc = jax.random.split(jax.random.fold_in(key, 1), C)
        if telemetry:
            chains, counts = jax.vmap(
                chain_round, in_axes=(0, 0, 0))(chains, temps, kc)
        else:
            chains = jax.vmap(chain_round,
                              in_axes=(0, 0, 0))(chains, temps, kc)
        # temperature swap between ladder-adjacent chains (even/odd
        # alternation); energies combine AFTER differencing the channels
        order = jnp.argsort(temps)
        e_sorted = chains.energy[order]                          # [C, 2]
        t_sorted = temps[order]
        off = rnd % 2
        i = jnp.arange(C)
        partner = jnp.where((i - off) % 2 == 0, i + 1, i - 1)
        partner = jnp.clip(partner, 0, C - 1)
        d_swap = (OBJ.combine(e_sorted - e_sorted[partner])
                  * (1.0 / jnp.maximum(t_sorted, 1e-9)
                     - 1.0 / jnp.maximum(t_sorted[partner], 1e-9)))
        u = jax.random.uniform(jax.random.fold_in(key, 2), (C,))
        u_pair = u[jnp.minimum(i, partner)]  # both sides draw the same uniform
        do = (partner != i) & ((d_swap > 0)
                               | (u_pair < jnp.exp(jnp.minimum(d_swap, 0.0))))
        do = do & do[partner]
        new_t_sorted = jnp.where(do, t_sorted[partner], t_sorted)
        temps = temps.at[order].set(new_t_sorted)
        if telemetry:
            # ladder-slot attribution: ``order`` maps slot -> chain for the
            # round the counts were earned in (temps only change after)
            slot_acc = slot_acc + counts[order]
            exch_att = exch_att + (partner != i).astype(jnp.int32)
            exch_acc = exch_acc + do.astype(jnp.int32)
            # per-round best combined energy (descent curve). f32 combine
            # is lossy under a hard violation — fine for a trend signal;
            # the authoritative winner is still picked in f64 on host.
            best_e = jnp.min(OBJ.combine(e_sorted))
            return (chains, temps, slot_acc, exch_att, exch_acc), best_e
        return (chains, temps), None

    if telemetry:
        z3 = jnp.zeros((C, 3), jnp.int32)
        z1 = jnp.zeros((C,), jnp.int32)
        (chains, temps, slot_acc, exch_att, exch_acc), best_curve = \
            jax.lax.scan(pt_round, (chains, temps, z3, z1, z1),
                         (jnp.arange(n_rounds), keys))
        return chains, temps, (slot_acc, exch_att, exch_acc, best_curve)
    (chains, temps), _ = jax.lax.scan(
        pt_round, (chains, temps), (jnp.arange(n_rounds), keys))
    return chains, temps


_RUN_PT_STATICS = ("cfg", "topic_mode", "n_rounds", "telemetry")
_run_pt = _partial(jax.jit, static_argnames=_RUN_PT_STATICS)(_run_pt_impl)
_run_pt_donated = _partial(jax.jit, static_argnames=_RUN_PT_STATICS,
                           donate_argnums=(0,))(_run_pt_impl)


def _rescore_chains_impl(chains, dt, th, weights, initial_broker_of,
                         topic_mode: str, num_topics: int = 1):
    """Exact per-chain rescore: recomputed load aggregates (immune to
    incremental float drift) plus the *maintained* topic counts — integer
    scatter-adds, hence already exact. Rebuilding the dense [B, T]
    histogram per chain here would cost more than the whole anneal.

    Returns ``(energies, broker_of, leader_of)``: passing the assignment
    rows through as outputs lets the donating variant free every *other*
    chain-state buffer (loads, counts, histogram) while the caller can
    still slice out the winning chain — with plain donation the caller's
    later ``chains.broker_of[best]`` would read a deleted buffer."""
    R, P, B = dt.num_replicas, dt.num_partitions, dt.num_brokers
    ones = replica_count_weights(dt).astype(jnp.float32)
    lead_ones = leader_count_weights(dt).astype(jnp.float32)

    def rescore(st: ChainState):
        eff = (dt.replica_base_load
               + jnp.where((st.leader_of[dt.partition_of_replica]
                            == jnp.arange(R))[:, None],
                           dt.leader_extra[dt.partition_of_replica], 0.0))
        broker_load = jax.ops.segment_sum(eff, st.broker_of, num_segments=B)
        host_load = jax.ops.segment_sum(broker_load, dt.host_of_broker,
                                        num_segments=dt.num_hosts)
        leader_broker = st.broker_of[st.leader_of]
        pl = (dt.leader_extra[:, res.NW_OUT]
              + dt.replica_base_load[st.leader_of, res.NW_OUT])
        st2 = st._replace(
            broker_load=broker_load,
            host_load=host_load,
            replica_count=jax.ops.segment_sum(ones, st.broker_of, num_segments=B),
            leader_count=jax.ops.segment_sum(lead_ones,
                                             leader_broker, num_segments=B),
            potential_nw_out=jax.ops.segment_sum(
                pl[dt.partition_of_replica], st.broker_of, num_segments=B),
            leader_bytes_in=jax.ops.segment_sum(
                dt.leader_bytes_in, leader_broker, num_segments=B),
        )
        return _chain_energy(dt, th, weights, st2, initial_broker_of,
                             topic_mode, num_topics)

    return (jax.vmap(rescore)(chains), chains.broker_of, chains.leader_of)


_RESCORE_STATICS = ("topic_mode", "num_topics")
_rescore_chains = _partial(jax.jit,
                           static_argnames=_RESCORE_STATICS)(_rescore_chains_impl)
_rescore_chains_donated = _partial(jax.jit, static_argnames=_RESCORE_STATICS,
                                   donate_argnums=(0,))(_rescore_chains_impl)


@_partial(jax.jit, static_argnames=("out_s",))
def _take_chain_rows(broker_of, leader_of, best, out_s=None):
    """`_take_chain` over the rescore's passed-through assignment rows —
    used when the chain state itself was donated away by the rescore."""
    bo, lo = broker_of[best], leader_of[best]
    if out_s is not None:
        bo = jax.lax.with_sharding_constraint(bo, out_s)
        lo = jax.lax.with_sharding_constraint(lo, out_s)
    return bo, lo
